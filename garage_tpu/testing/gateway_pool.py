"""GatewayPool — a health-checked multi-gateway S3 client (ISSUE 19).

Production object stores put N stateless gateways behind a client (or
LB) that health-checks them, backs off the ones that shed, and fails a
request over to a sibling when one dies mid-flight.  This module is
that client for the in-process harness: the gateway_failover drill,
bench --replay-phase, and the workload replayer all drive their
traffic through it, so "a gateway died mid-PUT" exercises the same
retry/resume ladder everywhere.

Failover policy, by request class:

  - idempotent requests (every S3 verb this harness issues — PUT with
    the full body in hand, GET, HEAD, DELETE, bucket ops) retry
    verbatim against a sibling on a transport error;
  - typed 503 sheds back the gateway off for the response's
    Retry-After (clamped to ``retry_after_cap`` — the satellite fix:
    the designed backoff, not client hammering) and fail over to a
    sibling immediately if one is available;
  - streaming GETs interrupted mid-body resume on a sibling with a
    ``Range: bytes=<got>-`` request (206) instead of refetching, so a
    gateway kill never re-pays the bytes already drained.

Counters ride an optional MetricsRegistry (``gateway_pool_*``
families, documented in docs/OBSERVABILITY.md) so drills can promlint
and metricsdoc them like any server-side family.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("garage_tpu.testing.gateway_pool")

# transport-level failures that mean "this gateway, this connection" —
# retryable against a sibling, never surfaced to the caller directly
def _is_transport_error(e: BaseException) -> bool:
    import aiohttp

    return isinstance(e, (
        ConnectionError,                 # incl. ConnectionResetError
        aiohttp.ClientConnectionError,   # incl. ServerDisconnectedError
        aiohttp.ClientPayloadError,      # body truncated mid-stream
        asyncio.TimeoutError,
        OSError,
    ))


class _Gateway:
    """One pool member: address + live health/backoff state."""

    __slots__ = ("name", "port", "healthy", "backoff_until")

    def __init__(self, name: str, port: int):
        self.name = name
        self.port = port
        self.healthy = True
        self.backoff_until = 0.0


class GatewayPool:
    """N gateways, one client.  ``endpoints`` is ``[(name, port), ...]``
    on 127.0.0.1 (the SimCluster shape); ``metrics`` (optional) is a
    MetricsRegistry the pool's counters register into."""

    def __init__(self, session, endpoints: Sequence[Tuple[str, int]],
                 key_id: str, secret: str, region: str = "garage",
                 metrics=None, retry_after_cap: float = 2.0,
                 max_attempts: int = 6):
        self.session = session
        self.gateways: List[_Gateway] = [
            _Gateway(n, p) for n, p in endpoints]
        self.key_id, self.secret, self.region = key_id, secret, region
        self.retry_after_cap = retry_after_cap
        self.max_attempts = max_attempts
        self.counters: Dict[str, int] = {
            "failovers": 0, "retries": 0, "sheds": 0,
            "probes": 0, "probe_failures": 0, "resumes": 0,
        }
        self._rr = 0  # round-robin cursor over equally-ranked members
        self._m = None
        if metrics is not None:
            self._m = {
                "failover": metrics.counter(
                    "gateway_pool_failover_total",
                    "Requests moved to a sibling gateway after a "
                    "transport error"),
                "retry": metrics.counter(
                    "gateway_pool_retry_total",
                    "Request attempts beyond the first (failovers + "
                    "shed-driven retries)"),
                "shed": metrics.counter(
                    "gateway_pool_shed_total",
                    "Typed 503 sheds observed by the pool client"),
                "probe": metrics.counter(
                    "gateway_pool_probe_total",
                    "Gateway health probes sent", ),
                "resume": metrics.counter(
                    "gateway_pool_resume_total",
                    "Streaming GETs resumed on a sibling via Range "
                    "after a mid-body gateway loss"),
            }

    def _count(self, key: str, metric: Optional[str] = None) -> None:
        self.counters[key] += 1
        if self._m is not None and metric in self._m:
            self._m[metric].inc()

    # --- member state -------------------------------------------------

    def set_port(self, name: str, port: int) -> None:
        """Re-point a member after a gateway restart (fresh socket)."""
        gw = next(g for g in self.gateways if g.name == name)
        gw.port, gw.healthy, gw.backoff_until = port, True, 0.0

    def _candidates(self, prefer: Optional[int] = None) -> List[_Gateway]:
        """Attempt order: preferred member first (if given), then
        healthy-and-not-backing-off, then backing-off, then unhealthy —
        never empty, so a fully-dark pool still surfaces a real error
        instead of an index crash.  Equally-ranked healthy members
        rotate round-robin (the LB half of "N stateless gateways"): a
        stable sort would pin every un-preferred request to member 0
        and a sibling's death would never intersect live traffic."""
        now = time.monotonic()

        def rank(g: _Gateway) -> tuple:
            return (not g.healthy, max(0.0, g.backoff_until - now))

        ordered = sorted(self.gateways, key=rank)
        top = rank(ordered[0])
        head = [g for g in ordered if rank(g) == top]
        self._rr = (self._rr + 1) % len(head)
        ordered = head[self._rr:] + head[:self._rr] + ordered[len(head):]
        if prefer is not None:
            p = self.gateways[prefer]
            ordered = [p] + [g for g in ordered if g is not p]
        return ordered

    # --- signing + raw send -------------------------------------------

    async def raw(self, idx: int, method: str, path: str, body: bytes = b"",
                  query: Sequence[Tuple[str, str]] = (),
                  extra_headers: Optional[Dict[str, str]] = None,
                  body_factory: Optional[Callable[[], object]] = None):
        """One signed request to ONE member, no failover — the drills'
        'talk to this specific gateway' primitive.  Returns
        ``(status, body_bytes, headers)``.  ``body_factory`` (when
        given) supplies the wire payload — e.g. a trickling async
        generator — while ``body`` is what gets SIGNED (and therefore
        what the factory must eventually yield)."""
        import yarl

        from ..api.signature import sign_request, uri_encode

        gw = self.gateways[idx]
        headers = {"host": f"127.0.0.1:{gw.port}"}
        if extra_headers:
            headers.update({k.lower(): v for k, v in extra_headers.items()})
        headers.update(sign_request(
            self.key_id, self.secret, self.region, method, path,
            list(query), headers, body, path_is_raw=True))
        qs = "&".join(f"{uri_encode(k)}={uri_encode(v)}" for k, v in query)
        url = yarl.URL(
            f"http://127.0.0.1:{gw.port}{path}" + (f"?{qs}" if qs else ""),
            encoded=True)
        payload = body_factory() if body_factory is not None else body
        if body_factory is not None:
            # generator bodies go chunked; the signed sha256 still
            # covers the full payload, which the server verifies
            headers["content-length"] = str(len(body))
        async with self.session.request(
                method, url, data=payload, headers=headers) as r:
            return r.status, await r.read(), r.headers

    def stream_request(self, idx: int, method: str, path: str,
                       extra_headers: Optional[Dict[str, str]] = None):
        """A signed streaming request context to one member (caller
        iterates ``resp.content`` itself — the slow-consumer drills)."""
        import yarl

        from ..api.signature import sign_request

        gw = self.gateways[idx]
        headers = {"host": f"127.0.0.1:{gw.port}"}
        if extra_headers:
            headers.update({k.lower(): v for k, v in extra_headers.items()})
        headers.update(sign_request(
            self.key_id, self.secret, self.region, method, path, [],
            headers, b"", path_is_raw=True))
        url = yarl.URL(f"http://127.0.0.1:{gw.port}{path}", encoded=True)
        return self.session.request(method, url, headers=headers)

    # --- health probes -------------------------------------------------

    async def probe(self) -> Dict[str, bool]:
        """One health-probe round: a signed ListBuckets per member.
        2xx/4xx = serving; 503 = backing off per Retry-After; transport
        error = unhealthy (next failover skips it)."""
        out: Dict[str, bool] = {}
        for i, gw in enumerate(self.gateways):
            self._count("probes", "probe")
            try:
                st, _b, hdrs = await asyncio.wait_for(
                    self.raw(i, "GET", "/"), 10.0)
            except BaseException as e:  # noqa: BLE001 — verdict, not crash
                if not _is_transport_error(e):
                    raise
                gw.healthy = False
                self._count("probe_failures")
                out[gw.name] = False
                continue
            gw.healthy = st < 500 or st == 503
            if st == 503:
                self._note_shed(gw, hdrs)
            out[gw.name] = gw.healthy and st != 503
        return out

    def _note_shed(self, gw: _Gateway, hdrs) -> None:
        self._count("sheds", "shed")
        try:
            ra = float(hdrs.get("Retry-After", 1))
        except (TypeError, ValueError):
            ra = 1.0
        gw.backoff_until = time.monotonic() + min(
            max(ra, 0.0), self.retry_after_cap)

    # --- the failover request path -------------------------------------

    async def request(self, method: str, path: str, body: bytes = b"",
                      query: Sequence[Tuple[str, str]] = (),
                      idempotent: bool = True,
                      prefer: Optional[int] = None,
                      extra_headers: Optional[Dict[str, str]] = None,
                      body_factory: Optional[Callable[[], object]] = None):
        """Send with health-aware member selection, typed-503 backoff,
        and sibling failover.  Returns ``(status, body, headers)`` of
        the final attempt; transport errors surface only when EVERY
        attempt (bounded by ``max_attempts``) died."""
        last_exc: Optional[BaseException] = None
        last_resp = None
        attempts = 0
        while attempts < self.max_attempts:
            for gw in self._candidates(prefer):
                if attempts >= self.max_attempts:
                    break
                attempts += 1
                if attempts > 1:
                    self._count("retries", "retry")
                wait = gw.backoff_until - time.monotonic()
                if wait > 0:
                    # every sibling is backing off too (sorted order):
                    # honor the clamped Retry-After instead of hammering
                    await asyncio.sleep(min(wait, self.retry_after_cap))
                idx = self.gateways.index(gw)
                try:
                    st, rb, hdrs = await self.raw(
                        idx, method, path, body, query,
                        extra_headers=extra_headers,
                        body_factory=body_factory)
                except BaseException as e:  # noqa: BLE001
                    if not _is_transport_error(e):
                        raise
                    gw.healthy = False
                    last_exc = e
                    if not idempotent:
                        raise
                    self._count("failovers", "failover")
                    prefer = None
                    continue
                gw.healthy = True
                if st == 503:
                    self._note_shed(gw, hdrs)
                    last_resp = (st, rb, hdrs)
                    prefer = None
                    continue  # sibling may have room right now
                return st, rb, hdrs
        if last_resp is not None:
            return last_resp
        assert last_exc is not None
        raise last_exc

    async def get_resumable(self, path: str, prefer: Optional[int] = None,
                            on_chunk=None):
        """Streaming GET with mid-body failover: bytes already drained
        are kept and the remainder is fetched from a sibling with
        ``Range: bytes=<got>-`` (206).  Returns ``(status, body,
        resumed)``.  ``on_chunk(total_bytes)`` fires per chunk — the
        drills use it to kill the serving gateway mid-stream."""
        buf = bytearray()
        resumed = False
        for attempt in range(self.max_attempts):
            order = self._candidates(prefer if attempt == 0 else None)
            gw = order[0]
            idx = self.gateways.index(gw)
            hdrs = {"range": f"bytes={len(buf)}-"} if buf else None
            try:
                async with self.stream_request(
                        idx, "GET", path, extra_headers=hdrs) as r:
                    if r.status not in (200, 206):
                        return r.status, bytes(buf), resumed
                    async for chunk in r.content.iter_any():
                        buf.extend(chunk)
                        if on_chunk is not None:
                            await on_chunk(len(buf))
                return (206 if resumed else 200), bytes(buf), resumed
            except BaseException as e:  # noqa: BLE001
                if not _is_transport_error(e):
                    raise
                gw.healthy = False
                self._count("failovers", "failover")
                if buf:
                    resumed = True
                    self._count("resumes", "resume")
        raise ConnectionError(
            f"get_resumable: every gateway died ({len(buf)} bytes in)")
