"""SimCluster — a 20–30 node, 3–5 zone in-process cluster harness.

Scales the 3-node chaos scaffolding (bench._mk_cluster + FaultInjector)
to cluster-sized drills: per-node config generation (memory db, CPU
codec, fast-twitch [rpc] tunables), bounded concurrent startup, a
zone-aware applied layout, one S3 gateway, and optional FaultyLink
interposition on every directed dial path so whole zones can be
partitioned/blackholed/slowed/killed live (FaultInjector zone verbs).

The three cluster-scale drills the ISSUE-7 acceptance names live here so
the pytest suite (tests/test_cluster_scale.py, marked slow+cluster) and
the standalone reproduction entrypoint (scripts/chaos.py --phases
zone_blackhole,zone_drain,rolling) run EXACTLY the same code:

  zone_blackhole_drill  one full zone dark under PUT/GET traffic —
                        reads served local-zone-first from survivors,
                        zero client-visible errors, boundary breakers
                        open and recover after heal
  zone_drain_drill      a layout change drains a whole zone while
                        clients keep writing — rebalance mover walks the
                        changed partitions (rebalance_partitions_done ==
                        total), every acked object bit-identical after
                        the drained nodes are gone
  rolling_restart_drill nodes restart one zone at a time with a bumped
                        version tag (handshake + gossip skew visible)
                        under live traffic, zero client errors

Invariants throughout are the chaos-soak ones: bit-identical read-back
of every acked object, deletes stay deleted, zero client-visible errors.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from pathlib import Path
from typing import Dict, List, Optional

from .faults import FAST_CHAOS_RPC, FaultInjector

logger = logging.getLogger("garage_tpu.testing.sim_cluster")

DEFAULT_ZONES = ("z1", "z2", "z3", "z4")


def p99(lats: List[float]) -> float:
    """Nearest-rank p99 over raw latency samples (0.0 when empty) —
    shared by the drills and bench phases so every quantile claim uses
    the same arithmetic."""
    ls = sorted(lats)
    return ls[min(len(ls) - 1, int(len(ls) * 0.99))] if ls else 0.0


async def make_tenant_client(garage, session, port: int, name: str,
                             bucket: str):
    """One QoS tenant: a fresh access key plus its own bucket, returned
    as a signing S3 client — shared by the noisy-neighbor drill and the
    Zipf bench phase so both harnesses mint tenants identically."""
    import bench

    helper = garage.helper()
    key = await helper.create_key(name)
    key.params().allow_create_bucket.update(True)
    await garage.key_table.insert(key)
    s3 = bench._S3(session, port, key.key_id, key.params().secret_key)
    st, _b, _h = await s3.req("PUT", f"/{bucket}")
    assert st == 200, f"bucket {bucket}: {st}"
    return s3


def check_typed_shed(body: bytes, headers,
                     codes=("SlowDown", "DeadlineExceeded")):
    """The typed-shed contract on a 503, encoded ONCE for every
    harness: S3 error XML with an allowed Code, a RequestId matching
    the x-amz-request-id header, and a positive integer Retry-After.
    Returns None when valid, else a short violation note."""
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(body)
        code, rid = root.findtext("Code"), root.findtext("RequestId")
    except ET.ParseError:
        return "503 body is not S3 error XML"
    if code not in codes:
        return f"503 code={code!r}"
    if not rid:
        return "503 missing RequestId"
    hdr_rid = headers.get("x-amz-request-id")
    if hdr_rid is not None and hdr_rid != rid:
        return "503 RequestId != x-amz-request-id header"
    ra = headers.get("Retry-After")
    try:
        if ra is None or int(ra) < 1:
            return f"503 Retry-After={ra!r}"
    except ValueError:
        return f"503 Retry-After={ra!r}"
    return None


def _zone_plan(n_nodes: int, n_zones: int) -> List[str]:
    """Round-robin zone assignment for `n_nodes` storage nodes."""
    zones = [f"z{i + 1}" for i in range(n_zones)]
    return [zones[i % n_zones] for i in range(n_nodes)]


class SimCluster:
    """n_storage nodes spread over n_zones, plus n_gateways gateway
    nodes (capacity None) that front the S3 API — so storage zones can
    be killed/restarted without taking the client's endpoint down, and
    (with n_gateways > 1) a GatewayPool client can fail requests over
    between siblings when one gateway dies or drains."""

    def __init__(self, tmp, n_storage: int = 24, n_zones: int = 4,
                 repl: str = "3", zone_redundancy="maximum",
                 db: str = "memory", rpc_cfg: Optional[dict] = None,
                 rebalance_rate_mib: float = 512.0,
                 extra_cfg: Optional[dict] = None,
                 n_gateways: int = 1):
        self.tmp = Path(tmp)
        self.n_storage = n_storage
        self.n_zones = n_zones
        self.n_gateways = n_gateways
        self.repl = repl
        self.zone_redundancy = zone_redundancy
        self.db = db
        self.rpc_cfg = dict(rpc_cfg if rpc_cfg is not None
                            else FAST_CHAOS_RPC)
        self.rebalance_rate_mib = rebalance_rate_mib
        # extra top-level config keys merged into EVERY node's config
        # (e.g. {"api": {"max_inflight": 2}} for the overload drill)
        self.extra_cfg = dict(extra_cfg or {})
        # index 0 = first gateway; storage nodes are 1..n_storage; extra
        # gateways ride at the tail (n_storage+1..) so every existing
        # storage_indices()/zone-drill invariant keeps holding.  Gateway
        # zone entries are None ON PURPOSE: zone-kill/rolling drills
        # enumerate zones through the injector and must never crash the
        # client's endpoint (their layout role still names a zone).
        self.zones: List[Optional[str]] = ([None] + _zone_plan(
            n_storage, n_zones) + [None] * (n_gateways - 1))
        self.garages: List = []
        self.injector: Optional[FaultInjector] = None
        self.servers: List = []   # one S3ApiServer per gateway
        self.ports: List[int] = []
        self.server = None        # first gateway's server (compat)
        self.port = self.key_id = self.secret = None

    # --- construction ---------------------------------------------------

    def _node_config(self, i: int) -> dict:
        cfg = {
            "metadata_dir": str(self.tmp / f"n{i}" / "meta"),
            "data_dir": str(self.tmp / f"n{i}" / "data"),
            "replication_mode": self.repl,
            "rpc_bind_addr": "127.0.0.1:0",
            "rpc_secret": "simcluster",
            "db_engine": self.db,
            "bootstrap_peers": [],
            "rebalance_rate_mib": self.rebalance_rate_mib,
            "codec": {"rs_data": 0, "rs_parity": 0, "backend": "cpu"},
            "rpc": dict(self.rpc_cfg),
        }
        cfg.update(self.extra_cfg)
        return cfg

    async def start(self, faults: bool = True,
                    startup_timeout: float = 120.0) -> None:
        from ..api.s3.api_server import S3ApiServer
        from ..model import Garage
        from ..rpc.layout import ClusterLayout, LayoutParameters, NodeRole
        from ..utils.config import config_from_dict

        t0 = time.monotonic()
        n = self.n_storage + self.n_gateways
        self.garages = [
            Garage(config_from_dict(self._node_config(i))) for i in range(n)
        ]
        for g in self.garages:
            await g.system.netapp.listen("127.0.0.1:0")
        ports = [g.system.netapp._server.sockets[0].getsockname()[1]
                 for g in self.garages]
        for i, g in enumerate(self.garages):
            g.system.config.rpc_public_addr = f"127.0.0.1:{ports[i]}"

        # full-mesh dial, bounded + concurrent (i<j so each pair dials
        # once); sequential dialing would dominate startup at 24+ nodes
        async def dial(i, j):
            await self.garages[i].system.netapp.connect(
                f"127.0.0.1:{ports[j]}",
                expected_id=self.garages[j].system.id)

        pairs = [(i, j) for i in range(n) for j in range(i + 1, n)]
        for lo in range(0, len(pairs), 64):
            await asyncio.wait_for(
                asyncio.gather(*[dial(i, j)
                                 for i, j in pairs[lo:lo + 64]]),
                timeout=max(5.0, startup_timeout - (time.monotonic() - t0)))

        # zone-aware layout: gateways (capacity None) + storage roles
        lay = self.garages[0].system.layout
        lay.stage_parameters(LayoutParameters(self.zone_redundancy))
        for gi in self.gateway_indices():
            lay.stage_role(bytes(self.garages[gi].system.id),
                           NodeRole(self.zones[1] or "z1", None,
                                    ["gateway"]))
        for i in self.storage_indices():
            lay.stage_role(bytes(self.garages[i].system.id),
                           NodeRole(self.zones[i], 1000))
        lay.apply_staged_changes()
        enc = lay.encode()
        for g in self.garages:
            g.system.layout = ClusterLayout.decode(enc)
            g.system._rebuild_ring()
            g.system.save_layout()
            g.spawn_workers()

        # make the peers known to each other's peer books (reconnects,
        # revives and the fault-link migration all read from them)
        for i, a in enumerate(self.garages):
            for j, b in enumerate(self.garages):
                if i != j:
                    a.system.peering.add_peer(
                        f"127.0.0.1:{ports[j]}", b.system.id)

        self.injector = FaultInjector(self.garages, zones=self.zones)
        # share the injector's list so a revive()'s replacement Garage is
        # visible here too (drills read movers/metrics through it)
        self.garages = self.injector.garages
        if faults:
            await self.injector.add_network_faults(
                rng=random.Random(1009))
            ok = await self.injector.reconnect(rounds=10)
            if not ok:
                logger.warning("mesh not fully re-established through "
                               "fault links within the round budget")
        else:
            await self.tick()

        helper = self.garages[0].helper()
        key = await helper.create_key("sim")
        key.params().allow_create_bucket.update(True)
        await self.garages[0].key_table.insert(key)
        self.servers, self.ports = [], []
        for gi in self.gateway_indices():
            srv = S3ApiServer(self.garages[gi])
            await srv.start("127.0.0.1:0")
            self.servers.append(srv)
            self.ports.append(srv.port)
        self.server, self.port = self.servers[0], self.ports[0]
        self.key_id = key.key_id
        self.secret = key.params().secret_key
        logger.info("SimCluster up: %d nodes / %d zones / %d gateways "
                    "in %.1fs", n, self.n_zones, self.n_gateways,
                    time.monotonic() - t0)

    async def tick(self, rounds: int = 2) -> None:
        """Drive every live node's peering tick (pings → RTT EWMAs,
        breaker probes) — SimCluster never starts the 15 s loops, so
        drills control time themselves."""
        dead = self.injector.dead if self.injector else set()
        for _ in range(rounds):
            await asyncio.gather(*[
                g.system.peering._tick()
                for i, g in enumerate(self.garages) if i not in dead
            ], return_exceptions=True)
            await asyncio.sleep(0.05)

    async def stop(self) -> None:
        for srv in (self.servers or
                    ([self.server] if self.server else [])):
            await srv.stop()  # idempotent: killed gateways are no-ops
        if self.injector is not None:
            await self.injector.stop_network()
        for i, g in enumerate(self.garages):
            if self.injector is not None and i in self.injector.dead:
                continue
            try:
                await g.shutdown()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                logger.exception("node %d shutdown failed", i)

    # --- helpers used by the drills ------------------------------------

    def storage_indices(self) -> List[int]:
        return list(range(1, self.n_storage + 1))

    def zone_names(self) -> List[str]:
        return [f"z{i + 1}" for i in range(self.n_zones)]

    def metrics_value(self, i: int, needle: str) -> bool:
        return needle in self.garages[i].system.metrics.render()

    async def precompute_layout_change(self, mutate) -> bytes:
        """Stage `mutate` on a decoded copy of the current layout, run
        the assignment solve, and return the committed layout encoded
        — WITHOUT delivering it.  The solve is pure CPU and can hold
        the GIL for tens of seconds on a big change; real deployments
        run it on the operator's machine and the cluster only ever
        sees the finished result.  Drills that sample latency across a
        layout change must split the same way: solve while idle, then
        `apply_encoded_layout` instantly — a mid-traffic solve stalls
        every node in this single-process sim, RPC timeouts fire in a
        burst, breakers trip, and the movers' first pushes all fail
        before the measurement even starts."""
        from ..rpc.layout import ClusterLayout

        lay = ClusterLayout.decode(self.garages[0].system.layout.encode())
        mutate(lay)
        await asyncio.to_thread(lay.apply_staged_changes)
        return lay.encode()

    async def apply_encoded_layout(self, enc: bytes) -> None:
        """Deliver an already-solved layout to every live node (the
        CRDT merge path a CLI `layout apply` takes) — broadcast-timing
        independent, so drills never race the gossip."""
        from ..rpc.layout import ClusterLayout

        dead = self.injector.dead if self.injector else set()
        for i, g in enumerate(self.garages):
            if i not in dead:
                await g.system.update_cluster_layout(
                    ClusterLayout.decode(enc))

    async def apply_layout_change(self, mutate) -> None:
        """Stage + solve + deliver in one call, for drills that do not
        sample during the solve."""
        await self.apply_encoded_layout(
            await self.precompute_layout_change(mutate))

    # --- gateway pool helpers (ISSUE 19) --------------------------------

    def gateway_indices(self) -> List[int]:
        return [0] + list(range(self.n_storage + 1,
                                self.n_storage + self.n_gateways))

    def gateway_endpoints(self) -> List:
        """[(name, port), ...] for a GatewayPool client."""
        return [(f"g{p}", self.ports[p]) for p in range(len(self.ports))]

    def apply_wan(self, matrix=None, jitter: float = 0.0) -> None:
        """Stretch the mesh into the 3-zone geography (WAN_3ZONE_RTT by
        default).  Gateways sit in the FIRST zone for WAN purposes:
        their injector zone entry stays None (zone-kill drills must
        never crash them) but their boundary links stretch like any z1
        resident's — matching their layout role's zone."""
        from .faults import WAN_3ZONE_RTT

        zones = list(self.zones)
        for gi in self.gateway_indices():
            zones[gi] = self.zones[1] or "z1"
        self.injector.apply_wan_matrix(
            WAN_3ZONE_RTT if matrix is None else matrix,
            zones=zones, jitter=jitter)

    async def kill_gateway(self, pos: int) -> None:
        """Abrupt gateway death (pool position `pos`): every live HTTP
        connection is aborted mid-byte — clients see resets, exactly
        like a kill -9 — then the listener closes.  The node's Garage
        stays up (it holds no data; the RPC mesh is untouched)."""
        srv = self.servers[pos]
        runner = getattr(srv, "_runner", None)
        if runner is not None and runner.server is not None:
            for proto in list(runner.server.connections):
                tr = getattr(proto, "transport", None)
                if tr is not None:
                    tr.abort()
        await srv.stop()

    async def restart_gateway(self, pos: int) -> int:
        """Bring a killed/drained gateway back on a fresh port; returns
        the new port (callers re-point their GatewayPool member)."""
        from ..api.s3.api_server import S3ApiServer

        g = self.garages[self.gateway_indices()[pos]]
        g.system.drain_state = None
        srv = S3ApiServer(g)
        await srv.start("127.0.0.1:0")
        self.servers[pos] = srv
        self.ports[pos] = srv.port
        if pos == 0:
            self.server, self.port = srv, srv.port
        return srv.port


class TrafficStats:
    def __init__(self):
        self.puts = 0
        self.gets = 0
        self.deletes = 0
        self.errors = 0
        self.error_notes: List[str] = []
        self.lats: List[float] = []

    def note_error(self, what: str) -> None:
        self.errors += 1
        if len(self.error_notes) < 8:
            self.error_notes.append(what)

    def summary(self) -> dict:
        lats = sorted(self.lats)
        out = {
            "puts": self.puts, "gets": self.gets, "deletes": self.deletes,
            "errors": self.errors, "ops": len(lats),
        }
        if self.error_notes:
            out["error_notes"] = list(self.error_notes)
        if lats:
            out["p50_ms"] = round(lats[len(lats) // 2] * 1000, 2)
            out["p99_ms"] = round(
                lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1000, 2)
            out["max_ms"] = round(lats[-1] * 1000, 2)
        return out


class TrafficDriver:
    """Sustained S3 PUT/GET/DELETE load against a SimCluster gateway,
    verifying the chaos-soak invariants inline: every GET of an acked
    object must be bit-identical, deleted objects must stay deleted."""

    def __init__(self, cluster: SimCluster, session, bucket: str = "drill",
                 seed: int = 4242):
        import bench

        self.cluster = cluster
        # honor (clamped) Retry-After on 503s: the drills' sustained
        # traffic is production-shaped, not a shed-hammering loop
        self.s3 = bench._S3(session, cluster.port, cluster.key_id,
                            cluster.secret, honor_retry_after=True,
                            retry_after_cap=0.5)
        self.bucket = bucket
        self.rng = random.Random(seed)
        self.acked: Dict[str, bytes] = {}
        self.deleted: set = set()
        self.stats = TrafficStats()
        self._seq = 0

    async def make_bucket(self) -> None:
        st, _b, _h = await self.s3.req("PUT", f"/{self.bucket}")
        assert st == 200, f"bucket create failed: {st}"

    def _body(self) -> bytes:
        n = self.rng.randrange(4 << 10, 128 << 10)
        # cheap deterministic filler (numpy-free: the drills run with
        # dozens of nodes on one core — keep the client light)
        seed = self.rng.randrange(256)
        return bytes((seed + i) & 0xFF for i in range(0, n, 7)) * 7

    async def step(self, tag: str = "t") -> None:
        """One traffic step: PUT a fresh object, GET-verify a random
        acked one, occasionally DELETE (and verify 404 stays 404)."""
        self._seq += 1
        name = f"{tag}-{self._seq:05d}"
        body = self._body()
        t0 = time.perf_counter()
        try:
            st, _b, _h = await self.s3.req(
                "PUT", f"/{self.bucket}/{name}", body)
        except Exception as e:  # noqa: BLE001 — client sees a failure
            self.stats.note_error(f"PUT {name}: {e!r}")
            st = 0
        self.stats.lats.append(time.perf_counter() - t0)
        if st == 200:
            self.acked[name] = body
            self.stats.puts += 1
        elif st:
            self.stats.note_error(f"PUT {name}: HTTP {st}")
        if self.acked:
            probe = self.rng.choice(sorted(self.acked))
            t0 = time.perf_counter()
            try:
                st, got, _h = await self.s3.req(
                    "GET", f"/{self.bucket}/{probe}")
            except Exception as e:  # noqa: BLE001
                self.stats.note_error(f"GET {probe}: {e!r}")
                st, got = 0, b""
            self.stats.lats.append(time.perf_counter() - t0)
            if st == 200 and got == self.acked[probe]:
                self.stats.gets += 1
            elif st:
                self.stats.note_error(
                    f"GET {probe}: HTTP {st} "
                    f"({'bad body' if st == 200 else 'error'})")
        if self.deleted and self.rng.random() < 0.2:
            probe = self.rng.choice(sorted(self.deleted))
            st, _b, _h = await self.s3.req("GET", f"/{self.bucket}/{probe}")
            if st != 404:
                self.stats.note_error(
                    f"GET deleted {probe}: HTTP {st} (expected 404)")
        if len(self.acked) > 4 and self.rng.random() < 0.1:
            victim = self.rng.choice(sorted(self.acked))
            st, _b, _h = await self.s3.req(
                "DELETE", f"/{self.bucket}/{victim}")
            if st in (200, 204):
                del self.acked[victim]
                self.deleted.add(victim)
                self.stats.deletes += 1
            else:
                self.stats.note_error(f"DELETE {victim}: HTTP {st}")

    async def run_for(self, secs: float, tag: str,
                      tick_every: int = 5) -> None:
        deadline = time.monotonic() + secs
        i = 0
        while time.monotonic() < deadline:
            i += 1
            await self.step(tag)
            if i % tick_every == 0:
                await self.cluster.tick(rounds=1)

    async def verify_all(self) -> int:
        """Read back EVERY acked object; returns mismatches (also
        counted into stats.errors)."""
        bad = 0
        for name, body in sorted(self.acked.items()):
            st, got, _h = await self.s3.req("GET", f"/{self.bucket}/{name}")
            if st != 200 or got != body:
                bad += 1
                self.stats.note_error(f"verify {name}: HTTP {st}")
        for name in sorted(self.deleted):
            st, _b, _h = await self.s3.req("GET", f"/{self.bucket}/{name}")
            if st != 404:
                bad += 1
                self.stats.note_error(
                    f"verify deleted {name}: HTTP {st} (expected 404)")
        return bad


# --- the three cluster-scale drills -----------------------------------


async def zone_blackhole_drill(cluster: SimCluster, traffic: TrafficDriver,
                               secs: float, zone: str = "z2") -> dict:
    """One full zone dark: traffic must see ZERO errors (replication
    spans zones by placement; reads fall back across the boundary), the
    gateway must order local-zone read candidates first, and the
    boundary breakers must open during the fault and close after heal +
    reconnect."""
    inj = cluster.injector
    g0 = cluster.garages[0]
    out: dict = {"zone": zone}

    # zone-aware routing is live on the gateway: for a partition with a
    # local-zone replica, that replica orders before every cross-zone one
    lz = g0.system.our_zone()
    zone_first = checked = 0
    for p in range(0, 256, 7):
        nodes = g0.system.ring.partition_nodes(p)
        order = g0.system.rpc.request_order(nodes)
        zs = [g0.system.zone_of(nx) for nx in order]
        if lz in zs:
            checked += 1
            if zs[0] == lz:
                zone_first += 1
    out["local_zone_first"] = f"{zone_first}/{checked}"
    assert checked == 0 or zone_first == checked, out

    inj.blackhole_zone(zone)
    await traffic.run_for(secs, f"bh-{zone}")
    # the dark zone must be visible in the gateway's breakers: at least
    # one zone member's breaker left "closed" while the zone was dark.
    # The evidence can trail the traffic window by a full ping/handshake
    # timeout cycle (~10 s — a blackholed peer fails SLOWLY by nature),
    # so wait for the verdict bounded, with the zone still dark.
    dark = [cluster.garages[i].system.id for i in inj.nodes_in_zone(zone)]
    wait_by = time.monotonic() + 15.0
    while (all(g0.system.peering.breaker_state(nid) == "closed"
               for nid in dark) and time.monotonic() < wait_by):
        await cluster.tick(rounds=1)
        await asyncio.sleep(0.3)
    states = [g0.system.peering.breaker_state(nid) for nid in dark]
    out["breaker_states_during"] = sorted(set(states))
    out["breaker_opened"] = any(s != "closed" for s in states)

    inj.heal_zone(zone)
    await inj.reconnect(rounds=8)
    open_secs = cluster.rpc_cfg.get("breaker_open_secs", 1.0)
    await asyncio.sleep(open_secs + 0.2)
    await traffic.run_for(max(secs / 2, 1.0), f"heal-{zone}")
    await cluster.tick()
    states = [g0.system.peering.breaker_state(nid) for nid in dark]
    out["breaker_states_after"] = sorted(set(states))
    out.update(traffic.stats.summary())
    return out


async def zone_drain_drill(cluster: SimCluster, traffic: TrafficDriver,
                           secs: float, zone: str = "z3",
                           settle_secs: float = 30.0) -> dict:
    """Drain a whole zone via a layout change while clients keep
    writing: the remaining zones must absorb the drained partitions
    (rebalance mover: partitions done == total on every node), and every
    object acked before OR during the drain must read back bit-identical
    afterwards — including after the drained nodes are gone dark."""
    from ..rpc.layout import NodeRole

    inj = cluster.injector
    drained = inj.nodes_in_zone(zone)
    out: dict = {"zone": zone, "drained_nodes": len(drained)}

    # seed some pre-drain data
    await traffic.run_for(max(secs / 2, 1.0), "pre-drain")

    async def change():
        def mutate(lay):
            for i in drained:
                lay.stage_role(
                    bytes(cluster.garages[i].system.id), None)
            # zone count shrinks: "maximum" recomputes, an int must
            # still fit — callers pick a legal zone_redundancy
        await cluster.apply_layout_change(mutate)

    # drain concurrently with live writes
    load = asyncio.ensure_future(traffic.run_for(secs, "during-drain"))
    await change()
    await load

    # wait until every live node's mover finished its run
    deadline = time.monotonic() + settle_secs
    movers = [g.rebalance_mover
              for i, g in enumerate(cluster.garages) if i not in inj.dead]
    while time.monotonic() < deadline:
        busy = [m for m in movers if not m.idle()]
        if not busy:
            break
        await traffic.step("drain-settle")
        await asyncio.sleep(0.1)
    out["rebalance"] = [
        {"done": m.partitions_done, "total": m.partitions_total,
         "bytes": m.bytes_moved}
        for m in movers if m.partitions_total
    ]
    out["rebalance_complete"] = all(
        m.idle() and m.partitions_done == m.partitions_total
        for m in movers)
    # give the confirm-before-drop offloads a moment to finish their
    # resync pushes, then take the drained zone completely dark and
    # verify every acked object still reads bit-identical
    for _ in range(10):
        if all(cluster.garages[i].block_resync.queue_len() == 0
               for i in range(len(cluster.garages)) if i not in inj.dead):
            break
        await asyncio.sleep(0.3)
    out["drained_metric_seen"] = cluster.metrics_value(
        1, "rebalance_partitions_done")
    inj.partition_zone(zone)
    bad = await traffic.verify_all()
    out["verify_mismatches_zone_dark"] = bad
    inj.heal_zone(zone)
    out.update(traffic.stats.summary())
    return out


async def node_rebuild_drill(cluster: SimCluster, traffic: TrafficDriver,
                             secs: float,
                             settle_secs: float = 90.0,
                             seed_objects: int = 24) -> dict:
    """ISSUE-20 acceptance drill: FULL storage-node loss.  Crash the
    heaviest storage node and drop it from the committed layout while
    clients keep reading and writing.  Proves:

      - the storm stays client-invisible (zero errors; degraded reads
        decode through the repair planner — GET p99 reported),
      - every new owner's fleet rebuild scheduler walks its lost
        partitions to done == total, paced under the governor
        (paced_sleeps > 0 shows the throttle engaged, never a free-run),
      - zero acked-data loss: every object acked before or during the
        storm reads back bit-identical after the rebuild settles,
      - repair ingress is partial-product attributed ("tree"/"ppr"
        modes in repair_fetch_bytes), not whole-block over-fetch."""
    inj = cluster.injector
    out: dict = {}

    # seed a FIXED object count, so the victim holds data worth
    # rebuilding regardless of host speed (a wall-clock window on a
    # slow/oversubscribed host seeds a couple of objects and the
    # schedulers legitimately find nothing to heal)
    for _ in range(seed_objects):
        await traffic.step("pre-loss")
    for g in cluster.garages:
        if g.block_manager.ec_accumulator is not None:
            await g.block_manager.ec_accumulator.drain()
    gateways = set(cluster.gateway_indices())
    sizes = []
    for i in cluster.storage_indices():
        if i in inj.dead or i in gateways:
            continue
        n = sum(os.path.getsize(p) for p in inj._block_files(i))
        sizes.append((n, i))
    lost_bytes, victim = max(sizes)
    victim_id = bytes(cluster.garages[victim].system.id)
    out["victim"], out["lost_bytes"] = victim, lost_bytes

    # solve the post-loss layout while idle (see precompute_layout_change
    # for why a mid-traffic solve would poison the latency sample)
    enc = await cluster.precompute_layout_change(
        lambda lay: lay.stage_role(victim_id, None))
    await inj.crash(victim)
    # storm: live traffic THROUGH the loss, the layout drop, and the
    # rebuild ramp-up — the ring change fires every survivor's
    # _feed_rebuild hook, so schedulers start under this load
    load = asyncio.ensure_future(traffic.run_for(secs, "rebuild-storm"))
    await cluster.apply_encoded_layout(enc)
    await load

    # settle: every live storage node's rebuild scheduler finishes its run
    live = [g for i, g in enumerate(cluster.garages)
            if i not in inj.dead and i not in gateways]
    scheds = [g.rebuild_scheduler for g in live]
    deadline = time.monotonic() + settle_secs
    stable_since = None
    while time.monotonic() < deadline:
        if all(s.idle() for s in scheds):
            # idle must HOLD: table sync still delivering migrated refs
            # re-arms a walk (note_ref), flipping idle back off
            if stable_since is None:
                stable_since = time.monotonic()
            elif time.monotonic() - stable_since >= 5.0:
                break
        else:
            stable_since = None
        await traffic.step("rebuild-settle")
        await asyncio.sleep(0.1)
    episodes = [s for s in scheds if s.partitions_total]
    out["rebuild"] = [
        {"done": s.partitions_done, "total": s.partitions_total,
         "blocks": s.blocks_healed, "bytes": s.bytes_healed,
         "paced": s.paced_sleeps, "rearms": s.rearms}
        for s in episodes]
    out["rebuild_complete"] = bool(episodes) and all(
        s.idle() and s.partitions_done == s.partitions_total
        for s in episodes)
    out["blocks_healed"] = sum(s.blocks_healed for s in episodes)
    out["paced_sleeps"] = sum(s.paced_sleeps for s in episodes)
    out["rearms"] = sum(s.rearms for s in episodes)
    # parked stragglers flow scheduler → resync (source="rebuild");
    # give that handoff a bounded moment to drain
    for _ in range(20):
        if all(g.block_resync.queue_len() == 0 for g in live):
            break
        await asyncio.sleep(0.3)
    out["resync_rebuild_skips"] = sum(
        g.block_resync.rebuild_skips for g in live)
    fetch: Dict[str, int] = {}
    for g in live:
        for mode, nbytes in g.block_manager.repair_fetch_bytes.items():
            fetch[mode] = fetch.get(mode, 0) + int(nbytes)
    out["repair_fetch_bytes"] = fetch
    out["verify_mismatches"] = await traffic.verify_all()
    out.update(traffic.stats.summary())
    return out


async def overload_drill(cluster: SimCluster, session, secs: float,
                         bucket: str = "drill-overload") -> dict:
    """The ISSUE-10 acceptance drill: drive the gateway 4× past its
    admission capacity and prove defined past-saturation behavior —

      - every rejected request is a TYPED 503 (S3 XML Code SlowDown or
        DeadlineExceeded, Retry-After present); no hangs, no untyped 500s
      - admitted-request p99 at 4× offered load stays within 3× the
        1×-offered (at-capacity) p99: admission keeps the in-service
        concurrency constant no matter the offered load
      - background_throttle_ratio observably drops while the gate is hot
        and recovers to ~1 afterwards (background bytes/s ceding)
      - zero acked-data loss: every 200-acked PUT reads back bit-identical

    The cluster must be built with a small ``[api] max_inflight`` (via
    SimCluster extra_cfg) so "4× capacity" is reachable from one client
    process."""
    import xml.etree.ElementTree as ET

    import bench

    g0 = cluster.garages[0]
    gate = g0.admission
    cap = max(gate.tun.max_inflight, 1)
    s3 = bench._S3(session, cluster.port, cluster.key_id, cluster.secret)
    st, _b, _h = await s3.req("PUT", f"/{bucket}")
    assert st == 200, f"bucket create: {st}"
    out: dict = {"capacity": cap, "errors": 0, "error_notes": []}
    acked: Dict[str, bytes] = {}
    seq = [0]

    def body_for(i: int) -> bytes:
        seed = (i * 131) & 0xFF
        return bytes(((seed + j) & 0xFF for j in range(4096))) * 8

    async def one_op(tag: str, lats, shed, i: int) -> str:
        name = f"{tag}-{i:06d}"
        body = body_for(i)
        t0 = time.monotonic()
        try:
            st, rb, hdrs = await asyncio.wait_for(
                s3.req("PUT", f"/{bucket}/{name}", body), 30.0)
        except asyncio.TimeoutError:
            out["errors"] += 1
            out["error_notes"].append(f"PUT {name}: HANG (client timeout)")
            return "error"
        except Exception as e:  # noqa: BLE001
            out["errors"] += 1
            out["error_notes"].append(f"PUT {name}: {e!r}")
            return "error"
        took = time.monotonic() - t0
        if st == 200:
            lats.append(took)
            acked[name] = body
        elif st == 503:
            # typed shed: the XML Code must be one of the two defined
            # overload answers and Retry-After must ride the response
            try:
                code = ET.fromstring(rb).findtext("Code")
                rid = ET.fromstring(rb).findtext("RequestId")
            except ET.ParseError:
                code = rid = None
            if code not in ("SlowDown", "DeadlineExceeded"):
                out["errors"] += 1
                out["error_notes"].append(f"PUT {name}: 503 code={code!r}")
                return "error"
            if "Retry-After" not in hdrs or not rid:
                out["errors"] += 1
                out["error_notes"].append(
                    f"PUT {name}: 503 missing Retry-After/RequestId")
                return "error"
            shed.append(name)
            return "shed"
        else:
            out["errors"] += 1
            out["error_notes"].append(f"PUT {name}: HTTP {st} (untyped)")
            return "error"
        return "ok"

    async def drive(concurrency: int, run_secs: float, tag: str,
                    lats: list, shed: list, ratio_min: list) -> None:
        deadline = time.monotonic() + run_secs

        async def worker() -> None:
            while time.monotonic() < deadline:
                seq[0] += 1
                verdict = await one_op(tag, lats, shed, seq[0])
                ratio_min[0] = min(ratio_min[0], g0.governor.ratio())
                if verdict == "shed":
                    # a minimally-behaved client pauses after a 503
                    # (far below the Retry-After hint): offered load
                    # stays 4× capacity, but the in-process client's
                    # closed-loop shed spin must not starve the server
                    # core and masquerade as admitted-latency inflation
                    await asyncio.sleep(0.02)

        await asyncio.gather(*[worker() for _ in range(concurrency)])

    # 1× offered = at capacity, no shedding expected — the honest
    # baseline for "what does an ADMITTED request cost"
    base_lats: list = []
    base_shed: list = []
    rmin = [1.0]
    await drive(cap, max(secs / 2, 2.0), "base", base_lats, base_shed, rmin)
    out["baseline_p99_ms"] = round(p99(base_lats) * 1000, 2)
    out["baseline_ops"] = len(base_lats)

    # 4× offered: the gate must shed the excess typed while admitted
    # work stays fast and the governor parks background load
    over_lats: list = []
    over_shed: list = []
    rmin = [g0.governor.ratio()]
    await drive(4 * cap, secs, "over", over_lats, over_shed, rmin)
    out["overload_p99_ms"] = round(p99(over_lats) * 1000, 2)
    out["overload_ops"] = len(over_lats)
    out["shed"] = len(over_shed) + len(base_shed)
    out["shed_rate"] = round(
        len(over_shed) / max(len(over_lats) + len(over_shed), 1), 3)
    out["throttle_ratio_min"] = round(rmin[0], 3)
    out["throttle_dropped"] = rmin[0] < 0.9
    out["p99_within_3x"] = (
        out["overload_p99_ms"] <= 3 * max(out["baseline_p99_ms"], 1.0))
    out["sheds_observed"] = len(over_shed) > 0
    out["admission_metric_seen"] = cluster.metrics_value(
        0, "api_admission_total")
    out["throttle_metric_seen"] = cluster.metrics_value(
        0, "background_throttle_ratio")

    # recovery: pressure gone → background rate restored
    recover_by = time.monotonic() + 30.0
    ratio = g0.governor.ratio()
    while ratio < 0.9 and time.monotonic() < recover_by:
        await asyncio.sleep(0.25)
        ratio = g0.governor.ratio()
    out["throttle_ratio_after"] = round(ratio, 3)
    out["throttle_recovered"] = ratio >= 0.9

    # zero acked-data loss, bit-identical
    bad = 0
    for name, body in sorted(acked.items()):
        st, got, _h = await s3.req("GET", f"/{bucket}/{name}")
        if st != 200 or got != body:
            bad += 1
            out["error_notes"].append(f"verify {name}: HTTP {st}")
    out["verify_mismatches"] = bad
    out["acked"] = len(acked)
    out["error_notes"] = out["error_notes"][:8]
    if not out["error_notes"]:
        del out["error_notes"]
    return out


async def noisy_neighbor_drill(cluster: SimCluster, session, secs: float,
                               n_well: int = 4,
                               hot_pressure: float = 2.0) -> dict:
    """The ISSUE-12 acceptance drill: one abusive tenant saturates the
    gateway while well-behaved tenants keep a gentle pace — the WDRR
    admission gate must isolate the abuse:

      - ZERO client errors (untyped or shed) for well-behaved tenants;
        their p99 holds within a small multiple of the no-abuser
        baseline measured first
      - the abuser's excess is shed TYPED (503, S3 XML Code SlowDown,
        Retry-After, RequestId), per-tenant, never gate-wide
      - cluster-aware admission: with a storage node's gossiped
        governor_pressure pinned hot, a request whose bucket lives on
        that node is shed `remote_pressure` at the gateway while the
        gateway's own gate is UNDER its watermark — and admitted again
        once the pressure heals
      - the new api_tenant_* / admission metric families render and
        pass the strict exposition lint

    The cluster must be built with a small ``[api] max_inflight`` (via
    SimCluster extra_cfg) so saturation is reachable from one client."""
    import xml.etree.ElementTree as ET

    g0 = cluster.garages[0]
    gate = g0.admission
    cap = max(gate.tun.max_inflight, 1)
    out: dict = {"capacity": cap, "errors": 0, "error_notes": [],
                 "well_tenants": n_well}

    well = [await make_tenant_client(g0, session, cluster.port,
                                     f"well{i}", f"nb-well{i}")
            for i in range(n_well)]
    abuser = await make_tenant_client(g0, session, cluster.port,
                                      "abuser", "nb-abuser")

    def body_for(i: int, size: int) -> bytes:
        seed = (i * 37) & 0xFF
        return bytes(((seed + j) & 0xFF for j in range(256))) * (size // 256)

    acked: Dict[str, tuple] = {}

    async def well_loop(idx: int, s3, lats: list, sheds: list,
                        deadline: float) -> None:
        i = 0
        while time.monotonic() < deadline:
            i += 1
            name, body = f"w{idx}-{i:05d}", body_for(i, 8 << 10)
            t0 = time.monotonic()
            try:
                st, _b, _h = await asyncio.wait_for(
                    s3.req("PUT", f"/nb-well{idx}/{name}", body), 30.0)
            except Exception as e:  # noqa: BLE001
                out["errors"] += 1
                out["error_notes"].append(f"well{idx} PUT {name}: {e!r}")
                continue
            lats.append(time.monotonic() - t0)
            if st == 200:
                acked[f"well{idx}/{name}"] = (s3, f"/nb-well{idx}/{name}",
                                              body)
            elif st == 503:
                sheds.append(name)     # acceptance: must stay EMPTY
            else:
                out["errors"] += 1
                out["error_notes"].append(f"well{idx} PUT {name}: HTTP {st}")
            await asyncio.sleep(0.005)  # gentle, well under fair share

    async def abuse_loop(conc: int, shed: list, deadline: float) -> None:
        seq = [0]

        async def worker() -> None:
            while time.monotonic() < deadline:
                seq[0] += 1
                name = f"a-{seq[0]:06d}"
                try:
                    st, rb, hdrs = await asyncio.wait_for(
                        abuser.req("PUT", f"/nb-abuser/{name}",
                                   body_for(seq[0], 16 << 10)), 30.0)
                except Exception as e:  # noqa: BLE001
                    out["errors"] += 1
                    out["error_notes"].append(f"abuser PUT {name}: {e!r}")
                    continue
                if st == 503:
                    bad = check_typed_shed(rb, hdrs)
                    if bad is not None:
                        out["errors"] += 1
                        out["error_notes"].append(
                            f"abuser {name}: untyped {bad}")
                    else:
                        shed.append(name)
                    # minimally-behaved backoff (well below the
                    # Retry-After hint): offered load stays saturating
                    # but the in-process client's closed-loop shed spin
                    # must not burn the single shared core and read as
                    # well-tenant latency
                    await asyncio.sleep(0.02)
                elif st != 200:
                    out["errors"] += 1
                    out["error_notes"].append(f"abuser {name}: HTTP {st}")

        await asyncio.gather(*[worker() for _ in range(conc)])

    # --- phase 1: no abuser — the honest baseline ---
    base_lats: list = []
    base_sheds: list = []
    deadline = time.monotonic() + max(secs / 2, 2.0)
    await asyncio.gather(*[
        well_loop(i, s3, base_lats, base_sheds, deadline)
        for i, s3 in enumerate(well)])
    out["well_p99_base_ms"] = round(p99(base_lats) * 1000, 2)
    out["well_ops_base"] = len(base_lats)

    # --- phase 2: the abuser saturates (>= 4x its fair share offered) ---
    abuse_lats: list = []
    abuse_sheds_well: list = []
    abuser_shed: list = []
    deadline = time.monotonic() + secs
    await asyncio.gather(
        abuse_loop(2 * cap, abuser_shed, deadline),
        *[well_loop(i, s3, abuse_lats, abuse_sheds_well, deadline)
          for i, s3 in enumerate(well)])
    out["well_p99_abuse_ms"] = round(p99(abuse_lats) * 1000, 2)
    out["well_ops_abuse"] = len(abuse_lats)
    out["well_sheds"] = len(base_sheds) + len(abuse_sheds_well)
    out["abuser_sheds"] = len(abuser_shed)
    out["abuser_shed_typed"] = len(abuser_shed) > 0
    # informational here: everything (clients + 4 server nodes) shares
    # one core, so admitted-abuser CPU inflates this ratio with noise
    # fairness can't remove; the Zipf BENCH phase owns the hard 2x bound
    out["well_p99_ratio"] = round(
        out["well_p99_abuse_ms"] / max(out["well_p99_base_ms"], 1.0), 2)
    out["tenant_stats"] = gate.tenant_stats()

    # --- phase 3: cluster-aware admission (remote_pressure shed) ---
    # pin a storage node that hosts well0's bucket hot, gossip it, and
    # prove the gateway sheds on its behalf while locally idle
    probe = g0.admission_probe
    bid = probe._ids.get("nb-well0")
    assert bid is not None, "probe never learned the bucket placement"
    nodes = g0.system.ring.get_nodes(
        bid, g0.system.replication_mode.replication_factor)
    victim_idx = next(
        i for i, g in enumerate(cluster.garages)
        if any(bytes(g.system.id) == bytes(n) for n in nodes) and i != 0)
    victim = cluster.garages[victim_idx]
    victim.governor.add_signal("noisy_drill", lambda: hot_pressure)
    await victim.system.advertise_status()
    before = gate.m_admission.get(verdict="remote_pressure")
    out["gateway_inflight_at_probe"] = gate.inflight
    st, rb, hdrs = await well[0].req(
        "PUT", "/nb-well0/remote-probe", body_for(1, 4 << 10))
    out["remote_pressure_status"] = st
    out["remote_pressure_sheds"] = (
        gate.m_admission.get(verdict="remote_pressure") - before)
    out["remote_shed_observed"] = (
        st == 503 and out["remote_pressure_sheds"] >= 1
        and gate.inflight < gate.limit)
    if st == 503:
        try:
            out["remote_pressure_code"] = ET.fromstring(rb).findtext("Code")
        except ET.ParseError:
            out["remote_pressure_code"] = None
    # heal: pressure gone -> admitted again
    victim.governor.remove_signal("noisy_drill")
    await victim.system.advertise_status()
    st, _b, _h = await well[0].req(
        "PUT", "/nb-well0/remote-heal", body_for(2, 4 << 10))
    out["admitted_after_heal"] = st == 200

    # --- the new families render and pass the strict lint ---
    from ..utils.promlint import lint_exposition

    body = g0.system.metrics.render()
    missing = [fam for fam in (
        "api_admission_total", "api_admission_limit",
        "api_admission_queue_depth", "api_admission_queue_wait_seconds",
        "api_tenant_inflight", "api_tenant_shed_total",
        "api_longpoll_parked", "cluster_peer_pressure",
    ) if fam not in body]
    out["metric_families_missing"] = missing
    out["promlint_errors"] = lint_exposition(body)[:4]

    # zero acked-data loss, bit-identical
    bad = 0
    for _k, (s3, path, bodyb) in sorted(acked.items()):
        st, got, _h = await s3.req("GET", path)
        if st != 200 or got != bodyb:
            bad += 1
    out["verify_mismatches"] = bad
    out["acked"] = len(acked)
    out["error_notes"] = out["error_notes"][:8]
    if not out["error_notes"]:
        del out["error_notes"]
    return out


async def compound_drill(cluster: SimCluster, traffic: TrafficDriver,
                         secs: float, zone: str = "z2",
                         disk_prob: float = 0.25) -> dict:
    """Compound failure from ROADMAP's scenario list: one whole zone
    blackholed AND a flaky disk (probabilistic read EIO) on a node in a
    surviving zone, at the same time, under live PUT/GET/DELETE traffic.
    Asserts zero client-visible errors through the compound fault (reads
    fail over across both the dark zone and the dying disk; writes stay
    clean — the disk fault is read-side so write quorums are untouched)
    and full recovery after heal: boundary breakers closed, disk errors
    stopped, every acked object bit-identical."""
    import errno as _errno

    inj = cluster.injector
    g0 = cluster.garages[0]
    out: dict = {"zone": zone}

    # flaky READ disk on a storage node OUTSIDE the blackholed zone: the
    # compound must be survivable by construction (replication still has
    # one clean replica per partition), the point is that BOTH degraded
    # paths run concurrently
    victim = next(i for i in cluster.storage_indices()
                  if cluster.zones[i] != zone)
    out["disk_victim"] = victim
    fd = inj.add_disk_faults(victim)
    fd.read_errno = _errno.EIO
    fd.read_error_prob = disk_prob

    inj.blackhole_zone(zone)
    await traffic.run_for(secs, f"compound-{zone}")
    # the drill must PROVE the disk fault was exercised, not just armed:
    # replica placement decides which surviving node serves each probe
    # (and step-traffic slows under the dark zone), so sweep GETs over
    # every acked object — deterministically touching every surviving
    # replica — until the victim's disk has actually thrown.  The read
    # errors stay client-invisible: the failover ladder serves from
    # another replica, which is exactly what the sweep asserts.
    extra_by = time.monotonic() + max(2 * secs, 10.0)
    while fd.injected["read"] == 0 and time.monotonic() < extra_by:
        for name in sorted(traffic.acked):
            st, got, _h = await traffic.s3.req(
                "GET", f"/{traffic.bucket}/{name}")
            if st != 200 or got != traffic.acked[name]:
                traffic.stats.note_error(
                    f"compound sweep GET {name}: HTTP {st}")
            else:
                traffic.stats.gets += 1
            if fd.injected["read"]:
                break
        if not traffic.acked:
            break

    dark = [cluster.garages[i].system.id for i in inj.nodes_in_zone(zone)]
    out["breaker_opened"] = any(
        g0.system.peering.breaker_state(nid) != "closed" for nid in dark)
    mgr = cluster.garages[victim].block_manager
    out["disk_errors_injected"] = fd.injected["read"] > 0

    # heal both faults, then prove recovery under fresh traffic
    inj.heal_disk(victim)
    inj.heal_zone(zone)
    await inj.reconnect(rounds=8)
    open_secs = cluster.rpc_cfg.get("breaker_open_secs", 1.0)
    await asyncio.sleep(open_secs + 0.2)
    await traffic.run_for(max(secs / 2, 1.0), f"heal-{zone}")
    await cluster.tick()
    out["breaker_states_after"] = sorted({
        g0.system.peering.breaker_state(nid) for nid in dark})
    out["disk_state_after"] = mgr.health.worst_state()
    out["verify_mismatches"] = await traffic.verify_all()
    out.update(traffic.stats.summary())
    return out


async def rolling_restart_drill(cluster: SimCluster,
                                traffic: TrafficDriver, secs: float,
                                new_version: str = "0.9.1-next") -> dict:
    """Rolling upgrade: one zone at a time, crash every node of the
    zone, bump its version tag, revive, wait for the mesh to converge —
    all under live traffic with zero client-visible errors.  Mid-roll,
    the gateway must see BOTH versions in its handshake-learned
    peer_versions (the mixed-version regime the wire format must
    survive)."""
    inj = cluster.injector
    g0 = cluster.garages[0]
    out: dict = {"zones": [], "mixed_versions_seen": False,
                 "new_version": new_version}
    per_zone = max(secs / max(cluster.n_zones, 1), 1.0)
    for zone in cluster.zone_names():
        members = inj.nodes_in_zone(zone)
        load = asyncio.ensure_future(
            traffic.run_for(per_zone, f"roll-{zone}"))
        for i in members:
            inj.configs[i].node_version = new_version
        await inj.kill_zone(zone)
        await asyncio.sleep(0.3)
        await inj.revive_zone(zone, wait_secs=15.0)
        await load
        await cluster.tick()
        vs = {v for v in g0.system.netapp.peer_versions.values() if v}
        if len(vs) > 1:
            out["mixed_versions_seen"] = True
        out["zones"].append({"zone": zone, "restarted": len(members),
                             "versions_seen": sorted(vs)})
    bad = await traffic.verify_all()
    out["verify_mismatches"] = bad
    out.update(traffic.stats.summary())
    return out


async def wan_drill(cluster: SimCluster, session, secs: float,
                    bucket: str = "wan-drill") -> dict:
    """The ISSUE-19 geo-WAN acceptance drill, on a 6-node/3-zone
    cluster with the WAN_3ZONE_RTT matrix applied:

      - local-zone-first GETs hold: gateway (a z1 resident) serves
        GET p50 near the LOCAL quorum cost (z1@0 + z2@20ms), nowhere
        near the cross-country z3 RTT
      - fail-slow scoring does NOT flag healthy-but-distant zones (the
        zone-aware baseline: a z3 peer is judged against z3 siblings,
        not against loopback neighbors) — and a GENUINELY slow peer
        still flags through the same scorer
      - cross-zone reads pay exactly the matrix: with the gateway cut
        off from z1 storage, GET quorum needs z2+z3 → p50 ≥ ~z1z3 RTT
        and ≥ 3× the local p50; write re-quorums pay the same toll

    Bodies are 2 KiB (< INLINE_THRESHOLD) so a GET is a pure metadata
    quorum read — latency IS the RPC geography, no streaming noise."""
    import bench

    inj = cluster.injector
    g0 = cluster.garages[0]
    out: dict = {"errors": 0, "error_notes": [],
                 "matrix_ms": {f"{a}-{b}": rtt * 1000 for (a, b), rtt
                               in (inj.wan_matrix or {}).items()}}

    cluster.apply_wan()
    out["matrix_ms"] = {f"{a}-{b}": rtt * 1000
                        for (a, b), rtt in inj.wan_matrix.items()}
    # prime the RTT EWMAs under WAN delays (adaptive timeouts must
    # learn the new geography before anything is measured against it)
    await cluster.tick(rounds=3)

    s3 = bench._S3(session, cluster.port, cluster.key_id, cluster.secret)
    st, _b, _h = await s3.req("PUT", f"/{bucket}")
    assert st == 200, f"bucket create: {st}"

    def body_for(i: int) -> bytes:
        return bytes(((i * 53 + j) & 0xFF) for j in range(256)) * 8  # 2 KiB

    # --- phase 1: local-zone traffic under the WAN matrix ---
    n_ops = max(8, min(16, int(4 * secs)))
    put_lats, get_lats = [], []
    acked: Dict[str, bytes] = {}
    for i in range(n_ops):
        name, body = f"wan-{i:04d}", body_for(i)
        t0 = time.perf_counter()
        st, _b, _h = await s3.req("PUT", f"/{bucket}/{name}", body)
        put_lats.append(time.perf_counter() - t0)
        if st != 200:
            out["errors"] += 1
            out["error_notes"].append(f"PUT {name}: HTTP {st}")
            continue
        acked[name] = body
        t0 = time.perf_counter()
        st, got, _h = await s3.req("GET", f"/{bucket}/{name}")
        get_lats.append(time.perf_counter() - t0)
        if st != 200 or got != body:
            out["errors"] += 1
            out["error_notes"].append(f"GET {name}: HTTP {st}")
    local_rtt = min(v for (a, b), v in inj.wan_matrix.items()
                    if "z1" in (a, b))
    local_p50 = sorted(get_lats)[len(get_lats) // 2]
    out["local_get_p50_ms"] = round(local_p50 * 1000, 2)
    out["local_put_p50_ms"] = round(
        sorted(put_lats)[len(put_lats) // 2] * 1000, 2)
    # local quorum = z1 (free) + metro z2: the GET must cost ~one metro
    # RTT per metadata read, generous slack for the in-process sim
    out["local_p50_ok"] = local_p50 <= local_rtt + 0.075

    # --- phase 2: healthy-but-distant zones must NOT read fail-slow ---
    # feed the scorers (peering pings pay the WAN tolls now), spanning
    # more than the sustained-flag window
    for _ in range(6):
        await cluster.tick(rounds=1)
        await asyncio.sleep(0.12)
    flagged = []
    scored_peers = 0
    for i, g in enumerate(cluster.garages):
        if inj and i in inj.dead:
            continue
        sc = g.system.health_scorer.scores()
        scored_peers += len(sc)
        flagged += [f"node{i}->{p}" for p, v in sc.items()
                    if v["fail_slow"]]
    out["wan_false_positives"] = flagged[:8]
    out["wan_scored_peers"] = scored_peers
    out["no_wan_false_positives"] = scored_peers > 0 and not flagged

    # ...and a GENUINELY slow peer (in the far zone, judged against its
    # own sibling) must still flag through the very same scorer
    victim = inj.nodes_in_zone("z3")[0]
    victim_hex = bytes(cluster.garages[victim].system.id).hex()[:16]
    inj.slow_peer(victim, 0.35)
    flag_by = time.monotonic() + 12.0
    genuine = False
    while not genuine and time.monotonic() < flag_by:
        await cluster.tick(rounds=1)
        await asyncio.sleep(0.1)
        for i, g in enumerate(cluster.garages):
            if i == victim:
                continue
            v = g.system.health_scorer.scores().get(victim_hex)
            if v is not None and v["fail_slow"]:
                genuine = True
                break
    out["genuine_slow_flagged"] = genuine
    # slow_peer overwrote the victim's WAN delays too: rebuild the
    # geography from scratch rather than guessing what it clobbered
    inj.clear_wan_matrix()
    cluster.apply_wan()

    # --- phase 3: cross-zone reads + write re-quorum pay the matrix ---
    # cut the gateway off from its OWN zone's storage (gateway-only
    # partition: the storage mesh keeps its full quorums) so every
    # metadata read must assemble quorum from z2 (metro) + z3 (far)
    z1_members = inj.nodes_in_zone("z1")
    for i in z1_members:
        inj.partition(0, i)
    for _ in range(3):  # open the gateway's z1 breakers (fail fast)
        await cluster.tick(rounds=1)
    for name in list(acked)[:2]:  # warm: absorb breaker-opening costs
        await s3.req("GET", f"/{bucket}/{name}")
    cross_get, cross_put = [], []
    probe_names = sorted(acked)[:8]
    for name in probe_names:
        t0 = time.perf_counter()
        st, got, _h = await s3.req("GET", f"/{bucket}/{name}")
        cross_get.append(time.perf_counter() - t0)
        if st != 200 or got != acked[name]:
            out["errors"] += 1
            out["error_notes"].append(f"cross GET {name}: HTTP {st}")
    for i in range(6):
        name, body = f"requorum-{i:03d}", body_for(100 + i)
        t0 = time.perf_counter()
        st, _b, _h = await s3.req("PUT", f"/{bucket}/{name}", body)
        cross_put.append(time.perf_counter() - t0)
        if st == 200:
            acked[name] = body
        else:
            out["errors"] += 1
            out["error_notes"].append(f"requorum PUT {name}: HTTP {st}")
    far_rtt = max(v for (a, b), v in inj.wan_matrix.items()
                  if "z1" in (a, b))
    cross_p50 = sorted(cross_get)[len(cross_get) // 2]
    out["cross_get_p50_ms"] = round(cross_p50 * 1000, 2)
    out["requorum_put_p50_ms"] = round(
        sorted(cross_put)[len(cross_put) // 2] * 1000, 2)
    # quorum 2-of-{z2@20, z3@80} waits on the far zone: the drill's
    # teeth — cross-zone pays the MATRIX, not some flat timeout
    out["cross_pays_matrix"] = cross_p50 >= 0.8 * far_rtt
    out["cross_vs_local_3x"] = cross_p50 >= 3.0 * max(local_p50, 1e-4)
    out["requorum_pays_matrix"] = (
        sorted(cross_put)[len(cross_put) // 2] >= 0.8 * far_rtt)

    # --- heal: flat mesh again, everything still bit-identical ---
    for i in z1_members:
        inj.heal_link(0, i)
    inj.clear_wan_matrix()
    await inj.reconnect(rounds=8)
    bad = 0
    for name, body in sorted(acked.items()):
        st, got, _h = await s3.req("GET", f"/{bucket}/{name}")
        if st != 200 or got != body:
            bad += 1
    out["verify_mismatches"] = bad
    out["acked"] = len(acked)
    out["error_notes"] = out["error_notes"][:8]
    if not out["error_notes"]:
        del out["error_notes"]
    return out


async def gateway_failover_drill(cluster: SimCluster, session,
                                 secs: float,
                                 bucket: str = "pool-drill") -> dict:
    """The ISSUE-19 zero-loss gateway failover drill (needs a cluster
    built with n_gateways >= 2):

      - a GatewayPool client drives live PUT/GET traffic across both
        gateways while g1 is killed mid-PUT-body and mid-streaming-GET:
        zero acked-data loss (bit-identical reads via the sibling),
        the interrupted unacked PUT retried to success on g0, the
        interrupted GET RESUMED on g0 via Range (no refetch)
      - graceful drain: a SIGTERM'd gateway sheds new requests typed
        (503 SlowDown + RequestId + Retry-After), finishes its
        in-flight streaming GET inside the bounded drain window, and
        its draining/drained state rides NodeStatus gossip
      - the new gateway_pool_* / gateway_drain_state families render,
        pass promlint, and are documented in docs/OBSERVABILITY.md"""
    from pathlib import Path as _Path

    from ..utils.metricsdoc import undocumented_families
    from ..utils.promlint import lint_exposition
    from .gateway_pool import GatewayPool

    assert cluster.n_gateways >= 2, "drill needs a gateway sibling"
    out: dict = {"errors": 0, "error_notes": [],
                 "gateways": cluster.n_gateways}
    pool = GatewayPool(session, cluster.gateway_endpoints(),
                       cluster.key_id, cluster.secret,
                       metrics=cluster.garages[0].system.metrics)
    st, _b, _h = await pool.request("PUT", f"/{bucket}")
    assert st == 200, f"bucket create: {st}"
    out["probe_initial"] = await pool.probe()

    # --- live background traffic through the pool, for the whole run ---
    acked: Dict[str, bytes] = {}
    stop_bg = asyncio.Event()

    async def bg_loop() -> None:
        i = 0
        rng = random.Random(77)
        while not stop_bg.is_set():
            i += 1
            name = f"bg-{i:05d}"
            body = bytes(((i * 31 + j) & 0xFF) for j in range(512)) * 4
            try:
                st, rb, hdrs = await pool.request(
                    "PUT", f"/{bucket}/{name}", body, prefer=i % 2)
            except Exception as e:  # noqa: BLE001
                out["errors"] += 1
                out["error_notes"].append(f"bg PUT {name}: {e!r}")
                continue
            if st == 200:
                acked[name] = body
            elif st == 503:
                bad = check_typed_shed(rb, hdrs)
                if bad is not None:
                    out["errors"] += 1
                    out["error_notes"].append(f"bg PUT {name}: {bad}")
            else:
                out["errors"] += 1
                out["error_notes"].append(f"bg PUT {name}: HTTP {st}")
            if acked and rng.random() < 0.5:
                probe = rng.choice(sorted(acked))
                try:
                    st, got, _h = await pool.request(
                        "GET", f"/{bucket}/{probe}")
                except Exception as e:  # noqa: BLE001
                    out["errors"] += 1
                    out["error_notes"].append(f"bg GET {probe}: {e!r}")
                    continue
                if st != 200 or got != acked[probe]:
                    out["errors"] += 1
                    out["error_notes"].append(
                        f"bg GET {probe}: HTTP {st}"
                        + (" bad body" if st == 200 else ""))
            await asyncio.sleep(0.01)

    bg = asyncio.ensure_future(bg_loop())
    pattern = bytes(range(256)) * (4 << 10)  # 1 MiB

    # --- scenario A: gateway dies mid-PUT-body ---
    big1 = pattern * 3
    killed = asyncio.Event()

    def trickle():
        async def gen():
            chunk = 64 << 10
            for off in range(0, len(big1), chunk):
                if off >= len(big1) // 2 and not killed.is_set():
                    killed.set()
                    await cluster.kill_gateway(1)
                yield big1[off:off + chunk]
        return gen()

    st, _b, _h = await pool.request(
        "PUT", f"/{bucket}/big-1", big1, prefer=1, body_factory=trickle)
    out["mid_put_status"] = st
    out["mid_put_killed"] = killed.is_set()
    out["mid_put_recovered"] = st == 200
    if st == 200:
        acked["big-1"] = big1
    st, got, _h = await pool.request("GET", f"/{bucket}/big-1")
    out["mid_put_bit_identical"] = st == 200 and got == big1

    # --- scenario B: gateway dies mid-streaming-GET → Range resume ---
    pool.set_port("g1", await cluster.restart_gateway(1))
    big2 = bytes(reversed(pattern)) * 8
    st, _b, _h = await pool.request(
        "PUT", f"/{bucket}/big-2", big2, prefer=0)
    assert st == 200, f"PUT big-2: {st}"
    acked["big-2"] = big2
    killed2 = [False]

    async def on_chunk(total: int) -> None:
        if total >= (256 << 10) and not killed2[0]:
            killed2[0] = True
            await cluster.kill_gateway(1)

    st, got, resumed = await pool.get_resumable(
        f"/{bucket}/big-2", prefer=1, on_chunk=on_chunk)
    out["get_resume_status"] = st
    out["get_resumed_via_range"] = resumed
    out["get_resume_bit_identical"] = got == big2

    stop_bg.set()
    await bg

    # --- scenario C: graceful drain under in-flight traffic ---
    pool.set_port("g1", await cluster.restart_gateway(1))
    g1i = cluster.gateway_indices()[1]
    g1_id = bytes(cluster.garages[g1i].system.id)
    got_slow = bytearray()

    async def slow_consumer() -> None:
        # client-paced DOWNLOAD: the handler may finish long before the
        # client (loopback kernel buffers swallow the body) — the bytes
        # must still arrive bit-identical across the drain close
        async with pool.stream_request(1, "GET", f"/{bucket}/big-2") as r:
            out["drain_slow_get_status"] = r.status
            async for chunk in r.content.iter_chunked(512 << 10):
                got_slow.extend(chunk)
                await asyncio.sleep(0.05)

    # ...while a client-paced UPLOAD holds a handler genuinely in
    # flight for the whole window (the server cannot finish reading
    # bytes the client hasn't sent): the drain MUST wait this one out
    slow_body = bytes(((j * 7) & 0xFF) for j in range(256 << 10)) * 8

    def drip():
        async def gen():
            chunk = 256 << 10
            for off in range(0, len(slow_body), chunk):
                yield slow_body[off:off + chunk]
                await asyncio.sleep(0.12)
        return gen()

    slow_task = asyncio.ensure_future(slow_consumer())
    put_task = asyncio.ensure_future(pool.raw(
        1, "PUT", f"/{bucket}/drain-slow", slow_body, body_factory=drip))
    await asyncio.sleep(0.25)  # both are in flight on g1
    drain_task = asyncio.ensure_future(
        cluster.servers[1].drain(timeout=8.0))
    await asyncio.sleep(0.05)
    # while draining: a NEW request to g1 sheds typed, never hangs —
    # and the listener must still be UP (the in-flight PUT pins the
    # window open), so a refused connection here is a drain bug
    try:
        st, rb, hdrs = await pool.raw(1, "GET", f"/{bucket}/big-2")
        out["drain_shed_status"] = st
        out["drain_shed_typed"] = (
            st == 503
            and check_typed_shed(rb, hdrs, codes=("SlowDown",)) is None)
    except Exception as e:  # noqa: BLE001 — evidence, not a stack trace
        out["drain_shed_status"] = f"unreachable: {e!r}"
        out["drain_shed_typed"] = False
    await asyncio.sleep(0.1)  # let the "draining" advertisement land
    # ...and the draining state is visible in a STORAGE node's gossip
    def _gossiped_drain() -> Optional[str]:
        sys1 = cluster.garages[1].system
        row = next((s for nid, s in sys1.node_status.items()
                    if bytes(nid) == g1_id), None)
        return getattr(row, "drain", None)

    out["drain_gossiped"] = _gossiped_drain() == "draining"
    window = await drain_task
    await slow_task
    st_put, _b, _h = await put_task
    if st_put == 200:
        acked["drain-slow"] = slow_body
    out["drain_window_s"] = round(window, 2)
    out["drain_bounded"] = window < 8.0
    out["drain_inflight_completed"] = (st_put == 200
                                       and bytes(got_slow) == big2)
    out["drained_gossiped"] = _gossiped_drain() == "drained"
    try:  # post-drain the socket is CLOSED, not wedged
        await pool.raw(1, "GET", "/")
        out["drain_socket_closed"] = False
    except Exception:  # noqa: BLE001 — refused/reset is the pass
        out["drain_socket_closed"] = True

    # --- zero acked-data loss, bit-identical, via the surviving pool ---
    bad = 0
    for name, body in sorted(acked.items()):
        st, got, _h = await pool.request("GET", f"/{bucket}/{name}")
        if st != 200 or got != body:
            bad += 1
            out["error_notes"].append(f"verify {name}: HTTP {st}")
    out["verify_mismatches"] = bad
    out["acked"] = len(acked)
    out["pool_counters"] = dict(pool.counters)
    out["failover_exercised"] = pool.counters["failovers"] >= 2
    out["resume_exercised"] = pool.counters["resumes"] >= 1

    # --- the new families render, lint clean, and are documented ---
    expo0 = cluster.garages[0].system.metrics.render()
    expo1 = cluster.garages[g1i].system.metrics.render()
    out["drain_gauge_rendered"] = "gateway_drain_state" in expo1
    out["pool_counters_rendered"] = "gateway_pool_failover_total" in expo0
    out["promlint_errors"] = (lint_exposition(expo0)
                              + lint_exposition(expo1))[:4]
    doc = (_Path(__file__).resolve().parents[2]
           / "docs" / "OBSERVABILITY.md").read_text()
    out["metricsdoc_missing"] = sorted(
        undocumented_families(expo0 + "\n" + expo1, doc))[:8]
    out["error_notes"] = out["error_notes"][:8]
    if not out["error_notes"]:
        del out["error_notes"]
    return out
