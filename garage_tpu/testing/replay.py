"""Trace-driven workload replayer (ISSUE 19).

Production traffic is not uniform: keys are Zipf-hot, sizes are
mixtures, and load breathes on a diurnal curve.  This module generates
a DETERMINISTIC operation trace from a seed — (kind, key, size, at_s)
tuples — and replays it against a GatewayPool, verifying every GET
bit-identical against the last acked body for its key.  Same seed ⇒
byte-identical trace ⇒ a chaos run (bench --replay-phase kills a
gateway mid-window) is exactly reproducible.

Shape knobs and their defaults:

  - keys: Zipf(theta) over ``n_keys`` ranks via a precomputed inverse
    CDF (theta 1.1 ⇒ top key ~22% of ops at 128 keys)
  - sizes: preset mixtures — "small" (metadata-heavy: 80% 512B–8KiB,
    18% 64–256KiB, 2% 1–2MiB) or "multipart" (block-heavy: 50%
    256KiB–1MiB, 35% 2–6MiB, 15% 8–16MiB)
  - arrival: inhomogeneous Poisson-ish pacing with rate(t) =
    base_ops_per_s * (1 + diurnal_amplitude * sin(2πt/period)) — a
    compressed day: peak/trough ratio (1+a)/(1-a)
  - mix: ``read_fraction`` GETs, ``delete_fraction`` DELETEs, the rest
    PUTs (a fresh version body per PUT, deterministic per (key, ver))

The generator is pure (no wall clock, no global RNG): tests assert
trace equality and shape; the runner does the pacing and verification.
"""

from __future__ import annotations

import asyncio
import hashlib
import math
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

SIZE_PRESETS = {
    # (probability, lo_bytes, hi_bytes) — probabilities sum to 1
    "small": ((0.80, 512, 8 << 10),
              (0.18, 64 << 10, 256 << 10),
              (0.02, 1 << 20, 2 << 20)),
    "multipart": ((0.50, 256 << 10, 1 << 20),
                  (0.35, 2 << 20, 6 << 20),
                  (0.15, 8 << 20, 16 << 20)),
}


@dataclass
class ReplayConfig:
    seed: int = 20260807
    n_keys: int = 128
    zipf_theta: float = 1.1
    size_preset: str = "small"
    base_ops_per_s: float = 20.0
    diurnal_amplitude: float = 0.6
    diurnal_period_s: float = 8.0
    read_fraction: float = 0.55
    delete_fraction: float = 0.03
    duration_s: float = 10.0
    bucket: str = "replay"


@dataclass
class ReplayStats:
    puts: int = 0
    gets: int = 0
    deletes: int = 0
    not_found: int = 0
    sheds: int = 0
    errors: int = 0
    error_notes: List[str] = field(default_factory=list)
    lats: List[float] = field(default_factory=list)
    behind_s: float = 0.0   # worst pacing debt (replay fell behind)

    def note_error(self, what: str) -> None:
        self.errors += 1
        if len(self.error_notes) < 8:
            self.error_notes.append(what)

    def summary(self) -> dict:
        lats = sorted(self.lats)
        out = {"puts": self.puts, "gets": self.gets,
               "deletes": self.deletes, "not_found": self.not_found,
               "sheds": self.sheds, "errors": self.errors,
               "ops": len(lats), "behind_s": round(self.behind_s, 2)}
        if self.error_notes:
            out["error_notes"] = list(self.error_notes)
        if lats:
            out["p50_ms"] = round(lats[len(lats) // 2] * 1000, 2)
            out["p99_ms"] = round(
                lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1000, 2)
        return out


def zipf_cdf(n_keys: int, theta: float) -> List[float]:
    """Cumulative weights of rank^-theta — the inverse-CDF table key
    sampling walks (bisect) so the hot set is exactly Zipfian."""
    ws = [1.0 / ((r + 1) ** theta) for r in range(n_keys)]
    total = sum(ws)
    acc, out = 0.0, []
    for w in ws:
        acc += w
        out.append(acc / total)
    return out


def _pick_key(rng: random.Random, cdf: List[float]) -> int:
    import bisect

    return bisect.bisect_left(cdf, rng.random())


def _pick_size(rng: random.Random, preset: str) -> int:
    u = rng.random()
    acc = 0.0
    for prob, lo, hi in SIZE_PRESETS[preset]:
        acc += prob
        if u <= acc:
            return rng.randrange(lo, hi)
    _p, lo, hi = SIZE_PRESETS[preset][-1]
    return rng.randrange(lo, hi)


def generate_ops(cfg: ReplayConfig) -> List[Tuple[str, int, int, float]]:
    """The deterministic trace: [(kind, key_rank, size, at_s), ...]
    sorted by at_s.  kind ∈ {put, get, delete}; size is 0 for get and
    delete.  Pure function of cfg — no wall clock, no global RNG."""
    rng = random.Random(cfg.seed)
    cdf = zipf_cdf(cfg.n_keys, cfg.zipf_theta)
    ops: List[Tuple[str, int, int, float]] = []
    t = 0.0
    while t < cfg.duration_s:
        # inhomogeneous arrivals: thin a homogeneous stream at the
        # diurnal envelope — rate(t) = base * (1 + a*sin(2πt/period))
        rate = cfg.base_ops_per_s * (
            1.0 + cfg.diurnal_amplitude
            * math.sin(2.0 * math.pi * t / cfg.diurnal_period_s))
        rate = max(rate, 0.05 * cfg.base_ops_per_s)
        t += rng.expovariate(rate)
        if t >= cfg.duration_s:
            break
        u = rng.random()
        key = _pick_key(rng, cdf)
        if u < cfg.read_fraction:
            ops.append(("get", key, 0, t))
        elif u < cfg.read_fraction + cfg.delete_fraction:
            ops.append(("delete", key, 0, t))
        else:
            ops.append(("put", key, _pick_size(rng, cfg.size_preset), t))
    return ops


def trace_signature(ops: List[Tuple[str, int, int, float]]) -> str:
    """Stable digest of a trace — two runs of the same config MUST
    produce the same signature (the determinism acceptance check)."""
    h = hashlib.sha256()
    for kind, key, size, at in ops:
        h.update(f"{kind}|{key}|{size}|{at:.6f}\n".encode())
    return h.hexdigest()[:16]


def body_for(cfg: ReplayConfig, key: int, version: int, size: int) -> bytes:
    """Deterministic body for (key, version): seeded 256-byte tile
    repeated to size — cheap to build, unique per version, and
    reproducible so GET verification needs no stored copies."""
    tile_rng = random.Random((cfg.seed, key, version).__hash__())
    tile = bytes(tile_rng.randrange(256) for _ in range(256))
    reps = size // 256 + 1
    return (tile * reps)[:size]


class Replayer:
    """Paces a generated trace against a GatewayPool and verifies the
    chaos-soak invariants inline (acked GETs bit-identical, deletes
    stay deleted) — tolerating typed sheds as non-errors."""

    def __init__(self, cfg: ReplayConfig, pool):
        self.cfg = cfg
        self.pool = pool
        self.ops = generate_ops(cfg)
        self.stats = ReplayStats()
        # key rank -> (version, body) of the last ACKED put; version
        # counts attempts so retried bodies never collide
        self.acked: Dict[int, Tuple[int, bytes]] = {}
        self.deleted: set = set()
        self._versions: Dict[int, int] = {}

    def _key_name(self, rank: int) -> str:
        return f"k{rank:05d}"

    async def _one(self, kind: str, key: int, size: int) -> None:
        cfg, st_ = self.cfg, self.stats
        path = f"/{cfg.bucket}/{self._key_name(key)}"
        t0 = time.perf_counter()
        try:
            if kind == "put":
                ver = self._versions.get(key, 0) + 1
                self._versions[key] = ver
                body = body_for(cfg, key, ver, size)
                st, rb, hdrs = await self.pool.request("PUT", path, body)
                st_.lats.append(time.perf_counter() - t0)
                if st == 200:
                    st_.puts += 1
                    self.acked[key] = (ver, body)
                    self.deleted.discard(key)
                elif st == 503:
                    st_.sheds += 1
                else:
                    st_.note_error(f"PUT k{key}: HTTP {st}")
            elif kind == "get":
                st, got, hdrs = await self.pool.request("GET", path)
                st_.lats.append(time.perf_counter() - t0)
                if st == 200:
                    exp = self.acked.get(key)
                    if exp is not None and got != exp[1]:
                        st_.note_error(f"GET k{key}: body mismatch "
                                       f"(ver {exp[0]})")
                    else:
                        st_.gets += 1
                elif st == 404:
                    if key in self.acked:
                        st_.note_error(f"GET k{key}: 404 after ack")
                    else:
                        st_.not_found += 1
                elif st == 503:
                    st_.sheds += 1
                else:
                    st_.note_error(f"GET k{key}: HTTP {st}")
            else:  # delete
                st, rb, hdrs = await self.pool.request("DELETE", path)
                st_.lats.append(time.perf_counter() - t0)
                if st in (200, 204):
                    st_.deletes += 1
                    self.acked.pop(key, None)
                    self.deleted.add(key)
                elif st == 503:
                    st_.sheds += 1
                else:
                    st_.note_error(f"DELETE k{key}: HTTP {st}")
        except Exception as e:  # noqa: BLE001 — a client-visible failure
            st_.note_error(f"{kind.upper()} k{key}: {e!r}")

    async def run(self, on_op=None) -> ReplayStats:
        """Replay the trace at its generated timestamps (sleeping into
        each op's at_s; pacing debt is recorded, never skipped).
        ``on_op(i, at_s)`` fires before each op — bench uses it to
        trigger the mid-window gateway kill at a deterministic index."""
        t_start = time.monotonic()
        for i, (kind, key, size, at) in enumerate(self.ops):
            now = time.monotonic() - t_start
            if at > now:
                await asyncio.sleep(at - now)
            else:
                self.stats.behind_s = max(self.stats.behind_s, now - at)
            if on_op is not None:
                await on_op(i, at)
            await self._one(kind, key, size)
        return self.stats

    async def verify_all(self) -> int:
        """Read back every acked key; returns mismatches."""
        bad = 0
        for key, (_ver, body) in sorted(self.acked.items()):
            path = f"/{self.cfg.bucket}/{self._key_name(key)}"
            st, got, _h = await self.pool.request("GET", path)
            if st != 200 or got != body:
                bad += 1
                self.stats.note_error(f"verify k{key}: HTTP {st}")
        for key in sorted(self.deleted):
            path = f"/{self.cfg.bucket}/{self._key_name(key)}"
            st, _b, _h = await self.pool.request("GET", path)
            if st != 404:
                bad += 1
                self.stats.note_error(
                    f"verify deleted k{key}: HTTP {st} (expected 404)")
        return bad
