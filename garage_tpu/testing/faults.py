"""Reusable fault injector (VERDICT r3 #7; SURVEY §5 aux subsystem).

The reference validates durability claims with cluster benchmarks under
"2 simulated node failures" (ref doc/book/design/benchmarks) but ships
no reusable rig; here the rig is in-tree: one object that can crash and
revive nodes of an in-process cluster and drop/corrupt chosen blocks on
disk, used by tests (generalizing the ad-hoc node kills in
tests/test_integration.py) and by bench.py's degraded-mode phase.

Crash semantics: `crash()` is abrupt — transport closed and workers
cancelled with NO graceful drains (a dying node doesn't flush its
write-time parity accumulator).  `revive()` rebuilds a Garage from the
same config/dirs, the crash-consistency path real restarts take —
meaningful only for persistent db engines (sqlite/native), not
"memory".

Network faults (the degraded-mode chaos rig; docs/ROBUSTNESS.md):
`add_network_faults()` interposes a ``FaultyLink`` — a LatencyProxy
subclass with mutable fault state — on every directed dial path i→j, so
a running cluster's links can then be degraded live:

  - latency spikes + jitter          set_latency / slow_peer
  - probabilistic connection resets  flaky_link
  - one-way partitions               partition_one_way (requests vanish,
                                     replies still flow — the asymmetric
                                     case gossip alone never detects)
  - hard partitions                  partition (refuse + kill)
  - blackholes                       blackhole_node (accept, never
                                     respond — only ADAPTIVE timeouts
                                     catch this; a static 60 s timeout
                                     burns in full per call)

Link (i, j) carries connections DIALED by i toward j; which link of a
pair serves the one shared TCP connection depends on who won the dial
race, so symmetric faults (latency, resets) are applied to both links of
the pair by the helpers.
"""

from __future__ import annotations

import asyncio
import errno
import logging
import os
import random
import time
from types import SimpleNamespace
from typing import Dict, List, Optional, Tuple

from ..block.health import DiskIo
from ..net.latency_proxy import LatencyProxy
from ..utils.data import Hash

logger = logging.getLogger("garage_tpu.testing.faults")

# Fast-twitch [rpc] tunables for chaos drives: sub-second adaptive
# timeouts against loopback RTTs, quick retries, a 1 s breaker cooldown.
# SHARED by tests/test_net_faults.py and scripts/chaos.py so the pytest
# acceptance proof and the standalone script exercise the same regime —
# tune it here, both rigs follow.
FAST_CHAOS_RPC = {
    "adaptive_timeout_base": 1.0,
    "adaptive_timeout_min": 0.4,
    "retry_backoff_base": 0.02,
    "retry_backoff_max": 0.2,
    "breaker_failure_threshold": 3,
    "breaker_open_secs": 1.0,
    "block_rpc_timeout": 20.0,
}

# Fast-twitch [health] tunables for the fail_slow drill (and any test
# that wants flag transitions inside seconds instead of the production
# 30 s sustained window): factor/hysteresis are the PRODUCTION values —
# only the time constants and sample floors shrink, so the drill proves
# the same comparative logic the fleet runs.
FAST_CHAOS_HEALTH = {
    "fail_slow_factor": 3.0,
    "clear_factor": 1.5,
    "window_s": 0.4,
    "min_samples": 4,
    "min_baseline_peers": 1,
    "sample_ttl_s": 60.0,
}

# The canonical geo-WAN profile (ISSUE 19): a symmetric 3-zone RTT
# matrix — z1↔z2 a metro pair, z1↔z3 cross-country, z2↔z3 the long
# diagonal.  Values are full round trips in SECONDS; the injector
# applies rtt/2 one-way per boundary link.  SHARED by the wan chaos
# phase, bench --replay-phase, and the WAN-matrix unit tests.
WAN_3ZONE_RTT = {
    ("z1", "z2"): 0.020,
    ("z1", "z3"): 0.080,
    ("z2", "z3"): 0.150,
}


class FaultyLink(LatencyProxy):
    """One directed network path with live-tunable faults.  All knobs are
    plain attributes read per-chunk/per-accept, so tests flip them while
    traffic is flowing."""

    def __init__(self, target_host: str, target_port: int,
                 rng: Optional[random.Random] = None):
        super().__init__(target_host, target_port, 0.0, 0.0)
        self.refuse = False           # hard partition: refuse new conns
        self.blackhole = False        # accept, forward nothing either way
        self.drop: set = set()        # {'tx','rx'} silently dropped
        self.reset_prob = 0.0         # P(connection aborted after accept)
        self.reset_delay = (0.02, 0.3)
        # go dark MID-TRANSFER: after this many total forwarded bytes the
        # link turns into a blackhole (the case a response-header timeout
        # cannot catch — only per-chunk inactivity deadlines do)
        self.blackhole_after_bytes: Optional[int] = None
        self._forwarded = 0
        self._rng = rng or random.Random()

    def clear(self) -> None:
        """Back to a clean, zero-latency link."""
        self.refuse = False
        self.blackhole = False
        self.drop = set()
        self.reset_prob = 0.0
        self.blackhole_after_bytes = None
        self._forwarded = 0
        self.delay = 0.0
        self.jitter = 0.0

    def _on_accept(self, reader, writer) -> bool:
        if self.refuse:
            return False
        if self.reset_prob and self._rng.random() < self.reset_prob:
            # accepted, then reset shortly after — the classic flaky
            # middlebox; in-flight RPCs on the conn fail all at once
            asyncio.get_running_loop().call_later(
                self._rng.uniform(*self.reset_delay), writer.close)
        return True

    def _filter(self, direction: str, data: bytes) -> Optional[bytes]:
        if self.blackhole_after_bytes is not None:
            self._forwarded += len(data)
            if self._forwarded > self.blackhole_after_bytes:
                self.blackhole = True
        if self.blackhole or direction in self.drop:
            return None
        return data


class SimulatedCrash(RuntimeError):
    """The process 'died' mid-write: NOT an OSError, so the write path
    neither converts it to a typed StorageError nor feeds the disk
    breaker — exactly like a real kill, the call just never returns.
    The on-disk state at raise time (torn tmp, unrenamed tmp) is what
    the startup janitor must clean up."""


class FaultyDisk(DiskIo):
    """Storage faults at BlockManager's filesystem boundary.  Wraps a
    manager's ``DiskIo`` (``mgr.disk = FaultyDisk(mgr.disk)``) so faults
    inject at exactly the seam the real kernel would error through — no
    os.* monkeypatching, per-node scoping for free.  All knobs are plain
    attributes read per-op, so tests flip them while traffic flows:

      - ``read_errno`` / ``write_errno`` (+ ``*_error_prob``): EIO on
        read, ENOSPC/EIO on write
      - ``fsync_errno``: the write lands, durability doesn't
      - ``crash_stage`` ∈ {tmp, rename, fsync}: SimulatedCrash at that
        write stage, leaving the torn on-disk state a real kill would
        (``torn_fraction`` of the tmp bytes for stage "tmp")
      - ``bitrot_prob``: silent single-byte corruption on read (the
        verify/scrub path must catch it by content hash)
      - ``latency``: per-op sleep (a dying disk is slow before it is
        dead); applied in the worker thread, never on the event loop
      - ``statvfs_free``: synthetic free-bytes override — drives the
        watermark state machine without actually filling a filesystem

    ``path_prefix`` scopes every fault to one data root (multi-root
    nodes degrade per root, not per node)."""

    def __init__(self, inner: Optional[DiskIo] = None,
                 rng: Optional[random.Random] = None,
                 path_prefix: Optional[str] = None):
        self.inner = inner or DiskIo()
        self._rng = rng or random.Random()
        self.path_prefix = path_prefix
        self.clear()

    def clear(self) -> None:
        """Back to a clean pass-through disk."""
        self.read_errno: Optional[int] = None
        self.read_error_prob = 1.0
        self.write_errno: Optional[int] = None
        self.write_error_prob = 1.0
        self.fsync_errno: Optional[int] = None
        self.bitrot_prob = 0.0
        self.latency = 0.0
        self.crash_stage: Optional[str] = None
        self.torn_fraction = 0.5
        self.statvfs_free: Optional[int] = None
        self.injected = {"read": 0, "write": 0, "fsync": 0,
                         "bitrot": 0, "crash": 0}

    def _applies(self, path: str) -> bool:
        return self.path_prefix is None or path.startswith(self.path_prefix)

    def _err(self, kind: str, eno: int, path: str) -> OSError:
        self.injected[kind] += 1
        return OSError(eno, os.strerror(eno), path)

    def read_file(self, path: str) -> bytes:
        return self._faulted_read(path, self.inner.read_file)

    def read_file_direct(self, path: str) -> bytes:
        # the scrub worker's O_DIRECT flavor: same fault surface as
        # read_file — a dying disk errors scrubs and GETs alike
        return self._faulted_read(path, self.inner.read_file_direct)

    def _faulted_read(self, path: str, read) -> bytes:
        if self._applies(path):
            if self.latency:
                time.sleep(self.latency)
            if (self.read_errno is not None
                    and self._rng.random() < self.read_error_prob):
                raise self._err("read", self.read_errno, path)
        data = read(path)
        if (self._applies(path) and data
                and self._rng.random() < self.bitrot_prob):
            i = self._rng.randrange(len(data))
            data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
            self.injected["bitrot"] += 1
        return data

    def write_file(self, path: str, data: bytes, fsync: bool = False) -> None:
        if self._applies(path):
            if self.latency:
                time.sleep(self.latency)
            if self.crash_stage == "tmp":
                # torn write: a prefix reaches the media, then the
                # "process" dies before finishing — never acknowledged
                self.injected["crash"] += 1
                with open(path, "wb") as f:
                    f.write(data[:int(len(data) * self.torn_fraction)])
                raise SimulatedCrash(f"kill mid tmp-write of {path}")
            if (self.write_errno is not None
                    and self._rng.random() < self.write_error_prob):
                raise self._err("write", self.write_errno, path)
            if fsync and self.fsync_errno is not None:
                # the data write succeeded; only durability failed —
                # the kernel reports that exactly once, at fsync
                self.inner.write_file(path, data, fsync=False)
                raise self._err("fsync", self.fsync_errno, path)
        return self.inner.write_file(path, data, fsync=fsync)

    def replace(self, src: str, dst: str) -> None:
        if self._applies(dst) and self.crash_stage == "rename":
            # died between tmp write and rename: a COMPLETE tmp file
            # orphaned next to a missing final — still unacknowledged
            self.injected["crash"] += 1
            raise SimulatedCrash(f"kill before rename {src} -> {dst}")
        return self.inner.replace(src, dst)

    def remove(self, path: str) -> None:
        return self.inner.remove(path)

    def fsync_dir(self, path: str) -> None:
        if self._applies(path):
            if self.crash_stage == "fsync":
                # died at the directory fsync: write + rename landed, the
                # PUT was NOT acked — the surviving block is a harmless
                # duplicate-to-be, never a loss
                self.injected["crash"] += 1
                raise SimulatedCrash(f"kill at dir fsync of {path}")
            if self.fsync_errno is not None:
                raise self._err("fsync", self.fsync_errno, path)
        return self.inner.fsync_dir(path)

    def statvfs(self, path: str):
        sv = self.inner.statvfs(path)
        if self._applies(path) and self.statvfs_free is not None:
            return SimpleNamespace(
                f_bavail=max(0, int(self.statvfs_free) // sv.f_frsize),
                f_frsize=sv.f_frsize,
                f_blocks=sv.f_blocks,
                f_fsid=getattr(sv, "f_fsid", 0),
            )
        return sv


class FaultInjector:
    """Faults over a list of in-process Garage nodes."""

    def __init__(self, garages: List, configs: Optional[List] = None,
                 zones: Optional[List[str]] = None):
        self.garages = list(garages)
        self.configs = list(configs) if configs else [
            g.config for g in garages]
        self.dead: set = set()
        self.links: Dict[Tuple[int, int], FaultyLink] = {}
        self.disks: Dict[int, FaultyDisk] = {}
        # the RTT matrix currently applied (apply_wan_matrix), or None
        self.wan_matrix: Optional[Dict[Tuple[str, str], float]] = None
        # node index -> zone (for the zone-grained fault helpers); when
        # not given, read from the committed layout
        self._zones = list(zones) if zones else None

    # --- zone topology -------------------------------------------------

    def zone_of_index(self, i: int) -> Optional[str]:
        if self._zones is not None:
            return self._zones[i]
        g = self.garages[i]
        return g.system.zone_of(g.system.id)

    def nodes_in_zone(self, zone: str) -> List[int]:
        return [i for i in range(len(self.garages))
                if self.zone_of_index(i) == zone]

    def _zone_members(self, zone: str) -> set:
        members = set(self.nodes_in_zone(zone))
        assert members, f"no nodes in zone {zone!r}"
        return members

    # --- network faults ---

    async def add_network_faults(
        self, rng: Optional[random.Random] = None
    ) -> None:
        """Interpose a FaultyLink on every directed dial path and migrate
        the cluster's connections through them: peer-book addresses are
        rewritten to the link ports, direct connections are closed, and
        the peering loop re-dials through the links."""
        assert not self.links, "network faults already installed"
        for i, gi in enumerate(self.garages):
            for j, gj in enumerate(self.garages):
                if i == j:
                    continue
                port = int(gj.config.rpc_public_addr.rsplit(":", 1)[1])
                link = FaultyLink("127.0.0.1", port, rng=rng)
                lport = await link.start()
                self.links[(i, j)] = link
                gi.system.peering.add_peer(f"127.0.0.1:{lport}", gj.system.id)
        for g in self.garages:
            for conn in list(g.system.netapp.conns.values()):
                await conn.close()
        await self.reconnect()

    async def reconnect(self, rounds: int = 5) -> bool:
        """Drive the live nodes' peering ticks until the mesh is whole
        (or `rounds` exhausted) — chaos tests must not race the 15 s
        reconnect loop."""
        live = [g for i, g in enumerate(self.garages) if i not in self.dead]
        for _ in range(rounds):
            for g in live:
                await g.system.peering._tick()
            await asyncio.sleep(0.05)
            if all(len(g.system.netapp.conns) >= len(live) - 1
                   for g in live):
                # one extra tick so freshly-dialed conns get PINGED: the
                # RTT EWMAs must exist or the adaptive-timeout layer falls
                # back to static timeouts for every peer
                for g in live:
                    await g.system.peering._tick()
                return True
        return False

    def _pair(self, i: int, j: int) -> List[FaultyLink]:
        return [self.links[(i, j)], self.links[(j, i)]]

    def set_latency(self, i: int, j: int, delay: float,
                    jitter: float = 0.0) -> None:
        """One-way `delay` (±jitter) on both links of the pair (i, j)."""
        for link in self._pair(i, j):
            link.delay, link.jitter = delay, jitter

    def slow_peer(self, k: int, delay: float, jitter: float = 0.0) -> None:
        """Latency spike on every link touching node k (a straggling
        datacenter, not a single bad cable)."""
        for (a, b), link in self.links.items():
            if k in (a, b):
                link.delay, link.jitter = delay, jitter

    def flaky_link(self, i: int, j: int, reset_prob: float) -> None:
        for link in self._pair(i, j):
            link.reset_prob = reset_prob

    def partition_one_way(self, src: int, dst: int) -> None:
        """Bytes from src never reach dst; dst's bytes still reach src
        (asymmetric routing failure).  Requests die, replies flow."""
        self.links[(src, dst)].drop.add("tx")
        self.links[(dst, src)].drop.add("rx")

    def partition(self, i: int, j: int) -> None:
        """Hard two-way partition: refuse new connections, kill live
        ones (both sides see resets, dials fail fast)."""
        for link in self._pair(i, j):
            link.refuse = True
            link.kill_connections()

    def blackhole_node(self, k: int) -> None:
        """Every link touching k accepts but never delivers — in-flight
        RPCs hang until (only) the adaptive timeout fires."""
        for (a, b), link in self.links.items():
            if k in (a, b):
                link.blackhole = True

    def heal_link(self, i: int, j: int) -> None:
        for link in self._pair(i, j):
            link.clear()

    def heal_network(self) -> None:
        for link in self.links.values():
            link.clear()

    # --- zone-grained faults (zone = the production failure domain;
    #     docs/ROBUSTNESS.md "Zone failures & rebalance").  Built on the
    #     FaultyLink primitives above: a zone fault degrades every link
    #     CROSSING the zone boundary and leaves intra-zone links alone —
    #     nodes inside a dark zone still see each other, exactly like a
    #     DC that lost its WAN uplink. ---

    def _boundary_links(self, zone: str):
        members = self._zone_members(zone)
        for (a, b), link in self.links.items():
            if (a in members) != (b in members):
                yield link

    def partition_zone(self, zone: str) -> None:
        """Hard-partition a whole zone: every boundary link refuses new
        connections and kills live ones (both sides fail fast)."""
        for link in self._boundary_links(zone):
            link.refuse = True
            link.kill_connections()

    def blackhole_zone(self, zone: str) -> None:
        """Every boundary link accepts and delivers nothing — in-flight
        cross-zone RPCs hang until the adaptive timeout fires (the
        fault class only breakers + adaptive timeouts catch)."""
        for link in self._boundary_links(zone):
            link.blackhole = True

    def slow_zone(self, zone: str, delay: float, jitter: float = 0.0) -> None:
        """WAN brown-out: one-way `delay` (±jitter) on every boundary
        link (a remote DC turning distant, not broken)."""
        for link in self._boundary_links(zone):
            link.delay, link.jitter = delay, jitter

    def heal_zone(self, zone: str) -> None:
        """Clear every fault on the zone's boundary links."""
        for link in self._boundary_links(zone):
            link.clear()

    # --- geo-WAN latency domains (ISSUE 19) ----------------------------

    def apply_wan_matrix(self, matrix: Dict[Tuple[str, str], float],
                         zones: Optional[List[Optional[str]]] = None,
                         jitter: float = 0.0) -> None:
        """Turn the flat loopback mesh into a geography: `matrix` maps an
        (orderless) zone pair to its full RTT in seconds, and every link
        CROSSING that pair's boundary gets rtt/2 one-way delay.  Links
        inside a zone stay untouched — a DC's LAN does not pay WAN tolls.

        `zones` overrides the per-index zone lookup (same length as the
        node list); pass it when some indices — gateways — carry no
        layout role but still live somewhere: the injector's own zone
        table deliberately reports None for them so zone-kill drills
        never crash a gateway, yet their WAN links must still stretch.
        Pairs absent from the matrix keep their current delay."""

        def _zone(i: int) -> Optional[str]:
            if zones is not None and zones[i] is not None:
                return zones[i]
            return self.zone_of_index(i)

        for (a, b), link in self.links.items():
            za, zb = _zone(a), _zone(b)
            if za is None or zb is None or za == zb:
                continue
            rtt = matrix.get((za, zb), matrix.get((zb, za)))
            if rtt is None:
                continue
            link.delay = rtt / 2.0
            link.jitter = jitter / 2.0
        self.wan_matrix = dict(matrix)

    def clear_wan_matrix(self) -> None:
        """Back to a flat zero-RTT mesh (only latency/jitter are reset —
        other live faults on the links are left alone)."""
        for link in self.links.values():
            link.delay = 0.0
            link.jitter = 0.0
        self.wan_matrix = None

    async def kill_zone(self, zone: str) -> None:
        """Abruptly crash every node in the zone (correlated failure —
        the regime zone_redundancy placement exists for)."""
        for i in self.nodes_in_zone(zone):
            if i not in self.dead:
                await self.crash(i)

    async def revive_zone(self, zone: str, wait_secs: float = 10.0) -> List:
        """Restart every dead node of the zone from its on-disk state."""
        out = []
        for i in self.nodes_in_zone(zone):
            if i in self.dead:
                out.append(await self.revive(i, wait_secs=wait_secs))
        return out

    async def stop_network(self) -> None:
        for link in self.links.values():
            await link.stop()
        self.links.clear()

    # --- disk faults (docs/ROBUSTNESS.md "Disk faults & degraded mode") ---

    def add_disk_faults(self, i: int, root: Optional[str] = None,
                        rng: Optional[random.Random] = None) -> FaultyDisk:
        """Interpose a FaultyDisk on node i's filesystem boundary (all
        roots, or just `root`).  Idempotent per node; returns the disk
        so the caller can flip knobs directly.  The health monitor's
        statvfs closure is late-bound through mgr.disk, so the synthetic
        free-space override is honored immediately."""
        fd = self.disks.get(i)
        if fd is None:
            mgr = self.garages[i].block_manager
            fd = FaultyDisk(mgr.disk, rng=rng, path_prefix=root)
            mgr.disk = fd
            self.disks[i] = fd
        return fd

    def flaky_disk(self, i: int, prob: float = 0.5,
                   eno: int = errno.EIO) -> FaultyDisk:
        """Probabilistic EIO on node i's reads AND writes — the dying-
        disk regime the self-healing read path and the error-streak
        breaker exist for."""
        fd = self.add_disk_faults(i)
        fd.read_errno = fd.write_errno = eno
        fd.read_error_prob = fd.write_error_prob = prob
        return fd

    def fill_disk(self, i: int, free_bytes: int = 0) -> FaultyDisk:
        """Synthetic ENOSPC: statvfs on node i reports `free_bytes`
        free, so the watermark preflight flips its roots read-only
        (StorageFull) without writing a single real byte."""
        fd = self.add_disk_faults(i)
        fd.statvfs_free = free_bytes
        return fd

    def bitrot_disk(self, i: int, prob: float) -> FaultyDisk:
        fd = self.add_disk_faults(i)
        fd.bitrot_prob = prob
        return fd

    def heal_disk(self, i: int) -> None:
        """Clear every injected fault on node i's disk (the wrapper
        stays installed — faults can be re-applied live)."""
        fd = self.disks.get(i)
        if fd is not None:
            fd.clear()

    # --- node faults ---

    async def crash(self, i: int) -> None:
        """Abrupt node death: close the transport and cancel workers,
        skipping every graceful-drain step of Garage.shutdown()."""
        g = self.garages[i]
        await g.bg.shutdown(timeout=0.5)
        await g.system.shutdown()
        if g._owns_db:
            g.db.close()
        self.dead.add(i)

    async def revive(self, i: int, peers: Optional[List[str]] = None,
                     wait_secs: float = 10.0):
        """Restart node i from its on-disk state; returns the new Garage.
        `peers` = "host:port" addresses to reconnect to (defaults to the
        rpc_public_addr — or fault-link port — of every live node).
        Dial failures are LOGGED (the peering loop keeps retrying them),
        and the call waits up to `wait_secs` for the peer handshakes so
        chaos tests don't race the reconnect loop."""
        from ..model import Garage

        assert i in self.dead, f"node {i} is not dead"
        g = Garage(self.configs[i])
        await g.system.netapp.listen(self.configs[i].rpc_bind_addr)
        port = g.system.netapp._server.sockets[0].getsockname()[1]
        g.config.rpc_public_addr = f"127.0.0.1:{port}"
        live = [j for j in range(len(self.garages))
                if j != i and j not in self.dead]
        if self.links:
            # the revived node listens on a fresh port: EVERY link
            # pointing at it must retarget — including links from
            # currently-dead nodes, or a later revive of those nodes
            # dials this node's stale port forever (failing ticks that
            # wrongly feed its breaker)
            for (a, b), link in self.links.items():
                if b == i:
                    link.retarget(port)

        def _addr_of(j: int) -> str:
            if self.links:
                return f"127.0.0.1:{self.links[(i, j)].port}"
            return self.garages[j].config.rpc_public_addr

        if peers is None:
            peers = [_addr_of(j) for j in live]
        for addr in peers:
            try:
                await g.system.netapp.connect(addr)
            except Exception as e:
                # not silent: a chaos run must be able to tell "revive
                # raced the reconnect loop" from "revive couldn't reach
                # anything" in its logs
                logger.warning(
                    "revive(%d): dial %s failed (%s); peering loop will "
                    "keep retrying", i, addr, e)
        for j in live:
            other = self.garages[j]
            other_addr = (f"127.0.0.1:{self.links[(j, i)].port}"
                          if self.links else g.config.rpc_public_addr)
            other.system.peering.add_peer(other_addr, g.system.id)
            g.system.peering.add_peer(_addr_of(j), other.system.id)
        # adopt the cluster's layout from any live node
        for j, other in enumerate(self.garages):
            if j != i and j not in self.dead:
                from ..rpc.layout import ClusterLayout

                g.system.layout = ClusterLayout.decode(
                    other.system.layout.encode())
                g.system._rebuild_ring()
                break
        g.spawn_workers()
        g.system.peering.start()
        self.garages[i] = g
        self.dead.discard(i)
        # the revived manager owns a fresh DiskIo: drop the stale fault
        # wrapper (re-install via add_disk_faults to fault the new disk)
        self.disks.pop(i, None)
        # bounded convergence wait: drive peering ticks (both sides —
        # the live nodes' 15 s loops would otherwise win every race)
        # until every live peer's handshake landed or the budget is out
        expected = {self.garages[j].system.id for j in live}
        deadline = time.monotonic() + wait_secs

        def _missing():
            return [n for n in expected if n not in g.system.netapp.conns]

        while _missing() and time.monotonic() < deadline:
            await g.system.peering._tick()
            for j in live:
                if j not in self.dead:
                    await self.garages[j].system.peering._tick()
            await asyncio.sleep(0.1)
        still = _missing()
        if still:
            logger.warning(
                "revive(%d): %d/%d peer handshakes still missing after "
                "%.1fs", i, len(still), len(expected), wait_secs)
        return g

    # --- block faults ---

    def _block_files(self, i: int) -> List[str]:
        dd = self.configs[i].data_dir  # [{"path": ..., ...}, ...]
        roots = [d["path"] if isinstance(d, dict) else str(d) for d in dd] \
            if isinstance(dd, list) else [str(dd)]
        out = []
        for root in roots:
            for dirpath, _dirs, files in os.walk(root):
                if "parity" in dirpath.split(os.sep):
                    continue
                for f in files:
                    if not f.endswith((".par", ".tmp", ".corrupted")):
                        out.append(os.path.join(dirpath, f))
        return out

    def list_blocks(self, i: int) -> List[Hash]:
        out = []
        for p in self._block_files(i):
            name = os.path.basename(p).split(".")[0]
            try:
                out.append(Hash(bytes.fromhex(name)))
            except ValueError:
                continue
        return out

    def _find(self, i: int, h: Hash) -> Optional[str]:
        want = bytes(h).hex()
        for p in self._block_files(i):
            if os.path.basename(p).startswith(want):
                return p
        return None

    def drop_block(self, i: int, h: Hash) -> bool:
        """Silently delete a block file (disk losing data without the
        node noticing — the scrub/resync machinery must detect it)."""
        p = self._find(i, h)
        if p is None:
            return False
        os.remove(p)
        return True

    def corrupt_block(self, i: int, h: Hash, at: int = 100) -> bool:
        """Flip one byte of a stored block (silent bitrot; scrub must
        catch it by content hash, never serve it)."""
        p = self._find(i, h)
        if p is None:
            return False
        with open(p, "r+b") as f:
            f.seek(at)
            b = f.read(1)
            f.seek(at)
            f.write(bytes([(b[0] if b else 0) ^ 0xFF]))
        return True


async def crash_heaviest_and_drop(inj: FaultInjector, skip=(0,),
                                  resync_workers: int = 4):
    """Shared repair-storm opener (bench --repair-storm-phase and
    scripts/chaos.py repair_storm): crash the heaviest data holder not
    in `skip` (typically the gateway), drop it from the committed
    layout, hand every survivor the new ring and a raised resync worker
    count.  Returns (victim_index, lost_bytes, survivors) — the heal
    itself is the product's own layout-sweep/resync path, which the
    callers then observe in their own ways."""
    from ..rpc.layout import ClusterLayout

    garages = inj.garages
    sizes = []
    for i in range(len(garages)):
        if i in skip or i in inj.dead:
            continue
        n = sum(os.path.getsize(p) for p in inj._block_files(i))
        sizes.append((n, i))
    lost, victim = max(sizes)
    await inj.crash(victim)
    # inj.dead includes the new victim AND any earlier casualties — a
    # second storm on the same injector must not touch closed nodes
    src = next(g for i, g in enumerate(garages) if i not in inj.dead)
    lay = ClusterLayout.decode(src.system.layout.encode())
    lay.stage_role(bytes(garages[victim].system.id), None)
    lay.apply_staged_changes()
    enc = lay.encode()
    survivors = []
    for i, g in enumerate(garages):
        if i in inj.dead:
            continue
        g.system.layout = ClusterLayout.decode(enc)
        g.system._rebuild_ring()
        g.block_resync.set_n_workers(resync_workers)
        survivors.append(g)
    return victim, lost, survivors
