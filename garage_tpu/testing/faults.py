"""Reusable fault injector (VERDICT r3 #7; SURVEY §5 aux subsystem).

The reference validates durability claims with cluster benchmarks under
"2 simulated node failures" (ref doc/book/design/benchmarks) but ships
no reusable rig; here the rig is in-tree: one object that can crash and
revive nodes of an in-process cluster and drop/corrupt chosen blocks on
disk, used by tests (generalizing the ad-hoc node kills in
tests/test_integration.py) and by bench.py's degraded-mode phase.

Crash semantics: `crash()` is abrupt — transport closed and workers
cancelled with NO graceful drains (a dying node doesn't flush its
write-time parity accumulator).  `revive()` rebuilds a Garage from the
same config/dirs, the crash-consistency path real restarts take —
meaningful only for persistent db engines (sqlite/native), not
"memory".
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..utils.data import Hash


class FaultInjector:
    """Faults over a list of in-process Garage nodes."""

    def __init__(self, garages: List, configs: Optional[List] = None):
        self.garages = list(garages)
        self.configs = list(configs) if configs else [
            g.config for g in garages]
        self.dead: set = set()

    # --- node faults ---

    async def crash(self, i: int) -> None:
        """Abrupt node death: close the transport and cancel workers,
        skipping every graceful-drain step of Garage.shutdown()."""
        g = self.garages[i]
        await g.bg.shutdown(timeout=0.5)
        await g.system.shutdown()
        if g._owns_db:
            g.db.close()
        self.dead.add(i)

    async def revive(self, i: int, peers: Optional[List[str]] = None):
        """Restart node i from its on-disk state; returns the new Garage.
        `peers` = "host:port" addresses to reconnect to (defaults to the
        rpc_public_addr of every live node)."""
        from ..model import Garage

        assert i in self.dead, f"node {i} is not dead"
        g = Garage(self.configs[i])
        await g.system.netapp.listen(self.configs[i].rpc_bind_addr)
        port = g.system.netapp._server.sockets[0].getsockname()[1]
        g.config.rpc_public_addr = f"127.0.0.1:{port}"
        if peers is None:
            peers = [
                self.garages[j].config.rpc_public_addr
                for j in range(len(self.garages))
                if j != i and j not in self.dead
            ]
        for addr in peers:
            try:
                await g.system.netapp.connect(addr)
            except Exception:
                pass  # peer may be down; the peering loop keeps trying
        for j, other in enumerate(self.garages):
            if j != i and j not in self.dead:
                other.system.peering.add_peer(
                    g.config.rpc_public_addr, g.system.id)
                g.system.peering.add_peer(
                    other.config.rpc_public_addr, other.system.id)
        # adopt the cluster's layout from any live node
        for j, other in enumerate(self.garages):
            if j != i and j not in self.dead:
                from ..rpc.layout import ClusterLayout

                g.system.layout = ClusterLayout.decode(
                    other.system.layout.encode())
                g.system._rebuild_ring()
                break
        g.spawn_workers()
        g.system.peering.start()
        self.garages[i] = g
        self.dead.discard(i)
        return g

    # --- block faults ---

    def _block_files(self, i: int) -> List[str]:
        dd = self.configs[i].data_dir  # [{"path": ..., ...}, ...]
        roots = [d["path"] if isinstance(d, dict) else str(d) for d in dd] \
            if isinstance(dd, list) else [str(dd)]
        out = []
        for root in roots:
            for dirpath, _dirs, files in os.walk(root):
                if "parity" in dirpath.split(os.sep):
                    continue
                for f in files:
                    if not f.endswith((".par", ".tmp", ".corrupted")):
                        out.append(os.path.join(dirpath, f))
        return out

    def list_blocks(self, i: int) -> List[Hash]:
        out = []
        for p in self._block_files(i):
            name = os.path.basename(p).split(".")[0]
            try:
                out.append(Hash(bytes.fromhex(name)))
            except ValueError:
                continue
        return out

    def _find(self, i: int, h: Hash) -> Optional[str]:
        want = bytes(h).hex()
        for p in self._block_files(i):
            if os.path.basename(p).startswith(want):
                return p
        return None

    def drop_block(self, i: int, h: Hash) -> bool:
        """Silently delete a block file (disk losing data without the
        node noticing — the scrub/resync machinery must detect it)."""
        p = self._find(i, h)
        if p is None:
            return False
        os.remove(p)
        return True

    def corrupt_block(self, i: int, h: Hash, at: int = 100) -> bool:
        """Flip one byte of a stored block (silent bitrot; scrub must
        catch it by content hash, never serve it)."""
        p = self._find(i, h)
        if p is None:
            return False
        with open(p, "r+b") as f:
            f.seek(at)
            b = f.read(1)
            f.seek(at)
            f.write(bytes([(b[0] if b else 0) ^ 0xFF]))
        return True
