"""Synthetic-link device codec — the hybrid crossover test backend.

The production TPU sits behind a bandwidth-metered tunnel that has never
sustained a rate above the hybrid gate threshold during a bench window
(BENCH_r03/r04: tpu_frac 0.0 with the gate correctly holding).  To prove
the hybrid's claimed steady-state model

    total ≈ cpu_rate + min(link_rate, device_rate)

and the gate behavior on BOTH sides of the threshold, this backend
stands in for TpuCodec with a CONFIGURABLE link: transfers are modeled
as sleeps (which release the GIL exactly like a real DMA leaves the CPU
free for the verify thread), and the probe hook reports the configured
rate so the gate decision is deterministic.

Two modes:
  - compute_real=False (timing mode): verification results are
    synthesized (the caller's hashes are trusted), so the backend
    consumes NO host CPU — the sleep is the entire cost, making the
    throughput model measurable on a 1-core host.  Only valid for
    fetch_parity=False flows.
  - compute_real=True (identity mode): results come from a real
    CpuCodec, so bit-identity of the hybrid merge/split machinery can
    be asserted through the probe/gate path.  Costs host CPU; timing
    is not meaningful on a 1-core host.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from ..ops.codec import CodecParams
from ..ops.cpu_codec import CpuCodec
from ..utils.data import Hash


class SyntheticLinkCodec:
    """TpuCodec stand-in with a modeled host→device link."""

    def __init__(self, params: CodecParams, link_gibs: float,
                 device_gibs: float = float("inf"),
                 fixed_latency_s: float = 0.0,
                 compute_real: bool = False):
        self.params = params
        self.link_gibs = link_gibs
        self.device_gibs = device_gibs
        self.fixed_latency_s = fixed_latency_s
        self.compute_real = compute_real
        self.cpu: Optional[CpuCodec] = (
            CpuCodec(params) if compute_real else None)
        self.submissions = 0
        self.bytes_submitted = 0

    # --- hooks the hybrid engine looks for ---

    def probe_link(self, nbytes: int) -> float:
        """The hybrid probe hook: the measured link rate, with the
        probe's own transfer time modeled."""
        time.sleep(min(nbytes / (self.link_gibs * 2**30), 0.05))
        return self.link_gibs

    def warm_scrub(self, nblocks: int, nbytes: int) -> None:
        pass  # nothing to compile

    def _batch_size(self, n: int) -> int:
        return max(n, 1)

    # --- submission ---

    def scrub_submit(self, blocks: Sequence[bytes],
                     hashes: Sequence[Hash]):
        nbytes = sum(len(b) for b in blocks)
        self.submissions += 1
        self.bytes_submitted += nbytes
        dt = self.fixed_latency_s + nbytes / (self.link_gibs * 2**30)
        if self.device_gibs != float("inf"):
            dt += nbytes / (self.device_gibs * 2**30)
        time.sleep(dt)
        if self.compute_real:
            ok = self.cpu.batch_verify(blocks, hashes)
            parity = self.cpu.rs_encode_blocks(blocks)
            return ok, parity, len(blocks)
        # timing mode: the caller's hashes are trusted correct-by-
        # construction; parity is None (fetch_parity=False flows only)
        return np.ones((len(blocks),), dtype=bool), None, len(blocks)
