"""Synthetic-link device codec — the hybrid crossover test backend.

The production TPU sits behind a bandwidth-metered tunnel that has never
sustained a rate above the hybrid gate threshold during a bench window
(BENCH_r03/r04: tpu_frac 0.0 with the gate correctly holding).  To prove
the hybrid's claimed steady-state model

    total ≈ cpu_rate + min(link_rate, device_rate)

and the gate behavior on BOTH sides of the threshold, this backend
stands in for TpuCodec with a CONFIGURABLE link: transfers are modeled
as sleeps (which release the GIL exactly like a real DMA leaves the CPU
free for the verify thread), and the probe hook reports the configured
rate so the gate decision is deterministic.

Two modes:
  - compute_real=False (timing mode): verification results are
    synthesized (the caller's hashes are trusted), so the backend
    consumes NO host CPU — the sleep is the entire cost, making the
    throughput model measurable on a 1-core host.  Only valid for
    fetch_parity=False flows.
  - compute_real=True (identity mode): results come from a real
    CpuCodec, so bit-identity of the hybrid merge/split machinery can
    be asserted through the probe/gate path.  Costs host CPU; timing
    is not meaningful on a 1-core host.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from ..ops.codec import CodecParams
from ..ops.cpu_codec import CpuCodec
from ..utils.data import Hash


def _wait_until(ready: float) -> None:
    dt = ready - time.monotonic()
    if dt > 0:
        time.sleep(dt)


class _Lazy:
    """Async-device result handle: np.asarray() blocks until the modeled
    link has delivered the submission (TpuCodec's device arrays behave
    the same way — sync happens at materialization)."""

    __slots__ = ("value", "ready")

    def __init__(self, value, ready: float):
        self.value = value
        self.ready = ready

    def __array__(self, dtype=None, copy=None):
        _wait_until(self.ready)
        out = np.asarray(self.value)
        return out.astype(dtype) if dtype is not None else out


class SyntheticLinkCodec:
    """TpuCodec stand-in with a modeled host→device link."""

    def __init__(self, params: CodecParams, link_gibs: float,
                 device_gibs: float = float("inf"),
                 fixed_latency_s: float = 0.0,
                 compute_real: bool = False,
                 compile_s: float = 0.0):
        self.params = params
        self.link_gibs = link_gibs
        self.device_gibs = device_gibs
        self.fixed_latency_s = fixed_latency_s
        self.compute_real = compute_real
        # modeled XLA compile: the FIRST array-level submission of each
        # (kind, shape) sleeps this long between adoption and dispatch
        # return, so the LinkProfiler's cold-call `compile` vs
        # steady-state `dispatch` split is deterministically testable
        self.compile_s = compile_s
        # LinkProfiler boundary stamps — the same contract TpuCodec
        # publishes (ops/link_profiler.py): the transport clears these
        # before submit/collect and reads them after
        self.last_adopt_ns = 0
        self.last_ready_ns = 0
        self.last_submit_compiled = False
        self._dispatched_shapes = set()
        self.last_probe_stages = None
        self.cpu: Optional[CpuCodec] = (
            CpuCodec(params) if compute_real else None)
        self.submissions = 0
        self.bytes_submitted = 0
        # transport A/B attribution: the bytes-level path (scrub_submit)
        # models the retired serialize+copy link — each block pays a
        # pack copy plus a transfer-serialize copy, exactly what the
        # real bytes-level TpuCodec path did; array-level submissions
        # arrive pre-staged (the transport's single copy is counted on
        # the transport's own meter, not here)
        self.host_copies = 0
        self.blocks_submitted = 0
        self.array_submissions = 0

    def _codec(self) -> CpuCodec:
        """identity-mode math on demand: the array-level transport API
        always computes real results (the transport's bit-identity is
        the thing under test), even when the bytes-level path runs in
        timing mode."""
        if self.cpu is None:
            self.cpu = CpuCodec(self.params)
        return self.cpu

    def _link_sleep(self, nbytes: int) -> None:
        # the link is ONE serial resource: concurrent callers reserve
        # windows on it and wait their own out, so two threads pushing
        # bytes cost the sum of their transfers, not the max — without
        # this, any caller-side threading would fake link bandwidth
        _wait_until(self._link_ready_at(nbytes))

    # --- hooks the hybrid engine looks for ---

    def probe_link(self, nbytes: int) -> float:
        """The hybrid probe hook: the measured link rate, with the
        probe's own transfer time modeled.  Publishes a per-stage
        breakdown (`last_probe_stages`) summing to the measured probe
        wall exactly — the modeled transfer is all device-busy time, so
        it lands in `compute` — which HybridCodec attaches to its gate
        probe events (ISSUE 16)."""
        t0 = time.monotonic()
        time.sleep(min(nbytes / (self.link_gibs * 2**30), 0.05))
        dt = time.monotonic() - t0
        self.last_probe_stages = {
            "stage_copy": 0.0, "adopt": 0.0, "dispatch": 0.0,
            "compute": round(dt, 9), "collect": 0.0}
        return self.link_gibs

    def warm_scrub(self, nblocks: int, nbytes: int) -> None:
        pass  # nothing to compile

    def _batch_size(self, n: int) -> int:
        return max(n, 1)

    # --- submission ---

    def scrub_submit(self, blocks: Sequence[bytes],
                     hashes: Sequence[Hash]):
        nbytes = sum(len(b) for b in blocks)
        self.submissions += 1
        self.bytes_submitted += nbytes
        self.blocks_submitted += len(blocks)
        self.host_copies += 2 * len(blocks)  # pack + transfer-serialize
        self._link_sleep(nbytes)
        if self.compute_real:
            ok = self.cpu.batch_verify(blocks, hashes)
            parity = self.cpu.rs_encode_blocks(blocks)
            return ok, parity, len(blocks)
        # timing mode: the caller's hashes are trusted correct-by-
        # construction; parity is None (fetch_parity=False flows only)
        return np.ones((len(blocks),), dtype=bool), None, len(blocks)

    # --- bytes-level ragged API (the LEGACY serialize+copy path) ---
    #
    # What HybridCodec routed feeder batches through before the
    # DeviceTransport: every block repacked (pack copy) and pushed over
    # the modeled link (transfer-serialize copy).  Kept as the "old"
    # side of the transport A/B (bench --transport-phase).

    def _bytes_level(self, nblocks: int, nbytes: int) -> None:
        self.submissions += 1
        self.bytes_submitted += nbytes
        self.blocks_submitted += nblocks
        self.host_copies += 2 * nblocks   # pack + transfer-serialize
        self._link_sleep(nbytes)

    def hash_ragged(self, groups):
        flat = [b for g in groups for b in g]
        self._bytes_level(len(flat), sum(len(b) for b in flat))
        return self._codec().hash_ragged(groups)

    def rs_encode_ragged(self, groups):
        flat = [b for g in groups for b in g]
        self._bytes_level(len(flat), sum(len(b) for b in flat))
        return self._codec().rs_encode_ragged(groups)

    def rs_reconstruct_ragged(self, items):
        rows = sum(int(sh.shape[0]) for sh, _p, _r in items)
        self._bytes_level(rows, sum(int(sh.nbytes)
                                    for sh, _p, _r in items))
        return self._codec().rs_reconstruct_ragged(items)

    def scrub_ragged(self, items):
        out = []
        for blocks, hashes, fetch_parity in items:
            ok, parity, _n = self.scrub_submit(blocks, hashes)
            out.append((ok, parity if fetch_parity else None))
        return out

    # --- the transport device API (ops/transport.py) ---
    #
    # Array-level entry points consuming the transport's staged buffers
    # directly.  Unlike the bytes-level path, these model an ASYNC
    # device: submit computes the result (real CpuCodec math, so the
    # transport's merge/split machinery is bit-identity-testable) and
    # returns a LAZY handle whose materialization blocks until the
    # modeled link — a serial resource, like a real DMA engine — has
    # "delivered" the bytes.  That is what lets the transport's double
    # buffering show its overlap: batch N+1 stages and submits while
    # batch N's transfer window elapses.

    def _link_ready_at(self, nbytes: int) -> float:
        dt = self.fixed_latency_s + nbytes / (self.link_gibs * 2**30)
        if self.device_gibs != float("inf"):
            dt += nbytes / (self.device_gibs * 2**30)
        now = time.monotonic()
        start = max(now, getattr(self, "_link_busy_until", 0.0))
        self._link_busy_until = start + dt
        return self._link_busy_until

    def staging_geometry(self, nlanes: int, maxlen: int, kind: str):
        k = max(1, self.params.rs_data)
        if kind in ("scrub", "encode"):
            nlanes += (-nlanes) % k
        return max(nlanes, 1), max(maxlen, 1)

    def _rows_bytes(self, arr: np.ndarray, lengths: np.ndarray):
        return [arr[i, :n].tobytes() for i, n in enumerate(lengths)]

    def _mark_adopt(self, kind: str, shape) -> None:
        """LinkProfiler stamp: adoption boundary + compile-vs-dispatch
        verdict, with the modeled compile (cold (kind, shape)) slept
        AFTER the adopt stamp so it attributes to `compile`."""
        self.last_adopt_ns = time.monotonic_ns()
        key = (kind, tuple(shape))
        self.last_submit_compiled = key not in self._dispatched_shapes
        self._dispatched_shapes.add(key)
        if self.last_submit_compiled and self.compile_s > 0:
            time.sleep(self.compile_s)

    def _mark_ready(self, ready: float) -> None:
        _wait_until(ready)
        self.last_ready_ns = time.monotonic_ns()

    def probe_submit(self, arr: np.ndarray):
        # async like the real device: the modeled transfer elapses
        # between submit-return and collect, so the transport probe's
        # stage breakdown attributes it to `compute`, not `dispatch`
        self._mark_adopt("probe", arr.shape)
        dt = min(arr.nbytes / (self.link_gibs * 2**30), 0.05)
        return _Lazy(int(arr.sum(dtype=np.uint32)),
                     time.monotonic() + dt)

    def probe_collect(self, handle) -> int:
        self._mark_ready(handle.ready)
        return int(np.asarray(handle))

    def hash_submit(self, arr: np.ndarray, lengths: np.ndarray):
        self.array_submissions += 1
        self.bytes_submitted += int(lengths.sum())
        self._mark_adopt("hash", arr.shape)
        ready = self._link_ready_at(int(lengths.sum()))
        return ready, self._codec().batch_hash(
            self._rows_bytes(arr, lengths))

    def hash_collect(self, handle, n: int):
        ready, digs = handle
        self._mark_ready(ready)
        return digs[:n]

    def _scrub_math(self, arr: np.ndarray, lengths: np.ndarray,
                    expected: np.ndarray, ready: float):
        """The fused scrub kernel body (real CpuCodec math): verify
        EVERY lane against its expected digest — pool-served lanes
        included, which is what makes every pool read hash-verified —
        plus RS parity per k-lane codeword."""
        codec = self._codec()
        digs = codec.batch_hash(self._rows_bytes(arr, lengths))
        ok = np.array(
            [bytes(d) == np.asarray(e, dtype="<u4").tobytes()
             for d, e in zip(digs, np.asarray(expected))], dtype=bool)
        k = self.params.rs_data
        parity = None
        if k > 0:
            groups = np.ascontiguousarray(arr).reshape(
                arr.shape[0] // k, k, arr.shape[1])
            parity = codec.rs_encode(groups)
        return None, _Lazy(ok, ready), int((~ok).sum()), \
            (_Lazy(parity, ready) if parity is not None else None)

    def scrub_encode_submit(self, arr: np.ndarray, lengths: np.ndarray,
                            expected: np.ndarray):
        self.array_submissions += 1
        self.bytes_submitted += int(lengths.sum())
        self._mark_adopt("scrub", arr.shape)
        ready = self._link_ready_at(int(lengths.sum()))
        return self._scrub_math(arr, lengths, expected, ready)

    def scrub_collect(self, out, fetch_parity: bool):
        _h, ok, _bad, parity = out
        self._mark_ready(ok.ready)
        return np.asarray(ok), (np.asarray(parity) if fetch_parity
                                and parity is not None else None)

    # --- the DevicePool API (ops/device_pool.py) ---
    #
    # Pool-aware scrub: only MISS lanes cross the modeled link (the
    # link sleep charges their lengths alone — a warm batch of all
    # hits pays zero link time, which is exactly the speedup the A/B
    # bench measures); resident lanes are composed from pool pages
    # device-side.  The full composed batch then runs the SAME fused
    # kernel as the plain path, so pool-served lanes are re-verified
    # against their expected digests on every read.

    def scrub_encode_submit_resident(self, miss_arr: np.ndarray,
                                     miss_rows, lengths: np.ndarray,
                                     expected: np.ndarray, resident):
        lanes = int(lengths.shape[0])
        cols = int(miss_arr.shape[1])
        miss_bytes = int(sum(int(lengths[r]) for r in miss_rows))
        self.array_submissions += 1
        self.bytes_submitted += miss_bytes
        self._mark_adopt("scrub", (lanes, cols))
        ready = self._link_ready_at(miss_bytes)
        # device-side composition: zeros (gap/pad lanes verify against
        # the empty digest), scattered miss uploads, pool-page lanes
        full = np.zeros((lanes, cols), dtype=np.uint8)
        for ci, r in enumerate(miss_rows):
            full[r] = miss_arr[ci]
        for r, pages, length in resident:
            row = np.concatenate([np.asarray(p) for p in pages])[:length]
            full[int(r), :int(length)] = row
        return self._scrub_math(full, lengths, expected, ready), full

    def pool_adopt(self, input_ref, lane: int, length: int,
                   page_bytes: int):
        """Slice one verified lane of a resident-submitted batch into
        fixed-size device pages (tail zero-padded) — a device-side
        copy, ZERO link bytes, so adoption never shows up on the
        transport's staging meter."""
        full = input_ref
        assert full is not None, "adoption needs a resident-path input"
        npages = max(1, -(-int(length) // int(page_bytes)))
        buf = np.zeros((npages * int(page_bytes),), dtype=np.uint8)
        buf[:int(length)] = full[int(lane), :int(length)]
        return [buf[i * int(page_bytes):(i + 1) * int(page_bytes)].copy()
                for i in range(npages)]

    def pool_read(self, pages, length: int) -> bytes:
        """D2H readback of a pooled block (tests/smoke only — the data
        path never reads pages back to the host), trimmed to the
        ragged tail."""
        return np.concatenate(
            [np.asarray(p) for p in pages])[:int(length)].tobytes()

    def encode_submit(self, groups: np.ndarray):
        self.array_submissions += 1
        self.bytes_submitted += int(groups.nbytes)
        self._mark_adopt("encode", groups.shape)
        ready = self._link_ready_at(int(groups.nbytes))
        return _Lazy(self._codec().rs_encode(
            np.ascontiguousarray(groups)), ready)

    def encode_collect(self, handle) -> np.ndarray:
        self._mark_ready(handle.ready)
        return np.asarray(handle)

    def decode_submit(self, shards: np.ndarray, present,
                      rows=None):
        self.array_submissions += 1
        self.bytes_submitted += int(shards.nbytes)
        self._mark_adopt("decode", shards.shape)
        ready = self._link_ready_at(int(shards.nbytes))
        return _Lazy(self._codec().rs_reconstruct(shards, present, rows),
                     ready)

    def decode_collect(self, handle) -> np.ndarray:
        self._mark_ready(handle.ready)
        return np.asarray(handle)
