"""BlockRc — per-block reference counts with delayed deletion.

Equivalent of reference src/block/rc.rs: the `block_local_rc` tree maps
hash → RcEntry, one of Present{count}, Deletable{at_time} (count fell to
zero: the block may be deleted after BLOCK_GC_DELAY) or Absent
(rc.rs:11-70).  Increments/decrements run inside the metadata update
transaction so the block layer and metadata can't diverge
(ref model/s3/block_ref_table.rs:65-81 calls these from `updated()`).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..db import Transaction, Tree
from ..utils.crdt import now_msec
from ..utils.data import Hash
from ..utils.migrate import pack, unpack

BLOCK_GC_DELAY_MS = 10 * 60 * 1000  # ref block/manager.rs:54 (10 min)


class RcEntry:
    """Present{count} | Deletable{at_time} | Absent (ref rc.rs:75-178)."""

    __slots__ = ("count", "at_time")

    def __init__(self, count: int = 0, at_time: Optional[int] = None):
        self.count = count
        self.at_time = at_time

    @classmethod
    def parse(cls, v: Optional[bytes]) -> "RcEntry":
        if v is None:
            return cls(0, None)  # Absent
        count, at_time = unpack(v)
        return cls(count, at_time)

    def serialize(self) -> Optional[bytes]:
        if self.count == 0 and self.at_time is None:
            return None  # Absent: entry removed
        return pack([self.count, self.at_time])

    def increment(self) -> "RcEntry":
        return RcEntry(self.count + 1, None)

    def decrement(self) -> "RcEntry":
        c = max(0, self.count - 1)
        if c == 0:
            return RcEntry(0, now_msec() + BLOCK_GC_DELAY_MS)
        return RcEntry(c, None)

    def is_deletable(self) -> bool:
        return self.count == 0 and (
            self.at_time is None or self.at_time < now_msec()
        )

    def is_zero(self) -> bool:
        return self.count == 0

    def is_needed(self) -> bool:
        return self.count > 0


class BlockRc:
    def __init__(self, tree: Tree):
        self.tree = tree

    def block_incref(self, tx: Transaction, h: Hash) -> bool:
        """Returns True if the block became needed (0→1), i.e. the caller
        should trigger a resync to fetch it (ref rc.rs:75-104)."""
        old = RcEntry.parse(tx.get(self.tree, bytes(h)))
        new = old.increment()
        tx.insert(self.tree, bytes(h), new.serialize())
        return old.is_zero()

    def block_decref(self, tx: Transaction, h: Hash) -> bool:
        """Returns True if the count fell to zero (deletion timer armed) —
        the caller should queue a resync at the deletion time
        (ref rc.rs:106-133)."""
        old = RcEntry.parse(tx.get(self.tree, bytes(h)))
        new = old.decrement()
        s = new.serialize()
        if s is None:
            tx.remove(self.tree, bytes(h))
        else:
            tx.insert(self.tree, bytes(h), s)
        return new.is_zero()

    def get(self, h: Hash) -> RcEntry:
        return RcEntry.parse(self.tree.get(bytes(h)))

    def clear_deleted_block_rc(self, h: Hash) -> None:
        """Remove a Deletable entry whose timer expired and whose block was
        deleted (ref rc.rs:135-158)."""

        def txn(tx: Transaction):
            ent = RcEntry.parse(tx.get(self.tree, bytes(h)))
            if ent.is_zero() and ent.at_time is not None and ent.at_time < now_msec():
                tx.remove(self.tree, bytes(h))

        self.tree.db.transaction(txn)

    def clear_stray_rc(self, h: Hash) -> None:
        """Remove a zero-count entry regardless of its timer — migration
        cleanup after drop_stray_copy, where the timer's grace serves no
        purpose (the ring no longer assigns this node the block and every
        owner confirmed possession).  A concurrent incref vetoes."""

        def txn(tx: Transaction):
            ent = RcEntry.parse(tx.get(self.tree, bytes(h)))
            if ent.is_zero():
                tx.remove(self.tree, bytes(h))

        self.tree.db.transaction(txn)

    def rc_len(self) -> int:
        return len(self.tree)

    def items(self, start: Optional[bytes] = None):
        return self.tree.items(start)

    def get_gt(self, key: bytes) -> Optional[Tuple[bytes, bytes]]:
        return self.tree.get_gt(key)
