"""BlockManager — content-addressed block storage + streaming block RPC.

Equivalent of reference src/block/manager.rs (SURVEY.md §2.5):
  - local storage: write_block (tmp file + rename + optional fsync incl.
    dir fsync, dedupe against existing copy, manager.rs:689-784), read_block
    with verify (corruption → rename `.corrupted` + immediate resync
    requeue, manager.rs:528-590), find_block across dirs and compression
    states (manager.rs:608-643).
  - RPC: rpc_get_block(_streaming) tries replicas in latency order with a
    per-node timeout then moves on (manager.rs:231-317); rpc_put_block
    compresses then quorum-writes via try_call_many (manager.rs:356-377).
  - 256-way sharded mutation locks (manager.rs:115) serialize writes to the
    same block without a global lock.

TPU-first: read-path verify goes through `codec.verify_one` — defined by
default in terms of the same batch_verify the scrub path uses (the TPU
codec overrides it with a bit-identical host hash so single reads never
pay a device roundtrip; batched scrub/resync still run on device).
"""

from __future__ import annotations

import asyncio
import errno as _errno
import logging
import os
from typing import AsyncIterator, List, Optional, Tuple

from ..db import Db
from ..net.frame import PRIO_BACKGROUND, PRIO_NORMAL
from ..rpc.system import System
from ..utils.crdt import now_msec
from ..utils.data import FixedBytes32, Hash, block_hash
from ..utils.error import (
    CorruptData,
    GarageError,
    NoSuchBlock,
    StorageError,
    StorageFull,
)
from ..utils.metrics import maybe_time
from ..utils.persister import Persister
from .block import DataBlock, DataBlockHeader
from .health import (DISK_STATE_VALUES, DiskHealthMonitor, DiskIo,
                     is_media_error, janitor_pass)
from .layout import DataLayout
from .rc import BlockRc

logger = logging.getLogger("garage_tpu.block.manager")

INLINE_THRESHOLD = 3072       # ref manager.rs:49
BLOCK_RW_TIMEOUT = 60.0
MUTEX_SHARDS = 256            # ref manager.rs:115
STREAM_CHUNK = 256 * 1024


class BlockManager:
    def __init__(
        self,
        config,
        db: Db,
        system: System,
        replication,            # TableShardedReplication for data partitions
        codec=None,
    ):
        self.config = config
        self.db = db
        self.system = system
        self.replication = replication
        # the codec gets the System's registry/tracer: per-stage
        # histograms, bytes-by-side counters and the gate-decision ring
        # become node-visible (/metrics, admin codec info/events) —
        # through round 5 the ops/ layer recorded nothing anywhere
        self.codec = codec or config.codec.make(
            config.compression_level,
            metrics=getattr(system, "metrics", None),
            tracer=getattr(system, "tracer", None),
            block_size=config.block_size,
        )
        self.hash_algo = config.codec.hash_algo
        self.compression_level = config.compression_level
        self.data_fsync = config.data_fsync
        # continuous-batching feeder for the FOREGROUND data path: PUT
        # block-id hashing (api/s3/put.py), write-time RS encodes
        # (block/parity.py WriteParityAccumulator) and degraded-read RS
        # decodes (ParityStore / model/parity_repair.py) submit here and
        # coalesce into ragged codec batches — K concurrent puts pay ~one
        # batched dispatch instead of K serial codec passes (ops/feeder.py)
        self.feeder = None
        if getattr(config.codec, "feeder", True):
            from ..ops.feeder import CodecFeeder

            self.feeder = CodecFeeder(
                self.codec,
                slo_ms=getattr(config.codec, "feeder_slo_ms", 2.0),
                max_batch_blocks=getattr(
                    config.codec, "feeder_max_batch_blocks", 256),
                metrics=getattr(system, "metrics", None),
                observer=self.codec.obs,
            )
        # static block-transfer timeout ([rpc].block_rpc_timeout): the
        # ceiling/fallback the adaptive per-peer layer clamps against
        # (used to be the hardcoded BLOCK_RW_TIMEOUT literal everywhere)
        rpc_cfg = getattr(config, "rpc", None)
        self.block_rpc_timeout = (
            rpc_cfg.block_rpc_timeout if rpc_cfg is not None
            else BLOCK_RW_TIMEOUT
        )

        # multi-drive layout, persisted (ref manager.rs:122-160)
        self._layout_persister = Persister(
            config.metadata_dir, "data_layout", DataLayout
        )
        saved = self._layout_persister.load()
        if saved is None:
            self.data_layout = DataLayout.initialize(config.data_dir)
            self._layout_persister.save(self.data_layout)
        elif saved.config_changed(config.data_dir):
            self.data_layout = saved.update(config.data_dir)
            self._layout_persister.save(self.data_layout)
        else:
            self.data_layout = saved
        for d in self.data_layout.data_dirs:
            os.makedirs(d.path, exist_ok=True)

        # the filesystem boundary: every byte this manager moves to or
        # from disk goes through self.disk, so storage faults inject at
        # exactly one seam (testing/faults.py FaultyDisk wraps it)
        self.disk = DiskIo()
        # per-root busy-seconds attribution (USE utilization): DiskIo
        # accumulates I/O wall time keyed by the root this hook maps
        # each path to
        self.disk.root_of = self._root_of
        # per-hash local-read error backoff (a bad sector must not be
        # re-hit by every read of a hot block while peers can serve it);
        # reuses the resync ErrorCounter schedule
        self._disk_errors: dict = {}
        m0 = getattr(system, "metrics", None)
        # per-data-root ok → degraded(read-only) → failed state machine:
        # free-space watermark preflight + disk-error streaks through
        # the RPC layer's CircuitBreaker (block/health.py).  statvfs is
        # routed through self.disk via a late-bound closure so a fault
        # wrapper installed later is honored.
        self.health = DiskHealthMonitor(
            [d.path for d in self.data_layout.data_dirs],
            watermark=getattr(config, "data_free_space_watermark", 128 << 20),
            error_threshold=getattr(config, "disk_error_threshold", 8),
            cooldown=getattr(config, "disk_error_cooldown", 30.0),
            statvfs=lambda p: self.disk.statvfs(p),
            counter=(m0.counter(
                "disk_error_total",
                "Disk I/O errors at the block store boundary, by "
                "operation and errno kind") if m0 is not None else None),
        )
        # gossiped next to the statvfs numbers so peers' `cluster stats`
        # show a remote node going read-only (rpc/system.py NodeStatus)
        system.disk_state_fn = self.health.worst_state
        self.quarantined = 0          # copies moved aside as .corrupted
        self.quarantine_errors = 0    # quarantine renames that failed

        self.rc = BlockRc(db.open_tree("block_local_rc"))
        # node-local record of which stored blocks are distributed-parity
        # shards: the is_parity RPC flag is transient, but resync
        # refetches and offload transfers must not feed parity back into
        # the accumulators (parity-of-parity cascade)
        self._parity_marks = db.open_tree("block_parity_marks")
        self._locks = [asyncio.Lock() for _ in range(MUTEX_SHARDS)]

        self.endpoint = system.netapp.endpoint("garage/block")
        self.endpoint.set_handler(self._handle)

        # attached after construction (circular dep): BlockResyncManager
        self.resync = None
        self._heal_tasks: set = set()       # post-decode write-backs
        self._heal_in_flight: set = set()   # hashes with a heal running
        self._heals_closed = False          # set by drain_heals()
        # attached by Garage when RS parity sidecars are enabled
        self.parity_store = None
        # attached by Garage when codec.parity_on_write is also enabled:
        # locally-stored blocks join write-time codewords → LOCAL sidecars
        self.write_parity = None
        # attached by Garage when codec.parity_distribute is enabled:
        # blocks THIS node writes into the cluster join distinct-node
        # codewords whose parity is distributed cross-node
        self.ec_accumulator = None
        # async h -> plain bytes | None, decoding from cross-node pieces
        self.parity_reconstructor = None
        self.blocks_reconstructed = 0
        # bandwidth-minimal degraded-read fetch planner (exact-k survivor
        # selection + partial-parallel repair, block/repair_plan.py);
        # None keeps the legacy sweep-everything gather
        self.repair_planner = None
        if (getattr(config.codec, "repair_planner", True)
                and config.codec.rs_data > 0):
            from .repair_plan import RepairPlanner

            hedge_ms = getattr(config.codec, "repair_hedge_ms", 0.0) or 0.0
            self.repair_planner = RepairPlanner(
                self,
                use_ppr=getattr(config.codec, "repair_ppr", True),
                hedge_delay=(hedge_ms / 1000.0) if hedge_ms > 0 else None,
                use_tree=getattr(config.codec, "repair_tree", True),
                tree_fanout=getattr(config.codec, "repair_tree_fanout", 4),
            )

        # metrics counters (ref block/metrics.rs:7-127)
        self.bytes_read = 0
        self.bytes_written = 0
        self.corruptions = 0
        # heal attribution (round-5 VERDICT: the claimed heal speedup
        # turned out to be the bench's own fallback kick — which heal
        # path actually fired must be a counter, not an inference):
        # source ∈ {writeback, resync_fetch, peer_sweep,
        # distributed_decode, local_sidecar}
        self.heal_counts: dict = {}
        # repair-bandwidth accounting (block/repair_plan.py + the legacy
        # gather in model/parity_repair.py): wire bytes fetched per
        # reconstruction mode, bytes of repaired rows produced, fetched
        # bytes that ended up unused, hedged replacement fetches, and
        # PPR requests that fell back to whole-shard (mixed-version /
        # missing-piece peers).  Plain attributes so bench/chaos read
        # them without a metrics registry.
        self.repair_fetch_bytes: dict = {
            "ppr": 0, "shard": 0, "gather": 0, "tree": 0}
        self.repair_repaired_bytes = 0
        self.repair_overfetch_bytes = 0
        self.repair_hedges = 0
        self.repair_ppr_fallbacks = 0
        # re-plans by reason (survivor_died / mid_tree / version_demote /
        # tree_abort) and the depth of the last aggregation tree served
        # or planned here — chaos/bench read the plain attrs.
        self.repair_replans: dict = {}
        self.repair_tree_depth_last = 0
        m = getattr(system, "metrics", None)
        if m is not None:
            m.gauge("block_compression_level", "Configured zstd level",
                    fn=lambda: self.compression_level or 0)
            m.gauge("block_rc_entries", "Refcounted block entries",
                    fn=self.rc_len)
            m.gauge("block_resync_queue_length", "Blocks awaiting resync",
                    fn=lambda: self.resync.queue_len() if self.resync else 0)
            m.gauge("block_resync_errored_blocks",
                    "Blocks in resync error backoff",
                    fn=lambda: self.resync.errors_len() if self.resync else 0)
            m.gauge("block_bytes_read_total", "Block payload bytes read",
                    fn=lambda: self.bytes_read)
            m.gauge("block_bytes_written_total", "Block payload bytes written",
                    fn=lambda: self.bytes_written)
            m.gauge("block_corruptions_total", "Corrupted blocks detected",
                    fn=lambda: self.corruptions)
            m.gauge("block_parity_indexed", "Blocks covered by RS parity sidecars",
                    fn=lambda: (self.parity_store.stats()["indexed_blocks"]
                                if self.parity_store else 0))
            m.gauge("block_local_reconstructions_total",
                    "Blocks rebuilt locally from RS parity",
                    fn=lambda: self.blocks_reconstructed)
            self.m_read_dur = m.histogram(
                "block_read_duration_seconds", "Local block read+verify")
            self.m_write_dur = m.histogram(
                "block_write_duration_seconds", "Local block write")
            # labeled render-time observers: any render() (admin
            # /metrics, tests, chaos scripts) sees CURRENT per-root
            # health with no scrape-side refresh hook to forget
            m.gauge(
                "disk_root_state",
                "Data-root health: 0 ok, 1 degraded (read-only), 2 failed",
                labeled_fn=lambda: [
                    ({"root": r}, DISK_STATE_VALUES[s])
                    for r, s in self.health.states().items()])
            m.gauge(
                "disk_free_bytes",
                "Free bytes per data root (statvfs, cached)",
                labeled_fn=lambda: [
                    ({"root": r}, float(self.health.free_bytes(r) or 0))
                    for r in self.health.roots()])
            m.gauge(
                "disk_busy_seconds",
                "Cumulative wall seconds spent in block-store I/O per "
                "data root (USE utilization; rate() = per-root busy "
                "fraction).  root=\"\" aggregates unmapped paths",
                labeled_fn=lambda: [
                    ({"root": r}, float(s))
                    for r, s in sorted(self._disk_busy().items())])
            self.m_quarantine = m.counter(
                "block_quarantine_total",
                "Block copies moved aside as .corrupted (read-path "
                "verify failures, unreadable files, scrub)")
            self.m_quarantine_err = m.counter(
                "block_quarantine_error_total",
                "Quarantine renames that failed (bad copy deleted "
                "instead so resync can refetch)")
            self.m_repair_fetch = m.counter(
                "repair_fetch_bytes_total",
                "Bytes fetched for degraded reads / reconstruction, by "
                "mode (ppr = partial-sum products, shard = whole-shard "
                "exact-k — both wire bytes; tree = coordinator ingress "
                "of the aggregated repair-tree root stream, flat in k; "
                "gather = legacy sweep-everything fallback, counted as "
                "verified plain bytes, an upper bound on its wire cost)")
            self.m_repair_repaired = m.counter(
                "repair_repaired_bytes_total",
                "Bytes of reconstructed codeword rows produced by "
                "degraded reads / repair")
            self.m_repair_overfetch = m.counter(
                "repair_overfetch_bytes_total",
                "Repair bytes fetched but discarded unused (hedge losers, "
                "pieces beyond the k the decode needed)")
            self.m_repair_hedge = m.counter(
                "repair_hedge_total",
                "Hedged replacement fetches launched by the repair "
                "planner on stalled piece fetches")
            self.m_repair_ppr_fb = m.counter(
                "repair_ppr_fallback_total",
                "PPR partial-product requests that fell back to a "
                "whole-shard fetch (old-version or piece-less peers)")
            self.m_repair_replan = m.counter(
                "repair_replan_total",
                "Repair plans re-planned mid-flight, by reason "
                "(survivor_died = survivor failed after acking the plan; "
                "mid_tree = subtree loss re-fetched flat under the same "
                "survivor set; version_demote = tree edge demoted to "
                "flat PPR for a mixed-version peer; tree_abort = "
                "aggregation tree abandoned for the flat planner)")
            m.gauge(
                "repair_tree_depth",
                "Depth of the most recent PPR aggregation tree planned "
                "or served by this node (0 = no tree yet)",
                fn=lambda: float(self.repair_tree_depth_last))
            self.m_heal = m.counter(
                "block_heal_total",
                "Blocks re-materialized, by heal source (writeback = "
                "read-path post-decode write-back; resync_fetch / "
                "peer_sweep / distributed_decode = resync chain; "
                "local_sidecar = local RS parity rebuild; rebuild = "
                "fleet rebuild scheduler after a full-node loss)")
            # gate-state gauges read THROUGH self.codec so a codec swap
            # (tests, future runtime rebuild) keeps /metrics truthful —
            # fn= observers on the codec itself would both pin the old
            # instance and keep reporting it after a swap (Gauge dedup
            # keeps the first registration's observer)
            m.gauge(
                "codec_device_attached",
                "1 when the codec's device side is attached "
                "(hybrid/tpu backends)",
                fn=lambda: 1.0 if getattr(self.codec, "tpu", None)
                is not None else 0.0)
            m.gauge(
                "codec_link_gibs",
                "Last measured host→device link rate (GiB/s; 0 = "
                "unprobed or failed)",
                fn=lambda: float(
                    getattr(self.codec, "last_link_gibs", None) or 0.0))
            m.gauge(
                "codec_tpu_frac",
                "Cumulative fraction of codec bytes processed "
                "device-side", fn=lambda: self.codec.obs.tpu_frac())
        else:
            self.m_read_dur = self.m_write_dur = None
            self.m_heal = None
            self.m_quarantine = self.m_quarantine_err = None
            self.m_repair_fetch = self.m_repair_repaired = None
            self.m_repair_overfetch = None
            self.m_repair_hedge = self.m_repair_ppr_fb = None
            self.m_repair_replan = None

    # --- paths ---

    def _block_dir(self, root: str, h: Hash) -> str:
        hx = bytes(h).hex()
        return os.path.join(root, hx[:2], hx[2:4])

    def block_path(self, root: str, h: Hash, compressed: bool) -> str:
        return os.path.join(
            self._block_dir(root, h), bytes(h).hex() + (".zst" if compressed else "")
        )

    def find_block(self, h: Hash) -> Optional[Tuple[str, bool]]:
        """Locate an existing copy: (path, compressed), preferring the
        primary dir then secondaries, compressed then plain
        (ref manager.rs:608-643)."""
        for root in self.data_layout.all_dirs(h):
            for compressed in (True, False):
                p = self.block_path(root, h, compressed)
                if os.path.exists(p):
                    return p, compressed
        return None

    def is_block_present(self, h: Hash) -> bool:
        return self.find_block(h) is not None

    def _disk_busy(self) -> dict:
        """Per-root cumulative I/O busy seconds — read through a fault
        wrapper's inner DiskIo when one is installed (FaultyDisk
        delegates the actual I/O, so the inner instance holds the
        truth).  Snapshot-copied: worker threads insert concurrently."""
        disk = self.disk
        busy = getattr(disk, "busy_seconds", None)
        if busy is None:
            inner = getattr(disk, "inner", None)
            busy = getattr(inner, "busy_seconds", None)
        return dict(busy) if busy else {}

    def _root_of(self, path: str) -> str:
        """Which data root a block file lives under (longest prefix
        match; falls back to the file's dirname for out-of-layout paths
        so health accounting never KeyErrors)."""
        best = ""
        for d in self.data_layout.data_dirs:
            r = d.path.rstrip(os.sep)
            if (path == r or path.startswith(r + os.sep)) and len(r) > len(best):
                best = r
        return best or os.path.dirname(path)

    def pool_invalidate(self, h: Hash, reason: str) -> None:
        """Strict device-pool invalidation (ops/device_pool.py): evict
        `h`'s device-resident pages SYNCHRONOUSLY, before the calling
        operation acks — block delete, quarantine, rebalance-drop and
        overwrite all come through here, so the pool can never serve a
        page for a block the store no longer holds.  Thread-safe and
        cheap (a dict op under the pool's lock), callable from worker
        threads and the event loop alike; a pool-less codec is a
        no-op."""
        pool = getattr(self.codec, "pool", None)
        if pool is None:
            return
        try:
            pool.invalidate(bytes(h), reason=reason)
        except Exception:  # noqa: BLE001 — invalidation must not fail the op
            logger.warning("device pool invalidation failed",
                           exc_info=True)

    def quarantine_path(self, path: str) -> None:
        """Move a bad copy aside as `.corrupted` for later forensics.
        A failing rename is NOT swallowed (the old `_move_corrupted`
        silently did, leaving a corrupt copy live and re-servable): it
        is logged with path+errno, counted, and the bad copy is deleted
        instead so resync refetches a clean one.  Runs in worker
        threads — keep it sync."""
        try:
            self.disk.replace(path, path + ".corrupted")
            self.quarantined += 1
            if self.m_quarantine is not None:
                self.m_quarantine.inc()
        except FileNotFoundError:
            # lost the race: a concurrent reader of the same bad copy
            # (or a delete) already quarantined/removed it — that IS the
            # desired end state, not a quarantine failure, and it must
            # not count errors or feed the root's streak
            return
        except OSError as e:
            self.quarantine_errors += 1
            if self.m_quarantine_err is not None:
                self.m_quarantine_err.inc()
            logger.error(
                "quarantine rename of %s failed (errno %s: %s); deleting "
                "the bad copy so resync can refetch", path, e.errno, e)
            try:
                self.disk.remove(path)
            except FileNotFoundError:
                pass
            except OSError as e2:
                logger.error("deleting bad copy %s also failed "
                             "(errno %s: %s)", path, e2.errno, e2)
                self.health.note_error(self._root_of(path), "quarantine", e2)

    def _note_disk_error(self, h: Hash) -> None:
        """Arm/extend the per-hash local-read backoff (ErrorCounter
        schedule: 60 s × 2^n).  While armed, read_block skips the local
        file immediately so reads fail over to peers instead of
        re-hitting a bad sector; a successful local write or read
        clears it."""
        from .resync import ErrorCounter

        hb = bytes(h)
        prev = self._disk_errors.get(hb)
        self._disk_errors[hb] = ErrorCounter(
            (prev.errors if prev is not None else 0) + 1, now_msec())
        if len(self._disk_errors) > 4096:
            # bounded: drop the oldest-armed entries (retrying a stale
            # hash locally once is harmless)
            for k in sorted(self._disk_errors,
                            key=lambda k: self._disk_errors[k].last_try
                            )[:1024]:
                del self._disk_errors[k]

    def startup_janitor(self) -> dict:
        """Boot-time crash-consistency pass (block/health.py
        janitor_pass): purge orphaned `.tmp` files (torn writes — never
        acknowledged), bound the `.corrupted` quarantine, and re-enqueue
        every surviving quarantined hash for resync so holes left by a
        crash between quarantine and enqueue are refilled.  Called by
        Garage right after the resync manager is attached."""
        roots = [d.path for d in self.data_layout.data_dirs]
        summary = janitor_pass(
            roots,
            max_quarantine_files=getattr(
                self.config, "quarantine_max_files", 128),
            max_quarantine_bytes=getattr(
                self.config, "quarantine_max_bytes", 256 << 20),
        )
        requeue = summary.get("requeue", [])
        if self.resync is not None:
            for hb in requeue:
                self.resync.put_to_resync(Hash(hb), 1.0, source="janitor")
        if summary["tmp_purged"] or summary["quarantine_purged"] or requeue:
            logger.info(
                "startup janitor: purged %d orphaned .tmp, pruned %d "
                "quarantined files (kept %d), requeued %d hashes for "
                "resync", summary["tmp_purged"],
                summary["quarantine_purged"], summary["quarantine_kept"],
                len(requeue))
        return summary

    def _lock_for(self, h: Hash) -> asyncio.Lock:
        return self._locks[h[0] % MUTEX_SHARDS]

    # --- local read/write (ref manager.rs:478-590,689-784) ---

    def _span(self, op: str, h: Hash):
        """Per-block-op tracing span (ref block/manager.rs:492-501);
        without a trace_sink this is a timing-only lite span feeding the
        always-on slow-op log."""
        return self.system.tracer.span(
            f"Block {op}", block=bytes(h).hex()[:16], op=op
        )

    def note_heal(self, source: str) -> None:
        """Record one completed block heal.  Called from every path that
        re-materializes a lost/corrupt copy; the per-source split is
        what makes 'which mechanism actually healed it' a measurement
        (round-5 heal non-repro)."""
        self.heal_counts[source] = self.heal_counts.get(source, 0) + 1
        if self.m_heal is not None:
            self.m_heal.inc(source=source)

    # --- repair-bandwidth accounting (planner + legacy gather) ---

    def note_repair_fetch(self, mode: str, n: int) -> None:
        """`n` wire bytes fetched for reconstruction under `mode`
        (ppr | shard | gather)."""
        self.repair_fetch_bytes[mode] = (
            self.repair_fetch_bytes.get(mode, 0) + n)
        if self.m_repair_fetch is not None:
            self.m_repair_fetch.inc(n, mode=mode)

    def note_repair_done(self, n: int) -> None:
        self.repair_repaired_bytes += n
        if self.m_repair_repaired is not None:
            self.m_repair_repaired.inc(n)

    def note_repair_overfetch(self, n: int) -> None:
        self.repair_overfetch_bytes += n
        if self.m_repair_overfetch is not None:
            self.m_repair_overfetch.inc(n)

    def note_repair_hedge(self) -> None:
        self.repair_hedges += 1
        if self.m_repair_hedge is not None:
            self.m_repair_hedge.inc()

    def note_repair_ppr_fallback(self) -> None:
        self.repair_ppr_fallbacks += 1
        if self.m_repair_ppr_fb is not None:
            self.m_repair_ppr_fb.inc()

    def note_repair_replan(self, reason: str) -> None:
        self.repair_replans[reason] = self.repair_replans.get(reason, 0) + 1
        if self.m_repair_replan is not None:
            self.m_repair_replan.inc(reason=reason)

    def note_repair_tree(self, depth: int) -> None:
        self.repair_tree_depth_last = int(depth)

    def is_parity_block(self, h: Hash) -> bool:
        """Was this hash ever stored here as a distributed-parity shard?"""
        return self._parity_marks.get(bytes(h)) is not None

    def is_assigned(self, h: Hash) -> bool:
        """Is this node in the block's data replica set?  (With
        data_replication_mode < replication_mode, the block_ref/rc
        partition holds rc on nodes the data ring does NOT assign.)"""
        return any(bytes(n) == bytes(self.system.id)
                   for n in self.replication.write_nodes(h))

    async def write_block(self, h: Hash, data: DataBlock,
                          is_parity: bool = False) -> None:
        with self._span("write", h), maybe_time(self.m_write_dur):
            if is_parity and not self.is_parity_block(h):
                self._parity_marks.insert(bytes(h), b"1")
            with_parity = is_parity or self.is_parity_block(h)
            async with self._lock_for(h):
                wrote = await asyncio.to_thread(
                    self._write_block_sync, h, data
                )
            if wrote and self.write_parity is not None and not with_parity:
                # write-time RS: the block joins an in-progress codeword;
                # encoding happens off this path (see WriteParityAccumulator).
                # Parity blocks themselves are excluded — wrapping parity
                # into further codewords would cascade encode rounds
                # across the cluster for no durability the decode can use.
                self.write_parity.add(h, data)

    def _write_block_sync(self, h: Hash, data: DataBlock) -> bool:
        root = self.data_layout.primary_dir(h)
        final = self.block_path(root, h, data.compressed)
        existing = self.find_block(h)
        if existing is not None:
            path, compressed = existing
            if compressed or not data.compressed:
                # an equal-or-better copy exists (compressed preferred):
                # keep it (ref manager.rs:717-735 dedupe).  Checked
                # BEFORE the health preflight — a degraded node that
                # already holds the block should acknowledge the PUT,
                # not reject data it has.
                return False
        # preflight: free-space watermark + error-streak breaker; raises
        # the typed StorageFull/StorageError the write quorum routes
        # around.  May consume the half-open probe slot — the outcome
        # below MUST be reported back (note_ok / note_error).
        self.health.check_writable(root, len(data.inner))
        try:
            d = os.path.dirname(final)
            os.makedirs(d, exist_ok=True)
            tmp = final + ".tmp"
            # O_DIRECT (buffered fallback inside): ~4x less CPU than the
            # page-cache copy and immune to dirty-page throttling, so
            # concurrent puts overlap their writes on a 1-core host; the
            # bulk of the block is on media at return even with
            # data_fsync=false (see utils/direct_io.py)
            self.disk.write_file(tmp, data.inner, fsync=self.data_fsync)
            self.disk.replace(tmp, final)
            if self.data_fsync:
                # fsync the directory so the rename is durable
                # (manager.rs:760-775)
                self.disk.fsync_dir(d)
        except OSError as e:
            # a failed write's tmp is deliberately LEFT BEHIND, exactly
            # as a crash would leave it: the path is deterministic (one
            # stale tmp per block at most, reclaimed by the next write's
            # truncate or the startup janitor), and cleanup attempts on
            # a disk that just errored tend to error too
            self.health.note_error(root, "write", e)
            cls = StorageFull if e.errno == _errno.ENOSPC else StorageError
            raise cls(f"block write failed on {root}: {e}") from e
        self.health.note_ok(root, "write")
        # a freshly-written good copy clears the hash's read backoff
        self._disk_errors.pop(bytes(h), None)
        if existing is not None and existing[0] != final:
            # plain copy superseded by compressed one
            try:
                self.disk.remove(existing[0])
            except OSError:
                pass
        self.bytes_written += len(data.inner)
        # overwrite: the on-disk form changed (fresh copy / compressed
        # upgrade) — drop any device pages so the pool re-adopts from
        # the new copy rather than trusting a page for a superseded one
        self.pool_invalidate(h, "overwrite")
        return True

    async def read_block(self, h: Hash) -> DataBlock:
        """Read + verify; on corruption move the file aside and requeue a
        resync so a good copy is re-fetched (ref manager.rs:528-590)."""
        with self._span("read", h), maybe_time(self.m_read_dur):
            return await self._read_block_inner(h)

    async def _read_block_inner(self, h: Hash) -> DataBlock:
        hb = bytes(h)
        ec = self._disk_errors.get(hb)
        if ec is not None and ec.next_try() > now_msec():
            # the local copy recently EIO'd and is in backoff: fail over
            # to peers immediately instead of re-hitting the bad sector
            raise NoSuchBlock(
                f"block {hb.hex()[:16]} local copy in disk-error backoff")
        found = self.find_block(h)
        if found is None:
            raise NoSuchBlock(f"block {hb.hex()[:16]} not found locally")
        path, compressed = found
        try:
            raw = await asyncio.to_thread(self.disk.read_file, path)
        except FileNotFoundError:
            # NOT a disk fault: the file vanished between find_block and
            # the read — a benign race with delete_if_unneeded / stray
            # cleanup.  Plain miss, no health/quarantine side effects
            # (8 such races must never flip a healthy root read-only).
            raise NoSuchBlock(
                f"block {hb.hex()[:16]} removed concurrently")
        except OSError as e:
            if not is_media_error(e):
                # process-level resource pressure (EMFILE/ENOMEM/…): the
                # bytes on disk are fine — fail over to a replica but
                # destroy nothing and keep the root's streak clean, or a
                # busy node would mass-quarantine its own healthy data
                logger.warning("transient read error on block %s at %s "
                               "(errno %s: %s)", hb.hex()[:16], path,
                               e.errno, e)
                raise NoSuchBlock(
                    f"block {hb.hex()[:16]} local read failed "
                    f"transiently: {e}") from e
            # read-time disk error (EIO, remount-ro, truncated dir):
            # quarantine the unreadable copy, arm the per-hash backoff,
            # enqueue a refetch, and surface NoSuchBlock so every caller
            # — the get_block RPC handler, the streaming failover loop —
            # transparently moves to the next replica instead of handing
            # the client an OSError
            root = self._root_of(path)
            self.health.note_error(root, "read", e)
            self._note_disk_error(h)
            logger.error("disk read error on block %s at %s "
                         "(errno %s: %s)", hb.hex()[:16], path, e.errno, e)
            self.pool_invalidate(h, "quarantine")
            await asyncio.to_thread(self.quarantine_path, path)
            if self.resync is not None:
                self.resync.put_to_resync(h, 0.0, source="disk_error")
            raise NoSuchBlock(
                f"block {hb.hex()[:16]} local copy unreadable: {e}") from e
        block = DataBlock(raw, compressed)
        try:
            await self._verify_block(h, block)
        except CorruptData:
            self.corruptions += 1
            logger.error("corrupted block %s at %s", hb.hex()[:16], path)
            self.pool_invalidate(h, "quarantine")
            await asyncio.to_thread(self.quarantine_path, path)
            if self.resync is not None:
                self.resync.put_to_resync(h, 0.0, source="corrupt_read")
            raise
        self.health.note_ok(self._root_of(path), "read")
        self._disk_errors.pop(hb, None)
        self.bytes_read += len(raw)
        return block

    async def _verify_block(self, h: Hash, block: DataBlock) -> None:
        """Read-path verify.  Plain blocks route their content hash
        through the codec feeder when one is armed (the ROADMAP feeder
        follow-through: until now only PUT hash / parity encode /
        degraded decode rode it): K concurrent GET verifies coalesce
        into one ragged multi-buffer hash pass, while the in-flight
        request hint keeps a lone read dispatching immediately — no SLO
        tax on solo p50.  Compressed blocks keep the inline zstd
        frame-checksum check, and a closed/absent feeder degrades to the
        pre-feeder inline verify."""
        if self.feeder is not None and not block.compressed:
            with self.feeder.request_scope() as feeder:
                got = await feeder.hash_async(
                    [block.inner], peers=feeder.inflight_requests or None)
            if bytes(got[0]) != bytes(h):
                raise CorruptData(
                    f"hash mismatch for block {bytes(h).hex()[:16]}")
            return
        block.verify(h, self.hash_algo, codec=self.codec)

    async def delete_if_unneeded(self, h: Hash) -> None:
        """Delete the local copy if rc says it's deletable (resync path,
        ref resync.rs:431-455).  Deliberately NO cluster-wide side
        effects here: local deletion also happens during migration and
        offload, which says nothing about the block's global liveness
        (the distributed-parity GC listens to the block_ref table's
        global deletion signal instead)."""
        async with self._lock_for(h):
            if not self.rc.get(h).is_deletable():
                return
            # strict pool invalidation BEFORE the copy disappears: a
            # deleted block must not survive as a servable device page
            self.pool_invalidate(h, "delete")
            while True:
                found = self.find_block(h)
                if found is None:
                    break
                await asyncio.to_thread(self.disk.remove, found[0])
            self.rc.clear_deleted_block_rc(h)

    # --- refcounting entry points (called from table updated() hooks) ---

    def block_incref(self, tx, h: Hash) -> None:
        if self.rc.block_incref(tx, h):
            # 0→1: we might not have the block yet — check after commit
            if self.resync is not None:
                def _after_commit():
                    # a ref landing after a node loss (table sync lags
                    # the ring change) re-arms the rebuild walk for its
                    # partition, so the planned flow — not a one-off
                    # resync — heals it
                    rb = getattr(self.resync, "rebuild", None)
                    if rb is not None:
                        rb.note_ref(h)
                    self.resync.put_to_resync(h, 2.0, source="incref")
                tx.on_commit(_after_commit)

    def block_decref(self, tx, h: Hash) -> None:
        if self.rc.block_decref(tx, h):
            # reached zero: schedule deletion check after the GC delay —
            # unless this node is no longer ring-assigned the block (a
            # layout change moved it away; the decref is the block_ref
            # partition offloading).  Waiting the full delay there left
            # sole-copy blocks (data replication "none") unreadable for
            # 10 minutes after a node left the layout; the prompt resync
            # offers the block to its new owners and only deletes once
            # they all confirm possession (resync migration branch).
            if self.resync is not None:
                from .rc import BLOCK_GC_DELAY_MS

                delay = BLOCK_GC_DELAY_MS / 1000.0
                if not self.is_assigned(h):
                    delay = 2.0
                tx.on_commit(lambda: self.resync.put_to_resync(
                    h, delay, source="decref"))

    # --- RPC client side ---

    async def _heal_after_decode(self, h: Hash, data: bytes) -> None:
        """Write a decode-recovered block back to its replica set (the
        read-path RS fallback's repair half).  skip_ec: the block
        PROVABLY has parity coverage — the decode that produced `data`
        just consumed it — so re-wrapping it into a fresh codeword
        would leak duplicate parity on every degraded read."""
        try:
            with self.system.tracer.span(
                "Block heal", block=bytes(h).hex()[:16], source="writeback"
            ):
                await self.rpc_put_block(h, data, skip_ec=True)
            self.note_heal("writeback")
        except Exception:  # noqa: BLE001 — repair is best-effort
            logger.warning("post-decode heal of %s failed",
                           bytes(h).hex()[:16], exc_info=True)

    def drain_heals(self) -> None:
        """Cancel in-flight post-decode heals and refuse new ones
        (shutdown path: the RPC layer is about to close under them; the
        resync entry queued alongside each heal is persistent and
        finishes the job on the next boot).  The refusal flag closes
        the window where a GET suspended inside the decode fallback
        resumes AFTER this drain and would spawn a fresh heal against
        the closing transport."""
        self._heals_closed = True
        for t in list(self._heal_tasks):
            t.cancel()
        self._heal_tasks.clear()

    async def rpc_put_block(self, h: Hash, data: bytes,
                            is_parity: bool = False,
                            skip_ec: bool = False) -> None:
        """Compress + quorum-write to the block's replica set
        (ref manager.rs:356-377).  is_parity marks distributed-parity
        shards so receiving nodes don't wrap them into codewords of
        their own."""
        who = self.replication.write_nodes(h)
        # re-sends of a shard this node stored as parity (resync offload,
        # repair re-push) must carry the flag even when the caller
        # doesn't know the provenance
        is_parity = is_parity or self.is_parity_block(h)
        block = await asyncio.to_thread(
            DataBlock.from_buffer, data, self.compression_level
        )
        from ..rpc.rpc_helper import RequestStrategy

        async def send(node, timeout):
            msg = {"t": "put_block", "h": bytes(h),
                   "hdr": block.header().pack()}
            if is_parity:
                msg["parity"] = True
            await self.endpoint.call(
                node,
                msg,
                prio=PRIO_NORMAL,
                timeout=timeout,
                body=_chunks(block.inner),
            )
            return node

        await self.system.rpc.try_call_many(
            self.endpoint,
            who,
            None,
            RequestStrategy(
                rs_quorum=self.replication.write_quorum(),
                rs_timeout=self.block_rpc_timeout,
                # the timeout covers the whole (bandwidth-bound) body
                # transfer — an RTT-derived clamp would false-fail large
                # blocks on slow links and feed the breaker; blackhole
                # detection on this path comes from the breaker's other
                # feeders (pings, probe-shaped calls)
                rs_adaptive_timeout=False,
                # hard zone_redundancy: block copies must land in enough
                # distinct failure domains before the PUT acks
                rs_required_zones=self.system.write_zone_requirement(who),
            ),
            make_call=send,
        )
        if (self.ec_accumulator is not None and not is_parity
                and not skip_ec
                and not self.ec_accumulator.recently_added(h)):
            # writer-side distributed codewords: grouping HERE (not on the
            # storing node) is what spreads a codeword's members across
            # distinct nodes — see WriteParityAccumulator's invariant note.
            # recently_added dedups re-PUTs of identical content, which
            # would otherwise mint a fresh codeword (new gid, new parity
            # blocks, new index rows) for an unchanged block every upload.
            self.ec_accumulator.add(h, block)

    async def rpc_get_block(self, h: Hash, order_tag: Optional[int] = None) -> bytes:
        """Fetch + decompress a block, trying replicas one at a time in
        latency order (ref manager.rs:231-317)."""
        chunks = []
        async for c in self.rpc_get_block_streaming(h, order_tag):
            chunks.append(c)
        return b"".join(chunks)

    async def rpc_get_raw_block(
        self, h: Hash, order_tag: Optional[int] = None,
        for_storage: bool = False, idempotent: bool = False,
    ) -> DataBlock:
        """Fetch one block as a storable DataBlock.  Rides the SAME
        streaming failover path as the GET plane — mid-transfer node
        death resumes from the next replica at the delivered offset
        (raw offsets are not comparable across replicas, which may hold
        different encodings, so failover happens in the decompressed
        domain).  With for_storage, the result is re-compressed so a
        resynced/repaired copy keeps the storage economics of the
        original."""
        meta: dict = {}
        chunks = []
        async for c in self.rpc_get_block_streaming(h, order_tag,
                                                    meta_out=meta,
                                                    idempotent=idempotent):
            chunks.append(c)
        data = b"".join(chunks)
        if for_storage:
            raw = meta.get("raw_chunks")
            if raw is not None:
                # whole block arrived from one replica: store the wire
                # bytes as received — zero codec work (re-compressing
                # every resynced block would tax whole-node rebuilds)
                return DataBlock(b"".join(raw),
                                 compressed=bool(meta.get("compressed")),
                                 parity=bool(meta.get("parity")))
            block = await asyncio.to_thread(
                DataBlock.from_buffer, data, self.compression_level
            )
            return DataBlock(block.inner, block.compressed,
                             parity=bool(meta.get("parity")))
        return DataBlock(data, compressed=False,
                         parity=bool(meta.get("parity")))

    async def rpc_get_block_streaming(
        self, h: Hash, order_tag: Optional[int] = None,
        meta_out: Optional[dict] = None, idempotent: bool = False,
    ) -> AsyncIterator[bytes]:
        """Async-iterate a block's DECOMPRESSED bytes with mid-transfer
        node failover: if the serving node dies mid-stream, the read
        resumes from the next replica, skipping the bytes already
        delivered (ref manager.rs:231-345 + the get-path streaming of
        get.rs:432-512).  Memory stays bounded by the transport chunk
        size — the block is never buffered whole.

        ``idempotent`` grants the whole fan-out ONE shared budget of
        ``retry_max`` full-jitter retries on TRANSPORT errors (same
        shared-budget semantics as RpcHelper: per-node budgets would
        multiply load during a correlated network failure), spent on
        same-node retries before failing over — safe for pure fetches:
        resync refetch, repair.  A GET already delivering a body to a
        client keeps single-attempt-per-node failover semantics, since
        the delivered-offset skip makes a same-node retry redundant with
        just trying the next replica."""
        from ..net.resilience import full_jitter_backoff, is_transport_error

        rpc = self.system.rpc
        who = rpc.request_order(self.replication.read_nodes(h))
        delivered = 0
        errors = []
        attempts_left = rpc.tunables.retry_max if idempotent else 0
        for node in who:
            # the streaming failover loop IS this path's retry/hedge
            # mechanism; it still consults the resilience layer so an
            # open-breaker replica fast-fails to the next copy and a
            # known-RTT replica gets the clamped adaptive timeout
            attempt = 0
            while True:
                if not rpc.peer_allows(node):
                    errors.append(f"{bytes(node).hex()[:8]}: breaker open")
                    break
                try:
                    # the transport timeout covers only time-to-response-
                    # header; the same (adaptive) budget is reused below
                    # as a PER-CHUNK inactivity deadline, because a peer
                    # that blackholes mid-stream keeps the connection
                    # "up" while bytes stop — without a chunk deadline
                    # the read hangs forever and the per-replica failover
                    # never runs
                    node_timeout = rpc.timeout_for(node,
                                                   self.block_rpc_timeout)
                    resp, stream = await self.endpoint.call_streaming(
                        node,
                        {"t": "get_block", "h": bytes(h), "order": order_tag},
                        prio=PRIO_NORMAL,
                        timeout=node_timeout,
                    )
                    if resp.get("err"):
                        raise NoSuchBlock(resp["err"])
                    compressed = DataBlockHeader.unpack(
                        resp["hdr"]).compressed
                    if meta_out is not None:
                        meta_out["parity"] = bool(resp.get("parity"))
                        meta_out["compressed"] = compressed
                        # wire frames as received: valid for storage as
                        # long as no failover stitched two replicas'
                        # (possibly differently-encoded) streams together
                        meta_out["raw_chunks"] = \
                            [] if delivered == 0 else None
                    decomp = None
                    if compressed:
                        from ..utils.zstd_compat import zstandard

                        decomp = zstandard.ZstdDecompressor().decompressobj()
                    skip = delivered
                    try:
                        if stream is not None:
                            it = stream.__aiter__()
                            while True:
                                try:
                                    chunk = await asyncio.wait_for(
                                        it.__anext__(), node_timeout)
                                except StopAsyncIteration:
                                    break
                                if (meta_out is not None
                                        and meta_out.get("raw_chunks")
                                        is not None):
                                    meta_out["raw_chunks"].append(
                                        bytes(chunk))
                                out = (decomp.decompress(chunk)
                                       if decomp else chunk)
                                if not out:
                                    continue
                                if skip:
                                    if len(out) <= skip:
                                        skip -= len(out)
                                        continue
                                    out = out[skip:]
                                    skip = 0
                                delivered += len(out)
                                self.bytes_read += len(out)
                                yield out
                    finally:
                        # abandoning mid-stream (consumer closed this
                        # generator, node failover, decompress error)
                        # must cancel the sender's pump, or it parks in
                        # its credit window until the connection dies;
                        # no-op after full consumption
                        if stream is not None:
                            await stream.aclose()
                    rpc.note_result(node, None)
                    return
                except (asyncio.CancelledError, GeneratorExit):
                    # consumer went away mid-fetch (client disconnect,
                    # task cancel): release the breaker's half-open probe
                    # slot if peer_allows granted it — no verdict on the
                    # peer, and a leaked slot would fast-fail the peer
                    # for a full cooldown
                    rpc.note_result(node, asyncio.CancelledError())
                    raise
                except Exception as e:
                    # ANY per-replica failure fails over to the next
                    # replica — a malformed header (version skew) or a
                    # corrupt zstd frame from one node must not mask a
                    # healthy copy one hop away (ref manager.rs:231-317
                    # tries each in turn)
                    rpc.note_result(node, e)
                    errors.append(f"{bytes(node).hex()[:8]}: {e}")
                    if meta_out is not None and delivered > 0:
                        meta_out["raw_chunks"] = None  # stitched frames
                    if attempts_left > 0 and is_transport_error(e):
                        attempts_left -= 1
                        if rpc.m_retries is not None:
                            from ..utils.error import error_code

                            rpc.m_retries.inc(endpoint=self.endpoint.path,
                                              reason=error_code(e))
                        await asyncio.sleep(full_jitter_backoff(
                            attempt, rpc.tunables, rpc._rng))
                        attempt += 1
                        continue
                    break
        # LAST RESORT, only from a clean start (stitching decoded bytes
        # after a partial replica stream would need offset bookkeeping
        # for no real case): every replica failed — decode the block
        # from the distributed RS parity RIGHT NOW so the client's read
        # succeeds, and requeue a resync so the copy is re-materialized
        # (the reference's only answer here is "another replica",
        # ref manager.rs:231-317; erasure coverage is this framework's
        # addition)
        if delivered == 0:
            data = None
            if self.parity_reconstructor is not None:
                try:
                    data = await self.parity_reconstructor(h)
                except Exception as e:  # noqa: BLE001 — degraded decode
                    errors.append(f"parity-decode: {e}")
                    data = None
                if data is not None:
                    logger.info("served block %s via distributed RS decode "
                                "(all replicas failed)", bytes(h).hex()[:16])
            if data is None and self.parity_store is not None:
                # final rung of the degraded-read ladder: the LOCAL RS
                # parity sidecar.  Reachable when the local copy EIO'd
                # (read failover quarantined it) and every replica is
                # down — the sidecar decode needs only surviving local
                # codeword members, zero network.
                try:
                    data = await asyncio.to_thread(
                        self.parity_store.try_reconstruct, h)
                except Exception as e:  # noqa: BLE001 — degraded decode
                    errors.append(f"local-sidecar: {e}")
                    data = None
                if data is not None:
                    logger.info("served block %s via LOCAL RS sidecar "
                                "(all replicas failed)", bytes(h).hex()[:16])
            if data is not None:
                self.blocks_reconstructed += 1
                if meta_out is not None:
                    meta_out["parity"] = False
                    meta_out["compressed"] = False
                    meta_out["raw_chunks"] = None
                if self.resync is not None:
                    self.resync.put_to_resync(h, 0.0,
                                              source="degraded_read")
                # re-materialize the lost copy THROUGH THE WRITE PATH in
                # the background: config-agnostic (in split meta/data
                # rings the data holder may carry no rc row, so a
                # resync-side heal has no local signal to act on), and
                # the normal dedupe makes it idempotent.  One heal per
                # hash at a time: N concurrent degraded reads of a hot
                # lost block must not spawn N identical quorum writes.
                if (bytes(h) not in self._heal_in_flight
                        and not self._heals_closed):
                    self._heal_in_flight.add(bytes(h))
                    task = asyncio.get_running_loop().create_task(
                        self._heal_after_decode(h, data))
                    self._heal_tasks.add(task)

                    def _done(t, hb=bytes(h)):
                        self._heal_tasks.discard(t)
                        self._heal_in_flight.discard(hb)

                    task.add_done_callback(_done)
                self.bytes_read += len(data)
                for i in range(0, len(data), STREAM_CHUNK):
                    yield data[i:i + STREAM_CHUNK]
                return
        raise GarageError(
            f"could not stream block {bytes(h).hex()[:16]} from any node "
            f"(delivered {delivered} bytes): {errors}"
        )

    async def need_block(self, h: Hash, drain: bool = False) -> bool:
        """Do we need a copy of this block? (ring-assigned + rc>0 but no
        local file; the assignment check keeps rc holders outside the
        data ring — possible when data_replication_mode differs — from
        accumulating copies).  A read-only/failed primary root answers
        False: soliciting a block offer the subsequent put would reject
        with StorageFull only wastes the offerer's bandwidth.  A root
        whose breaker cooldown has elapsed (half-open) answers True —
        the solicited push doubles as the probe write that walks the
        root back to ok.

        ``drain``: the prober is a freshly un-assigned holder whose OWN
        rc is still live — right after a layout change our refs are as
        stale as its assignment, so accept on ring assignment alone
        (the prober's refs vouch for the block; ours arrive with table
        sync, and a push that outlives its object is ordinary stray GC).
        Without this, a zone drain's data motion waits on metadata
        migration instead of riding the paced rebalance mover."""
        return ((self.rc.get(h).is_needed() or drain)
                and not self.is_block_present(h)
                and self.is_assigned(h)
                and self.health.writable(self.data_layout.primary_dir(h)))

    async def sweep_get_block(self, h: Hash,
                              try_ring: bool = True) -> Optional[bytes]:
        """Migration-aware block fetch: own store → ring placement →
        EVERY other alive peer.  Returns verified plain bytes or None.

        After an abrupt layout change the sole copy of a block (data
        replication "none") can sit on a node the NEW ring no longer
        lists for it, while the holder's rc is still positive (its
        block_ref partition hasn't offloaded yet) so the holder won't
        push either — the ring fetch alone would deadlock availability
        until the metadata migration completes.  The reference sidesteps
        this by draining removed nodes before they leave; here layout
        changes are instant and the PULLER does the finding.  O(cluster)
        worst case — callers are repair paths, where completeness beats
        elegance.  Liveness ORDERS the sweep (likely-up peers first) but
        never vetoes it: is_up is a stale hint, and skipping a reachable
        holder turns recoverable data into loss."""
        from ..utils.data import block_hash

        raw = None
        if self.is_block_present(h):
            try:
                block = await self.read_block(h)
                raw = await asyncio.to_thread(block.decompressed)
            except Exception:
                raw = None
        if raw is not None and bytes(
                block_hash(raw, self.hash_algo)) == bytes(h):
            return raw
        raw = None
        try:
            if not try_ring:
                # caller just failed a full ring fetch (resync fallback);
                # re-paying that timeout chain per missing block would
                # double degraded-repair latency
                raise GarageError("ring fetch skipped by caller")
            raw = await self.rpc_get_block(h)
        except Exception as ring_err:
            ring_nodes = {bytes(x) for x in self.replication.read_nodes(h)}
            tried = []
            peers = sorted(
                self.system.peering.peers.items(),
                key=lambda kv: not kv[1].is_up,
            )
            for nid, _st in peers:
                if bytes(nid) in ring_nodes:
                    continue
                try:
                    # adaptive per-peer timeout keeps the O(cluster) walk
                    # cheap past slow peers; no breaker veto (see above —
                    # a stale "broken" verdict must not hide the only copy)
                    resp, stream = await self.endpoint.call_streaming(
                        nid, {"t": "get_block", "h": bytes(h)},
                        timeout=self.system.rpc.timeout_for(
                            nid, self.block_rpc_timeout),
                    )
                    if resp.get("err") or stream is None:
                        tried.append(f"{bytes(nid).hex()[:8]}:miss")
                        continue
                    from .block import DataBlock, DataBlockHeader

                    hdr = DataBlockHeader.unpack(resp["hdr"])
                    # whole-body deadline: a peer blackholing mid-stream
                    # must cost one timeout, not hang the sweep forever
                    try:
                        body = await asyncio.wait_for(
                            stream.read_all(), self.block_rpc_timeout)
                    except BaseException:
                        await stream.aclose()  # stop the sender's pump
                        raise
                    raw = DataBlock(body, hdr.compressed).decompressed()
                    break
                except Exception as e:
                    tried.append(f"{bytes(nid).hex()[:8]}:{type(e).__name__}")
                    continue
            if raw is None:
                logger.info(
                    "sweep fetch of %s failed everywhere: ring=%s; "
                    "sweep=%s", bytes(h).hex()[:12], ring_err, tried)
        if raw is None:
            return None
        if bytes(block_hash(raw, self.hash_algo)) != bytes(h):
            logger.warning("sweep fetch of %s: hash mismatch",
                           bytes(h).hex()[:12])
            return None
        return raw

    async def drop_stray_copy(self, h: Hash) -> None:
        """Physically delete a local copy this node is NOT assigned —
        migration cleanup, called by resync only after every assigned
        node confirmed possession.  Unlike delete_if_unneeded this does
        not wait out the rc GC delay: the copies exist where the ring
        wants them, so the stray is redundant regardless of timers.  A
        freshly-arrived local ref (rc>0 again) vetoes, to be safe."""
        async with self._lock_for(h):
            if self.rc.get(h).is_needed() or self.is_assigned(h):
                return
            # rebalance-drop: evict the device pages before the copy
            # goes (strict pool invalidation, synchronous pre-ack)
            self.pool_invalidate(h, "rebalance")
            while True:
                found = self.find_block(h)
                if found is None:
                    break
                await asyncio.to_thread(self.disk.remove, found[0])
            # also drop the Deletable{at_time} rc row: nothing would
            # ever clear it for a departed block (clear_deleted_block_rc
            # only fires from delete_if_unneeded after the timer), and a
            # phantom row inflates rc_len and re-enqueues a no-op resync
            # on every `repair blocks` pass forever
            self.rc.clear_stray_rc(h)

    # --- RPC server side (ref manager.rs:671-687) ---

    async def _handle(self, remote, msg, body):
        t = msg.get("t")
        if t == "put_block":
            h = Hash(bytes(msg["h"]))
            hdr = DataBlockHeader.unpack(msg["hdr"])
            raw = await body.read_all() if body is not None else b""
            await self.write_block(h, DataBlock(raw, hdr.compressed),
                                   is_parity=bool(msg.get("parity")))
            return {"ok": True}, None
        if t == "get_block":
            h = Hash(bytes(msg["h"]))
            try:
                block = await self.read_block(h)
            except (NoSuchBlock, CorruptData) as e:
                # a serving miss is a REPAIR SIGNAL: if this node is
                # assigned the block and its refs say it should exist, a
                # silently-vanished file (disk mishap — nothing walked
                # it since the scrub walker only sees files that exist)
                # would otherwise stay lost until the next offline
                # repair; the resync chain (replica fetch → peer sweep →
                # RS decode) knows how to rebuild it
                if (self.resync is not None
                        and self.rc.get(h).is_needed()
                        and self.is_assigned(h)
                        and not self.is_block_present(h)):
                    self.resync.put_to_resync(h, 0.0, source="serve_miss")
                return {"err": str(e)}, None
            hdr = {"hdr": block.header().pack()}
            if self.is_parity_block(h):
                hdr["parity"] = True
            return hdr, _chunks(block.inner)
        if t == "need_block":
            h = Hash(bytes(msg["h"]))
            # "present" lets a departing holder learn when every assigned
            # node has a copy, unlocking prompt stray deletion (see
            # resync._resync_block_inner migration branch)
            return {"needed": await self.need_block(
                        h, drain=bool(msg.get("drain"))),
                    "present": self.is_block_present(h)}, None
        if t == "ppr":
            # partial-parallel repair: multiply the LOCAL shard by the
            # decode coefficient in GF(256) and ship the partial product
            # truncated to the target row's length — one sub-shard-sized
            # result per survivor link instead of the whole piece, and
            # the coordinator only XOR-accumulates (block/repair_plan.py;
            # docs/ROBUSTNESS.md "Repair bandwidth")
            h = Hash(bytes(msg["h"]))
            try:
                block = await self.read_block(h)
            except (NoSuchBlock, CorruptData) as e:
                # same serve-miss repair signal as get_block: a vanished
                # assigned piece re-enters the resync chain
                if (self.resync is not None
                        and self.rc.get(h).is_needed()
                        and self.is_assigned(h)
                        and not self.is_block_present(h)):
                    self.resync.put_to_resync(h, 0.0, source="serve_miss")
                return {"err": str(e)}, None
            coeff = int(msg["coeff"]) & 0xFF
            want = max(0, int(msg["len"]))
            is_par = bool(msg.get("parity"))

            def _partial():
                raw = block.decompressed()
                if is_par:
                    from .parity import unpack_parity_shard

                    shard = unpack_parity_shard(raw)
                    if shard is None:
                        return None
                else:
                    shard = raw
                # coefficient-multiply through the codec's GF kernel
                # (native GFNI when built, numpy log/exp tables else)
                return self.codec.gf_scale(coeff, shard, want)

            part = await asyncio.to_thread(_partial)
            if part is None:
                return {"err": "not a parity shard"}, None
            return {"n": len(part)}, _chunks(part)
        if t == "ppr_tree":
            # tree-aggregated PPR: serve OWN pieces as GF(256) partial
            # products, recursively collect the children's aggregated
            # streams, XOR everything into one accumulator per target
            # row, and forward a single stream upward — so the
            # coordinator's ingress stays flat in k (repair_plan.py
            # `_run_tree`; docs/ROBUSTNESS.md "Full-node rebuild")
            wants = [max(0, int(w)) for w in msg.get("want") or []]
            plan = msg.get("plan") or {}
            if not wants:
                return {"err": "empty want list"}, None
            self.note_repair_tree(_tree_depth(plan))
            buf, got, miss = await self._serve_ppr_tree(plan, wants)
            return {"n": len(buf), "got": got, "miss": miss}, _chunks(buf)
        raise GarageError(f"unknown block rpc {t!r}")

    async def _serve_ppr_tree(self, plan: dict, wants: list):
        """One level of the repair aggregation tree.  Returns
        (concatenated per-target accumulator rows, contributed piece
        indexes, missing piece indexes).  A dead child is NOT fatal:
        its whole subtree lands on the miss list and the coordinator
        re-fetches those pieces flat (subtree re-plan, never a
        codeword abort)."""
        import numpy as np

        accs = [np.zeros(w, dtype=np.uint8) for w in wants]
        got: list = []
        miss: list = []

        def _xor(payload: bytes, coeffs) -> None:
            for a, w, c in zip(accs, wants, coeffs):
                c = int(c) & 0xFF
                if not c or not w:
                    continue
                data = self.codec.gf_scale(c, payload, w)
                if data:
                    arr = np.frombuffer(data, dtype=np.uint8)
                    a[: len(arr)] ^= arr

        for ent in plan.get("p") or []:
            hb, is_par, coeffs, idx = ent[0], ent[1], ent[2], int(ent[3])
            h = Hash(bytes(hb))
            try:
                block = await self.read_block(h)
            except (NoSuchBlock, CorruptData):
                # same serve-miss repair signal as get_block/ppr
                if (self.resync is not None
                        and self.rc.get(h).is_needed()
                        and self.is_assigned(h)
                        and not self.is_block_present(h)):
                    self.resync.put_to_resync(h, 0.0, source="serve_miss")
                miss.append(idx)
                continue

            def _shard(block=block, is_par=is_par):
                raw = block.decompressed()
                if is_par:
                    from .parity import unpack_parity_shard

                    return unpack_parity_shard(raw)
                return raw

            shard = await asyncio.to_thread(_shard)
            if shard is None:
                miss.append(idx)
                continue
            await asyncio.to_thread(_xor, shard, coeffs)
            got.append(idx)

        async def _child(cnode, sub):
            node = FixedBytes32(bytes(cnode))
            depth = _tree_depth(sub)
            try:
                resp, stream = await self.endpoint.call_streaming(
                    node, {"t": "ppr_tree", "plan": sub,
                           "want": [int(w) for w in wants]},
                    prio=PRIO_NORMAL,
                    timeout=self.block_rpc_timeout * max(1, depth))
                if resp.get("err") or stream is None:
                    raise GarageError(
                        resp.get("err") or "empty ppr_tree answer")
                try:
                    body = await asyncio.wait_for(
                        stream.read_all(),
                        self.block_rpc_timeout * max(1, depth))
                except BaseException:
                    await stream.aclose()
                    raise
                if len(body) != sum(wants):
                    raise GarageError("short ppr_tree aggregate")
                return (list(resp.get("got") or []),
                        list(resp.get("miss") or []), body)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — subtree → miss list
                logger.debug("ppr_tree child %s failed: %s",
                             bytes(cnode).hex()[:8], e)
                return None

        children = plan.get("c") or []
        if children:
            answers = await asyncio.gather(
                *[_child(cnode, sub) for cnode, sub in children])
            for (cnode, sub), ans in zip(children, answers):
                if ans is None:
                    miss.extend(_tree_piece_indexes(sub))
                    continue
                cgot, cmiss, body = ans
                # relay ingress: counted as ppr on THIS node, so the
                # cluster-wide wire total still sums to ≈ k partials
                # while the coordinator's "tree" ingress stays one
                # stream
                self.note_repair_fetch("ppr", len(body))
                off = 0
                for a, w in zip(accs, wants):
                    if w:
                        a ^= np.frombuffer(body[off:off + w],
                                           dtype=np.uint8)
                    off += w
                got.extend(int(i) for i in cgot)
                miss.extend(int(i) for i in cmiss)
        buf = b"".join(a.tobytes() for a in accs)
        return buf, got, miss

    # --- introspection ---

    def rc_len(self) -> int:
        return self.rc.rc_len()


def _tree_piece_indexes(plan: dict) -> list:
    """Every piece index carried anywhere in a (sub)tree plan — the
    miss set when a whole child subtree is unreachable."""
    out = [int(p[3]) for p in plan.get("p") or []]
    for _cnode, sub in plan.get("c") or []:
        out.extend(_tree_piece_indexes(sub))
    return out


def _tree_depth(plan: dict) -> int:
    kids = plan.get("c") or []
    return 1 + max((_tree_depth(s) for _n, s in kids), default=0)


async def _chunks(data: bytes) -> AsyncIterator[bytes]:
    for i in range(0, len(data), STREAM_CHUNK):
        yield data[i : i + STREAM_CHUNK]
    if not data:
        return
