"""ParityStore — local Reed-Solomon sidecars for scrub-time self-repair.

The reference repairs a corrupted block only by refetching it from a
replica (ref src/block/resync.rs:457-468); if every replica is
unreachable or equally damaged, the data is gone.  Here the scrub
worker's fused verify+encode pass (the BlockCodec north star) already
computes RS(k, m) parity over each codeword of k blocks — this module
persists that parity as a local sidecar so a corrupted or lost block can
be **reconstructed on this node alone**, with zero network, as long as
≥ k of the codeword's k+m pieces survive.  Network resync remains the
fallback; the sidecar is a best-effort cache refreshed on every scrub
pass.

Layout: one msgpack manifest per codeword under
`<data_dir>/parity/xx/<group_id>.par` (group_id = blake2s over the
member hashes), plus a small db tree mapping block hash → group file so
repair can find a block's codeword in O(1).  Data shards are the member
blocks themselves (zero-padded to the codeword width), read back from
the block store and re-verified by content hash at reconstruction time;
parity shards carry their own checksums.  Any mismatch disqualifies the
piece — reconstruction either produces a block whose hash matches, or
fails loudly and the caller falls back to the network.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Sequence

import msgpack
import numpy as np

from ..utils.data import Hash, blake2s_sum, block_hash
from .block import DataBlock

logger = logging.getLogger("garage_tpu.block.parity")

MANIFEST_VERSION = 1


class ParityStore:
    def __init__(self, manager, db, codec):
        from ..db.counted_tree import CountedTree

        self.manager = manager
        self.codec = codec
        # CountedTree: the coverage gauge reads len() per metrics scrape,
        # and sqlite COUNT(*) is O(n)
        self.index = CountedTree(db.open_tree("block_parity_index"))
        # new sidecars go to the first WRITABLE data dir (a drained
        # read_only drive must not keep accumulating them); lookups and
        # the purge walk EVERY dir so sidecars written before a drain or
        # layout change stay reachable and collectable
        dirs = manager.data_layout.data_dirs
        root = next(
            (d.path for d in dirs if not d.read_only), dirs[0].path
        )
        self.dir = os.path.join(root, "parity")
        self.all_dirs = [os.path.join(d.path, "parity") for d in dirs]

    # --- write path (scrub) ------------------------------------------------

    @staticmethod
    def _gid(k: int, m: int, hashes: Sequence[Hash]) -> Hash:
        """Group id over (manifest version, k, m, member hashes).  The
        codec geometry is part of the identity: with member-hashes-only
        gids, an rs_parity config change made put_codeword mtime-touch the
        old-geometry file forever (so purge never removed it) while
        _load_manifest rejected it on its (k, m) check — silently and
        permanently losing local-repair coverage for the codeword."""
        import struct

        head = struct.pack("<III", MANIFEST_VERSION, k, m)
        return blake2s_sum(head + b"".join(bytes(h) for h in hashes))

    def _group_path(self, gid: bytes) -> str:
        """Write location for a group (the writable dir)."""
        hx = gid.hex()
        return os.path.join(self.dir, hx[:2], hx + ".par")

    def _find_group_path(self, gid: bytes) -> Optional[str]:
        """Read location: search every data dir's parity tree."""
        hx = gid.hex()
        for base in self.all_dirs:
            p = os.path.join(base, hx[:2], hx + ".par")
            if os.path.exists(p):
                return p
        return None

    def put_codeword(
        self,
        hashes: Sequence[Hash],
        lengths: Sequence[int],
        parity: np.ndarray,
    ) -> None:
        """Persist one codeword's parity: `hashes`/`lengths` are the k
        member blocks in codeword order, `parity` is (m, maxlen) uint8.
        Called by the scrub worker for rows whose members all verified."""
        k = len(hashes)
        gid = self._gid(k, int(parity.shape[0]), hashes)
        existing = self._find_group_path(bytes(gid))
        if existing is not None:
            # gid hashes the member set AND the (version, k, m) geometry,
            # so an existing file has identical content: a fresh mtime
            # (what the purge keys on) is all a stable codeword needs —
            # skip rewriting ~m/k of the dataset every scrub pass
            try:
                os.utime(existing)
            except OSError:
                existing = None
        if existing is None:
            # manifest built only on the miss path: in steady state most
            # codewords take the touch shortcut, and serializing + hashing
            # ~m rows of parity per codeword per pass would dominate it
            manifest = {
                "v": MANIFEST_VERSION,
                "k": k,
                "m": int(parity.shape[0]),
                "maxlen": int(parity.shape[1]),
                "hashes": [bytes(h) for h in hashes],
                "lengths": [int(n) for n in lengths],
                "parity": [parity[i].tobytes() for i in range(parity.shape[0])],
                "parity_sums": [
                    bytes(blake2s_sum(parity[i].tobytes()))
                    for i in range(parity.shape[0])
                ],
            }
            path = self._group_path(bytes(gid))
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(msgpack.packb(manifest, use_bin_type=True))
            os.replace(tmp, path)
        for h in hashes:
            self.index.insert(bytes(h), bytes(gid))

    # --- repair path -------------------------------------------------------

    def _load_manifest(self, h: Hash) -> Optional[dict]:
        gid = self.index.get(bytes(h))
        if gid is None:
            return None
        path = self._find_group_path(bytes(gid))
        if path is None:
            return None
        try:
            with open(path, "rb") as f:
                man = msgpack.unpackb(f.read(), raw=False)
        except Exception:  # noqa: BLE001 — any bad sidecar = no coverage
            return None
        if man.get("v") != MANIFEST_VERSION or bytes(h) not in man["hashes"]:
            return None
        man["_path"] = path  # saves re-resolving for the mtime touch
        # a sidecar from an older (k, m) config cannot be decoded by the
        # current codec; the next scrub pass rewrites it
        if (man["k"] != self.codec.params.rs_data
                or man["m"] != self.codec.params.rs_parity):
            return None
        return man

    def coverage(self, h: Hash) -> bool:
        """Is this block covered by a (possibly stale) parity sidecar?"""
        return self._load_manifest(h) is not None

    def try_reconstruct(self, h: Hash) -> Optional[bytes]:
        """Rebuild block `h` from its codeword's surviving pieces.

        Every candidate piece is verified before use (data shards by
        content hash, parity shards by stored checksum); the rebuilt
        block is verified against `h` before being returned.  Returns
        the plain block bytes, or None if fewer than k trustworthy
        pieces survive."""
        man = self._load_manifest(h)
        if man is None:
            return None
        k, m, maxlen = man["k"], man["m"], man["maxlen"]
        hashes = [Hash(x) for x in man["hashes"]]
        target_i = man["hashes"].index(bytes(h))

        pieces: List[np.ndarray] = []
        present: List[int] = []
        # data shards: re-read surviving member blocks from the store
        for i, mh in enumerate(hashes):
            if i == target_i:
                continue
            raw = self._read_verified_member(mh)
            if raw is None:
                continue
            shard = np.zeros(maxlen, dtype=np.uint8)
            shard[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            pieces.append(shard)
            present.append(i)
            if len(present) >= k:
                break
        # parity shards as needed
        if len(present) < k:
            for j in range(m):
                p = np.frombuffer(man["parity"][j], dtype=np.uint8)
                if bytes(blake2s_sum(man["parity"][j])) != bytes(
                        man["parity_sums"][j]):
                    continue
                pieces.append(p)
                present.append(k + j)
                if len(present) >= k:
                    break
        if len(present) < k:
            return None

        shards = np.stack(pieces)[None, :, :]  # (1, p, maxlen)
        try:
            data = self.codec.rs_reconstruct(shards, present)[0]  # (k, maxlen)
        except Exception:
            logger.exception("parity reconstruction failed for %s",
                             bytes(h).hex()[:16])
            return None
        out = data[target_i].tobytes()[: man["lengths"][target_i]]
        if bytes(block_hash(out, self.manager.hash_algo)) != bytes(h):
            logger.warning(
                "parity reconstruction of %s produced wrong hash "
                "(stale codeword?)", bytes(h).hex()[:16],
            )
            return None
        logger.info("locally reconstructed block %s from RS parity",
                    bytes(h).hex()[:16])
        # refresh the sidecar's mtime: its row failed verify this scrub
        # pass (that is why we are here), so the pass will not rewrite
        # it — without the touch the purge could drop it
        try:
            os.utime(man["_path"])
        except OSError:
            pass
        return out

    def _read_verified_member(self, h: Hash) -> Optional[bytes]:
        """A member block's plain bytes, only if present and intact."""
        found = self.manager.find_block(h)
        if found is None:
            return None
        path, compressed = found
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            block = DataBlock(raw, compressed)
            data = block.decompressed()
        except Exception:
            return None
        if bytes(block_hash(data, self.manager.hash_algo)) != bytes(h):
            return None
        return data

    def purge_stale(self, older_than: float) -> int:
        """Delete sidecars not refreshed since `older_than` (unix time)
        and prune index entries pointing at missing files.  Codeword
        membership shifts with block churn, so every completed scrub
        pass calls this with its own start time — without it, orphaned
        .par files would accumulate on every pass."""
        removed = 0
        for base in self.all_dirs:
            if not os.path.isdir(base):
                continue
            for sub in os.listdir(base):
                d = os.path.join(base, sub)
                try:
                    names = os.listdir(d)
                except OSError:
                    continue
                for name in names:
                    p = os.path.join(d, name)
                    try:
                        if os.stat(p).st_mtime < older_than:
                            os.remove(p)
                            removed += 1
                    except OSError:
                        pass
        # prune index entries whose group file is gone
        dead = [
            k for k, gid in list(self.index.items(None, None))
            if self._find_group_path(bytes(gid)) is None
        ]
        for k in dead:
            self.index.remove(k)
        if removed or dead:
            logger.info("parity purge: %d stale sidecars, %d index entries",
                        removed, len(dead))
        return removed

    def stats(self) -> dict:
        return {"indexed_blocks": len(self.index)}
