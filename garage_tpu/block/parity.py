"""ParityStore — local Reed-Solomon sidecars for scrub-time self-repair.

The reference repairs a corrupted block only by refetching it from a
replica (ref src/block/resync.rs:457-468); if every replica is
unreachable or equally damaged, the data is gone.  Here the scrub
worker's fused verify+encode pass (the BlockCodec north star) already
computes RS(k, m) parity over each codeword of k blocks — this module
persists that parity as a local sidecar so a corrupted or lost block can
be **reconstructed on this node alone**, with zero network, as long as
≥ k of the codeword's k+m pieces survive.  Network resync remains the
fallback; the sidecar is a best-effort cache refreshed on every scrub
pass.

Layout: one msgpack manifest per codeword under
`<data_dir>/parity/xx/<group_id>.par` (group_id = blake2s over the
member hashes), plus a small db tree mapping block hash → group file so
repair can find a block's codeword in O(1).  Data shards are the member
blocks themselves (zero-padded to the codeword width), read back from
the block store and re-verified by content hash at reconstruction time;
parity shards carry their own checksums.  Any mismatch disqualifies the
piece — reconstruction either produces a block whose hash matches, or
fails loudly and the caller falls back to the network.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import List, Optional, Sequence

import msgpack
import numpy as np

from ..utils.data import Hash, blake2s_sum, block_hash
from .block import DataBlock

logger = logging.getLogger("garage_tpu.block.parity")

MANIFEST_VERSION = 1


class ParityStore:
    def __init__(self, manager, db, codec):
        from ..db.counted_tree import CountedTree

        self.manager = manager
        self.codec = codec
        # CountedTree: the coverage gauge reads len() per metrics scrape,
        # and sqlite COUNT(*) is O(n)
        self.index = CountedTree(db.open_tree("block_parity_index"))
        # new sidecars go to the first WRITABLE data dir (a drained
        # read_only drive must not keep accumulating them); lookups and
        # the purge walk EVERY dir so sidecars written before a drain or
        # layout change stay reachable and collectable
        dirs = manager.data_layout.data_dirs
        root = next(
            (d.path for d in dirs if not d.read_only), dirs[0].path
        )
        self.dir = os.path.join(root, "parity")
        self.all_dirs = [os.path.join(d.path, "parity") for d in dirs]

    # --- write path (scrub) ------------------------------------------------

    @staticmethod
    def _gid(k: int, m: int, hashes: Sequence[Hash]) -> Hash:
        """Group id over (manifest version, k, m, member hashes).  The
        codec geometry is part of the identity: with member-hashes-only
        gids, an rs_parity config change made put_codeword mtime-touch the
        old-geometry file forever (so purge never removed it) while
        _load_manifest rejected it on its (k, m) check — silently and
        permanently losing local-repair coverage for the codeword."""
        import struct

        head = struct.pack("<III", MANIFEST_VERSION, k, m)
        return blake2s_sum(head + b"".join(bytes(h) for h in hashes))

    def _group_path(self, gid: bytes) -> str:
        """Write location for a group (the writable dir)."""
        hx = gid.hex()
        return os.path.join(self.dir, hx[:2], hx + ".par")

    def _find_group_path(self, gid: bytes) -> Optional[str]:
        """Read location: search every data dir's parity tree."""
        hx = gid.hex()
        for base in self.all_dirs:
            p = os.path.join(base, hx[:2], hx + ".par")
            if os.path.exists(p):
                return p
        return None

    def put_codeword(
        self,
        hashes: Sequence[Hash],
        lengths: Sequence[int],
        parity: np.ndarray,
    ) -> None:
        """Persist one codeword's parity: `hashes`/`lengths` are the j ≤ k
        member blocks in codeword order, `parity` is (m, maxlen) uint8
        encoded at the codec's (k, m) geometry.  j < k means a PARTIAL
        codeword (write-time encoding flushes one before k blocks
        accumulate): members j..k-1 are implicit all-zero shards —
        GF-linear, so the parity is identical to a k-member codeword
        whose tail members are zero, and reconstruction counts the zero
        shards as always-available pieces.  Called by the scrub worker
        (full rows whose members all verified) and the write-path
        accumulator (possibly partial)."""
        k = self.codec.params.rs_data
        assert 0 < len(hashes) <= k, (len(hashes), k)
        gid = self._gid(k, int(parity.shape[0]), hashes)
        existing = self._find_group_path(bytes(gid))
        if existing is not None:
            # gid hashes the member set AND the (version, k, m) geometry,
            # so an existing file has identical content: a fresh mtime
            # (what the purge keys on) is all a stable codeword needs —
            # skip rewriting ~m/k of the dataset every scrub pass
            try:
                os.utime(existing)
            except OSError:
                existing = None
        if existing is None:
            # manifest built only on the miss path: in steady state most
            # codewords take the touch shortcut, and serializing + hashing
            # ~m rows of parity per codeword per pass would dominate it
            manifest = {
                "v": MANIFEST_VERSION,
                "k": k,
                "m": int(parity.shape[0]),
                "maxlen": int(parity.shape[1]),
                "hashes": [bytes(h) for h in hashes],
                "lengths": [int(n) for n in lengths],
                "parity": [parity[i].tobytes() for i in range(parity.shape[0])],
                "parity_sums": [
                    bytes(blake2s_sum(parity[i].tobytes()))
                    for i in range(parity.shape[0])
                ],
            }
            path = self._group_path(bytes(gid))
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(msgpack.packb(manifest, use_bin_type=True))
            os.replace(tmp, path)
        for h in hashes:
            self.index.insert(bytes(h), bytes(gid))

    # --- repair path -------------------------------------------------------

    def _load_manifest(self, h: Hash) -> Optional[dict]:
        gid = self.index.get(bytes(h))
        if gid is None:
            return None
        path = self._find_group_path(bytes(gid))
        if path is None:
            return None
        try:
            with open(path, "rb") as f:
                man = msgpack.unpackb(f.read(), raw=False)
        except Exception:  # noqa: BLE001 — any bad sidecar = no coverage
            return None
        if man.get("v") != MANIFEST_VERSION or bytes(h) not in man["hashes"]:
            return None
        man["_path"] = path  # saves re-resolving for the mtime touch
        # a sidecar from an older (k, m) config cannot be decoded by the
        # current codec; the next scrub pass rewrites it
        if (man["k"] != self.codec.params.rs_data
                or man["m"] != self.codec.params.rs_parity):
            return None
        if len(man["hashes"]) > man["k"]:
            return None  # malformed
        return man

    def coverage(self, h: Hash) -> bool:
        """Is this block covered by a (possibly stale) parity sidecar?"""
        return self._load_manifest(h) is not None

    def try_reconstruct(self, h: Hash) -> Optional[bytes]:
        """Rebuild block `h` from its codeword's surviving pieces.

        Every candidate piece is verified before use (data shards by
        content hash, parity shards by stored checksum); the rebuilt
        block is verified against `h` before being returned.  Returns
        the plain block bytes, or None if fewer than k trustworthy
        pieces survive."""
        man = self._load_manifest(h)
        if man is None:
            return None
        k, m, maxlen = man["k"], man["m"], man["maxlen"]
        hashes = [Hash(x) for x in man["hashes"]]
        target_i = man["hashes"].index(bytes(h))

        pieces: List[np.ndarray] = []
        present: List[int] = []
        # data shards: re-read surviving member blocks from the store
        for i, mh in enumerate(hashes):
            if i == target_i:
                continue
            raw = self._read_verified_member(mh)
            if raw is None:
                continue
            shard = np.zeros(maxlen, dtype=np.uint8)
            shard[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            pieces.append(shard)
            present.append(i)
            if len(present) >= k:
                break
        # implicit zero shards of a partial codeword: members j..k-1 are
        # all-zero by construction, always "present" at no cost
        if len(present) < k:
            for i in range(len(hashes), k):
                pieces.append(np.zeros(maxlen, dtype=np.uint8))
                present.append(i)
                if len(present) >= k:
                    break
        # parity shards as needed
        if len(present) < k:
            for j in range(m):
                p = np.frombuffer(man["parity"][j], dtype=np.uint8)
                if bytes(blake2s_sum(man["parity"][j])) != bytes(
                        man["parity_sums"][j]):
                    continue
                pieces.append(p)
                present.append(k + j)
                if len(present) >= k:
                    break
        if len(present) < k:
            return None

        shards = np.stack(pieces)[None, :, :]  # (1, p, maxlen)
        try:
            # rows=[target_i]: a single-block repair pays for ONE decoded
            # row, not all k (k× GF work saving).  Routed through the
            # manager's codec feeder when present: concurrent degraded
            # reads of the same loss pattern share one cached RS
            # schedule and one ragged dispatch (ops/feeder.py); a
            # closed/absent feeder decodes inline.  Guarded on identity:
            # the feeder fronts the MANAGER's codec, and this store may
            # run a different one (geometry change mid-flight, tests
            # swapping codecs) — a mismatched (k, m) must decode direct.
            feeder = getattr(self.manager, "feeder", None)
            if feeder is not None and feeder.codec is self.codec:
                # cls="bg": sidecar rebuilds run from the scrub/resync
                # heal paths — in the device transport's single queue
                # they yield to live foreground verifies/decodes
                data = feeder.decode_or_direct(
                    shards, present, rows=[target_i], cls="bg")[0]
            else:
                data = self.codec.rs_reconstruct(
                    shards, present, rows=[target_i])[0]  # (1, maxlen)
        except Exception:
            logger.exception("parity reconstruction failed for %s",
                             bytes(h).hex()[:16])
            return None
        out = data[0].tobytes()[: man["lengths"][target_i]]
        if bytes(block_hash(out, self.manager.hash_algo)) != bytes(h):
            logger.warning(
                "parity reconstruction of %s produced wrong hash "
                "(stale codeword?)", bytes(h).hex()[:16],
            )
            return None
        logger.info("locally reconstructed block %s from RS parity",
                    bytes(h).hex()[:16])
        # refresh the sidecar's mtime: its row failed verify this scrub
        # pass (that is why we are here), so the pass will not rewrite
        # it — without the touch the purge could drop it
        try:
            os.utime(man["_path"])
        except OSError:
            pass
        return out

    def _read_verified_member(self, h: Hash) -> Optional[bytes]:
        """A member block's plain bytes, only if present and intact."""
        found = self.manager.find_block(h)
        if found is None:
            return None
        path, compressed = found
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            block = DataBlock(raw, compressed)
            data = block.decompressed()
        except Exception:
            return None
        if bytes(block_hash(data, self.manager.hash_algo)) != bytes(h):
            return None
        return data

    def purge_stale(self, older_than: float) -> int:
        """Delete sidecars not refreshed since `older_than` (unix time)
        and prune index entries pointing at missing files.  Codeword
        membership shifts with block churn, so every completed scrub
        pass calls this with its own start time — without it, orphaned
        .par files would accumulate on every pass."""
        removed = 0
        for base in self.all_dirs:
            if not os.path.isdir(base):
                continue
            for sub in os.listdir(base):
                d = os.path.join(base, sub)
                try:
                    names = os.listdir(d)
                except OSError:
                    continue
                for name in names:
                    p = os.path.join(d, name)
                    try:
                        if os.stat(p).st_mtime < older_than:
                            os.remove(p)
                            removed += 1
                    except OSError:
                        pass
        # prune index entries whose group file is gone
        dead = [
            k for k, gid in list(self.index.items(None, None))
            if self._find_group_path(bytes(gid)) is None
        ]
        for k in dead:
            self.index.remove(k)
        if removed or dead:
            logger.info("parity purge: %d stale sidecars, %d index entries",
                        removed, len(dead))
        return removed

    def stats(self) -> dict:
        return {"indexed_blocks": len(self.index)}


# Distributed parity shards carry an 8-byte header {magic, salt}: the
# salt is searched so the shard's CONTENT HASH — which is its identity
# and therefore its ring placement — lands on a node carrying no other
# piece of the codeword.  Without it, hash-random placement can stack
# several pieces on one node and a single node loss can exceed m.  With
# it (and the accumulator's distinct-member-node invariant), a codeword
# of k+m pieces occupies k+m distinct nodes whenever the cluster has
# that many — deterministic m-node-loss tolerance, not probabilistic.
PARITY_SHARD_MAGIC = b"GTPS"
PARITY_SHARD_HEADER = 8
_SALT_TRIES = 32


def pack_parity_shard(shard: bytes, salt: int) -> bytes:
    import struct

    return PARITY_SHARD_MAGIC + struct.pack("<I", salt) + shard


def unpack_parity_shard(blob: bytes) -> Optional[bytes]:
    if blob[:4] != PARITY_SHARD_MAGIC:
        return None
    return blob[PARITY_SHARD_HEADER:]


class ParityDistributor:
    """Cross-node half of write-time parity: stores each parity shard as
    an ordinary refcounted BLOCK (ring-placed on the cluster, fetched via
    rpc_get_block, scrubbed like any block) and records the codeword in
    the replicated parity index table, sharded by member hash.  See
    model/parity_index_table.py for the durability economics vs the
    reference's replication-only model."""

    def __init__(self, manager, parity_index_table):
        self.manager = manager
        self.table = parity_index_table
        self.codewords_distributed = 0

    def holds_index_for(self, h: Hash) -> bool:
        """Is this node an index replica for member `h`?  locally_covered
        is only authoritative on such nodes — with data factor > meta
        factor a storing node may NOT hold the index partition, and a
        local miss there means nothing (refreshing from it would mint a
        fresh codeword every scrub pass, forever)."""
        from ..table.schema import hash_partition_key

        me = bytes(self.manager.system.id)
        ph = hash_partition_key(bytes(h))
        return any(bytes(n) == me
                   for n in self.table.replication.read_nodes(ph))

    def locally_covered(self, h: Hash) -> bool:
        """Any live parity-index row for member `h` in the LOCAL store.
        The index is sharded by member hash with the same ring walk as
        block placement, so (when data factor ≤ meta factor) a node
        storing the block also holds its index rows — a local read is
        authoritative once table sync has converged.  Used by the scrub
        worker's coverage refresh: blocks that lost distributed coverage
        (failed distribution, a wrongly-tombstoned codeword, pre-EC
        data) are re-fed to the write accumulator, making coverage
        CONVERGENT instead of write-time-or-never.  Callers must gate on
        holds_index_for (see its docstring) and run this off-loop for
        batches (synchronous DB iteration)."""
        from ..table.schema import hash_partition_key

        data = self.table.data
        prefix = bytes(hash_partition_key(bytes(h)))
        for k, raw in data.store.items(prefix, None):
            if k[:32] != prefix:
                break
            try:
                ent = data.decode_entry(raw)
            except Exception:
                continue
            if not ent.is_tombstone():
                return True
        return False

    def _salted(self, shard: bytes, taken: set) -> tuple:
        """(blob, hash) for the first salt whose placement avoids nodes
        already carrying a piece of this codeword; best-effort after
        _SALT_TRIES (small clusters can't always avoid overlap)."""
        best = None
        for salt in range(_SALT_TRIES):
            blob = pack_parity_shard(shard, salt)
            ph = block_hash(blob, self.manager.hash_algo)
            nodes = self.manager.replication.write_nodes(ph)
            node = bytes(nodes[0]) if nodes else b""
            if node not in taken:
                taken.add(node)
                return blob, ph
            if best is None:
                best = (blob, ph, node)
        blob, ph, node = best
        taken.add(node)
        return blob, ph

    async def distribute(self, hashes: Sequence[Hash],
                         lengths: Sequence[int],
                         parity: np.ndarray) -> None:
        from ..model.parity_index_table import ParityIndexEntry
        from ..utils.crdt import now_msec

        m = int(parity.shape[0])
        k = self.manager.codec.params.rs_data
        # Salted gid: DISTRIBUTED codeword ids must be unique per encode,
        # not deterministic — a revert after a failed index insert leaves
        # a sticky or-merged tombstone under the gid, and a deterministic
        # id would make any later re-encode of the same member set merge
        # into that tombstone and silently yield zero coverage.  (The
        # LOCAL sidecar store keeps the deterministic _gid: its files are
        # refreshed in place each scrub pass and carry no CRDT.)  Cost:
        # two writers racing the same group create two independent
        # codewords — double parity until GC, never wrong coverage.
        gid = blake2s_sum(
            bytes(ParityStore._gid(k, m, hashes)) + os.urandom(8))
        taken = set()
        for h in hashes:
            nodes = self.manager.replication.write_nodes(Hash(h))
            if nodes:
                taken.add(bytes(nodes[0]))
        blobs, phashes = [], []
        for j in range(m):
            blob, ph = self._salted(parity[j].tobytes(), taken)
            blobs.append(blob)
            phashes.append(ph)
        # parity blocks first, index second: the index's member-0 entry
        # refs the parity hashes, and a ref to a not-yet-written block
        # would trigger spurious resync fetches
        for ph, b in zip(phashes, blobs):
            await self.manager.rpc_put_block(ph, b, is_parity=True)
        ts = now_msec()
        entries = [
            ParityIndexEntry(
                member=Hash(h), gid=gid, timestamp=ts, k=k, m=m,
                member_index=i,
                members=[bytes(x) for x in hashes],
                lengths=[int(n) for n in lengths],
                parity_hashes=[bytes(p) for p in phashes],
            )
            for i, h in enumerate(hashes)
        ]
        # The shards are on disk cluster-wide but carry rc only once the
        # index's member-0 row lands (parity_index_table.updated).  If
        # the insert is lost the shards are orphans nothing reclaims, so
        # retry, then on terminal failure mark them Deletable through
        # the ordinary ref machinery (incref+decref → GC delay → reclaim).
        for attempt in range(3):
            try:
                await self.table.insert_many(entries)
                break
            except Exception:
                if attempt == 2:
                    logger.exception(
                        "parity index insert failed for gid %s; "
                        "tombstoning the codeword", bytes(gid).hex()[:16])
                    await self._revert_codeword(entries)
                    return
                await asyncio.sleep(0.5 * (attempt + 1))
        self.codewords_distributed += 1

    async def _revert_codeword(self, entries) -> None:
        """Best-effort revert after a terminal index-insert failure.

        Tombstone the INDEX rows, not the parity block-refs: a quorum
        failure can be a partial success, and a minority node that
        applied a live member-0 row would anti-entropy it cluster-wide
        later.  The or-merged tombstone neutralizes any such row (its
        updated() hook then performs the decref that reclaims the
        shards); if no row was applied anywhere, the shards simply have
        rc = 0 and phase 2 of `repair blocks` hands them to resync,
        which deletes unreferenced local blocks."""
        for e in entries:
            e.deleted.set()
        try:
            await self.table.insert_many(entries)
        except Exception:
            logger.warning(
                "codeword revert insert also failed; shards are rc-less "
                "orphans until the next `repair blocks` pass")


class WriteParityAccumulator:
    """Write-time RS encoding: parity exists from first write, not from
    the first scrub pass 25 days later.

    The reference's put path offers no erasure protection at all — a
    freshly-PUT block is guarded only by replication
    (ref src/api/s3/put.rs:286-360 writes, src/rpc/replication_mode.rs
    durability) — and the scrub-generated sidecars above leave a window
    between write and first scrub.  This accumulator closes the window:
    blocks join an in-progress codeword; when k members accumulate (or
    `flush_after` seconds pass — partial codewords encode against
    implicit zero shards) the parity is encoded OFF the write path (one
    to_thread hop through the codec's gather kernel).  PutObject latency
    is unaffected: the put path only appends bytes it already holds.

    Two deployments with DIFFERENT grouping invariants:
      - storing-node side (`store` set): every block this node stores
        joins a codeword persisted as a LOCAL sidecar — co-location is
        the point (zero-network local repair).
      - writer side (`distributor` set, hooked into rpc_put_block):
        codewords group blocks bound for DISTINCT nodes — add() flushes
        early rather than admit two members placed on the same node, so
        RS(k, m) deterministically survives m member-node losses.
        Grouping on the storing side instead would co-locate all k
        members on the dying node, reducing node-loss tolerance to
        codewords with ≤ m members.

    All mutation happens on the event loop; the encode runs on a
    snapshot in a worker thread.  Blocks deleted before their codeword's
    other members merely cost decode head-room (the sidecar holds m
    parity shards), and the next scrub pass re-groups survivors."""

    def __init__(self, store: Optional[ParityStore], codec,
                 flush_after: float = 5.0,
                 distributor: Optional[ParityDistributor] = None,
                 manager=None):
        self.store = store
        self.codec = codec
        self.flush_after = flush_after
        self.distributor = distributor
        self.manager = manager if manager is not None else (
            store.manager if store is not None else None)
        self._pending: List[tuple] = []  # (hash, DataBlock)
        self._pending_nodes: set = set()  # primary data node per member
        self._timer: Optional[object] = None  # asyncio.TimerHandle
        self._tasks: set = set()
        # writer-side re-PUT dedup: an OrderedDict-as-LRU of hashes this
        # writer recently wrapped into codewords (bounded; cross-writer
        # repeats still duplicate, which the ref-driven GC cleans up)
        from collections import OrderedDict

        self._recent: "OrderedDict[bytes, None]" = OrderedDict()
        self._recent_cap = 4096
        self.codewords_encoded = 0

    def recently_added(self, h: Hash) -> bool:
        return bytes(h) in self._recent

    def add(self, h: Hash, block: "DataBlock") -> None:
        """Register a freshly-written block.  Event loop only; the block
        is held as stored (possibly compressed) and decompressed on the
        encode thread, so the write path pays nothing."""
        k = self.codec.params.rs_data
        if k <= 0:
            return
        if self.distributor is not None and self.manager is not None:
            self._recent[bytes(h)] = None
            self._recent.move_to_end(bytes(h))
            while len(self._recent) > self._recent_cap:
                self._recent.popitem(last=False)
            # distinct-node invariant for distributed codewords
            nodes = self.manager.replication.write_nodes(h)
            node = bytes(nodes[0]) if nodes else b""
            if node in self._pending_nodes:
                self._flush()
            self._pending_nodes.add(node)
        self._pending.append((h, block))
        if len(self._pending) >= k:
            self._flush()
        elif self._timer is None:
            loop = asyncio.get_running_loop()
            self._timer = loop.call_later(self.flush_after, self._flush)

    def _flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        group, self._pending = self._pending, []
        self._pending_nodes = set()
        task = asyncio.get_running_loop().create_task(
            self._encode_and_store(group)
        )
        # keep a strong ref (create_task results are weakly held)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _encode_and_store(self, group: List[tuple]) -> None:
        try:
            hashes = [h for h, _ in group]

            def encode_and_store():
                blocks = [b.decompressed() for _, b in group]
                # rs_encode_blocks zero-pads the member count to a whole
                # codeword — exactly the partial-codeword zero-shard
                # semantics.  Via the codec feeder when the manager has
                # one: concurrent write-time codewords (every in-flight
                # PUT under parity_on_write) coalesce into one ragged
                # pointer-gather/device pass instead of one GF call each.
                feeder = getattr(self.manager, "feeder", None) \
                    if self.manager is not None else None
                if feeder is not None and feeder.codec is self.codec:
                    parity = feeder.encode_or_direct(blocks)
                else:
                    parity = self.codec.rs_encode_blocks(blocks)
                if self.store is not None:
                    self.store.put_codeword(
                        hashes, [len(b) for b in blocks], parity[0])
                return parity[0], [len(b) for b in blocks]

            parity_row, lengths = await asyncio.to_thread(encode_and_store)
            self.codewords_encoded += 1
            if self.distributor is not None:
                await self.distributor.distribute(hashes, lengths, parity_row)
        except Exception:  # noqa: BLE001 — write-path parity is best-effort
            logger.exception("write-time parity encode failed")

    async def drain(self) -> None:
        """Flush the partial codeword and wait for in-flight encodes
        (shutdown path — a clean stop must not lose the tail)."""
        self._flush()
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
