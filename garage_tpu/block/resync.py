"""BlockResyncManager — the persistent resync queue and its workers.

Equivalent of reference src/block/resync.rs (SURVEY.md §2.5): a persistent
queue keyed `timestamp(8B BE ms) ‖ hash(32B)` of blocks to re-examine, an
error tree with exponential backoff (60 s × 2^n, capped at 2^6 ≈ 1 h,
resync.rs:38-41), up to MAX_RESYNC_WORKERS concurrent workers throttled by
a Tranquilizer and deduplicated through a shared busy-set (resync.rs:80-86).

resync_block (resync.rs:361-471) is the convergence step:
  - rc = 0 and block on disk  → offer it to replicas that need it
    (NeedBlockQuery), upload to all needy nodes, then delete locally.
  - rc > 0 and block missing  → fetch from a replica and store it.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Optional, Set

from ..db import Db
from ..db.counted_tree import CountedTree
from ..net.frame import PRIO_BACKGROUND
from ..utils.background import Worker, WorkerState
from ..utils.crdt import now_msec
from ..utils.data import Hash
from ..utils.error import GarageError
from ..utils.migrate import Migrated, pack, unpack
from ..utils.persister import Persister
from ..utils.tranquilizer import Tranquilizer

logger = logging.getLogger("garage_tpu.block.resync")

RESYNC_RETRY_DELAY = 60.0       # ref resync.rs:38
RESYNC_RETRY_MAX_EXP = 6        # ref resync.rs:41 (max 60s * 2^6)
MAX_RESYNC_WORKERS = 8          # ref resync.rs:44
DEFAULT_RESYNC_TRANQUILITY = 2  # ref resync.rs:47


class ErrorCounter:
    """ref resync.rs ErrorCounter: (errors, last_try) with backoff."""

    __slots__ = ("errors", "last_try")

    def __init__(self, errors: int = 0, last_try: int = 0):
        self.errors = errors
        self.last_try = last_try

    @classmethod
    def parse(cls, v: bytes) -> "ErrorCounter":
        e, lt = unpack(v)
        return cls(e, lt)

    def serialize(self) -> bytes:
        return pack([self.errors, self.last_try])

    def delay_ms(self) -> int:
        return int(
            RESYNC_RETRY_DELAY * 1000 * (1 << min(self.errors - 1, RESYNC_RETRY_MAX_EXP))
        )

    def next_try(self) -> int:
        return self.last_try + self.delay_ms()


class ResyncPersistedConfig(Migrated):
    """Persisted resync tunables (ref resync.rs:143-173): survive restarts,
    settable at runtime via `worker set resync-worker-count / -tranquility`."""

    VERSION_MARKER = b"GT01rscfg"

    def __init__(self, n_workers: int = 1,
                 tranquility: int = DEFAULT_RESYNC_TRANQUILITY):
        self.n_workers = n_workers
        self.tranquility = tranquility

    def fields(self):
        return [self.n_workers, self.tranquility]

    @classmethod
    def from_fields(cls, b):
        return cls(*b)


class BlockResyncManager:
    def __init__(self, manager, db: Db,
                 persister: Optional[Persister] = None):
        self.manager = manager
        self.queue = CountedTree(db.open_tree("block_local_resync_queue"))
        self.errors = CountedTree(db.open_tree("block_local_resync_errors"))
        self.busy_set: Set[bytes] = set()
        self.notify = asyncio.Event()
        self.persister = persister
        # fleet rebuild scheduler (block/rebuild.py), wired by the model
        # layer: hashes whose codewords it currently OWNS are skipped by
        # the queue workers and the rebalance mover so a full-node-loss
        # storm never repairs the same block twice (the double-fetch
        # used to surface as overfetch)
        self.rebuild = None
        self.rebuild_skips = 0
        # enqueue attribution: WHO put work on the resync queue.  The
        # round-5 heal non-repro was exactly this blind spot — the
        # bench's fallback kick (a refs-only RepairWorker, source
        # "layout_sweep") was doing the healing attributed to the decode
        # path.  Counting at the enqueue seam makes that one scrape.
        self.enqueue_counts: dict = {}
        m = getattr(manager.system, "metrics", None)
        self.m_enqueue = (m.counter(
            "block_resync_enqueue_total",
            "Resync queue insertions by originating path",
        ) if m is not None else None)
        cfg = (persister.load() if persister is not None else None) \
            or ResyncPersistedConfig()
        self.n_workers = cfg.n_workers
        self.tranquility = cfg.tranquility

    def _persist_config(self) -> None:
        if self.persister is not None:
            self.persister.save(
                ResyncPersistedConfig(self.n_workers, self.tranquility)
            )

    def set_n_workers(self, n: int) -> None:
        n = int(n)
        if not 1 <= n <= MAX_RESYNC_WORKERS:
            raise ValueError(
                f"resync-worker-count must be in [1, {MAX_RESYNC_WORKERS}]"
            )
        self.n_workers = n
        self._persist_config()
        self.notify.set()

    def set_tranquility(self, t: int) -> None:
        t = int(t)
        if t < 0:
            raise ValueError("resync-tranquility must be >= 0")
        self.tranquility = t
        self._persist_config()

    # --- queue management (ref resync.rs:88-260) ---

    def put_to_resync(self, h: Hash, delay_secs: float,
                      source: str = "other") -> None:
        """`source` labels the originating path (incref, corrupt_read,
        degraded_read, serve_miss, scrub_corrupt, layout_sweep,
        disk_error = read-path EIO failover, janitor = boot-time
        quarantine requeue, rebuild = hashes the fleet rebuild
        scheduler parked after exhausting its own attempts, …) for the
        enqueue-attribution counter;
        internal requeues/backoffs use put_to_resync_at directly and are
        deliberately not counted."""
        self.enqueue_counts[source] = self.enqueue_counts.get(source, 0) + 1
        if self.m_enqueue is not None:
            self.m_enqueue.inc(source=source)
        when = now_msec() + int(delay_secs * 1000)
        self.put_to_resync_at(h, when)

    def put_to_resync_at(self, h: Hash, when_ms: int) -> None:
        key = struct.pack(">Q", when_ms) + bytes(h)
        self.queue.insert(key, b"")
        self.notify.set()

    def clear_backoff(self, h: Hash) -> None:
        if self.errors.get(bytes(h)) is not None:
            self.errors.remove(bytes(h))

    def queue_len(self) -> int:
        return len(self.queue)

    def errors_len(self) -> int:
        return len(self.errors)

    # --- iteration (ref resync.rs:262-359) ---

    async def resync_iter(self) -> WorkerState:
        """Process (at most) the first due queue entry; returns the worker
        state to report."""
        first = self.queue.first()
        if first is None:
            return WorkerState.IDLE
        key, _v = first
        when = struct.unpack(">Q", key[:8])[0]
        now = now_msec()
        if when > now:
            return WorkerState.IDLE  # head not due yet
        h = Hash(key[8:])
        hb = bytes(h)
        if hb in self.busy_set:
            # another worker is on it; drop this queue entry (it will be
            # requeued if needed)
            self.queue.remove(key)
            return WorkerState.BUSY
        if self.rebuild is not None and self.rebuild.owns(hb):
            # the rebuild scheduler will reach this hash in its own
            # partition walk — drop the queue entry instead of paying a
            # duplicate k-fetch (the scheduler re-parks anything it
            # ultimately fails onto this queue)
            self.queue.remove(key)
            self.rebuild_skips += 1
            return WorkerState.BUSY
        # error backoff check (ref resync.rs:317-343)
        ev = self.errors.get(hb)
        if ev is not None:
            ec = ErrorCounter.parse(ev)
            if ec.next_try() > now:
                # not yet: move the queue entry to the retry time
                self.queue.remove(key)
                self.put_to_resync_at(h, ec.next_try())
                return WorkerState.BUSY
        self.busy_set.add(hb)
        try:
            await self.resync_block(h)
        except Exception as e:
            logger.warning("resync of %s failed: %s", hb.hex()[:16], e)
            ec = ErrorCounter.parse(ev) if ev is not None else ErrorCounter()
            ec = ErrorCounter(ec.errors + 1, now)
            self.errors.insert(hb, ec.serialize())
            self.queue.remove(key)
            self.put_to_resync_at(h, ec.next_try())
            return WorkerState.BUSY
        finally:
            self.busy_set.discard(hb)
        self.clear_backoff(h)
        self.queue.remove(key)
        return WorkerState.BUSY

    # --- the convergence step (ref resync.rs:361-471) ---

    async def resync_block(self, h: Hash) -> int:
        """One convergence step; returns the data-plane bytes it moved
        (pushed to peers + fetched/reconstructed locally) so callers
        driving motion deliberately — the layout-rebalance mover — can
        attribute traffic without a second accounting seam."""
        # per-resync tracing span (ref block/resync.rs:286-303)
        with self.manager.system.tracer.span(
            "Block resync", block=bytes(h).hex()[:16]
        ):
            return await self._resync_block_inner(h)

    async def rebalance_hash(self, h: Hash) -> int:
        """Foreground convergence step driven by the rebalance mover:
        the same logic as a queued resync, sharing the busy-set so a
        queue worker and the mover never double-process a hash.  A
        failed move parks the hash on the persistent queue
        (source="rebalance") instead of raising — the mover keeps
        walking and the retry inherits resync's backoff machinery."""
        hb = bytes(h)
        if hb in self.busy_set:
            return 0
        if self.rebuild is not None and self.rebuild.owns(hb):
            # rebalance_hash bypasses resync_iter, so the scheduler
            # dedupe must sit here too
            self.rebuild_skips += 1
            return 0
        self.busy_set.add(hb)
        try:
            moved = await self.resync_block(h)
        except Exception as e:
            logger.warning("rebalance move of %s failed: %s",
                           hb.hex()[:16], e)
            self.put_to_resync(h, 5.0, source="rebalance")
            return 0
        finally:
            self.busy_set.discard(hb)
        return moved

    async def _resync_block_inner(self, h: Hash) -> int:
        mgr = self.manager
        rc = mgr.rc.get(h)
        present = mgr.is_block_present(h)
        moved = 0  # data-plane bytes pushed/fetched by this step

        unassigned = not mgr.is_assigned(h)
        migrating = rc.is_zero() and present and unassigned
        # draining: a layout change un-assigned us but our refs have NOT
        # migrated off yet (rc still nonzero).  Waiting for the refs
        # means the drain's data motion rides table-sync timing instead
        # of the paced mover — push proactively NOW; the local copy
        # stays until the refs migrate (the migrating/deletable branches
        # handle deletion later).
        draining = rc.is_needed() and present and unassigned
        if (rc.is_deletable() and present) or migrating or draining:
            # we hold a block nobody references: offer to under-replicated
            # peers, then delete (ref resync.rs:376-455).  The migrating
            # case (rc just hit zero because a layout change moved the
            # block's refs away) runs the same offer/push immediately —
            # with data replication "none" this node may hold the ONLY
            # copy, and its new owner cannot serve reads until it lands.
            who = [n for n in mgr.replication.write_nodes(h) if n != mgr.system.id]
            probe = {"t": "need_block", "h": bytes(h)}
            if draining:
                # the new owner's refs are as stale as ours — it would
                # answer "not needed" on rc alone.  Our live rc vouches
                # for the block, so the probe asks it to accept on ring
                # assignment instead.
                probe["drain"] = True
            needy, remote_present = [], 0
            for node in who:
                # need_block is a pure probe (idempotent): route it
                # through the resilience gate so it retries transient
                # resets with backoff, gets the adaptive per-peer
                # timeout, and fast-fails open-breaker peers instead of
                # stalling the resync worker a full static timeout
                resp = await mgr.system.rpc.call(
                    mgr.endpoint,
                    node,
                    probe,
                    prio=PRIO_BACKGROUND,
                    timeout=mgr.block_rpc_timeout,
                    idempotent=True,
                )
                if resp.get("needed"):
                    needy.append(node)
                elif resp.get("present"):
                    remote_present += 1
            if needy:
                block = await mgr.read_block(h)
                from .manager import _chunks

                msg = {
                    "t": "put_block",
                    "h": bytes(h),
                    "hdr": block.header().pack(),
                }
                if mgr.is_parity_block(h):
                    msg["parity"] = True
                for node in needy:
                    # push carries a streaming body → never retried; it
                    # still gains the adaptive timeout + breaker gate
                    await mgr.system.rpc.call(
                        mgr.endpoint,
                        node,
                        msg,
                        prio=PRIO_BACKGROUND,
                        timeout=mgr.block_rpc_timeout,
                        body=_chunks(block.inner),
                    )
                    moved += len(block.inner)
                logger.info(
                    "offloaded block %s to %d nodes", bytes(h).hex()[:16], len(needy)
                )
            confirmed = bool(who) and remote_present + len(needy) >= len(who)
            if draining:
                # bytes are safe on the new owners, but local refs are
                # still live: keep the copy until they migrate (rc hits
                # zero → the migrating branch finishes the job).  Only
                # requeue if an owner could not take its copy yet.
                if not confirmed:
                    self.put_to_resync(h, 30.0, source="migration_retry")
            elif unassigned and not confirmed:
                # owners' refs (rc) haven't migrated yet, so they
                # answered neither needed nor present.  Hold the only
                # copy and retry soon — NEVER delete unconfirmed, even
                # after the GC timer expires (a backlogged meta sync must
                # not turn into data loss; the timer's promise is only
                # valid where the ring still assigns us the block).
                self.put_to_resync(h, 30.0, source="migration_retry")
            elif rc.is_deletable():
                # both drop paths invalidate the device pool BEFORE the
                # file goes (manager.pool_invalidate inside each helper):
                # a rebalance-dropped block must not keep serving scrub
                # hits from device pages after its local copy is gone
                await mgr.delete_if_unneeded(h)
            else:
                # unassigned, every owner confirmed, timer still running:
                # the stray is redundant, drop it without waiting
                await mgr.drop_stray_copy(h)

        elif rc.is_needed() and not present and mgr.is_assigned(h):
            # we are ring-ASSIGNED this block but don't have it: rebuild
            # locally from the RS parity sidecar when possible (zero
            # network — works with every replica down), else fetch from a
            # replica (ref resync.rs:457-468).  is_assigned matters when
            # data_replication_mode < replication_mode: the block_ref
            # partition (meta factor) then holds rc on nodes the data
            # ring does NOT assign the block to, and without the check
            # every rc holder would pull its own copy.
            if mgr.parity_store is not None:
                data = await asyncio.to_thread(
                    mgr.parity_store.try_reconstruct, h
                )
                if data is not None:
                    from .block import DataBlock

                    await mgr.write_block(h, DataBlock.plain(data))
                    mgr.blocks_reconstructed += 1
                    mgr.note_heal("local_sidecar")
                    return len(data)
            try:
                # a pure refetch is idempotent: a bounded retry budget
                # (shared across the replica fan-out) on transport
                # errors, like the need_block probe above (satellite:
                # read-path disk_error entries land here and must not
                # give up on one connection reset)
                block = await mgr.rpc_get_raw_block(h, for_storage=True,
                                                    idempotent=True)
            except Exception:
                # Replicas unreachable or damaged.  Next: the
                # migration-aware peer sweep — after an abrupt layout
                # change the sole copy can sit on a node outside the new
                # ring whose rc hasn't migrated yet (so it won't push,
                # and the ring fetch above can't see it); the puller
                # must find it (sweep_get_block docstring).  Last line:
                # DISTRIBUTED parity — fetch ≥ k surviving codeword
                # pieces cluster-wide and decode the missing row
                # (survives whole-node loss, which neither fetch can;
                # the reference's only answer here is replication,
                # resync.rs:457-468).
                data = await mgr.sweep_get_block(h, try_ring=False)
                swept = data is not None
                if data is None:
                    if mgr.parity_reconstructor is None:
                        raise
                    data = await mgr.parity_reconstructor(h)
                if data is None:
                    raise
                from .block import DataBlock

                await mgr.write_block(h, DataBlock.plain(data))
                if swept:
                    mgr.note_heal("peer_sweep")
                    logger.info("fetched displaced block %s via peer "
                                "sweep", bytes(h).hex()[:16])
                else:
                    mgr.blocks_reconstructed += 1
                    mgr.note_heal("distributed_decode")
                    logger.info("reconstructed block %s from DISTRIBUTED "
                                "parity", bytes(h).hex()[:16])
                return len(data)
            await mgr.write_block(h, block, is_parity=block.parity)
            mgr.note_heal("resync_fetch")
            logger.info("resynced missing block %s", bytes(h).hex()[:16])
            moved += len(block.inner)
        return moved

    async def next_due_in(self) -> float:
        first = self.queue.first()
        if first is None:
            return 10.0
        when = struct.unpack(">Q", first[0][:8])[0]
        return max(0.05, min((when - now_msec()) / 1000.0, 10.0))


class ResyncWorker(Worker):
    """ref resync.rs:481-567; spawn `n_workers` of these."""

    def __init__(self, resync: BlockResyncManager, index: int = 0):
        self.resync = resync
        self.index = index
        self.tranquilizer = Tranquilizer()

    def name(self) -> str:
        return f"Block resync worker #{self.index + 1}"

    async def work(self) -> WorkerState:
        if self.index >= self.resync.n_workers:
            await asyncio.sleep(1.0)
            return WorkerState.IDLE
        st = self.status()
        st.queue_length = self.resync.queue_len()
        st.persistent_errors = self.resync.errors_len()
        st.tranquility = self.resync.tranquility
        self.tranquilizer.reset()
        state = await self.resync.resync_iter()
        if state == WorkerState.BUSY:
            return await self.tranquilizer.tranquilize_worker(
                self.resync.tranquility
            )
        return state

    async def wait_for_work(self) -> None:
        self.resync.notify.clear()
        delay = await self.resync.next_due_in()
        try:
            await asyncio.wait_for(self.resync.notify.wait(), timeout=delay)
        except asyncio.TimeoutError:
            pass
