"""RebalanceMover — rate-bounded data motion for layout changes.

When the committed layout changes (a zone added, a zone drained, a node
swapped), the partitions whose replica set changed need their blocks
moved: new owners must FETCH what they gained, old owners must PUSH what
they lost — and the old copies must never be dropped before the new set
acks (the resync migration branch's confirm-before-delete invariant,
block/resync.py).

The generic safety net for this already exists: the refs-only layout
sweep re-enqueues EVERY referenced hash to the persistent resync queue.
This mover is the foreground, observable, rate-bounded flavor on top:

  - it walks ONLY the partitions whose node set changed (diffed by the
    model layer against the previous ring), in partition order, so a
    one-zone drain touches the drained data and nothing else;
  - each block is moved through the SAME convergence step a queued
    resync runs (BlockResyncManager.rebalance_hash → resync_block),
    sharing the busy-set so mover and queue workers never double-process
    a hash and failed moves fall back onto the persistent queue;
  - motion is paced against `rebalance_rate_mib` (config) so a drain
    under live client load cannot starve the foreground data path;
  - progress is first-class: rebalance_partitions_done / _total gauges
    and the rebalance_bytes_total counter say exactly how far a drain
    has gotten and how much data it streamed — `rebalance done == total`
    is the drill's completion criterion (docs/ROBUSTNESS.md).

One long-lived worker per node, idle until the model layer feeds it
changed partitions (enqueue); layout changes arriving mid-run merge into
the current run instead of stacking workers.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from ..utils.background import Worker, WorkerState
from ..utils.data import Hash

logger = logging.getLogger("garage_tpu.block.rebalance")

# blocks moved per work() slice: bounds event-loop occupancy between
# scheduler yields, NOT throughput (pacing below does that)
MOVE_BATCH = 8


class RebalanceMover(Worker):
    def __init__(self, manager, resync, rate_mib_s: float = 64.0,
                 metrics=None, governor=None):
        self.manager = manager
        self.resync = resync
        self.rate_bytes = max(float(rate_mib_s), 0.001) * (1 << 20)
        # load governor (utils/overload.py): scales the effective pacing
        # rate by the background throttle ratio, so a drain under client
        # overload cedes bandwidth beyond the static rate ceiling and
        # speeds back up when foreground pressure clears
        self.governor = governor
        self._pending: List[int] = []   # partitions left, walk order
        self._queued = set()
        self._cursor: Optional[bytes] = None  # rc-tree key inside head
        self._notify = asyncio.Event()
        self.partitions_total = 0
        self.partitions_done = 0
        self.bytes_moved = 0
        self.blocks_moved = 0
        self.runs = 0
        if metrics is not None:
            self.m_done = metrics.gauge(
                "rebalance_partitions_done",
                "Partitions fully walked by the current/last layout "
                "rebalance run")
            self.m_total = metrics.gauge(
                "rebalance_partitions_total",
                "Partitions whose replica set changed in the "
                "current/last layout rebalance run")
            self.m_bytes = metrics.counter(
                "rebalance_bytes_total",
                "Data-plane bytes streamed by the layout rebalance "
                "mover (pushes to new owners + fetches of gained "
                "blocks)")
            self.m_done.set(0.0)
            self.m_total.set(0.0)
        else:
            self.m_done = self.m_total = self.m_bytes = None

    def name(self) -> str:
        return "Layout rebalance mover"

    # --- feeding (model layer, on ring change) ---

    def enqueue(self, partitions: List[int]) -> None:
        """Add changed partitions to the walk.  A partition already
        pending stays where it is; a COMPLETED run starting anew resets
        the done/total progress pair (one run = one layout-change
        episode, possibly merged from several ring deltas)."""
        fresh = [p for p in partitions if p not in self._queued]
        if not fresh:
            return
        if not self._pending:
            # new episode
            self.partitions_total = 0
            self.partitions_done = 0
            self.runs += 1
        self._pending.extend(fresh)
        self._queued.update(fresh)
        self.partitions_total += len(fresh)
        self._observe()
        self._notify.set()
        logger.info("rebalance: %d changed partition(s) enqueued "
                    "(%d pending)", len(fresh), len(self._pending))

    def _observe(self) -> None:
        if self.m_done is not None:
            self.m_done.set(float(self.partitions_done))
            self.m_total.set(float(self.partitions_total))

    def idle(self) -> bool:
        return not self._pending

    # --- the walk ---

    def _next_entries(self, partition: int, n: int):
        """Up to n (key, _) rc entries of `partition` after the cursor —
        partition == first hash byte (ring.partition_of)."""
        rc = self.manager.rc
        out = []
        cursor = self._cursor
        while len(out) < n:
            if cursor is None:
                # strictly-greater probe from the partition's floor: the
                # max key of partition-1 (first byte IS the partition,
                # ring.partition_of)
                nxt = rc.get_gt(bytes([partition - 1]) + b"\xff" * 31) \
                    if partition else rc.tree.first()
            else:
                nxt = rc.get_gt(cursor)
            if nxt is None or nxt[0][0] != partition:
                return out, True
            out.append(nxt[0])
            cursor = nxt[0]
            self._cursor = cursor
        return out, False

    async def work(self) -> WorkerState:
        if not self._pending:
            return WorkerState.IDLE
        p = self._pending[0]
        # on-loop on purpose: a handful of point lookups, and the rc
        # tree's other writers (table hooks, resync) run on the loop —
        # an off-thread scan would race them on the memory engine
        keys, part_done = self._next_entries(p, MOVE_BATCH)
        moved = 0
        for key in keys:
            moved += await self.resync.rebalance_hash(Hash(key))
            self.blocks_moved += 1
        if moved:
            self.bytes_moved += moved
            if self.m_bytes is not None:
                self.m_bytes.inc(moved)
        if part_done:
            self._pending.pop(0)
            self._queued.discard(p)
            self._cursor = None
            self.partitions_done += 1
            self._observe()
            if not self._pending:
                logger.info(
                    "rebalance run complete: %d/%d partitions, %d blocks "
                    "examined, %d bytes moved", self.partitions_done,
                    self.partitions_total, self.blocks_moved,
                    self.bytes_moved)
        st = self.status()
        st.progress = (
            f"{self.partitions_done}/{self.partitions_total} partitions")
        st.queue_length = len(self._pending)
        if moved:
            # pacing: sleep the time this slice's bytes "cost" at the
            # configured rate, so a drain shares the wire with clients;
            # the governor's throttle ratio shrinks the effective rate
            # further while foreground pressure is high
            rate = self.rate_bytes
            if self.governor is not None:
                rate *= max(self.governor.ratio(), 1e-3)
            await asyncio.sleep(min(moved / rate, 5.0))
        return WorkerState.BUSY

    async def wait_for_work(self) -> None:
        self._notify.clear()
        if self._pending:
            return
        try:
            await asyncio.wait_for(self._notify.wait(), timeout=10.0)
        except asyncio.TimeoutError:
            pass
