"""Disk-fault robustness: the manager's filesystem boundary, the
per-root health state machine, and the crash-consistency janitor.

The reference trusts the local disk blindly — `write_block` has no
ENOSPC story and a read-time EIO surfaces as an unhandled error
(ref src/block/manager.rs:478-590).  Its durability loop (scrub →
quarantine → resync refetch, repair.rs/resync.rs) only covers *content*
corruption.  This module gives the storage layer the same degraded-mode
treatment PR 4 gave the RPC layer:

  - ``DiskIo`` — every byte BlockManager moves to or from disk goes
    through one of these methods, so a test (``testing/faults.py``
    FaultyDisk) can inject EIO / ENOSPC / fsync failure / torn writes /
    bit-rot / latency at exactly the boundary the real kernel would.
  - ``DiskHealthMonitor`` — per-data-root ``ok → degraded(read-only) →
    failed`` state machine, driven by a free-space watermark (statvfs
    preflight before every block write) and by disk-error streaks via
    the same ``CircuitBreaker`` the RPC layer uses per peer
    (net/resilience.py).  A degraded root rejects writes with a typed
    ``StorageFull``/``StorageError`` wire code so write quorums route
    around the node while reads keep flowing.
  - ``janitor_pass`` — boot-time crash-consistency sweep: purge
    orphaned ``.tmp`` files (a torn write whose rename never happened —
    by construction unacknowledged), bound the ``.corrupted``
    quarantine (oldest-first purge over a files/bytes budget), and
    report quarantined hashes so the caller re-enqueues them for
    resync.

Everything here is synchronous and dependency-light; BlockManager calls
it from threads (to_thread) on hot paths and inline at boot.
"""

from __future__ import annotations

import errno
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..net.resilience import CircuitBreaker, ResilienceTunables
from ..utils.direct_io import write_file_direct
from ..utils.error import StorageError, StorageFull

logger = logging.getLogger("garage_tpu.block.health")

# disk_root_state gauge encoding (docs/ROBUSTNESS.md + dashboard
# mappings rely on these values, mirroring BREAKER_STATE_VALUES)
DISK_STATE_VALUES = {"ok": 0.0, "degraded": 1.0, "failed": 2.0}

# a root whose consecutive-error streak reaches threshold × this factor
# is FAILED: even the half-open write probe is refused, only successful
# reads (or operator intervention) walk it back
DISK_FAILED_FACTOR = 4

# quarantine purge policy defaults (config quarantine_max_files/_bytes)
QUARANTINE_MAX_FILES = 128
QUARANTINE_MAX_BYTES = 256 << 20


class DiskIo:
    """The manager's filesystem boundary.  One instance per
    BlockManager (``manager.disk``); FaultyDisk wraps it to inject
    faults per data root without monkeypatching os.*  Methods raise
    plain OSError — classification into StorageFull/StorageError
    happens at the manager, where the root is known.

    Every call also accumulates per-root busy seconds (``busy_seconds``,
    keyed by the root the path maps to via the manager-installed
    ``root_of`` hook) — the per-root U of the USE method, scraped as
    ``disk_busy_seconds{root=}``.  Two clock reads per I/O call,
    negligible next to the syscall."""

    def __init__(self):
        # set by BlockManager: path -> data-root; unmapped paths (meta
        # dir fsyncs, tests) accumulate under ""
        self.root_of = None
        self.busy_seconds: dict = {}
        # concurrent executor threads finish I/O on the same root: the
        # read-modify-write below would lose increments without a lock —
        # exactly under the load the gauge exists to diagnose
        self._busy_lock = threading.Lock()

    def _note(self, path: str, t0: float) -> None:
        dt = time.perf_counter() - t0
        fn = self.root_of
        try:
            root = fn(path) if fn is not None else ""
        except Exception:  # noqa: BLE001 — accounting must never raise
            root = ""
        root = root or ""
        with self._busy_lock:
            self.busy_seconds[root] = self.busy_seconds.get(root, 0.0) + dt

    def read_file(self, path: str) -> bytes:
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                return f.read()
        finally:
            self._note(path, t0)

    def read_file_direct(self, path: str) -> bytes:
        """O_DIRECT read (buffered fallback inside) — the scrub path's
        flavor: it must not evict the GET path's page-cache working set
        (see utils/direct_io.py)."""
        from ..utils.direct_io import read_file_direct
        t0 = time.perf_counter()
        try:
            return read_file_direct(path)
        finally:
            self._note(path, t0)

    def write_file(self, path: str, data: bytes, fsync: bool = False) -> None:
        t0 = time.perf_counter()
        try:
            write_file_direct(path, data, fsync=fsync)
        finally:
            self._note(path, t0)

    def replace(self, src: str, dst: str) -> None:
        t0 = time.perf_counter()
        try:
            os.replace(src, dst)
        finally:
            self._note(dst, t0)

    def remove(self, path: str) -> None:
        os.remove(path)

    def fsync_dir(self, path: str) -> None:
        t0 = time.perf_counter()
        dirfd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
            self._note(path, t0)

    def statvfs(self, path: str):
        return os.statvfs(path)


def _error_kind(e: BaseException) -> str:
    """Bounded-cardinality label for a disk error: the errno mnemonic
    (EIO, ENOSPC, …) when there is one, the class name otherwise."""
    eno = getattr(e, "errno", None)
    if eno is not None:
        return errno.errorcode.get(eno, f"E{eno}")
    return type(e).__name__


# OSError kinds that blame the PROCESS, not the disk: fd exhaustion,
# memory pressure, interrupted syscalls.  They clear the moment load
# drops, so they must never quarantine a healthy copy or feed a root's
# error streak (32 EMFILE reads would otherwise latch the root FAILED
# and mass-evict good data).  Everything else — EIO, EROFS, EISDIR,
# ENOTDIR, unknown errnos — implicates the media or the on-disk layout.
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, n) for n in
    ("EMFILE", "ENFILE", "ENOMEM", "EAGAIN", "EWOULDBLOCK", "EINTR",
     "EDEADLK")
    if hasattr(errno, n))


def is_media_error(e: BaseException) -> bool:
    """Does this OSError justify destructive handling (quarantine the
    copy, feed the root's health streak), or is it transient process
    resource pressure where the bytes on disk are fine?"""
    return getattr(e, "errno", None) not in _TRANSIENT_ERRNOS


class DiskHealthMonitor:
    """Per-data-root health: ``ok → degraded(read-only) → failed``.

    Two independent drivers, matching how disks actually die:

      - **space**: a cached statvfs preflight before every write; free
        bytes below ``watermark`` flips the root read-only
        (``StorageFull``) until space recovers — no error streak needed,
        full is not broken.
      - **errors**: read/write OSErrors feed a per-root CircuitBreaker
        (reused from net/resilience.py, injectable clock): a streak of
        ``error_threshold`` opens it → degraded (writes rejected with
        ``StorageError``, reads keep flowing and failing over per-hash);
        after ``cooldown`` one half-open probe write is admitted, and a
        success closes it.  A streak of ``error_threshold ×
        DISK_FAILED_FACTOR`` latches FAILED: no probe writes at all;
        only a successful operation (reads still run) resets the streak
        and walks the root back through the breaker.

    Any successful op on the root clears the streak — a disk serving
    reads fine while a write blips is flaky, not dead; the watermark
    covers the common write-only failure (disk full) regardless."""

    def __init__(
        self,
        roots: List[str],
        watermark: int = 128 << 20,
        error_threshold: int = 8,
        cooldown: float = 30.0,
        statvfs: Optional[Callable[[str], object]] = None,
        clock: Callable[[], float] = time.monotonic,
        counter=None,          # disk_error_total{op,kind} (optional)
    ):
        self.watermark = int(watermark)
        self.error_threshold = max(1, int(error_threshold))
        self.cooldown = float(cooldown)
        self._statvfs = statvfs or (lambda p: os.statvfs(p))
        self._clock = clock
        self._counter = counter
        self._tun = ResilienceTunables(
            breaker_failure_threshold=self.error_threshold,
            breaker_open_secs=self.cooldown,
            # every disk error is its own event: the burst dedupe exists
            # for one TCP conn failing N RPCs at once, which has no disk
            # analogue, and tests need deterministic streak counting
            breaker_failure_window=0.0,
        )
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._streak: Dict[str, int] = {}
        self._space_low: Dict[str, bool] = {}
        # root -> (checked_at, free_bytes|None); statvfs is cheap but a
        # hot write path must not syscall per block
        self._space_cache: Dict[str, Tuple[float, Optional[int]]] = {}
        self.cache_ttl = 0.5
        self.error_counts: Dict[Tuple[str, str], int] = {}
        for r in roots:
            self._ensure(r)

    @staticmethod
    def _norm(root: str) -> str:
        """One accounting key per root regardless of trailing slashes:
        a data_dir configured as '/data/' must not split its health
        between '/data/' (registered) and '/data' (what the manager's
        longest-prefix _root_of derives from block paths)."""
        return root.rstrip(os.sep) or os.sep

    def _ensure(self, root: str) -> CircuitBreaker:
        root = self._norm(root)
        br = self._breakers.get(root)
        if br is None:
            br = CircuitBreaker(self._tun, clock=self._clock)
            self._breakers[root] = br
            self._streak[root] = 0
            self._space_low[root] = False
        return br

    def roots(self) -> List[str]:
        return list(self._breakers)

    # --- space watermark ---

    def free_bytes(self, root: str, fresh: bool = False) -> Optional[int]:
        """Cached statvfs free bytes; None when statvfs itself fails
        (the root's filesystem is gone — treated as space-low)."""
        root = self._norm(root)
        now = self._clock()
        cached = self._space_cache.get(root)
        if cached is not None and not fresh and now - cached[0] < self.cache_ttl:
            return cached[1]
        try:
            sv = self._statvfs(root)
            free: Optional[int] = sv.f_bavail * sv.f_frsize
        except OSError as e:
            logger.warning("statvfs on %s failed: %s", root, e)
            free = None
        self._space_cache[root] = (now, free)
        self._space_low[root] = free is None or free < self.watermark
        return free

    # --- state machine ---

    def state(self, root: str) -> str:
        root = self._norm(root)
        self._ensure(root)
        self.free_bytes(root)   # refresh space_low through the cache
        if self._streak[root] >= self.error_threshold * DISK_FAILED_FACTOR:
            return "failed"
        if self._space_low[root]:
            return "degraded"
        if self._breakers[root].state_now() != "closed":
            return "degraded"
        return "ok"

    def states(self) -> Dict[str, str]:
        # snapshot: note_error in a worker thread may _ensure a root
        # while a scrape-time render iterates
        return {r: self.state(r) for r in list(self._breakers)}

    def worst_state(self) -> str:
        worst = "ok"
        for s in self.states().values():
            if DISK_STATE_VALUES[s] > DISK_STATE_VALUES[worst]:
                worst = s
        return worst

    def writable(self, root: str) -> bool:
        """Non-consuming writability hint (used by the need_block gate:
        a read-only root must not solicit block offers it would then
        reject).  Unlike check_writable this never takes the half-open
        probe slot.  A half-open root answers True: the resync push a
        need_block=True solicits is exactly the probe write that walks
        the root back to ok — answering False on a node with no direct
        PUT traffic would starve it of both recovery and its missing
        blocks (circular wait)."""
        root = self._norm(root)
        self._ensure(root)
        if self._streak[root] >= self.error_threshold * DISK_FAILED_FACTOR:
            return False
        self.free_bytes(root)   # refresh space_low through the cache
        if self._space_low[root]:
            return False
        return self._breakers[root].state_now() in ("closed", "half_open")

    def check_writable(self, root: str, need_bytes: int = 0) -> None:
        """Write preflight: raises StorageFull (space) or StorageError
        (error streak / failed) when the root is read-only.  A True-ish
        return path may consume the breaker's half-open probe slot —
        the caller MUST report the write's outcome via note_ok /
        note_error, exactly like the RPC breaker contract."""
        root = self._norm(root)
        self._ensure(root)
        if self._streak[root] >= self.error_threshold * DISK_FAILED_FACTOR:
            raise StorageError(
                f"data root {root} FAILED "
                f"({self._streak[root]} consecutive disk errors)")
        free = self.free_bytes(root)
        if free is None or free - need_bytes < self.watermark:
            raise StorageFull(
                f"data root {root} read-only: free space "
                f"{free if free is not None else 'unknown'} below "
                f"watermark {self.watermark}")
        if not self._breakers[root].allow():
            raise StorageError(
                f"data root {root} degraded (read-only): disk error "
                f"streak, retry after cooldown")

    # --- outcome reporting ---

    def note_error(self, root: str, op: str, e: BaseException) -> None:
        root = self._norm(root)
        self._ensure(root)
        kind = _error_kind(e)
        key = (op, kind)
        self.error_counts[key] = self.error_counts.get(key, 0) + 1
        if self._counter is not None:
            self._counter.inc(op=op, kind=kind)
        if getattr(e, "errno", None) == errno.ENOSPC:
            # full is not broken: a write-time ENOSPC the watermark
            # missed (quota, reserved blocks — statvfs can't see either)
            # flips the root space-low for one cache TTL, after which
            # the next preflight re-probes statvfs — but it never feeds
            # the streak/breaker, which would otherwise walk a merely
            # full disk to a latched FAILED within minutes on an
            # ingest-heavy node
            self._space_low[root] = True
            self._space_cache[root] = (self._clock(), None)
            # the failed write may have been the half-open probe
            # (check_writable consumed the slot): ENOSPC is a verdict
            # about space, not the streak — free the slot, or the root
            # stays un-probeable for a full extra cooldown after space
            # recovers
            self._breakers[root].release_probe()
            return
        self._streak[root] += 1
        self._breakers[root].on_failure()

    def note_ok(self, root: str, op: str = "read") -> None:
        root = self._norm(root)
        self._ensure(root)
        self._streak[root] = 0
        self._breakers[root].on_success()


# --- crash-consistent startup --------------------------------------------


def janitor_pass(
    roots: List[str],
    max_quarantine_files: int = QUARANTINE_MAX_FILES,
    max_quarantine_bytes: int = QUARANTINE_MAX_BYTES,
) -> Dict[str, object]:
    """One boot-time sweep over every data root:

      1. delete orphaned ``*.tmp`` files — a write that never reached
         its rename, so by the write path's construction it was never
         acknowledged; leaving it would shadow disk space forever (the
         tmp path is deterministic, so at most one per block, but a
         crashed bulk ingest leaves many);
      2. bound the ``.corrupted`` quarantine: oldest-first deletion
         until both the file-count and byte budgets hold (quarantined
         copies exist only as forensic evidence; resync re-fetches the
         content, so purging old ones loses nothing durable);
      3. collect the hashes of every surviving quarantined file so the
         caller re-enqueues them for resync — a node that crashed
         between quarantine and the resync enqueue must not leave the
         hole unfilled until the next scrub.

    The parity sidecar subtree is skipped — its files belong to
    ParityStore, which has its own refresh/purge cycle.  Returns a
    summary dict (counts + requeue hash list) for logging/tests."""
    tmp_purged = 0
    quarantined: List[Tuple[float, int, str]] = []  # (mtime, size, path)
    for root in roots:
        for dirpath, dirnames, files in os.walk(root):
            if "parity" in dirnames:
                dirnames.remove("parity")
            for name in files:
                p = os.path.join(dirpath, name)
                if name.endswith(".tmp"):
                    try:
                        os.remove(p)
                        tmp_purged += 1
                    except OSError as e:
                        logger.warning("janitor: purge of %s failed: %s",
                                       p, e)
                elif name.endswith(".corrupted"):
                    try:
                        st = os.stat(p)
                        quarantined.append((st.st_mtime, st.st_size, p))
                    except OSError:
                        continue
    quarantined.sort()  # oldest first
    q_purged = 0
    total = sum(sz for _m, sz, _p in quarantined)
    unpurgeable: List[Tuple[float, int, str]] = []
    while quarantined and (len(quarantined) > max_quarantine_files
                          or total > max_quarantine_bytes):
        entry = quarantined.pop(0)
        _m, sz, p = entry
        # the byte budget drops either way so the loop always advances,
        # but a FAILED purge is not a purge: the file survives on disk,
        # so it must stay counted as kept and its hash must still reach
        # the requeue scan below (a read-only root at boot must not make
        # the janitor silently forget quarantined holes)
        total -= sz
        try:
            os.remove(p)
        except OSError as e:
            logger.warning("janitor: quarantine purge of %s failed: %s", p, e)
            unpurgeable.append(entry)
            continue
        q_purged += 1
    quarantined = unpurgeable + quarantined
    requeue: List[bytes] = []
    seen = set()
    for _m, _sz, p in quarantined:
        base = os.path.basename(p)[: -len(".corrupted")]
        if base.endswith(".zst"):
            base = base[:-4]
        try:
            hb = bytes.fromhex(base)
        except ValueError:
            continue
        if len(hb) == 32 and hb not in seen:
            seen.add(hb)
            requeue.append(hb)
    return {
        "tmp_purged": tmp_purged,
        "quarantine_purged": q_purged,
        "quarantine_kept": len(quarantined),
        "requeue": requeue,
    }
