"""DataLayout — multi-drive placement of data blocks.

Equivalent of reference src/block/layout.rs: 1024 drive-partitions
(DRIVE_NPART layout.rs:12) mapped to data dirs proportionally to capacity;
hash bytes (2,3) pick the partition (HASH_DRIVE_BYTES layout.rs:14); each
partition has one *primary* dir (where blocks are written) and possibly
*secondary* dirs (older locations still checked on read, drained by the
rebalance worker, layout.rs:41-175).

Block file path: <dir>/<hex byte 0>/<hex byte 1>/<full hash hex>[.zst]
(ref block/manager.rs block_path / block_dir).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional

from ..utils.data import Hash
from ..utils.error import GarageError
from ..utils.migrate import Migrated

DRIVE_NPART = 1024          # ref layout.rs:12
HASH_DRIVE_BYTES = (2, 3)   # ref layout.rs:14


def drive_partition(h: Hash) -> int:
    b0, b1 = HASH_DRIVE_BYTES
    return ((h[b0] << 8) | h[b1]) % DRIVE_NPART


@dataclasses.dataclass
class DataDir:
    path: str
    capacity: Optional[int] = None   # None = read_only (no new writes)
    read_only: bool = False

    def pack(self):
        return [self.path, self.capacity, self.read_only]

    @classmethod
    def unpack(cls, v):
        return cls(path=v[0], capacity=v[1], read_only=bool(v[2]))


class DataLayout(Migrated):
    """ref layout.rs:17-27; persisted in the metadata dir so partition→dir
    assignment survives restarts and only moves minimally on change."""

    VERSION_MARKER = b"GT01datalayout"

    def __init__(
        self,
        data_dirs: Optional[List[DataDir]] = None,
        part_prim: Optional[List[int]] = None,
        part_sec: Optional[List[List[int]]] = None,
    ):
        self.data_dirs: List[DataDir] = data_dirs or []
        self.part_prim: List[int] = part_prim or []
        self.part_sec: List[List[int]] = part_sec or []

    # --- construction (ref layout.rs:41-81 initialize / :84-175 update) ---

    @classmethod
    def initialize(cls, dirs_cfg: List[Dict]) -> "DataLayout":
        dirs = _parse_dirs(dirs_cfg)
        writable = [i for i, d in enumerate(dirs) if not d.read_only]
        if not writable:
            raise GarageError("no writable data directory")
        lay = cls(data_dirs=dirs)
        lay.part_prim = _assign_partitions(dirs, writable)
        lay.part_sec = [[] for _ in range(DRIVE_NPART)]
        return lay

    def update(self, dirs_cfg: List[Dict]) -> "DataLayout":
        """New layout for a config change: keep blocks where they are when
        possible (old primary becomes secondary if the partition moved)."""
        dirs = _parse_dirs(dirs_cfg)
        writable = [i for i, d in enumerate(dirs) if not d.read_only]
        if not writable:
            raise GarageError("no writable data directory")
        new = DataLayout(data_dirs=dirs)
        new.part_prim = _assign_partitions(dirs, writable)
        new.part_sec = [[] for _ in range(DRIVE_NPART)]
        # map old dir indices to new by path
        path_to_new = {d.path: i for i, d in enumerate(dirs)}
        for p in range(DRIVE_NPART):
            olds = []
            if p < len(self.part_prim):
                olds.append(self.part_prim[p])
            if p < len(self.part_sec):
                olds.extend(self.part_sec[p])
            for oi in olds:
                if oi >= len(self.data_dirs):
                    continue
                ni = path_to_new.get(self.data_dirs[oi].path)
                if ni is not None and ni != new.part_prim[p] and ni not in new.part_sec[p]:
                    new.part_sec[p].append(ni)
        return new

    # --- lookup (ref layout.rs primary_block_dir / secondary_block_dirs) ---

    def primary_dir(self, h: Hash) -> str:
        p = drive_partition(h)
        return self.data_dirs[self.part_prim[p]].path

    def secondary_dirs(self, h: Hash) -> List[str]:
        p = drive_partition(h)
        return [self.data_dirs[i].path for i in self.part_sec[p]]

    def all_dirs(self, h: Hash) -> List[str]:
        return [self.primary_dir(h)] + self.secondary_dirs(h)

    def config_changed(self, dirs_cfg: List[Dict]) -> bool:
        return _parse_dirs(dirs_cfg) != self.data_dirs

    # --- serialization ---

    def fields(self):
        return {
            "data_dirs": [d.pack() for d in self.data_dirs],
            "part_prim": list(self.part_prim),
            "part_sec": [list(s) for s in self.part_sec],
        }

    @classmethod
    def from_fields(cls, d):
        return cls(
            data_dirs=[DataDir.unpack(v) for v in d["data_dirs"]],
            part_prim=list(d["part_prim"]),
            part_sec=[list(s) for s in d["part_sec"]],
        )


def _parse_dirs(dirs_cfg: List[Dict]) -> List[DataDir]:
    out = []
    for d in dirs_cfg:
        out.append(
            DataDir(
                path=d["path"],
                capacity=d.get("capacity"),
                read_only=bool(d.get("read_only", False)),
            )
        )
    return out


def _assign_partitions(dirs: List[DataDir], writable: List[int]) -> List[int]:
    """Distribute the 1024 partitions over writable dirs proportionally to
    capacity (equal weights when no capacities are given), deterministically
    (seeded shuffle so all nodes with the same config agree)."""
    weights = []
    for i in writable:
        cap = dirs[i].capacity
        weights.append(cap if cap else 1)
    total = sum(weights)
    counts = [w * DRIVE_NPART // total for w in weights]
    while sum(counts) < DRIVE_NPART:
        counts[counts.index(min(counts))] += 1
    assignment = []
    for idx, c in zip(writable, counts):
        assignment.extend([idx] * c)
    rng = random.Random(0x6172616765)  # fixed seed: deterministic layout
    rng.shuffle(assignment)
    return assignment[:DRIVE_NPART]
