"""DataBlock — a block payload, plain or zstd-compressed.

Equivalent of reference src/block/block.rs:10-115: `Plain(bytes)` vs
`Compressed(bytes)` (zstd frame with content checksum); `verify` checks
the content hash for plain data and the zstd frame checksum for compressed
data (block.rs:66-78); `from_buffer` compresses when it shrinks the block
(block.rs:80-91).

`verify` routes through a BlockCodec when one is supplied (the
BlockManager read path passes its codec — `codec.verify_one`, whose
default is defined in terms of the same batch_verify the scrub path
uses); without a codec it falls back to hashlib directly (standalone
DataBlock uses in tests/tools).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..utils.data import Hash, block_hash
from ..utils.zstd_compat import zstandard
from ..utils.error import CorruptData


@dataclasses.dataclass
class DataBlockHeader:
    """Wire header accompanying a block body (ref block.rs DataBlockHeader)."""

    compressed: bool

    def pack(self) -> str:
        return "zst" if self.compressed else "plain"

    @classmethod
    def unpack(cls, v: str) -> "DataBlockHeader":
        return cls(compressed=(v == "zst"))


class DataBlock:
    # `parity`: this block is a distributed-parity shard (travels with
    # fetches so re-writes on other nodes keep it out of the write-time
    # codeword accumulators — parity of parity protects nothing the
    # decode can use)
    __slots__ = ("compressed", "inner", "parity")

    def __init__(self, inner: bytes, compressed: bool,
                 parity: bool = False):
        self.inner = inner
        self.compressed = compressed
        self.parity = parity

    @classmethod
    def plain(cls, data: bytes) -> "DataBlock":
        return cls(data, compressed=False)

    @classmethod
    def compressed_from(cls, data: bytes) -> "DataBlock":
        return cls(data, compressed=True)

    @classmethod
    def from_buffer(
        cls, data: bytes, compression_level: Optional[int]
    ) -> "DataBlock":
        """Compress if configured and it shrinks the block
        (ref block.rs:80-91)."""
        if compression_level is not None:
            c = zstandard.ZstdCompressor(
                level=compression_level,
                write_checksum=True,
                write_content_size=True,
            )
            out = c.compress(data)
            if len(out) < len(data):
                return cls(out, compressed=True)
        return cls(data, compressed=False)

    def header(self) -> DataBlockHeader:
        return DataBlockHeader(self.compressed)

    def verify(self, hash: Hash, algo: str = "blake2s", codec=None) -> None:
        """ref block.rs:66-78: plain → content hash must match; compressed →
        zstd frame checksum validates (content hash covers the *uncompressed*
        bytes, which we don't have without decompressing).  With `codec`,
        plain-block hashing goes through codec.verify_one — the same seam
        the batched scrub path uses."""
        if self.compressed:
            try:
                zstandard.ZstdDecompressor().decompress(self.inner)
            except zstandard.ZstdError as e:
                raise CorruptData(f"zstd verify failed: {e}") from None
        elif codec is not None:
            if not codec.verify_one(self.inner, hash):
                raise CorruptData(f"hash mismatch for block {hash.hex()[:16]}")
        else:
            if bytes(block_hash(self.inner, algo)) != bytes(hash):
                raise CorruptData(f"hash mismatch for block {hash.hex()[:16]}")

    def decompressed(self) -> bytes:
        if self.compressed:
            return zstandard.ZstdDecompressor().decompress(self.inner)
        return self.inner

    def __len__(self) -> int:
        return len(self.inner)
