"""RebuildScheduler — a full-node loss healed as ONE planned flow.

Losing a whole storage node used to heal as thousands of independent
greedy per-codeword repairs: the layout sweep dumps every referenced
hash onto the resync queue, each queue worker fetches its own k pieces,
and nobody paces the storm as a whole.  This worker plans the rebuild
globally instead:

  - it walks ONLY the partitions whose replica set lost a node (diffed
    by the model layer, like the rebalance mover), in partition order,
    over this node's rc tree — every missing block this node is now
    responsible for is found exactly once;
  - each lost block resolves to its CODEWORD: all of the codeword's
    lost rows are decoded from ONE shared fetch (chain repair,
    repair_plan.reconstruct_group) and the sibling rows this node is
    not assigned are pushed straight to their needy owners — a
    codeword never pays k fetches per lost row;
  - repair trees are rooted round-robin per survivor-set group
    (`rotate`), so one well-placed peer does not become the
    aggregation root — and the fan-in hotspot — of every tree;
  - motion is paced against `rebuild_rate_mib` (config) scaled by the
    LoadGovernor throttle ratio, so the storm cedes bandwidth to
    foreground traffic under pressure and speeds back up when it
    clears;
  - progress checkpoints (partition cursor + pending set) persist via
    the standard Persister, so a coordinator restart RESUMES the walk
    where it stopped instead of restarting from partition zero.

Dedupe contract with resync (block/resync.py): while a partition is
pending here, queue workers and the rebalance mover skip its hashes
(`owns`); anything this worker ultimately fails to rebuild is parked
back onto the persistent queue with source="rebuild" once its
partition completes — so the two subsystems never double-repair a
block, and nothing is ever dropped on the floor.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

from ..net.frame import PRIO_BACKGROUND
from ..utils.background import Worker, WorkerState
from ..utils.data import Hash
from ..utils.migrate import Migrated

logger = logging.getLogger("garage_tpu.block.rebuild")

# blocks examined per work() slice — event-loop occupancy, not
# throughput (pacing below does that)
REBUILD_BATCH = 8
# checkpoint cadence: a restart re-examines at most this many codewords
# (re-examining a healed block is a cheap is_block_present hit)
CHECKPOINT_EVERY = 32
# bound on the per-survivor-set root-rotation table
MAX_ROTATION_GROUPS = 1024
# After a node loss, refs for the lost partitions keep arriving by table
# sync for a while (the new owner gains the block_ref partition WITH the
# block assignment, and sync lags the ring change — at fleet scale by
# minutes).  A ref that lands AFTER the walk passed its partition
# re-queues that partition (note_ref) for this long, so late arrivals
# heal through the planned flow instead of leaking to one-off resyncs.
REARM_WINDOW_S = 600.0


class RebuildCheckpoint(Migrated):
    """Persistent rebuild progress: the pending partition walk, the
    cursor inside the head partition, and the parked-failure list."""

    VERSION_MARKER = b"GT01rbld"

    def __init__(self, active: bool = False, ring_digest: bytes = b"",
                 pending: Optional[List[int]] = None,
                 cursor: bytes = b"", partitions_done: int = 0,
                 partitions_total: int = 0, codewords: int = 0,
                 blocks: int = 0, bytes_healed: int = 0,
                 parked: Optional[List[bytes]] = None):
        self.active = active
        self.ring_digest = ring_digest
        self.pending = list(pending or [])
        self.cursor = cursor
        self.partitions_done = partitions_done
        self.partitions_total = partitions_total
        self.codewords = codewords
        self.blocks = blocks
        self.bytes_healed = bytes_healed
        self.parked = list(parked or [])

    def fields(self):
        return [self.active, self.ring_digest, self.pending, self.cursor,
                self.partitions_done, self.partitions_total,
                self.codewords, self.blocks, self.bytes_healed,
                self.parked]

    @classmethod
    def from_fields(cls, body):
        return cls(bool(body[0]), bytes(body[1]),
                   [int(p) for p in body[2]], bytes(body[3]),
                   int(body[4]), int(body[5]), int(body[6]),
                   int(body[7]), int(body[8]),
                   [bytes(b) for b in body[9]])


class RebuildScheduler(Worker):
    def __init__(self, manager, resync, rate_mib_s: float = 256.0,
                 persister=None, metrics=None, governor=None,
                 lookup=None, decode_fallback=None,
                 probe_siblings: bool = True):
        self.manager = manager
        self.resync = resync
        self.rate_bytes = max(float(rate_mib_s), 0.001) * (1 << 20)
        self.persister = persister
        self.governor = governor
        # model-layer bindings (parity_repair): codeword lookup for a
        # member hash, and the decode-ladder fallback for codewords the
        # planner cannot serve
        self.lookup = lookup
        self.decode_fallback = decode_fallback
        self.probe_siblings = probe_siblings
        self._pending: List[int] = []   # partitions left, walk order
        self._queued = set()
        self._cursor: Optional[bytes] = None  # rc-tree key inside head
        self._parked: List[bytes] = []  # failures, flushed per partition
        self._rotation: Dict[frozenset, int] = {}
        # late-ref re-arm state (see REARM_WINDOW_S / note_ref)
        self._rearm_parts: set = set()
        self._rearm_until = 0.0
        self._rewalk: set = set()
        self.rearms = 0
        self._notify = asyncio.Event()
        self.ring_digest = b""
        self.partitions_total = 0
        self.partitions_done = 0
        self.codewords_rebuilt = 0
        self.blocks_healed = 0
        self.bytes_healed = 0
        self.runs = 0
        self._since_checkpoint = 0
        # governor-coexistence evidence for the chaos drill: how often
        # the walk paused to pace, and the lowest throttle ratio seen
        self.paced_sleeps = 0
        self.governor_ratio_min = 1.0
        if metrics is not None:
            self.m_done = metrics.gauge(
                "rebuild_partitions_done",
                "Partitions fully walked by the current/last full-node "
                "rebuild run")
            self.m_total = metrics.gauge(
                "rebuild_partitions_total",
                "Partitions that lost a replica in the current/last "
                "full-node rebuild run")
            self.m_bytes = metrics.counter(
                "rebuild_bytes_total",
                "Bytes of lost rows decoded and re-materialized by the "
                "fleet rebuild scheduler")
            self.m_rearm = metrics.counter(
                "rebuild_rearm_total",
                "Lost partitions re-queued because a block ref arrived "
                "(table sync) after the rebuild walk had passed them")
            self.m_done.set(0.0)
            self.m_total.set(0.0)
        else:
            self.m_done = self.m_total = self.m_bytes = None
            self.m_rearm = None

    def name(self) -> str:
        return "Fleet rebuild scheduler"

    # --- feeding (model layer, on ring change) ---

    def node_lost(self, partitions: List[int], ring_digest: bytes) -> None:
        """Partitions whose replica set lost a node.  Merging semantics
        match the rebalance mover: a completed run starting anew resets
        the progress pair; partitions already pending stay put."""
        fresh = [p for p in partitions if p not in self._queued]
        self.ring_digest = bytes(ring_digest)
        self._rearm_parts = set(partitions)
        self._rearm_until = time.monotonic() + REARM_WINDOW_S
        if not fresh:
            self._checkpoint(force=True)
            return
        if not self._pending:
            # new episode
            self.partitions_total = 0
            self.partitions_done = 0
            self.runs += 1
        self._pending.extend(fresh)
        self._queued.update(fresh)
        self.partitions_total += len(fresh)
        self._observe()
        self._checkpoint(force=True)
        self._notify.set()
        logger.info("rebuild: %d lost partition(s) enqueued (%d pending)",
                    len(fresh), len(self._pending))

    def maybe_resume(self, ring_digest: bytes) -> bool:
        """Boot-time: restore an interrupted rebuild if the ring still
        matches the checkpoint (a further layout change means the lost
        set changed — the fresh ring diff re-feeds us instead)."""
        if self.persister is None:
            return False
        chk = self.persister.load()
        if chk is None or not chk.active:
            return False
        if bytes(chk.ring_digest) != bytes(ring_digest):
            logger.info("rebuild checkpoint is for another ring: discarded")
            self._checkpoint(force=True)  # persist the inactive state
            return False
        self.ring_digest = bytes(chk.ring_digest)
        self._pending = list(chk.pending)
        self._queued = set(chk.pending)
        self._cursor = chk.cursor or None
        self._parked = list(chk.parked)
        self.partitions_done = chk.partitions_done
        self.partitions_total = chk.partitions_total
        self.codewords_rebuilt = chk.codewords
        self.blocks_healed = chk.blocks
        self.bytes_healed = chk.bytes_healed
        self.runs += 1
        self._observe()
        self._notify.set()
        logger.info(
            "rebuild resumed from checkpoint: %d/%d partitions done, "
            "%d pending", self.partitions_done, self.partitions_total,
            len(self._pending))
        return True

    def _checkpoint(self, force: bool = False) -> None:
        self._since_checkpoint += 1
        if not force and self._since_checkpoint < CHECKPOINT_EVERY:
            return
        self._since_checkpoint = 0
        if self.persister is None:
            return
        self.persister.save(RebuildCheckpoint(
            active=bool(self._pending), ring_digest=self.ring_digest,
            pending=list(self._pending), cursor=self._cursor or b"",
            partitions_done=self.partitions_done,
            partitions_total=self.partitions_total,
            codewords=self.codewords_rebuilt, blocks=self.blocks_healed,
            bytes_healed=self.bytes_healed, parked=list(self._parked)))

    def _observe(self) -> None:
        if self.m_done is not None:
            self.m_done.set(float(self.partitions_done))
            self.m_total.set(float(self.partitions_total))

    def idle(self) -> bool:
        return not self._pending

    # --- resync dedupe seam ---

    def owns(self, hb: bytes) -> bool:
        """True while this scheduler will (still) reach `hb` in its own
        walk — resync workers and the rebalance mover skip such hashes.
        A hash at or behind the head partition's cursor was already
        examined (and parked if it failed), so it is NOT claimed."""
        if not self._pending or hb[0] not in self._queued:
            return False
        if (hb[0] == self._pending[0] and self._cursor is not None
                and bytes(hb) <= self._cursor):
            return False
        return True

    def note_ref(self, h: Hash) -> bool:
        """A block ref just landed (incref 0→1, usually table sync
        delivering a migrated partition).  If it belongs to a partition
        of the recent node loss that the walk has already passed,
        re-queue the partition — table sync lags the ring change, and a
        walk that raced ahead of it would otherwise declare the rebuild
        complete while the refs it is responsible for are still in
        flight.  Returns True when the scheduler will (re)visit the
        hash.  Bounded: only within REARM_WINDOW_S of the loss, only
        for its partitions, one queue entry per partition at a time."""
        hb = bytes(h)
        p = hb[0]
        if p not in self._rearm_parts or time.monotonic() > self._rearm_until:
            return False
        if p in self._queued:
            if (self._pending and p == self._pending[0]
                    and self._cursor is not None and hb <= self._cursor):
                # head partition, walk already past this key: finish the
                # pass, then walk the partition once more
                self._rewalk.add(p)
            return True
        self._pending.append(p)
        self._queued.add(p)
        self.partitions_total += 1
        self.rearms += 1
        if self.m_rearm is not None:
            self.m_rearm.inc()
        self._observe()
        self._notify.set()
        logger.info("rebuild: partition %d re-queued (late ref %s)",
                    p, hb.hex()[:16])
        return True

    # --- the walk ---

    def _next_entries(self, partition: int, n: int):
        """Up to n rc keys of `partition` after the cursor — partition
        == first hash byte (ring.partition_of), like the mover's walk."""
        rc = self.manager.rc
        out = []
        cursor = self._cursor
        while len(out) < n:
            if cursor is None:
                nxt = rc.get_gt(bytes([partition - 1]) + b"\xff" * 31) \
                    if partition else rc.tree.first()
            else:
                nxt = rc.get_gt(cursor)
            if nxt is None or nxt[0][0] != partition:
                return out, True
            out.append(nxt[0])
            cursor = nxt[0]
            self._cursor = cursor
        return out, False

    async def work(self) -> WorkerState:
        if not self._pending:
            return WorkerState.IDLE
        p = self._pending[0]
        keys, part_done = self._next_entries(p, REBUILD_BATCH)
        healed = 0
        for key in keys:
            try:
                healed += await self._rebuild_hash(Hash(key))
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — park, keep walking
                logger.warning("rebuild of %s failed: %s",
                               key.hex()[:16], e)
                self._parked.append(bytes(key))
        if healed:
            self.bytes_healed += healed
            if self.m_bytes is not None:
                self.m_bytes.inc(healed)
        if part_done:
            self._pending.pop(0)
            self._cursor = None
            self.partitions_done += 1
            if p in self._rewalk:
                # a ref landed behind the cursor mid-walk: keep the
                # partition queued and walk it again from the top
                self._rewalk.discard(p)
                self._pending.append(p)
                self.partitions_total += 1
                self.rearms += 1
                if self.m_rearm is not None:
                    self.m_rearm.inc()
            else:
                self._queued.discard(p)
            self._observe()
            parked, self._parked = self._parked, []
            if p in self._queued:
                # partition re-queued for a rewalk: the next pass
                # re-examines (and re-parks) these, don't flush yet
                parked = []
            # flush failures AFTER the partition leaves the owned set,
            # so owns() no longer claims them and resync takes over
            for hb in parked:
                self.resync.put_to_resync(Hash(hb), 30.0, source="rebuild")
            self._checkpoint(force=True)
            if not self._pending:
                logger.info(
                    "rebuild run complete: %d/%d partitions, %d codewords, "
                    "%d blocks healed, %d bytes", self.partitions_done,
                    self.partitions_total, self.codewords_rebuilt,
                    self.blocks_healed, self.bytes_healed)
        else:
            self._checkpoint()
        st = self.status()
        st.progress = (
            f"{self.partitions_done}/{self.partitions_total} partitions")
        st.queue_length = len(self._pending)
        if healed:
            rate = self.rate_bytes
            if self.governor is not None:
                ratio = max(self.governor.ratio(), 1e-3)
                self.governor_ratio_min = min(
                    self.governor_ratio_min, ratio)
                rate *= ratio
            self.paced_sleeps += 1
            await asyncio.sleep(min(healed / rate, 5.0))
        return WorkerState.BUSY

    async def wait_for_work(self) -> None:
        self._notify.clear()
        if self._pending:
            return
        try:
            await asyncio.wait_for(self._notify.wait(), timeout=10.0)
        except asyncio.TimeoutError:
            pass

    # --- one lost block → its whole codeword ---

    async def _rebuild_hash(self, h: Hash) -> int:
        mgr = self.manager
        hb = bytes(h)
        if hb in self.resync.busy_set:
            return 0  # a queue worker beat us to it
        if mgr.is_block_present(h):
            return 0
        if not (mgr.rc.get(h).is_needed() and mgr.is_assigned(h)):
            return 0  # not this node's row to re-materialize
        self.resync.busy_set.add(hb)
        try:
            ent = None
            if self.lookup is not None:
                for cand in await self.lookup(h):
                    if (cand.member_index < len(cand.members)
                            and bytes(cand.members[cand.member_index])
                            == hb):
                        ent = cand
                        break
            if ent is None:
                # no codeword coverage (pre-EC data, parity of a dead
                # word): the resync ladder's replica fetch / sweep is
                # the only option — park it
                self._parked.append(hb)
                return 0
            healed = await self._rebuild_codeword(h, ent)
            if healed == 0 and not mgr.is_block_present(h):
                self._parked.append(hb)
            return healed
        finally:
            self.resync.busy_set.discard(hb)

    async def _rebuild_codeword(self, h: Hash, ent) -> int:
        """Decode EVERY lost row of `h`'s codeword from one shared
        fetch set (chain repair) and deliver each row to its owner —
        locally written when this node is assigned, pushed via
        put_block when a sibling's owner probes as needy."""
        mgr = self.manager
        targets = [int(ent.member_index)]
        push_to: Dict[int, object] = {}
        for i, mh in enumerate(ent.members):
            if i == int(ent.member_index):
                continue
            sib = Hash(bytes(mh))
            if mgr.is_block_present(sib):
                continue
            if mgr.is_assigned(sib):
                if mgr.rc.get(sib).is_needed():
                    targets.append(i)
                continue
            if not self.probe_siblings:
                continue
            node = await self._probe_needy(sib)
            if node is not None:
                targets.append(i)
                push_to[i] = node
        targets = sorted(set(targets))
        rotate = self._next_rotation(ent)
        rows: Dict[int, Optional[bytes]] = {}
        planner = getattr(mgr, "repair_planner", None)
        if planner is not None:
            rows = await planner.reconstruct_group(ent, targets,
                                                   rotate=rotate)
        want = int(ent.member_index)
        if rows.get(want) is None and self.decode_fallback is not None:
            data = await self.decode_fallback(h, ent)
            if data is not None:
                rows[want] = data
        healed = 0
        from .block import DataBlock

        for t in targets:
            data = rows.get(t)
            if data is None:
                continue
            mh = Hash(bytes(ent.members[t]))
            if mgr.is_assigned(mh):
                await mgr.write_block(mh, DataBlock.plain(data))
                mgr.blocks_reconstructed += 1
                mgr.note_heal("rebuild")
                self.blocks_healed += 1
                healed += len(data)
            elif t in push_to:
                if await self._push_row(mh, data, push_to[t]):
                    self.blocks_healed += 1
                    healed += len(data)
        if healed:
            self.codewords_rebuilt += 1
        return healed

    def _next_rotation(self, ent) -> int:
        """Round-robin tree-root rotation per survivor-set group: the
        group key is the set of primary holders of the codeword's
        pieces (pure ring math — no RPC), so codewords sharing a
        survivor set spread their aggregation roots instead of all
        rooting at the same best-ranked peer."""
        mgr = self.manager
        holders = []
        for mh in list(ent.members) + list(ent.parity_hashes):
            nodes = mgr.replication.read_nodes(Hash(bytes(mh)))
            if nodes:
                holders.append(bytes(nodes[0]))
        sig = frozenset(holders)
        if len(self._rotation) > MAX_ROTATION_GROUPS:
            self._rotation.clear()
        r = self._rotation.get(sig, 0)
        self._rotation[sig] = r + 1
        return r

    async def _probe_needy(self, h: Hash):
        """First assigned node that needs (and lacks) `h` — an
        idempotent need_block probe, same as the resync offer path."""
        mgr = self.manager
        for node in mgr.replication.write_nodes(h):
            if node == mgr.system.id:
                continue
            try:
                resp = await mgr.system.rpc.call(
                    mgr.endpoint, node, {"t": "need_block", "h": bytes(h)},
                    prio=PRIO_BACKGROUND, timeout=mgr.block_rpc_timeout,
                    idempotent=True)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — next candidate
                continue
            if resp.get("needed") and not resp.get("present"):
                return node
            if resp.get("present"):
                return None
        return None

    async def _push_row(self, h: Hash, data: bytes, node) -> bool:
        from .block import DataBlock
        from .manager import _chunks

        mgr = self.manager
        block = DataBlock.plain(data)
        try:
            await mgr.system.rpc.call(
                mgr.endpoint, node,
                {"t": "put_block", "h": bytes(h),
                 "hdr": block.header().pack()},
                prio=PRIO_BACKGROUND, timeout=mgr.block_rpc_timeout,
                body=_chunks(block.inner))
            return True
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — its owner's resync retries
            logger.info("rebuilt row push of %s failed: %s",
                        bytes(h).hex()[:16], e)
            return False
