"""Scrub / repair / rebalance workers — batch-first.

Equivalent of reference src/block/repair.rs (SURVEY.md §2.5):
  - ScrubWorker: full-datastore integrity pass every 25-35 days
    (randomized, repair.rs:24,244-254), resumable via a persisted iterator
    checkpoint (60 s cadence), Start/Pause/Resume/Cancel commands,
    tranquilizer-throttled, corruption counter.
  - RepairWorker: one-shot: re-enqueue every referenced hash to resync,
    then walk the disk and enqueue every found block (repair.rs:35-155).
  - RebalanceWorker: move blocks to their primary dir after a data-layout
    change (repair.rs:531-626).
  - BlockStoreIterator: resumable hash-ordered walk of the block store
    with fixed-point progress (repair.rs:634-764).

TPU-first difference (the north-star design, BASELINE.md): the reference
scrubs strictly one block at a time — read, blake2, next
(repair.rs:438-490).  Here the iterator feeds *batches* to the BlockCodec:
one device dispatch hashes `batch_blocks` blocks at once, so a TPU codec
turns scrub from CPU-bound into IO-bound.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from typing import List, Optional, Tuple

import numpy as np

from ..utils.background import Worker, WorkerState
from ..utils.crdt import now_msec
from ..utils.data import Hash
from ..utils.migrate import Migrated
from ..utils.persister import Persister
from ..utils.tranquilizer import Tranquilizer

logger = logging.getLogger("garage_tpu.block.repair")

SCRUB_INTERVAL_MIN = 25 * 86400   # ref repair.rs:24 (randomized 25-35 days)
SCRUB_INTERVAL_MAX = 35 * 86400
DEFAULT_SCRUB_TRANQUILITY = 4     # ref repair.rs:27
CHECKPOINT_INTERVAL = 60.0        # ref repair.rs:460-464
REPAIR_BATCH = 1000               # ref repair.rs:92-101 (sqlite-safe batches)


class BlockStoreIterator:
    """Hash-ordered walk over every block file across all data dirs,
    resumable from a serialized position (ref repair.rs:634-764).

    Position = last fully-processed 2-level prefix (0..65536); progress is
    prefix/65536 — equivalent to the reference's fixed-point fraction."""

    def __init__(self, roots: List[str], position: int = 0):
        self.roots = roots
        self.position = position  # next 2-byte prefix to scan
        self._prefixes: Optional[List[int]] = None  # existing dirs, sorted

    def progress(self) -> float:
        return self.position / 65536.0

    def is_done(self) -> bool:
        return self.position >= 65536

    def _scan_prefixes(self) -> List[int]:
        """Enumerate existing 2-level prefix dirs (≤256 listdir calls per
        root instead of probing all 65536 combinations)."""
        pref = set()
        for root in self.roots:
            try:
                level1 = os.listdir(root)
            except FileNotFoundError:
                continue
            for a in level1:
                if len(a) != 2:
                    continue
                try:
                    ai = int(a, 16)
                    level2 = os.listdir(os.path.join(root, a))
                except (ValueError, OSError):
                    continue
                for b in level2:
                    if len(b) == 2:
                        try:
                            pref.add((ai << 8) | int(b, 16))
                        except ValueError:
                            pass
        return sorted(pref)

    def next_prefix(self) -> Optional[List[Tuple[Hash, str, bool]]]:
        """All blocks under the next existing prefix dir:
        [(hash, path, compressed)]; None when the walk is complete."""
        if self._prefixes is None:
            self._prefixes = self._scan_prefixes()
        import bisect

        i = bisect.bisect_left(self._prefixes, self.position)
        if i >= len(self._prefixes) or self.is_done():
            self.position = 65536
            return None
        p = self._prefixes[i]
        self.position = p + 1
        d1, d2 = f"{p >> 8:02x}", f"{p & 0xFF:02x}"
        seen = {}
        for root in self.roots:
            d = os.path.join(root, d1, d2)
            try:
                names = os.listdir(d)
            except FileNotFoundError:
                continue
            for name in names:
                base = name[:-4] if name.endswith(".zst") else name
                if len(base) != 64 or name.endswith((".tmp", ".corrupted")):
                    continue
                try:
                    h = Hash(bytes.fromhex(base))
                except ValueError:
                    continue
                # prefer the compressed copy, first root wins (primary first)
                if bytes(h) not in seen or name.endswith(".zst"):
                    seen[bytes(h)] = (h, os.path.join(d, name), name.endswith(".zst"))
        return sorted(seen.values(), key=lambda t: bytes(t[0]))


class ScrubWorkerState(Migrated):
    """Persisted scrub state (ref repair.rs:165-232)."""

    VERSION_MARKER = b"GT01scrub"

    def __init__(
        self,
        position: int = 0,
        running: bool = False,
        paused: bool = False,
        time_next_run: int = 0,
        tranquility: int = DEFAULT_SCRUB_TRANQUILITY,
        corruptions: int = 0,
        time_last_complete: int = 0,
        time_last_start: int = 0,
    ):
        self.position = position
        self.running = running
        self.paused = paused
        self.time_next_run = time_next_run
        self.tranquility = tranquility
        self.corruptions = corruptions
        self.time_last_complete = time_last_complete
        self.time_last_start = time_last_start

    def fields(self):
        return [
            self.position, self.running, self.paused, self.time_next_run,
            self.tranquility, self.corruptions, self.time_last_complete,
            self.time_last_start,
        ]

    @classmethod
    def from_fields(cls, b):
        return cls(*b)


def randomize_next_scrub() -> int:
    return now_msec() + random.randint(
        SCRUB_INTERVAL_MIN * 1000, SCRUB_INTERVAL_MAX * 1000
    )


class ScrubWorker(Worker):
    """Batch-first scrub: BlockStoreIterator prefixes → codec.batch_verify
    (one device dispatch per batch) → corrupted blocks moved aside +
    requeued for resync."""

    def __init__(self, manager, persister: Optional[Persister] = None):
        self.manager = manager
        self.persister = persister
        st = persister.load() if persister is not None else None
        self.state: ScrubWorkerState = st or ScrubWorkerState(
            time_next_run=randomize_next_scrub()
        )
        self.iterator: Optional[BlockStoreIterator] = None
        if self.state.running:
            self.iterator = BlockStoreIterator(
                self._roots(), self.state.position
            )
        self.tranquilizer = Tranquilizer()
        self.coverage_refreshed = 0  # blocks re-fed to the EC accumulator
        self._last_checkpoint = time.monotonic()
        self._cmd: asyncio.Queue = asyncio.Queue()
        self._wake = asyncio.Event()
        # read-ahead: next prefix's file contents load while the current
        # one verifies; checkpoints record the VERIFIED position, not the
        # iterator's (which runs one prefix ahead)
        self._ra_task: Optional[asyncio.Task] = None
        self._verified_pos = self.state.position
        # verified plain blocks carried between batches until a full RS
        # codeword (k blocks) accumulates for the parity sidecar store
        self._parity_carry: Tuple[list, list] = ([], [])
        self._prev_pass_start = 0.0  # resumed pass: purge nothing extra

    def _roots(self) -> List[str]:
        return [d.path for d in self.manager.data_layout.data_dirs]

    def name(self) -> str:
        return "Block scrub worker"

    # --- operator commands (ref repair.rs Start/Pause/Resume/Cancel) ---

    def send_command(self, cmd: str) -> None:
        self._cmd.put_nowait(cmd)
        self._wake.set()

    def set_tranquility(self, t: int) -> None:
        t = int(t)
        if t < 0:
            raise ValueError("scrub-tranquility must be >= 0")
        self.state.tranquility = t
        self._checkpoint(force=True)

    def _apply_command(self, cmd: str) -> None:
        st = self.state
        if cmd == "start":
            if self.iterator is None:
                self.iterator = BlockStoreIterator(self._roots())
                st.running, st.paused, st.position, st.corruptions = True, False, 0, 0
                self._verified_pos = 0
                self._drop_read_ahead()
                self._drop_parity_carry()
                # purge grace is ONE pass: remember the previous start
                # before overwriting it (a sidecar skipped this pass —
                # its row held the corruption being repaired — must
                # survive until the NEXT pass refreshes it)
                self._prev_pass_start = st.time_last_start / 1000.0
                st.time_last_start = now_msec()
                # one full scrub pass == one device-pool clock tick: the
                # pool's LRU ages in scrub CYCLES, not wall time, so an
                # idle cluster never evicts its warm working set while
                # nothing else competes for pages (ops/device_pool.py)
                pool = getattr(self.manager.codec, "pool", None)
                if pool is not None:
                    pool.tick()
        elif cmd == "pause":
            st.paused = True
        elif cmd == "resume":
            st.paused = False
        elif cmd == "cancel":
            self.iterator = None
            st.running, st.paused, st.position = False, False, 0
            self._verified_pos = 0
            self._drop_read_ahead()
        self._checkpoint(force=True)

    def _drop_parity_carry(self) -> None:
        self._parity_carry = ([], [])

    def _drop_read_ahead(self) -> None:
        if self._ra_task is not None:
            self._ra_task.cancel()
            self._ra_task = None

    def _checkpoint(self, force: bool = False) -> None:
        if self.persister is None:
            return
        if force or time.monotonic() - self._last_checkpoint > CHECKPOINT_INTERVAL:
            # resume must re-verify anything not actually verified yet, so
            # the persisted position trails the (read-ahead) iterator
            self.state.position = self._verified_pos if self.iterator else 0
            self.persister.save(self.state)
            self._last_checkpoint = time.monotonic()

    # --- the batch scrub step ---

    async def work(self) -> WorkerState:
        while not self._cmd.empty():
            self._apply_command(self._cmd.get_nowait())
        st = self.state
        status = self.status()
        status.tranquility = st.tranquility
        if self.iterator is None:
            # waiting for the next scheduled run
            if now_msec() >= st.time_next_run:
                self._apply_command("start")
                return WorkerState.BUSY
            return WorkerState.IDLE
        if st.paused:
            return WorkerState.IDLE
        self.tranquilizer.reset()
        task = self._ra_task or asyncio.ensure_future(self._read_ahead())
        # clear BEFORE awaiting: if the read fails, the next work() cycle
        # must retry a fresh read, not re-await the cached exception
        self._ra_task = None
        item = await task
        if item is None:
            # complete
            st.time_last_complete = now_msec()
            st.time_next_run = randomize_next_scrub()
            st.running = False
            self.iterator = None
            self._drop_parity_carry()  # <k leftover: next pass retries
            if self.manager.parity_store is not None:
                # codeword membership shifts with churn: drop sidecars
                # refreshed by NEITHER this pass nor the previous one,
                # else orphans accumulate forever (one-pass grace keeps
                # coverage for rows that failed verify this pass)
                await asyncio.to_thread(
                    self.manager.parity_store.purge_stale,
                    self._prev_pass_start,
                )
            self._checkpoint(force=True)
            logger.info("scrub complete, %d corruptions found", st.corruptions)
            return WorkerState.BUSY
        batch, reads, pos_after = item
        # prefetch the NEXT prefix while this one verifies: disk reads
        # overlap the codec dispatch (read→batch→device, SURVEY.md §3.4)
        self._ra_task = asyncio.ensure_future(self._read_ahead())
        status.progress = f"{self.iterator.progress() * 100:.2f}%"
        if batch:
            await self.scrub_batch(batch, reads)
        self._verified_pos = pos_after
        self._checkpoint()
        return await self.tranquilizer.tranquilize_worker(st.tranquility)

    async def _read_ahead(self):
        """Next prefix's batch + file contents, read off-thread.  Returns
        (batch, reads, iterator_position_after) or None at end-of-store."""
        it = self.iterator
        if it is None:
            return None
        batch = await asyncio.to_thread(it.next_prefix)
        if batch is None:
            return None
        reads = await asyncio.gather(
            *[asyncio.to_thread(_try_read, self.manager, path)
              for _h, path, _c in batch]
        )
        # hint the device pool about the upcoming prefix: the transport
        # stages these blocks as background-class work WHILE the current
        # batch computes (riding the PR 11 double buffer), so the next
        # batch's H2D cost hides under compute and its scrub becomes a
        # pool hit.  Plain blocks only — compressed copies are verified
        # on their decompressed content, which we don't have yet.
        feeder = self.manager.feeder
        if feeder is not None:
            p_blocks, p_hashes = [], []
            for (h, _path, compressed), raw in zip(batch, reads):
                if not compressed and isinstance(raw, bytes):
                    p_blocks.append(raw)
                    p_hashes.append(h)
            if p_blocks:
                feeder.prefetch_scrub(p_blocks, p_hashes)
        return batch, list(reads), it.position

    async def scrub_batch(self, batch: List[Tuple[Hash, str, bool]],
                          reads: Optional[List[Optional[bytes]]] = None) -> None:
        """Verify one batch through the codec; quarantine corrupt blocks.

        Plain blocks go through codec.batch_verify (the device dispatch);
        compressed blocks validate their zstd frame checksum on CPU, as in
        the reference (block.rs:66-78)."""
        mgr = self.manager
        plain_idx, plain_blocks, plain_hashes = [], [], []
        if reads is None:
            reads = await asyncio.gather(
                *[asyncio.to_thread(_try_read, mgr, path)
                  for _h, path, _c in batch]
            )
        for i, ((h, path, compressed), raw) in enumerate(zip(batch, reads)):
            if raw is None:
                continue
            if raw is _READ_ERROR:
                # unreadable on media: the copy is as lost as a content
                # mismatch — quarantine it and let the sidecar/resync
                # ladder re-materialize a clean one
                await self._quarantine(h, path)
                continue
            if compressed:
                # decompress so the codec verifies the CONTENT hash (a
                # stronger check than the reference's zstd-checksum-only
                # verify, block.rs:66-78) and the block joins a parity
                # codeword — compressed blocks must be locally repairable
                # too, not just the plain ones
                data = await asyncio.to_thread(_try_decompress, raw)
                if data is None:
                    await self._quarantine(h, path)
                    continue
                plain_idx.append(i)
                plain_blocks.append(data)
                plain_hashes.append(h)
            else:
                plain_idx.append(i)
                plain_blocks.append(raw)
                plain_hashes.append(h)
        if plain_blocks:
            store = mgr.parity_store
            want_parity = (
                store is not None and mgr.codec.params.rs_data > 0
            )
            # prepend the carry (already-verified blocks from previous
            # batches) so RS codewords align to k across batch boundaries
            # — a per-prefix batch rarely holds k blocks by itself.  The
            # ≤ k-1 carry blocks are re-hashed by the fused dispatch and
            # the trailing partial row's parity is recomputed next batch:
            # bounded waste (< k blocks per batch) accepted to keep the
            # verify+encode a single codec call
            carry_b, carry_h = self._parity_carry if want_parity else ([], [])
            nc = len(carry_b)
            all_b = carry_b + plain_blocks
            all_h = carry_h + plain_hashes
            # span per fused dispatch: a slow batch (gated link, mid-pass
            # XLA compile, CPU steal) shows up in the slow-op log even on
            # nodes with no trace_sink configured
            with mgr.system.tracer.span(
                "Scrub batch", blocks=len(all_b),
                bytes=sum(len(b) for b in all_b),
            ):
                # through the codec feeder when armed: scrub batches are
                # background-class submissions in the SAME queue as the
                # foreground verifies, so on a device-armed node they
                # enter the zero-copy transport deadline-ordered behind
                # live traffic instead of talking to the device behind
                # the feeder's back (ops/transport.py); a closed/absent
                # feeder keeps the pre-transport direct call
                if mgr.feeder is not None:
                    ok, parity = await mgr.feeder.scrub_async(
                        all_b, all_h, want_parity)
                else:
                    ok, parity = await asyncio.to_thread(
                        mgr.codec.scrub_encode_batch, all_b, all_h,
                        want_parity,
                    )
            for j, good in enumerate(ok[nc:]):
                if not good:
                    h, path, _ = batch[plain_idx[j]]
                    await self._quarantine(h, path)
            # Coverage refresh: verified blocks with NO live distributed
            # codeword (distribution failed at write time, coverage was
            # wrongly tombstoned, or the data predates EC) re-enter the
            # write-side accumulator — scrub makes erasure coverage
            # convergent, mirroring how it refreshes local sidecars.
            #
            # Because every refreshed block is stored on THIS node, the
            # accumulator's distinct-primary invariant flushes per block
            # and the refresh emits 1-member partial codewords.  That is
            # the intended SAFE shape for a single-node stream: the k−1
            # implicit zero shards are always-available pieces, so the
            # member survives the loss of up to m of its parity nodes —
            # full m-loss tolerance at m×(block size) overhead, paid only
            # for refreshed blocks (rewritten objects regroup at k).
            acc = mgr.ec_accumulator
            if acc is not None and acc.distributor is not None:
                from .block import DataBlock

                cand = []
                for j, good in enumerate(ok[nc:]):
                    h = all_h[nc + j]
                    # NOT gated on acc.recently_added: that LRU remembers
                    # the WRITE-time add, which is exactly the add whose
                    # coverage may have been lost — locally_covered is
                    # the authoritative duplicate guard, and a rare
                    # double codeword (add raced an in-flight flush) is
                    # benign extra parity, reclaimed by normal GC
                    if good and not mgr.is_parity_block(h):
                        cand.append((h, all_b[nc + j]))

                def _uncovered():
                    # one off-loop hop for the whole batch: the per-hash
                    # index probes are synchronous DB iteration
                    d = acc.distributor
                    return [
                        (h, b) for h, b in cand
                        if d.holds_index_for(h) and not d.locally_covered(h)
                    ]

                for h, b in await asyncio.to_thread(_uncovered):
                    self.coverage_refreshed += 1
                    acc.add(h, DataBlock.plain(b))
            if want_parity and parity is not None:
                # persist RS sidecars for every COMPLETE codeword whose
                # members all verified — this is what makes a later
                # corruption locally repairable with zero network
                # (the BlockCodec north star's decode-repair half)
                k = mgr.codec.params.rs_data
                nrows = len(all_b) // k
                for row in range(nrows):
                    lo = row * k
                    if not all(ok[lo:lo + k]):
                        continue
                    # trim to the row's own width: pad columns beyond the
                    # longest member are zero parity (GF-linear) and would
                    # bloat the sidecar to the batch-global maxlen
                    row_max = max(len(b) for b in all_b[lo:lo + k])
                    await asyncio.to_thread(
                        store.put_codeword,
                        all_h[lo:lo + k],
                        [len(b) for b in all_b[lo:lo + k]],
                        np.asarray(parity[row])[:, :row_max],
                    )
                rest = nrows * k
                self._parity_carry = (
                    [b for b, good in zip(all_b[rest:], ok[rest:]) if good],
                    [h for h, good in zip(all_h[rest:],
                                          ok[rest:]) if good],
                )

    async def _quarantine(self, h: Hash, path: str) -> None:
        self.state.corruptions += 1
        self.manager.corruptions += 1
        logger.error("scrub: corrupted block %s at %s", bytes(h).hex()[:16], path)
        # manager.quarantine_path: counted (block_quarantine_total), and
        # a failing rename deletes the bad copy instead of silently
        # leaving it servable (the old _move_aside swallowed OSError)
        self.manager.pool_invalidate(h, "quarantine")
        await asyncio.to_thread(self.manager.quarantine_path, path)
        # first line of defense: rebuild locally from the RS parity
        # sidecar — with every replica down this is the ONLY repair;
        # network resync stays as the fallback
        store = self.manager.parity_store
        if store is not None:
            data = await asyncio.to_thread(store.try_reconstruct, h)
            if data is not None:
                from .block import DataBlock

                await self.manager.write_block(h, DataBlock.plain(data))
                self.manager.blocks_reconstructed += 1
                self.manager.note_heal("local_sidecar")
                return
        if self.manager.resync is not None:
            self.manager.resync.put_to_resync(h, 0.0,
                                              source="scrub_corrupt")

    async def wait_for_work(self) -> None:
        self._wake.clear()
        delay = max(1.0, (self.state.time_next_run - now_msec()) / 1000.0)
        try:
            await asyncio.wait_for(self._wake.wait(), timeout=min(delay, 10.0))
        except asyncio.TimeoutError:
            pass


class LayoutSweepMarker(Migrated):
    """Ring-assignment digest persisted AFTER a layout sweep completes: a
    node that crashed mid-sweep (or was down for the layout change
    entirely) finds a stale digest at startup and re-sweeps — without
    this, gained assignments would hold holes until the next unrelated
    ring change."""

    VERSION_MARKER = b"GT01lsweep"

    def __init__(self, digest: bytes = b""):
        self.digest = digest

    def fields(self):
        return [self.digest]

    @classmethod
    def from_fields(cls, body):
        return cls(bytes(body[0]))


class RepairWorker(Worker):
    """One-shot consistency repair (ref repair.rs:35-155): phase 1 enqueues
    every referenced hash to resync; phase 2 walks the disk and enqueues
    every found block (catches rc=0 leftovers).

    refs_only=True runs phase 1 alone — the shape used by the automatic
    layout-change sweep (spawned on every ring change): a ring change by
    itself fires no table hook, so a node that GAINED the assignment for
    an already-referenced block (rc>0, no 0→1 incref) would otherwise
    hold a hole until an operator ran `repair blocks`.  The reference
    leaves this to the operator; the sweep makes post-failure healing
    self-driven.  restart() rewinds a still-running sweep instead of
    stacking a second one (ring changes arrive in bursts as a layout
    propagates); on_done fires once when the sweep completes (the model
    layer persists the swept ring digest there)."""

    def __init__(self, manager, refs_only: bool = False, on_done=None):
        self.manager = manager
        self.refs_only = refs_only
        self.on_done = on_done
        self.phase = 1
        self.cursor: Optional[bytes] = b""
        self.iterator: Optional[BlockStoreIterator] = None
        self.finished = False

    def restart(self) -> None:
        self.phase = 1
        self.cursor = b""
        self.iterator = None

    def _done(self) -> WorkerState:
        self.finished = True
        if self.on_done is not None:
            try:
                self.on_done()
            except Exception:
                # e.g. marker persistence hitting disk-full: the sweep
                # itself succeeded, but the node will re-sweep at next
                # boot — say so instead of hiding the degradation
                logger.warning("repair worker on_done callback failed",
                               exc_info=True)
        return WorkerState.DONE

    def name(self) -> str:
        return "Block layout sweep" if self.refs_only else "Block repair worker"

    async def work(self) -> WorkerState:
        mgr = self.manager
        if self.phase == 1:
            # phase 1 is pure CPU (db iteration) and the worker runner
            # re-invokes BUSY workers back-to-back: yield the event loop
            # once per batch or a large rc table freezes RPC/S3 handling
            # for the whole scan — worst exactly when a layout change
            # just made the cluster fragile
            await asyncio.sleep(0)
            batch = 0
            while batch < REPAIR_BATCH:
                nxt = (
                    mgr.rc.tree.first()
                    if self.cursor == b""
                    else mgr.rc.get_gt(self.cursor)
                )
                if nxt is None:
                    if self.refs_only:
                        return self._done()
                    self.phase = 2
                    self.iterator = BlockStoreIterator(
                        [d.path for d in mgr.data_layout.data_dirs]
                    )
                    return WorkerState.BUSY
                key, _v = nxt
                mgr.resync.put_to_resync(
                    Hash(key), 0.0,
                    source="layout_sweep" if self.refs_only
                    else "repair_sweep")
                self.cursor = key
                batch += 1
            self.status().progress = "phase 1"
            # the backlog this sweep is generating: resync drains it, so
            # `worker list` shows sweep progress AND the induced queue
            self.status().queue_length = mgr.resync.queue_len()
            return WorkerState.BUSY
        batch = await asyncio.to_thread(self.iterator.next_prefix)
        if batch is None:
            return self._done()
        for h, _path, _c in batch:
            mgr.resync.put_to_resync(h, 0.0, source="repair_sweep")
        self.status().progress = f"phase 2: {self.iterator.progress() * 100:.1f}%"
        return WorkerState.BUSY


class RebalanceWorker(Worker):
    """One-shot: move blocks into their primary dir after a layout change,
    dropping secondary copies (ref repair.rs:531-626)."""

    def __init__(self, manager):
        self.manager = manager
        self.iterator = BlockStoreIterator(
            [d.path for d in manager.data_layout.data_dirs]
        )
        self.moved = 0

    def name(self) -> str:
        return "Block rebalance worker"

    async def work(self) -> WorkerState:
        mgr = self.manager
        batch = await asyncio.to_thread(self.iterator.next_prefix)
        if batch is None:
            logger.info("rebalance done, moved %d blocks", self.moved)
            return WorkerState.DONE
        for h, path, compressed in batch:
            primary = mgr.block_path(mgr.data_layout.primary_dir(h), h, compressed)
            if os.path.abspath(path) == os.path.abspath(primary):
                continue
            await asyncio.to_thread(_move_into_place, mgr, path, primary)
            self.moved += 1
        self.status().progress = f"{self.iterator.progress() * 100:.1f}%"
        return WorkerState.BUSY


# sentinel distinguishing "file unreadable, disk implicated" from a
# benign concurrent deletion (None): scrub_batch quarantines the former
_READ_ERROR = object()


def _try_read(mgr, path: str):
    """Scrub read through the manager's disk seam (DiskIo.
    read_file_direct: O_DIRECT with buffered fallback — the buffered
    path is kernel-CPU-bound on 1-core hosts and scrubbing through the
    page cache evicts the GET path's working set, see
    utils/direct_io.py).  Returns the bytes; None for a vanished file
    (deleted concurrently) or a transient resource error (EMFILE-class
    — skip this pass, the copy is fine); ``_READ_ERROR`` for a media
    error, after feeding the root's health accounting so a scrub
    churning through an EIO-ing disk shows up in disk_error_total and
    the root's breaker instead of staying silently 'ok'.

    A SUCCESSFUL read reports note_ok: the streak is *consecutive*
    errors, and on an archival node with no client GETs the scrub is
    the only reader — without the reset, isolated bad sectors spread
    over weeks of passes would accumulate into a streak and flip a
    fundamentally healthy root read-only."""
    from .health import is_media_error

    try:
        raw = mgr.disk.read_file_direct(path)
    except FileNotFoundError:
        return None
    except OSError as e:
        if not is_media_error(e):
            logger.warning("scrub: transient read error on %s "
                           "(errno %s: %s)", path, e.errno, e)
            return None
        logger.error("scrub: read of %s failed (errno %s: %s)",
                     path, e.errno, e)
        mgr.health.note_error(mgr._root_of(path), "scrub", e)
        return _READ_ERROR
    mgr.health.note_ok(mgr._root_of(path), "scrub")
    return raw


def _try_decompress(raw: bytes) -> Optional[bytes]:
    from ..utils.zstd_compat import zstandard

    try:
        return zstandard.ZstdDecompressor().decompress(raw)
    except zstandard.ZstdError:
        return None


def _move_into_place(mgr, src: str, dst: str) -> None:
    """Rebalance move through the manager's disk seam so FaultyDisk can
    inject into it and a media error feeds the destination root's
    health accounting before surfacing to the worker error handler."""
    from .health import is_media_error

    try:
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.exists(dst):
            mgr.disk.remove(src)
        else:
            mgr.disk.replace(src, dst)
    except OSError as e:
        if is_media_error(e):
            mgr.health.note_error(mgr._root_of(dst), "rebalance", e)
        raise
