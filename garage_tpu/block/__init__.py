"""Block store — content-addressed data blocks with batched TPU codec ops.

Equivalent of reference src/block/ (SURVEY.md §2.5): BlockManager local
file storage + streaming RPC get/put, refcounting, persistent resync queue
with error backoff, and scrub/repair/rebalance workers.  TPU-first
difference: the scrub/verify/RS paths are *batch-first* — the workers feed
block batches to the configured BlockCodec (ops/) instead of hashing one
block at a time (ref block/repair.rs:438-490 is strictly sequential).
"""

from .block import DataBlock, DataBlockHeader
from .layout import DataLayout
from .rc import BlockRc, RcEntry
from .manager import BlockManager, INLINE_THRESHOLD
from .resync import BlockResyncManager, ResyncWorker
from .repair import BlockStoreIterator, RepairWorker, ScrubWorker

__all__ = [
    "DataBlock",
    "DataBlockHeader",
    "DataLayout",
    "BlockRc",
    "RcEntry",
    "BlockManager",
    "INLINE_THRESHOLD",
    "BlockResyncManager",
    "ResyncWorker",
    "BlockStoreIterator",
    "RepairWorker",
    "ScrubWorker",
]
