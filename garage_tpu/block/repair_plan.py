"""RepairPlanner — repair-bandwidth-optimal degraded reads.

The decode ladder's original gather (model/parity_repair.py) fetched
every surviving data member AND every parity shard of a codeword even
though a decode needs exactly k pieces, so one degraded read could move
(k+m−1)/k× the necessary bytes — and every fetch walked the full
sweep/timeout chain, dead nodes included.  This module turns every
degraded read / reconstruction into a *planned* fetch, per the two
PAPERS.md schemes the ROADMAP names:

  1. **Exact-k survivor selection** ("Boosting the Performance of
     Degraded Reads in RS-coded Distributed Storage Systems"): candidate
     pieces are ranked by their best holder's `RpcHelper.peer_rank` —
     the per-peer RTT EWMA, circuit-breaker state, zone locality,
     gossiped load-governor pressure and fail-slow verdict (the
     least-loaded / healthiest-survivor half of the same paper;
     utils/health_score.py) — with data members before
     parity (parity only fills the gap left by dead members) and
     pieces whose every holder is breaker-open last.  Exactly k fetches
     go out; a *ranked replacement* launches only when a fetch fails, or
     hedges in when the wave stalls past the hedge delay.  Fetched bytes
     that end up unused are counted in repair_overfetch_bytes_total.

  2. **Partial-parallel repair / PPR** (+ the sub-shard idea of "Fast
     Product-Matrix Regenerating Codes"): instead of shipping whole
     shards, each survivor multiplies its local shard by the decode
     coefficient in GF(256) — the `ppr` block RPC, served through
     ops/gf256 / the native kernel in ops/cpu_codec — and ships the
     partial product *truncated to the target row's length*, so a
     reconstruction moves at most one target-row-sized partial sum per
     survivor link and the coordinator only XOR-accumulates.  The GF
     work parallelizes across the survivors' CPUs; min(shard, target)
     truncation makes PPR ≤ whole-shard byte-wise.  Peers that predate
     the endpoint (version gossip, PR 7) or answer it with "unknown
     rpc" fall back to whole-shard fetch for that piece — mixed-version
     clusters reconstruct bit-identically, just less cheaply.

Replacement algebra: a survivor's partial c_old ⊗ shard is rescaled
locally to any later coefficient via (c_new ⊗ c_old⁻¹) ⊗ partial, so a
failed fetch that changes the survivor set never invalidates partials
already in hand — the coordinator re-plans, rescales, and fetches only
the replacement.  Re-plans are first-class: every survivor that dies
mid-plan (flat replacement, mid-tree subtree loss, version demotion,
tree abort) lands in repair_replan_total{reason}.

Two extensions finish the regenerating-codes program (ISSUE 20):

  3. **Tree-aggregated PPR** (`ppr_tree` block RPC): survivors forward
     their GF(256)-scaled partials along a repair tree shaped from the
     gossiped peer-rank map (breaker / fail-slow / zone / pressure /
     RTT) — interior nodes XOR-accumulate their children's aggregates
     into their own partials before forwarding, so the COORDINATOR
     ingests ONE row-set-sized stream regardless of k.  A mid-tree node
     failure surfaces as that subtree's pieces in the response's `miss`
     list; since the missing pieces are re-fetched (same survivor set,
     same decode row), the aggregate stays valid and the coordinator
     completes the sum with flat neutral-coefficient fetches — only a
     piece that is UNFETCHABLE anywhere aborts the tree back to the
     flat planner (the aggregate cannot be per-piece rescaled after a
     set change).  Mixed-version holders (pre-`ppr_tree` gossip, or an
     "unknown block rpc" answer) demote that edge to flat PPR.

  4. **Chain repair** (`reconstruct_group`): a codeword that lost
     m′ > 1 rows decodes ALL m′ targets from ONE set of k fetched /
     aggregated partials — the tree carries m′ coefficients per piece
     and m′ accumulator rows per stream; the flat path fetches
     neutral-coefficient raw sub-shards once and rescales locally per
     target row.  m′ repairs cost ≤ k fetches total, not m′·k.

Safety is unchanged from the gather path: whole-shard pieces are
verified by content hash before use, partial products cannot be (they
are not content-addressed), but the rebuilt block must hash to the
requested id before it is returned — a corrupt partial costs a fallback,
never wrong data.
"""

from __future__ import annotations

import asyncio
import logging
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..net.frame import PRIO_NORMAL
from ..ops import gf256
from ..utils.data import Hash, block_hash
from ..utils.error import GarageError

logger = logging.getLogger("garage_tpu.block.repair_plan")

# Gossiped software version from which peers answer the `ppr` block RPC;
# older peers are never sent a partial-product request.  Unknown or
# unparseable versions are tried optimistically — an "unknown block rpc"
# answer demotes the peer to whole-shard for the rest of the process.
PPR_MIN_VERSION = (0, 9, 0)

# Gossiped software version from which peers serve the `ppr_tree`
# aggregation RPC; older (but PPR-capable) peers get their edge demoted
# to flat PPR instead of a tree role.
PPR_TREE_MIN_VERSION = (0, 9, 5)

# c_applied sentinel: the payload is the raw (unscaled) shard bytes —
# whole-shard fetches and PPR fallbacks land here; the coordinator
# scales by the final coefficient itself.
RAW = -1

_VER_RE = re.compile(r"^(\d+)\.(\d+)(?:\.(\d+))?")


def parse_version(v: Optional[str]) -> Optional[tuple]:
    """Leading numeric (major, minor, patch) of a gossiped version tag;
    None when absent/unparseable (suffixes like '-dev' are ignored)."""
    if not v:
        return None
    m = _VER_RE.match(str(v))
    if m is None:
        return None
    return (int(m.group(1)), int(m.group(2)), int(m.group(3) or 0))


class _Piece:
    """One fetchable codeword piece: a surviving data member or a parity
    shard (implicit zero shards of a partial codeword are free and never
    fetched)."""

    __slots__ = ("index", "hash", "kind")

    def __init__(self, index: int, hash_: bytes, kind: str):
        self.index = index          # position in the extended codeword
        self.hash = bytes(hash_)    # content hash == ring placement
        self.kind = kind            # "data" | "parity"

    def __repr__(self) -> str:  # debug/log friendliness
        return f"<piece {self.index} {self.kind} {self.hash.hex()[:8]}>"


class RepairPlanner:
    """Plans and executes bandwidth-minimal reconstruction of one
    codeword row.  Owned by the BlockManager; model/parity_repair.py
    routes every distributed decode through it (falling back to the
    legacy sweep-everything gather only if the plan comes up empty)."""

    def __init__(self, manager, use_ppr: bool = True,
                 hedge_delay: Optional[float] = None,
                 use_tree: bool = True, tree_fanout: int = 4):
        self.manager = manager
        self.use_ppr = use_ppr
        # tree-aggregated PPR: survivors forward partials along a repair
        # tree so the coordinator ingests one stream regardless of k
        self.use_tree = use_tree
        self.tree_fanout = max(1, int(tree_fanout))
        # None → derive from the block endpoint's observed latency
        # quantile (same source as read hedging), 1 s static until
        # enough samples exist
        self.hedge_delay = hedge_delay
        self._no_ppr: set = set()     # peers observed not to answer `ppr`
        self._no_tree: set = set()    # peers observed not to answer `ppr_tree`
        self._row_cache: dict = {}    # (k, m, present, target) -> row
        self.plans = 0
        self.hedges = 0
        self.ppr_fallbacks = 0
        self.tree_plans = 0           # reconstructions served by a tree
        self.tree_demotions = 0       # edges demoted to flat (version)
        self.replans: dict = {}       # reason -> count (mirror of the
        #                               manager's repair_replan_total)

    # --- ranking ------------------------------------------------------------

    def rank_pieces(self, pieces: Sequence[_Piece]) -> List[_Piece]:
        """Fetch order: data members before parity (parity only fills
        the gap left by dead members), each band ordered by the piece's
        BEST holder under RpcHelper.peer_rank — the (breaker,
        fail-slow, zone, pressure-bucket, RTT) survivor key: self <
        local-zone < cross-zone < FAIL-SLOW < breaker-open, and within
        a zone band lightly-loaded holders (gossiped governor pressure,
        System.peer_pressure) before pressured ones — the load-aware
        survivor scheduling of the degraded-reads paper.  Pieces whose
        every holder is breaker-open rank dead-last — even behind
        healthy parity, since their fetches can only burn timeouts that
        healthy pieces avoid."""
        rpc = self.manager.system.rpc

        def key(p: _Piece):
            nodes = self.manager.replication.read_nodes(Hash(p.hash))
            best = min((rpc.peer_rank(n) for n in nodes),
                       default=(9, 9, 0.0))
            dead = 1 if best[0] >= 4 else 0
            kind = 0 if p.kind == "data" else 1
            return (dead, kind, best, p.index)

        return sorted(pieces, key=key)

    def _holder_order(self, h: Hash) -> List:
        rpc = self.manager.system.rpc
        return rpc.request_order(self.manager.replication.read_nodes(h))

    def _hedge_after(self) -> float:
        if self.hedge_delay is not None:
            return self.hedge_delay
        rpc = self.manager.system.rpc
        d = None
        if rpc.m_duration is not None:
            d = rpc.m_duration.quantile(
                rpc.tunables.hedge_quantile,
                min_count=rpc.tunables.hedge_min_samples,
                endpoint=self.manager.endpoint.path,
            )
        return max(d, 0.05) if d is not None else 1.0

    # --- PPR capability gate ------------------------------------------------

    def _peer_ppr_ok(self, node) -> bool:
        if bytes(node) in self._no_ppr:
            return False
        ver = parse_version(self.manager.system.peer_version(node))
        if ver is not None and ver < PPR_MIN_VERSION:
            return False
        return True  # unknown version: try it, demote on "unknown rpc"

    def _peer_tree_ok(self, node) -> bool:
        """May `node` take a tree role (root / interior / leaf) in a
        `ppr_tree` plan?  A PPR-capable but pre-tree peer demotes that
        edge to flat PPR; unknown versions are tried optimistically and
        demoted on the first "unknown block rpc" answer."""
        if bytes(node) in self._no_tree or not self._peer_ppr_ok(node):
            return False
        ver = parse_version(self.manager.system.peer_version(node))
        if ver is not None and ver < PPR_TREE_MIN_VERSION:
            return False
        return True

    @staticmethod
    def _is_unknown_rpc(e: BaseException) -> bool:
        return isinstance(e, GarageError) and "unknown block rpc" in str(e)

    def _note_replan(self, reason: str) -> None:
        """One re-plan event: a survivor died mid-plan (survivor_died),
        a tree subtree was lost and its pieces re-fetched flat
        (mid_tree), a mixed-version holder's edge was demoted at plan
        time (version_demote), or a whole tree was abandoned for the
        flat planner (tree_abort)."""
        self.replans[reason] = self.replans.get(reason, 0) + 1
        note = getattr(self.manager, "note_repair_replan", None)
        if note is not None:
            note(reason)

    # --- decode coefficients ------------------------------------------------

    def _decode_row(self, k: int, m: int, present: tuple,
                    target: int) -> np.ndarray:
        """Coefficients c_j with data[target] = Σ_j c_j ⊗
        shards[present[j]].  Shares the codec's cached decode schedule
        (ops/cpu_codec.py) when the live geometry matches the entry's;
        a small local cache covers old-geometry entries."""
        key = (k, m, present, target)
        row = self._row_cache.get(key)
        if row is not None:
            return row
        codec = self.manager.codec
        if (getattr(codec, "decode_matrix", None) is not None
                and codec.params.rs_data == k
                and codec.params.rs_parity == m):
            row = codec.decode_matrix(list(present), rows=[target])[0]
        else:
            row = gf256.rs_decode_row(k, m, list(present), target)
        if len(self._row_cache) >= 512:
            self._row_cache.clear()
        self._row_cache[key] = row
        return row

    # --- fetch primitives ---------------------------------------------------

    async def _read_local(self, piece: _Piece) -> Optional[bytes]:
        """This node's own verified copy of a piece (unpacked if parity);
        zero wire bytes."""
        mgr = self.manager
        h = Hash(piece.hash)
        if not mgr.is_block_present(h):
            return None
        try:
            block = await mgr.read_block(h)
            raw = await asyncio.to_thread(block.decompressed)
        except Exception:  # noqa: BLE001 — any local failure → fetch remote
            return None
        # read_block already content-verified a PLAIN block; only a
        # compressed copy (frame-checksum-verified) needs the content
        # hash re-checked over the decompressed bytes — off-loop and
        # feeder-batched like every other planner verify
        if block.compressed and not await self._verify(raw, piece.hash):
            return None
        if piece.kind == "parity":
            from .parity import unpack_parity_shard

            return unpack_parity_shard(raw)
        return raw

    async def _fetch_whole(self, piece: _Piece) -> Tuple[bytes, int, int]:
        """One piece's verified shard bytes: local copy → ranked ring
        holders → the O(cluster) sweep as the completeness backstop.
        Returns (shard, c_applied=RAW, wire_bytes_moved)."""
        payload, moved = await self._fetch_whole_inner(piece)
        return payload, RAW, moved

    async def _fetch_whole_inner(self, piece: _Piece) -> Tuple[bytes, int]:
        # Deliberately NOT rpc_get_block_streaming: that path serves
        # whole blocks to clients (decompressed iteration, bytes_read
        # accounting, heal/decode fallbacks that would recurse into
        # reconstruction); a piece fetch wants raw wire frames, its own
        # byte accounting, and the parity unpack.  The resilience
        # primitives (peer_allows fast-fail, adaptive timeout,
        # note_result) are shared.
        from .block import DataBlock, DataBlockHeader

        mgr = self.manager
        rpc = mgr.system.rpc
        h = Hash(piece.hash)
        local = await self._read_local(piece)
        if local is not None:
            return local, 0
        our_id = mgr.system.id
        for node in self._holder_order(h):
            if bytes(node) == bytes(our_id):
                continue  # local copy already tried
            if not rpc.peer_allows(node):
                # breaker open: fast-fail to the next holder — the
                # sweep backstop below still tries everyone, so a stale
                # verdict can delay but never hide the only copy
                continue
            try:
                timeout = rpc.timeout_for(node, mgr.block_rpc_timeout)
                resp, stream = await mgr.endpoint.call_streaming(
                    node, {"t": "get_block", "h": piece.hash},
                    prio=PRIO_NORMAL, timeout=timeout,
                )
                if resp.get("err") or stream is None:
                    rpc.note_result(node, None)  # live handler: path works
                    continue
                hdr = DataBlockHeader.unpack(resp["hdr"])
                try:
                    body = await asyncio.wait_for(
                        stream.read_all(), mgr.block_rpc_timeout)
                except BaseException:
                    await stream.aclose()  # stop the sender's pump
                    raise
                rpc.note_result(node, None)
                raw = await asyncio.to_thread(
                    DataBlock(body, hdr.compressed).decompressed)
                if not await self._verify(raw, piece.hash):
                    continue
                if piece.kind == "parity":
                    from .parity import unpack_parity_shard

                    shard = unpack_parity_shard(raw)
                    if shard is None:
                        continue
                    return shard, len(body)
                return raw, len(body)
            except asyncio.CancelledError:
                rpc.note_result(node, asyncio.CancelledError())
                raise
            except Exception as e:  # noqa: BLE001 — next holder
                rpc.note_result(node, e)
                continue
        # completeness backstop: after a layout change the only copy may
        # sit on a node the ring no longer lists (sweep_get_block's
        # raison d'être); ring holders were already tried above
        raw = await mgr.sweep_get_block(h, try_ring=False)
        if raw is None:
            raise GarageError(f"piece {piece.hash.hex()[:12]} unavailable")
        if piece.kind == "parity":
            from .parity import unpack_parity_shard

            shard = unpack_parity_shard(raw)
            if shard is None:
                raise GarageError(
                    f"piece {piece.hash.hex()[:12]} not a parity shard")
            return shard, len(raw)
        return raw, len(raw)

    async def _fetch_ppr(self, piece: _Piece, coeff: int,
                         want: int) -> Tuple[bytes, int, int]:
        """coeff ⊗ shard truncated to `want` bytes, computed survivor-
        side when a PPR-capable holder has the piece; local copies scale
        locally (zero wire bytes) and holder exhaustion falls back to a
        whole-shard fetch.  Returns (payload, c_applied, wire_bytes)."""
        mgr = self.manager
        rpc = mgr.system.rpc
        h = Hash(piece.hash)
        local = await self._read_local(piece)
        if local is not None:
            return local, RAW, 0
        msg = {"t": "ppr", "h": piece.hash, "coeff": int(coeff),
               "len": int(want)}
        if piece.kind == "parity":
            msg["parity"] = True
        our_id = mgr.system.id
        for node in self._holder_order(h):
            if bytes(node) == bytes(our_id):
                continue
            if not self._peer_ppr_ok(node) or not rpc.peer_allows(node):
                continue  # old version / open breaker: next holder
            try:
                timeout = rpc.timeout_for(node, mgr.block_rpc_timeout)
                resp, stream = await mgr.endpoint.call_streaming(
                    node, msg, prio=PRIO_NORMAL, timeout=timeout)
                if resp.get("err") or stream is None:
                    # err-less but body-less answers are a MISS like the
                    # whole-shard path treats them — XOR-accumulating a
                    # phantom zero partial would corrupt the row and
                    # waste the whole planned fetch on the hash check
                    rpc.note_result(node, None)
                    continue
                try:
                    body = await asyncio.wait_for(
                        stream.read_all(), mgr.block_rpc_timeout)
                except BaseException:
                    await stream.aclose()
                    raise
                rpc.note_result(node, None)
                if not body:
                    continue  # empty partial: same phantom-zero hazard
                return body, int(coeff), len(body)
            except asyncio.CancelledError:
                rpc.note_result(node, asyncio.CancelledError())
                raise
            except Exception as e:  # noqa: BLE001
                if self._is_unknown_rpc(e):
                    # peer predates the endpoint: remember, and never
                    # count a version miss against its breaker
                    self._no_ppr.add(bytes(node))
                    rpc.note_result(node, None)
                else:
                    rpc.note_result(node, e)
                continue
        # no PPR-capable holder answered — whole-shard for this piece
        self.ppr_fallbacks += 1
        mgr.note_repair_ppr_fallback()
        payload, moved = await self._fetch_whole_inner(piece)
        return payload, RAW, moved

    async def _verify(self, raw: bytes, want_hash: bytes) -> bool:
        """Content-hash check for a fetched whole piece, batched through
        the codec feeder when one is armed so a repair storm's many
        concurrent piece verifies coalesce into one ragged hash pass."""
        mgr = self.manager
        feeder = getattr(mgr, "feeder", None)
        if feeder is not None:
            got = (await feeder.hash_async([raw]))[0]
        else:
            got = await asyncio.to_thread(block_hash, raw, mgr.hash_algo)
        return bytes(got) == bytes(want_hash)

    async def _hash_many(self, raws: Sequence[bytes]) -> List[bytes]:
        """Content hashes of several buffers in ONE feeder ragged pass
        (chain repair verifies all m′ rebuilt rows together)."""
        mgr = self.manager
        feeder = getattr(mgr, "feeder", None)
        if feeder is not None:
            return [bytes(x) for x in await feeder.hash_async(list(raws))]
        return [bytes(await asyncio.to_thread(block_hash, r, mgr.hash_algo))
                for r in raws]

    # --- the planned reconstruction ----------------------------------------

    async def reconstruct(self, h: Hash, ent) -> Optional[bytes]:
        """Rebuild the codeword row whose content hash is `h` with a
        planned, exactly-k fetch.  The row index comes from locating `h`
        in `ent.members` (index entries fetched for a sibling carry that
        sibling's `member_index`, not ours).  Returns verified plain
        bytes or None (callers fall back to the legacy gather)."""
        target = int(ent.member_index)
        hb = bytes(h)
        for i, mh in enumerate(ent.members):
            if bytes(mh) == hb:
                target = i
                break
        out = await self.reconstruct_group(ent, [target])
        return out.get(target)

    async def reconstruct_group(self, ent, targets: Sequence[int],
                                rotate: int = 0) -> Dict[int, Optional[bytes]]:
        """Chain repair: rebuild ALL of `targets` (lost member indexes of
        ONE codeword) from a single set of k fetched / tree-aggregated
        partials — the fetch is shared and coefficients rescale locally
        per target row, so m′ lost rows cost ≤ k fetches, not m′·k.
        `rotate` rotates which survivor roots the aggregation tree (the
        rebuild scheduler spreads tree roots across a codeword group's
        shared survivor set).  Returns {member_index: verified bytes or
        None}; callers fall back per-target."""
        k, m = int(ent.k), int(ent.m)
        targets = sorted({int(t) for t in targets})
        out: Dict[int, Optional[bytes]] = {t: None for t in targets}
        lengths = list(ent.lengths)
        if (not targets or not lengths or k <= 0
                or any(t >= len(ent.members) for t in targets)):
            return out
        maxlen = max(lengths)
        wants = [int(lengths[t]) for t in targets]
        if maxlen == 0 or any(w == 0 for w in wants):
            return out
        tset = set(targets)
        zeros = list(range(len(ent.members), k))
        cands = [
            _Piece(i, ent.members[i], "data")
            for i in range(len(ent.members)) if i not in tset
        ] + [
            _Piece(k + j, ph, "parity")
            for j, ph in enumerate(ent.parity_hashes)
        ]
        needed = k - len(zeros)
        if len(cands) < needed:
            return out
        self.plans += 1
        mgr = self.manager
        ranked = self.rank_pieces(cands)
        rows: Optional[Dict[int, bytes]] = None
        if self.use_ppr and self.use_tree and needed >= 2:
            try:
                rows = await self._run_tree(ranked, zeros, k, m, targets,
                                            wants, needed, rotate)
            except Exception:  # noqa: BLE001 — tree failure = flat
                logger.exception("tree-aggregated repair failed, flat "
                                 "fallback")
                self._note_replan("tree_abort")
                rows = None
        if rows is None:
            try:
                if len(targets) == 1:
                    one = await self._run(ranked, zeros, k, m, targets[0],
                                          wants[0], maxlen, needed)
                    rows = None if one is None else {targets[0]: one}
                else:
                    rows = await self._run_chain(ranked, zeros, k, m,
                                                 targets, wants, maxlen,
                                                 needed)
            except Exception:  # noqa: BLE001 — planner failure = fallback
                logger.exception("planned reconstruction of %s failed",
                                 bytes(ent.members[targets[0]]).hex()[:16])
                return out
        if rows is None:
            return out
        # one feeder-batched ragged hash pass verifies every target row
        bufs = [rows.get(t) for t in targets]
        got = await self._hash_many([b or b"" for b in bufs])
        for t, buf, gh in zip(targets, bufs, got):
            if buf is not None and bytes(gh) == bytes(ent.members[t]):
                out[t] = buf
                mgr.note_repair_done(len(buf))
            elif buf is not None:
                logger.warning("planned reconstruction of row %d (%s) "
                               "produced wrong hash", t,
                               bytes(ent.members[t]).hex()[:16])
        return out

    # --- tree-aggregated PPR ------------------------------------------------

    async def _run_tree(self, ranked: List[_Piece], zeros: List[int],
                        k: int, m: int, targets: List[int],
                        wants: List[int], needed: int,
                        rotate: int = 0) -> Optional[Dict[int, bytes]]:
        """One `ppr_tree` root request serves every remote piece of the
        plan: interior survivors XOR-accumulate their children before
        forwarding, so coordinator ingress is one row-set regardless of
        k.  Local pieces scale locally (zero wire); holders that are
        not tree-capable get their edge demoted to flat PPR; a lost
        subtree's pieces are re-fetched flat with the NEUTRAL
        coefficient (same survivor set → the aggregate stays valid).
        Returns {target: row bytes} or None → flat planner."""
        mgr = self.manager
        rpc = mgr.system.rpc
        our_id = bytes(mgr.system.id)
        chosen = ranked[:needed]
        present = tuple(sorted([p.index for p in chosen] + zeros))
        rows = [self._decode_row(k, m, present, t) for t in targets]
        pos = {idx: j for j, idx in enumerate(present)}
        coeff = {p.index: [int(r[pos[p.index]]) for r in rows]
                 for p in chosen}
        locals_: List[_Piece] = []
        flat: List[_Piece] = []
        by_node: Dict[bytes, list] = {}
        node_of: Dict[bytes, object] = {}
        for p in chosen:
            if not any(coeff[p.index]):
                continue  # zero coefficient for every target row
            if mgr.is_block_present(Hash(p.hash)):
                locals_.append(p)
                continue
            best = None
            for n in self._holder_order(Hash(p.hash)):
                if bytes(n) == our_id:
                    continue
                if rpc.peer_allows(n):
                    best = n
                    break
            if best is None:
                flat.append(p)  # no live holder: flat path sweeps
            elif self._peer_tree_ok(best):
                by_node.setdefault(bytes(best), []).append(p)
                node_of[bytes(best)] = best
            else:
                # mixed-version holder: this edge serves flat PPR
                self.tree_demotions += 1
                self._note_replan("version_demote")
                flat.append(p)
        if sum(len(v) for v in by_node.values()) < 2:
            return None  # a tree of one remote partial IS flat PPR
        nodes = sorted(by_node, key=lambda nb: rpc.peer_rank(node_of[nb]))
        if rotate:
            r = rotate % len(nodes)
            nodes = nodes[r:] + nodes[:r]
        fanout = self.tree_fanout

        def build(i: int) -> dict:
            sub = {"p": [[p.hash, 1 if p.kind == "parity" else 0,
                          coeff[p.index], p.index]
                         for p in by_node[nodes[i]]],
                   "c": []}
            for j in range(fanout * i + 1,
                           min(fanout * i + 1 + fanout, len(nodes))):
                sub["c"].append([nodes[j], build(j)])
            return sub

        plan = build(0)
        depth, covered, span = 1, 1, 1
        while covered < len(nodes):
            span *= fanout
            covered += span
            depth += 1
        self.tree_plans += 1
        note_tree = getattr(mgr, "note_repair_tree", None)
        if note_tree is not None:
            note_tree(depth)
        msg = {"t": "ppr_tree", "plan": plan,
               "want": [int(w) for w in wants]}
        try:
            got, _miss, body = await self._call_tree(
                node_of[nodes[0]], msg, depth)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — root died: flat re-plan
            logger.debug("ppr_tree root %s failed: %s",
                         nodes[0].hex()[:8], e)
            self._note_replan("tree_abort")
            return None
        if len(body) != sum(wants):
            self._note_replan("tree_abort")
            return None
        accs = [np.zeros(w, dtype=np.uint8) for w in wants]
        off = 0
        for a, w in zip(accs, wants):
            if w:
                a ^= np.frombuffer(body[off:off + w], dtype=np.uint8)
            off += w
        # coordinator ingress: ONE aggregated stream for the whole tree
        mgr.note_repair_fetch("tree", len(body))
        # our own diff of planned-vs-contributed beats the reported miss
        # list (a buggy/partial answer must not double-XOR a piece)
        got_set = {int(i) for i in got}
        planned = [p for nb in nodes for p in by_node[nb]]
        missing = [p for p in planned if p.index not in got_set]
        for _ in missing:
            # subtree re-plan, NOT codeword abort: the missing pieces
            # are re-fetched below under the SAME survivor set, so the
            # aggregate's coefficients stay exact
            self._note_replan("mid_tree")
        scale = getattr(mgr.codec, "gf_scale", gf256.gf_scale_bytes)
        maxwant = max(wants)

        def xor_raw(payload: bytes, cs: List[int]) -> None:
            for a, w, c in zip(accs, wants, cs):
                if not c:
                    continue
                data = scale(c, payload, w)
                if data:
                    arr = np.frombuffer(data, dtype=np.uint8)
                    a[:len(arr)] ^= arr

        for p in locals_:
            raw = await self._read_local(p)
            if raw is None:
                missing.append(p)  # local copy vanished mid-plan
                continue
            xor_raw(raw, coeff[p.index])

        async def fetch_flat(p: _Piece):
            # NEUTRAL coefficient: a raw sub-shard the coordinator
            # rescales per target row — chain repair shares this one
            # fetch across all m′ targets
            payload, c_app, nbytes = await self._fetch_ppr(p, 1, maxwant)
            if nbytes:
                mgr.note_repair_fetch(
                    "shard" if c_app == RAW else "ppr", nbytes)
            return p, payload

        flat_all = flat + missing
        if flat_all:
            try:
                fetched = await asyncio.gather(
                    *[fetch_flat(p) for p in flat_all])
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                # a piece with NO live copy anywhere: the survivor set
                # must change, which invalidates the aggregate — only
                # the flat planner can re-plan from scratch
                logger.debug("tree completion fetch failed: %s", e)
                self._note_replan("tree_abort")
                return None
            for p, payload in fetched:
                xor_raw(payload, coeff[p.index])
        return {t: a.tobytes() for t, a in zip(targets, accs)}

    async def _call_tree(self, node, msg: dict,
                         depth: int) -> Tuple[list, list, bytes]:
        """Send the recursive plan to the tree root and read back ONE
        aggregated stream + the contributed/missing piece lists."""
        mgr = self.manager
        rpc = mgr.system.rpc
        try:
            timeout = rpc.timeout_for(node, mgr.block_rpc_timeout) \
                * max(1, depth)
            resp, stream = await mgr.endpoint.call_streaming(
                node, msg, prio=PRIO_NORMAL, timeout=timeout)
            if resp.get("err") or stream is None:
                rpc.note_result(node, None)
                raise GarageError(resp.get("err") or "empty ppr_tree answer")
            try:
                body = await asyncio.wait_for(
                    stream.read_all(), mgr.block_rpc_timeout * max(1, depth))
            except BaseException:
                await stream.aclose()
                raise
            rpc.note_result(node, None)
            return (list(resp.get("got") or []),
                    list(resp.get("miss") or []), body)
        except asyncio.CancelledError:
            rpc.note_result(node, asyncio.CancelledError())
            raise
        except Exception as e:  # noqa: BLE001
            if self._is_unknown_rpc(e):
                # peer predates ppr_tree: demote its edges from now on
                self._no_tree.add(bytes(node))
                rpc.note_result(node, None)
            else:
                rpc.note_result(node, e)
            raise

    # --- chain repair, flat transport ---------------------------------------

    async def _run_chain(self, ranked: List[_Piece], zeros: List[int],
                         k: int, m: int, targets: List[int],
                         wants: List[int], maxlen: int,
                         needed: int) -> Optional[Dict[int, bytes]]:
        """Multiple lost rows, ONE shared fetch set: PPR mode pulls
        neutral-coefficient raw sub-shards (truncated to the longest
        target row) and rescales locally per target; shard mode pulls
        whole pieces and decodes every target row in one feeder pass.
        Failed fetches re-plan with the next-ranked replacement."""
        mgr = self.manager
        mode = "ppr" if self.use_ppr else "shard"
        pieces: Dict[int, _Piece] = {p.index: p for p in ranked}
        order = [p.index for p in ranked]
        failed: set = set()
        results: Dict[int, Tuple[Optional[bytes], int]] = {}
        moved: Dict[int, int] = {}
        active: Dict[asyncio.Task, int] = {}
        maxwant = max(wants)
        final: List[int] = []
        try:
            while True:
                w = [i for i in order if i not in failed][:needed]
                if len(w) < needed:
                    return None  # candidates exhausted
                sat = [i for i in w if i in results]
                if len(sat) >= needed:
                    final = sat[:needed]
                    break
                limit = needed
                gov = getattr(mgr, "governor", None)
                if gov is not None:
                    limit = max(1, int(needed * gov.ratio() + 0.9999))
                inflight = set(active.values())
                for i in w:
                    if len(active) >= limit:
                        break
                    if i not in results and i not in inflight:
                        p = pieces[i]
                        if mode == "ppr":
                            t = asyncio.ensure_future(
                                self._fetch_ppr(p, 1, maxwant))
                        else:
                            t = asyncio.ensure_future(self._fetch_whole(p))
                        active[t] = i
                        inflight.add(i)
                if not active:
                    continue
                done, _ = await asyncio.wait(
                    active.keys(), return_when=asyncio.FIRST_COMPLETED)
                for tk in done:
                    i = active.pop(tk)
                    try:
                        payload, c_app, nbytes = tk.result()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        logger.debug("chain piece %s fetch failed: %s",
                                     pieces[i], e)
                        failed.add(i)
                        self._note_replan("survivor_died")
                        continue
                    results[i] = (payload, c_app)
                    moved[i] = nbytes
                    fmode = "shard" if (mode == "shard" or c_app == RAW) \
                        else "ppr"
                    if nbytes:
                        mgr.note_repair_fetch(fmode, nbytes)
        finally:
            for tk in list(active):
                tk.cancel()
            if active:
                await asyncio.gather(*active, return_exceptions=True)
        for i in results:
            if i not in final and moved.get(i):
                mgr.note_repair_overfetch(moved[i])
        if mode == "ppr":
            return {t: self._finish_ppr(final, zeros, k, m, t, wt, results)
                    for t, wt in zip(targets, wants)}
        rows = await self._finish_shard(final, zeros, k, m, targets,
                                        wants, maxlen, results)
        return None if rows is None else dict(zip(targets, rows))

    async def _run(self, ranked: List[_Piece], zeros: List[int], k: int,
                   m: int, target: int, want: int, maxlen: int,
                   needed: int) -> Optional[bytes]:
        mgr = self.manager
        mode = "ppr" if self.use_ppr else "shard"
        pieces: Dict[int, _Piece] = {p.index: p for p in ranked}
        order = [p.index for p in ranked]
        failed: set = set()
        trivial: set = set()                     # zero-coeff, nothing fetched
        no_trivial: set = set()  # rejected at finalize: must really fetch
        results: Dict[int, Tuple[Optional[bytes], int]] = {}
        moved: Dict[int, int] = {}
        active: Dict[asyncio.Task, int] = {}
        hedge = self._hedge_after()

        def working_set() -> List[int]:
            return [i for i in order if i not in failed][:needed]

        def coeffs(w: List[int]) -> Dict[int, int]:
            present = tuple(sorted(w + zeros))
            row = self._decode_row(k, m, present, target)
            return {idx: int(row[j]) for j, idx in enumerate(present)}

        def launch(i: int, cmap: Dict[int, int]) -> None:
            p = pieces[i]
            if mode == "ppr":
                c = cmap.get(i)
                if c == 0 and i not in no_trivial:
                    # zero coefficient under the current set: the piece
                    # contributes nothing — trivially satisfied, revisited
                    # if a replacement changes the set
                    results[i] = (None, 0)
                    trivial.add(i)
                    return
                # a piece finalize rejected (zero here, nonzero in the
                # final set) fetches with the neutral coefficient 1 — a
                # raw sub-shard the finish pass rescales — so the
                # trivial/required oscillation can never loop
                t = asyncio.ensure_future(
                    self._fetch_ppr(p, c or 1, want))
            else:
                t = asyncio.ensure_future(self._fetch_whole(p))
            active[t] = i

        try:
            while True:
                w = working_set()
                if len(w) < needed:
                    return None  # candidates exhausted
                cmap = coeffs(w) if mode == "ppr" else {}
                if mode == "ppr":
                    # a replacement may have made a previously-zero
                    # coefficient live: the piece must really be fetched
                    for i in list(trivial):
                        if cmap.get(i, 0) != 0:
                            trivial.discard(i)
                            results.pop(i, None)
                sat = [i for i in order
                       if i in results and i not in failed]
                if len(sat) >= needed:
                    final = sat[:needed]
                    if mode == "ppr":
                        present = tuple(sorted(final + zeros))
                        row = self._decode_row(k, m, present, target)
                        cfin = {idx: int(row[j])
                                for j, idx in enumerate(present)}
                        bad = [i for i in final
                               if results[i][0] is None and cfin[i] != 0]
                        if bad:
                            no_trivial.update(bad)
                            for i in bad:
                                trivial.discard(i)
                                results.pop(i, None)
                            continue
                    break
                inflight = set(active.values())
                # dynamic background yielding: under foreground pressure
                # the load governor shrinks how many of the exact-k
                # fetches run CONCURRENTLY (never below 1 — repairs still
                # finish, just serialized), so a repair storm's fan-out
                # cedes wire/CPU to client traffic and widens back out
                # when pressure clears
                limit = needed
                gov = getattr(mgr, "governor", None)
                if gov is not None:
                    limit = max(1, int(needed * gov.ratio() + 0.9999))
                for i in w:
                    if len(active) >= limit:
                        break
                    if i not in results and i not in inflight:
                        launch(i, cmap)
                        inflight.add(i)
                if not active:
                    continue  # launches were all trivial: re-evaluate
                can_hedge = any(
                    i not in failed and i not in inflight
                    and i not in results
                    for i in order if i not in set(w))
                done, _ = await asyncio.wait(
                    active.keys(),
                    return_when=asyncio.FIRST_COMPLETED,
                    timeout=hedge if can_hedge else None,
                )
                if not done:
                    # stalled wave: hedge the next-ranked replacement —
                    # whichever answers first forms the final set, the
                    # loser's bytes land in the overfetch counter
                    nxt = next(
                        (i for i in order
                         if i not in failed and i not in inflight
                         and i not in results and i not in set(w)), None)
                    if nxt is None:
                        continue
                    self.hedges += 1
                    mgr.note_repair_hedge()
                    hyp = (w[:-1] + [nxt]) if w else [nxt]
                    launch(nxt, coeffs(hyp) if mode == "ppr" else {})
                    continue
                for t in done:
                    i = active.pop(t)
                    try:
                        payload, c_app, nbytes = t.result()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        logger.debug("piece %s fetch failed: %s",
                                     pieces[i], e)
                        failed.add(i)
                        # survivor died mid-PPR (post-ack, pre-partial):
                        # re-plan with the next-ranked replacement and
                        # rescale — never a codeword abort
                        self._note_replan("survivor_died")
                        continue
                    results[i] = (payload, c_app)
                    moved[i] = nbytes
                    fmode = "shard" if (mode == "shard" or c_app == RAW) \
                        else "ppr"
                    if nbytes:
                        mgr.note_repair_fetch(fmode, nbytes)
        finally:
            for t in list(active):
                t.cancel()
            if active:
                await asyncio.gather(*active, return_exceptions=True)

        # satisfied-but-unused pieces (hedge losers that completed) are
        # pure overfetch
        for i in results:
            if i not in final and moved.get(i):
                mgr.note_repair_overfetch(moved[i])

        if mode == "ppr":
            return self._finish_ppr(final, zeros, k, m, target, want,
                                    results)
        rows = await self._finish_shard(final, zeros, k, m, [target],
                                        [want], maxlen, results)
        return None if rows is None else rows[0]

    def _finish_ppr(self, final: List[int], zeros: List[int], k: int,
                    m: int, target: int, want: int,
                    results: Dict[int, Tuple[Optional[bytes], int]]
                    ) -> bytes:
        """XOR-accumulate the partial sums, rescaling any partial whose
        applied coefficient differs from the final decode row (set
        changes, whole-shard fallbacks) via c_new ⊗ c_old⁻¹."""
        mgr = self.manager
        present = tuple(sorted(final + zeros))
        row = self._decode_row(k, m, present, target)
        cfin = {idx: int(row[j]) for j, idx in enumerate(present)}
        acc = np.zeros(want, dtype=np.uint8)
        scale = getattr(mgr.codec, "gf_scale", gf256.gf_scale_bytes)
        for i in final:
            payload, c_app = results[i]
            c_need = cfin[i]
            if c_need == 0 or payload is None:
                continue
            if c_app == RAW:
                data = scale(c_need, payload, want)
            elif c_app == c_need:
                data = payload[:want]
            else:
                data = scale(gf256.gf_mul(c_need, gf256.gf_inv(c_app)),
                             payload, want)
            if data:
                arr = np.frombuffer(data, dtype=np.uint8)
                acc[:len(arr)] ^= arr
        return acc.tobytes()

    async def _finish_shard(self, final: List[int], zeros: List[int],
                            k: int, m: int, targets: List[int],
                            wants: List[int], maxlen: int,
                            results: Dict[int, Tuple[Optional[bytes], int]]
                            ) -> Optional[List[bytes]]:
        """Whole-shard decode of exactly the k chosen pieces — batched
        through the manager's codec feeder when the entry's geometry
        matches the live codec (a repair storm's concurrent decodes
        share one cached RS schedule and one ragged dispatch).  Chain
        repair passes ALL m′ target rows through one decode submission,
        riding the feeder's background class so storm decodes coalesce
        behind foreground work."""
        mgr = self.manager
        present = sorted(final + zeros)
        zset = set(zeros)
        arrs = []
        for idx in present:
            a = np.zeros(maxlen, dtype=np.uint8)
            if idx not in zset:
                payload = results[idx][0] or b""
                b = payload[:maxlen]
                a[:len(b)] = np.frombuffer(b, dtype=np.uint8)
            arrs.append(a)
        shards = np.stack(arrs)[None, :, :]
        feeder = getattr(mgr, "feeder", None)
        live = feeder.codec.params if feeder is not None else None
        if (feeder is not None and live.rs_data == k
                and live.rs_parity == m):
            out = await feeder.decode_async(shards, present,
                                            list(targets), cls="bg")
        else:
            from ..ops.codec import CodecParams
            from ..ops.cpu_codec import CpuCodec

            codec = CpuCodec(CodecParams(rs_data=k, rs_parity=m))
            out = await asyncio.to_thread(
                codec.rs_reconstruct, shards, present, list(targets))
        return [out[0, j].tobytes()[:w] for j, w in enumerate(wants)]
