"""RepairPlanner — repair-bandwidth-optimal degraded reads.

The decode ladder's original gather (model/parity_repair.py) fetched
every surviving data member AND every parity shard of a codeword even
though a decode needs exactly k pieces, so one degraded read could move
(k+m−1)/k× the necessary bytes — and every fetch walked the full
sweep/timeout chain, dead nodes included.  This module turns every
degraded read / reconstruction into a *planned* fetch, per the two
PAPERS.md schemes the ROADMAP names:

  1. **Exact-k survivor selection** ("Boosting the Performance of
     Degraded Reads in RS-coded Distributed Storage Systems"): candidate
     pieces are ranked by their best holder's `RpcHelper.peer_rank` —
     the per-peer RTT EWMA, circuit-breaker state, zone locality,
     gossiped load-governor pressure and fail-slow verdict (the
     least-loaded / healthiest-survivor half of the same paper;
     utils/health_score.py) — with data members before
     parity (parity only fills the gap left by dead members) and
     pieces whose every holder is breaker-open last.  Exactly k fetches
     go out; a *ranked replacement* launches only when a fetch fails, or
     hedges in when the wave stalls past the hedge delay.  Fetched bytes
     that end up unused are counted in repair_overfetch_bytes_total.

  2. **Partial-parallel repair / PPR** (+ the sub-shard idea of "Fast
     Product-Matrix Regenerating Codes"): instead of shipping whole
     shards, each survivor multiplies its local shard by the decode
     coefficient in GF(256) — the `ppr` block RPC, served through
     ops/gf256 / the native kernel in ops/cpu_codec — and ships the
     partial product *truncated to the target row's length*, so a
     reconstruction moves at most one target-row-sized partial sum per
     survivor link and the coordinator only XOR-accumulates.  The GF
     work parallelizes across the survivors' CPUs; min(shard, target)
     truncation makes PPR ≤ whole-shard byte-wise.  Peers that predate
     the endpoint (version gossip, PR 7) or answer it with "unknown
     rpc" fall back to whole-shard fetch for that piece — mixed-version
     clusters reconstruct bit-identically, just less cheaply.

Replacement algebra: a survivor's partial c_old ⊗ shard is rescaled
locally to any later coefficient via (c_new ⊗ c_old⁻¹) ⊗ partial, so a
failed fetch that changes the survivor set never invalidates partials
already in hand — the coordinator re-plans, rescales, and fetches only
the replacement.

Safety is unchanged from the gather path: whole-shard pieces are
verified by content hash before use, partial products cannot be (they
are not content-addressed), but the rebuilt block must hash to the
requested id before it is returned — a corrupt partial costs a fallback,
never wrong data.
"""

from __future__ import annotations

import asyncio
import logging
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..net.frame import PRIO_NORMAL
from ..ops import gf256
from ..utils.data import Hash, block_hash
from ..utils.error import GarageError

logger = logging.getLogger("garage_tpu.block.repair_plan")

# Gossiped software version from which peers answer the `ppr` block RPC;
# older peers are never sent a partial-product request.  Unknown or
# unparseable versions are tried optimistically — an "unknown block rpc"
# answer demotes the peer to whole-shard for the rest of the process.
PPR_MIN_VERSION = (0, 9, 0)

# c_applied sentinel: the payload is the raw (unscaled) shard bytes —
# whole-shard fetches and PPR fallbacks land here; the coordinator
# scales by the final coefficient itself.
RAW = -1

_VER_RE = re.compile(r"^(\d+)\.(\d+)(?:\.(\d+))?")


def parse_version(v: Optional[str]) -> Optional[tuple]:
    """Leading numeric (major, minor, patch) of a gossiped version tag;
    None when absent/unparseable (suffixes like '-dev' are ignored)."""
    if not v:
        return None
    m = _VER_RE.match(str(v))
    if m is None:
        return None
    return (int(m.group(1)), int(m.group(2)), int(m.group(3) or 0))


class _Piece:
    """One fetchable codeword piece: a surviving data member or a parity
    shard (implicit zero shards of a partial codeword are free and never
    fetched)."""

    __slots__ = ("index", "hash", "kind")

    def __init__(self, index: int, hash_: bytes, kind: str):
        self.index = index          # position in the extended codeword
        self.hash = bytes(hash_)    # content hash == ring placement
        self.kind = kind            # "data" | "parity"

    def __repr__(self) -> str:  # debug/log friendliness
        return f"<piece {self.index} {self.kind} {self.hash.hex()[:8]}>"


class RepairPlanner:
    """Plans and executes bandwidth-minimal reconstruction of one
    codeword row.  Owned by the BlockManager; model/parity_repair.py
    routes every distributed decode through it (falling back to the
    legacy sweep-everything gather only if the plan comes up empty)."""

    def __init__(self, manager, use_ppr: bool = True,
                 hedge_delay: Optional[float] = None):
        self.manager = manager
        self.use_ppr = use_ppr
        # None → derive from the block endpoint's observed latency
        # quantile (same source as read hedging), 1 s static until
        # enough samples exist
        self.hedge_delay = hedge_delay
        self._no_ppr: set = set()     # peers observed not to answer `ppr`
        self._row_cache: dict = {}    # (k, m, present, target) -> row
        self.plans = 0
        self.hedges = 0
        self.ppr_fallbacks = 0

    # --- ranking ------------------------------------------------------------

    def rank_pieces(self, pieces: Sequence[_Piece]) -> List[_Piece]:
        """Fetch order: data members before parity (parity only fills
        the gap left by dead members), each band ordered by the piece's
        BEST holder under RpcHelper.peer_rank — the (breaker,
        fail-slow, zone, pressure-bucket, RTT) survivor key: self <
        local-zone < cross-zone < FAIL-SLOW < breaker-open, and within
        a zone band lightly-loaded holders (gossiped governor pressure,
        System.peer_pressure) before pressured ones — the load-aware
        survivor scheduling of the degraded-reads paper.  Pieces whose
        every holder is breaker-open rank dead-last — even behind
        healthy parity, since their fetches can only burn timeouts that
        healthy pieces avoid."""
        rpc = self.manager.system.rpc

        def key(p: _Piece):
            nodes = self.manager.replication.read_nodes(Hash(p.hash))
            best = min((rpc.peer_rank(n) for n in nodes),
                       default=(9, 9, 0.0))
            dead = 1 if best[0] >= 4 else 0
            kind = 0 if p.kind == "data" else 1
            return (dead, kind, best, p.index)

        return sorted(pieces, key=key)

    def _holder_order(self, h: Hash) -> List:
        rpc = self.manager.system.rpc
        return rpc.request_order(self.manager.replication.read_nodes(h))

    def _hedge_after(self) -> float:
        if self.hedge_delay is not None:
            return self.hedge_delay
        rpc = self.manager.system.rpc
        d = None
        if rpc.m_duration is not None:
            d = rpc.m_duration.quantile(
                rpc.tunables.hedge_quantile,
                min_count=rpc.tunables.hedge_min_samples,
                endpoint=self.manager.endpoint.path,
            )
        return max(d, 0.05) if d is not None else 1.0

    # --- PPR capability gate ------------------------------------------------

    def _peer_ppr_ok(self, node) -> bool:
        if bytes(node) in self._no_ppr:
            return False
        ver = parse_version(self.manager.system.peer_version(node))
        if ver is not None and ver < PPR_MIN_VERSION:
            return False
        return True  # unknown version: try it, demote on "unknown rpc"

    @staticmethod
    def _is_unknown_rpc(e: BaseException) -> bool:
        return isinstance(e, GarageError) and "unknown block rpc" in str(e)

    # --- decode coefficients ------------------------------------------------

    def _decode_row(self, k: int, m: int, present: tuple,
                    target: int) -> np.ndarray:
        """Coefficients c_j with data[target] = Σ_j c_j ⊗
        shards[present[j]].  Shares the codec's cached decode schedule
        (ops/cpu_codec.py) when the live geometry matches the entry's;
        a small local cache covers old-geometry entries."""
        key = (k, m, present, target)
        row = self._row_cache.get(key)
        if row is not None:
            return row
        codec = self.manager.codec
        if (getattr(codec, "decode_matrix", None) is not None
                and codec.params.rs_data == k
                and codec.params.rs_parity == m):
            row = codec.decode_matrix(list(present), rows=[target])[0]
        else:
            row = gf256.rs_decode_row(k, m, list(present), target)
        if len(self._row_cache) >= 512:
            self._row_cache.clear()
        self._row_cache[key] = row
        return row

    # --- fetch primitives ---------------------------------------------------

    async def _read_local(self, piece: _Piece) -> Optional[bytes]:
        """This node's own verified copy of a piece (unpacked if parity);
        zero wire bytes."""
        mgr = self.manager
        h = Hash(piece.hash)
        if not mgr.is_block_present(h):
            return None
        try:
            block = await mgr.read_block(h)
            raw = await asyncio.to_thread(block.decompressed)
        except Exception:  # noqa: BLE001 — any local failure → fetch remote
            return None
        # read_block already content-verified a PLAIN block; only a
        # compressed copy (frame-checksum-verified) needs the content
        # hash re-checked over the decompressed bytes — off-loop and
        # feeder-batched like every other planner verify
        if block.compressed and not await self._verify(raw, piece.hash):
            return None
        if piece.kind == "parity":
            from .parity import unpack_parity_shard

            return unpack_parity_shard(raw)
        return raw

    async def _fetch_whole(self, piece: _Piece) -> Tuple[bytes, int, int]:
        """One piece's verified shard bytes: local copy → ranked ring
        holders → the O(cluster) sweep as the completeness backstop.
        Returns (shard, c_applied=RAW, wire_bytes_moved)."""
        payload, moved = await self._fetch_whole_inner(piece)
        return payload, RAW, moved

    async def _fetch_whole_inner(self, piece: _Piece) -> Tuple[bytes, int]:
        # Deliberately NOT rpc_get_block_streaming: that path serves
        # whole blocks to clients (decompressed iteration, bytes_read
        # accounting, heal/decode fallbacks that would recurse into
        # reconstruction); a piece fetch wants raw wire frames, its own
        # byte accounting, and the parity unpack.  The resilience
        # primitives (peer_allows fast-fail, adaptive timeout,
        # note_result) are shared.
        from .block import DataBlock, DataBlockHeader

        mgr = self.manager
        rpc = mgr.system.rpc
        h = Hash(piece.hash)
        local = await self._read_local(piece)
        if local is not None:
            return local, 0
        our_id = mgr.system.id
        for node in self._holder_order(h):
            if bytes(node) == bytes(our_id):
                continue  # local copy already tried
            if not rpc.peer_allows(node):
                # breaker open: fast-fail to the next holder — the
                # sweep backstop below still tries everyone, so a stale
                # verdict can delay but never hide the only copy
                continue
            try:
                timeout = rpc.timeout_for(node, mgr.block_rpc_timeout)
                resp, stream = await mgr.endpoint.call_streaming(
                    node, {"t": "get_block", "h": piece.hash},
                    prio=PRIO_NORMAL, timeout=timeout,
                )
                if resp.get("err") or stream is None:
                    rpc.note_result(node, None)  # live handler: path works
                    continue
                hdr = DataBlockHeader.unpack(resp["hdr"])
                try:
                    body = await asyncio.wait_for(
                        stream.read_all(), mgr.block_rpc_timeout)
                except BaseException:
                    await stream.aclose()  # stop the sender's pump
                    raise
                rpc.note_result(node, None)
                raw = await asyncio.to_thread(
                    DataBlock(body, hdr.compressed).decompressed)
                if not await self._verify(raw, piece.hash):
                    continue
                if piece.kind == "parity":
                    from .parity import unpack_parity_shard

                    shard = unpack_parity_shard(raw)
                    if shard is None:
                        continue
                    return shard, len(body)
                return raw, len(body)
            except asyncio.CancelledError:
                rpc.note_result(node, asyncio.CancelledError())
                raise
            except Exception as e:  # noqa: BLE001 — next holder
                rpc.note_result(node, e)
                continue
        # completeness backstop: after a layout change the only copy may
        # sit on a node the ring no longer lists (sweep_get_block's
        # raison d'être); ring holders were already tried above
        raw = await mgr.sweep_get_block(h, try_ring=False)
        if raw is None:
            raise GarageError(f"piece {piece.hash.hex()[:12]} unavailable")
        if piece.kind == "parity":
            from .parity import unpack_parity_shard

            shard = unpack_parity_shard(raw)
            if shard is None:
                raise GarageError(
                    f"piece {piece.hash.hex()[:12]} not a parity shard")
            return shard, len(raw)
        return raw, len(raw)

    async def _fetch_ppr(self, piece: _Piece, coeff: int,
                         want: int) -> Tuple[bytes, int, int]:
        """coeff ⊗ shard truncated to `want` bytes, computed survivor-
        side when a PPR-capable holder has the piece; local copies scale
        locally (zero wire bytes) and holder exhaustion falls back to a
        whole-shard fetch.  Returns (payload, c_applied, wire_bytes)."""
        mgr = self.manager
        rpc = mgr.system.rpc
        h = Hash(piece.hash)
        local = await self._read_local(piece)
        if local is not None:
            return local, RAW, 0
        msg = {"t": "ppr", "h": piece.hash, "coeff": int(coeff),
               "len": int(want)}
        if piece.kind == "parity":
            msg["parity"] = True
        our_id = mgr.system.id
        for node in self._holder_order(h):
            if bytes(node) == bytes(our_id):
                continue
            if not self._peer_ppr_ok(node) or not rpc.peer_allows(node):
                continue  # old version / open breaker: next holder
            try:
                timeout = rpc.timeout_for(node, mgr.block_rpc_timeout)
                resp, stream = await mgr.endpoint.call_streaming(
                    node, msg, prio=PRIO_NORMAL, timeout=timeout)
                if resp.get("err") or stream is None:
                    # err-less but body-less answers are a MISS like the
                    # whole-shard path treats them — XOR-accumulating a
                    # phantom zero partial would corrupt the row and
                    # waste the whole planned fetch on the hash check
                    rpc.note_result(node, None)
                    continue
                try:
                    body = await asyncio.wait_for(
                        stream.read_all(), mgr.block_rpc_timeout)
                except BaseException:
                    await stream.aclose()
                    raise
                rpc.note_result(node, None)
                if not body:
                    continue  # empty partial: same phantom-zero hazard
                return body, int(coeff), len(body)
            except asyncio.CancelledError:
                rpc.note_result(node, asyncio.CancelledError())
                raise
            except Exception as e:  # noqa: BLE001
                if self._is_unknown_rpc(e):
                    # peer predates the endpoint: remember, and never
                    # count a version miss against its breaker
                    self._no_ppr.add(bytes(node))
                    rpc.note_result(node, None)
                else:
                    rpc.note_result(node, e)
                continue
        # no PPR-capable holder answered — whole-shard for this piece
        self.ppr_fallbacks += 1
        mgr.note_repair_ppr_fallback()
        payload, moved = await self._fetch_whole_inner(piece)
        return payload, RAW, moved

    async def _verify(self, raw: bytes, want_hash: bytes) -> bool:
        """Content-hash check for a fetched whole piece, batched through
        the codec feeder when one is armed so a repair storm's many
        concurrent piece verifies coalesce into one ragged hash pass."""
        mgr = self.manager
        feeder = getattr(mgr, "feeder", None)
        if feeder is not None:
            got = (await feeder.hash_async([raw]))[0]
        else:
            got = await asyncio.to_thread(block_hash, raw, mgr.hash_algo)
        return bytes(got) == bytes(want_hash)

    # --- the planned reconstruction ----------------------------------------

    async def reconstruct(self, h: Hash, ent) -> Optional[bytes]:
        """Rebuild codeword row `ent.member_index` (content hash `h`)
        with a planned, exactly-k fetch.  Returns verified plain bytes
        or None (callers fall back to the legacy gather)."""
        k, m = int(ent.k), int(ent.m)
        target = int(ent.member_index)
        lengths = list(ent.lengths)
        if not lengths or target >= len(ent.members):
            return None
        maxlen = max(lengths)
        want = int(lengths[target])
        if maxlen == 0 or want == 0 or k <= 0:
            return None
        zeros = list(range(len(ent.members), k))
        cands = [
            _Piece(i, ent.members[i], "data")
            for i in range(len(ent.members)) if i != target
        ] + [
            _Piece(k + j, ph, "parity")
            for j, ph in enumerate(ent.parity_hashes)
        ]
        needed = k - len(zeros)
        if len(cands) < needed:
            return None
        self.plans += 1
        mgr = self.manager
        try:
            out = await self._run(
                self.rank_pieces(cands), zeros, k, m, target,
                want, maxlen, needed)
        except Exception:  # noqa: BLE001 — planner failure = fallback
            logger.exception("planned reconstruction of %s failed",
                             bytes(h).hex()[:16])
            return None
        if out is None:
            return None
        if not await self._verify(out, bytes(h)):
            logger.warning("planned reconstruction of %s produced wrong "
                           "hash", bytes(h).hex()[:16])
            return None
        mgr.note_repair_done(len(out))
        return out

    async def _run(self, ranked: List[_Piece], zeros: List[int], k: int,
                   m: int, target: int, want: int, maxlen: int,
                   needed: int) -> Optional[bytes]:
        mgr = self.manager
        mode = "ppr" if self.use_ppr else "shard"
        pieces: Dict[int, _Piece] = {p.index: p for p in ranked}
        order = [p.index for p in ranked]
        failed: set = set()
        trivial: set = set()                     # zero-coeff, nothing fetched
        no_trivial: set = set()  # rejected at finalize: must really fetch
        results: Dict[int, Tuple[Optional[bytes], int]] = {}
        moved: Dict[int, int] = {}
        active: Dict[asyncio.Task, int] = {}
        hedge = self._hedge_after()

        def working_set() -> List[int]:
            return [i for i in order if i not in failed][:needed]

        def coeffs(w: List[int]) -> Dict[int, int]:
            present = tuple(sorted(w + zeros))
            row = self._decode_row(k, m, present, target)
            return {idx: int(row[j]) for j, idx in enumerate(present)}

        def launch(i: int, cmap: Dict[int, int]) -> None:
            p = pieces[i]
            if mode == "ppr":
                c = cmap.get(i)
                if c == 0 and i not in no_trivial:
                    # zero coefficient under the current set: the piece
                    # contributes nothing — trivially satisfied, revisited
                    # if a replacement changes the set
                    results[i] = (None, 0)
                    trivial.add(i)
                    return
                # a piece finalize rejected (zero here, nonzero in the
                # final set) fetches with the neutral coefficient 1 — a
                # raw sub-shard the finish pass rescales — so the
                # trivial/required oscillation can never loop
                t = asyncio.ensure_future(
                    self._fetch_ppr(p, c or 1, want))
            else:
                t = asyncio.ensure_future(self._fetch_whole(p))
            active[t] = i

        try:
            while True:
                w = working_set()
                if len(w) < needed:
                    return None  # candidates exhausted
                cmap = coeffs(w) if mode == "ppr" else {}
                if mode == "ppr":
                    # a replacement may have made a previously-zero
                    # coefficient live: the piece must really be fetched
                    for i in list(trivial):
                        if cmap.get(i, 0) != 0:
                            trivial.discard(i)
                            results.pop(i, None)
                sat = [i for i in order
                       if i in results and i not in failed]
                if len(sat) >= needed:
                    final = sat[:needed]
                    if mode == "ppr":
                        present = tuple(sorted(final + zeros))
                        row = self._decode_row(k, m, present, target)
                        cfin = {idx: int(row[j])
                                for j, idx in enumerate(present)}
                        bad = [i for i in final
                               if results[i][0] is None and cfin[i] != 0]
                        if bad:
                            no_trivial.update(bad)
                            for i in bad:
                                trivial.discard(i)
                                results.pop(i, None)
                            continue
                    break
                inflight = set(active.values())
                # dynamic background yielding: under foreground pressure
                # the load governor shrinks how many of the exact-k
                # fetches run CONCURRENTLY (never below 1 — repairs still
                # finish, just serialized), so a repair storm's fan-out
                # cedes wire/CPU to client traffic and widens back out
                # when pressure clears
                limit = needed
                gov = getattr(mgr, "governor", None)
                if gov is not None:
                    limit = max(1, int(needed * gov.ratio() + 0.9999))
                for i in w:
                    if len(active) >= limit:
                        break
                    if i not in results and i not in inflight:
                        launch(i, cmap)
                        inflight.add(i)
                if not active:
                    continue  # launches were all trivial: re-evaluate
                can_hedge = any(
                    i not in failed and i not in inflight
                    and i not in results
                    for i in order if i not in set(w))
                done, _ = await asyncio.wait(
                    active.keys(),
                    return_when=asyncio.FIRST_COMPLETED,
                    timeout=hedge if can_hedge else None,
                )
                if not done:
                    # stalled wave: hedge the next-ranked replacement —
                    # whichever answers first forms the final set, the
                    # loser's bytes land in the overfetch counter
                    nxt = next(
                        (i for i in order
                         if i not in failed and i not in inflight
                         and i not in results and i not in set(w)), None)
                    if nxt is None:
                        continue
                    self.hedges += 1
                    mgr.note_repair_hedge()
                    hyp = (w[:-1] + [nxt]) if w else [nxt]
                    launch(nxt, coeffs(hyp) if mode == "ppr" else {})
                    continue
                for t in done:
                    i = active.pop(t)
                    try:
                        payload, c_app, nbytes = t.result()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        logger.debug("piece %s fetch failed: %s",
                                     pieces[i], e)
                        failed.add(i)
                        continue
                    results[i] = (payload, c_app)
                    moved[i] = nbytes
                    fmode = "shard" if (mode == "shard" or c_app == RAW) \
                        else "ppr"
                    if nbytes:
                        mgr.note_repair_fetch(fmode, nbytes)
        finally:
            for t in list(active):
                t.cancel()
            if active:
                await asyncio.gather(*active, return_exceptions=True)

        # satisfied-but-unused pieces (hedge losers that completed) are
        # pure overfetch
        for i in results:
            if i not in final and moved.get(i):
                mgr.note_repair_overfetch(moved[i])

        if mode == "ppr":
            return self._finish_ppr(final, zeros, k, m, target, want,
                                    results)
        return await self._finish_shard(final, zeros, k, m, target, want,
                                        maxlen, results)

    def _finish_ppr(self, final: List[int], zeros: List[int], k: int,
                    m: int, target: int, want: int,
                    results: Dict[int, Tuple[Optional[bytes], int]]
                    ) -> bytes:
        """XOR-accumulate the partial sums, rescaling any partial whose
        applied coefficient differs from the final decode row (set
        changes, whole-shard fallbacks) via c_new ⊗ c_old⁻¹."""
        mgr = self.manager
        present = tuple(sorted(final + zeros))
        row = self._decode_row(k, m, present, target)
        cfin = {idx: int(row[j]) for j, idx in enumerate(present)}
        acc = np.zeros(want, dtype=np.uint8)
        scale = getattr(mgr.codec, "gf_scale", gf256.gf_scale_bytes)
        for i in final:
            payload, c_app = results[i]
            c_need = cfin[i]
            if c_need == 0 or payload is None:
                continue
            if c_app == RAW:
                data = scale(c_need, payload, want)
            elif c_app == c_need:
                data = payload[:want]
            else:
                data = scale(gf256.gf_mul(c_need, gf256.gf_inv(c_app)),
                             payload, want)
            if data:
                arr = np.frombuffer(data, dtype=np.uint8)
                acc[:len(arr)] ^= arr
        return acc.tobytes()

    async def _finish_shard(self, final: List[int], zeros: List[int],
                            k: int, m: int, target: int, want: int,
                            maxlen: int,
                            results: Dict[int, Tuple[Optional[bytes], int]]
                            ) -> Optional[bytes]:
        """Whole-shard decode of exactly the k chosen pieces — batched
        through the manager's codec feeder when the entry's geometry
        matches the live codec (a repair storm's concurrent decodes
        share one cached RS schedule and one ragged dispatch)."""
        mgr = self.manager
        present = sorted(final + zeros)
        zset = set(zeros)
        arrs = []
        for idx in present:
            a = np.zeros(maxlen, dtype=np.uint8)
            if idx not in zset:
                payload = results[idx][0] or b""
                b = payload[:maxlen]
                a[:len(b)] = np.frombuffer(b, dtype=np.uint8)
            arrs.append(a)
        shards = np.stack(arrs)[None, :, :]
        feeder = getattr(mgr, "feeder", None)
        live = feeder.codec.params if feeder is not None else None
        if (feeder is not None and live.rs_data == k
                and live.rs_parity == m):
            out = await feeder.decode_async(shards, present, [target])
        else:
            from ..ops.codec import CodecParams
            from ..ops.cpu_codec import CpuCodec

            codec = CpuCodec(CodecParams(rs_data=k, rs_parity=m))
            out = await asyncio.to_thread(
                codec.rs_reconstruct, shards, present, [target])
        return out[0, 0].tobytes()[:want]
