"""WAN emulation harness — a TCP relay that adds propagation delay.

The reference's headline S3 benchmark runs on a simulated WAN (mknet
topologies with 100 ms RTT and 20 ms jitter between zones —
ref doc/book/design/benchmarks/index.md:20-62); its claim is that reads
and writes complete in ≈1 RTT because the quorum machinery contacts the
fastest replicas first.  This module is the in-tree equivalent of that
rig for an environment without tc/netem privileges: an asyncio TCP
proxy inserted between nodes that delays every chunk by a configurable
one-way latency (propagation-delay model: order-preserving, unbounded
bandwidth, optional jitter), so a 3-node loopback cluster behaves like
three datacenters.

Used by tests/test_wan_latency.py (1-RTT assertions + latency-ordered
candidate selection), bench.py's WAN phase, and — via the subclass hooks
`_on_accept` / `_filter` — by testing/faults.py's FaultyLink, which
composes partitions, resets and blackholes on top of the delay line.
Pure harness: the product stack (net/netapp.py, rpc/rpc_helper.py) is
measured through it, never modified by it.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

__all__ = ["LatencyProxy"]


class LatencyProxy:
    """Relay 127.0.0.1:<port> → target, adding one-way delay each way.

    Each direction is an order-preserving delay line: a reader task
    stamps every chunk with `now + delay` and a writer task releases
    chunks at their deadlines, so concurrent chunks pipeline (as real
    propagation delay does) instead of serializing (as a sleep between
    read and write would)."""

    def __init__(self, target_host: str, target_port: int,
                 one_way_delay: float, jitter: float = 0.0):
        self.target = (target_host, target_port)
        self.delay = one_way_delay      # mutable: read per-chunk
        self.jitter = jitter
        self._server: Optional[asyncio.AbstractServer] = None
        self._tasks: set = set()
        self._conn_writers: set = set()  # live writers, for kill_connections

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(
            self._accept, "127.0.0.1", port)
        return self._server.sockets[0].getsockname()[1]

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    def retarget(self, port: int, host: Optional[str] = None) -> None:
        """Point the relay at a new upstream (a revived node listens on a
        fresh port); existing connections keep their old upstream."""
        self.target = (host or self.target[0], port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # cancel relays BEFORE wait_closed: in 3.12+ wait_closed waits
        # for every accepted connection, and the pipes hold them open
        for t in list(self._tasks):
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()

    def _spawn(self, coro) -> None:
        t = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    def kill_connections(self) -> None:
        """Abort every relayed connection (both sides see a reset-like
        close).  The listener keeps running."""
        for w in list(self._conn_writers):
            try:
                w.close()
            except Exception:
                pass
        self._conn_writers.clear()

    # --- subclass hooks (fault injection) ---

    def _on_accept(self, reader, writer) -> bool:
        """Return False to refuse the connection (hard partition)."""
        return True

    def _filter(self, direction: str, data: bytes) -> Optional[bytes]:
        """Per-chunk hook; direction is 'tx' (client→target) or 'rx'.
        Return None to silently drop the chunk (one-way partition /
        blackhole); EOF still propagates."""
        return data

    async def _accept(self, reader, writer):
        if not self._on_accept(reader, writer):
            writer.close()
            return
        try:
            up_r, up_w = await asyncio.open_connection(*self.target)
        except OSError:
            writer.close()
            return
        self._conn_writers.add(writer)
        self._conn_writers.add(up_w)
        self._spawn(self._pipe(reader, up_w, "tx"))
        self._spawn(self._pipe(up_r, writer, "rx"))

    async def _pipe(self, reader, writer, direction: str = "tx"):
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        async def release():
            try:
                while True:
                    deadline, data = await queue.get()
                    dt = deadline - loop.time()
                    if dt > 0:
                        await asyncio.sleep(dt)
                    if data is None:
                        break
                    writer.write(data)
                    await writer.drain()
            except (ConnectionError, asyncio.CancelledError):
                pass
            finally:
                self._conn_writers.discard(writer)
                try:
                    writer.close()
                except Exception:
                    pass

        self._spawn(release())
        try:
            while True:
                data = await reader.read(64 * 1024)
                if data:
                    data = self._filter(direction, data)
                    if data is None:
                        continue  # dropped: read on, deliver nothing
                d = self.delay
                if self.jitter:
                    d += random.uniform(-self.jitter, self.jitter)
                    d = max(0.0, d)
                await queue.put((loop.time() + d, data or None))
                if not data:
                    break
        except (ConnectionError, asyncio.CancelledError):
            await queue.put((0.0, None))
