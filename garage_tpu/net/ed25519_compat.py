"""ed25519 import shim — the `cryptography` wheel when present, a ctypes
libsodium fallback when not.

The RPC handshake (netapp.py) needs exactly four ed25519 operations:
keygen, raw (de)serialization, sign, verify.  Containers this repo grows
in do not always ship the `cryptography` wheel (and installing one is
off-limits), but libsodium is part of the base image — so the fallback
binds `crypto_sign_{seed_keypair,detached,verify_detached}` directly and
exposes the same class surface netapp.py already uses.  Raw private
bytes are the 32-byte seed in both backends, so node_key files written
by one backend load under the other.
"""

from __future__ import annotations

try:
    from cryptography.exceptions import InvalidSignature  # noqa: F401
    from cryptography.hazmat.primitives import serialization  # noqa: F401
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa: F401
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:
    import ctypes
    import ctypes.util
    import os
    import types

    HAVE_CRYPTOGRAPHY = False

    _lib = None
    _path = ctypes.util.find_library("sodium")
    for _cand in ([_path] if _path else []) + [
        "libsodium.so.23", "libsodium.so.26", "libsodium.so",
        "libsodium.dylib",
    ]:
        try:
            _lib = ctypes.CDLL(_cand)
            break
        except OSError:
            continue
    if _lib is None:
        raise ImportError(
            "ed25519 unavailable: neither the 'cryptography' wheel nor "
            "libsodium is present in this environment"
        )
    if _lib.sodium_init() < 0:
        raise ImportError("libsodium failed to initialize")

    class InvalidSignature(Exception):
        pass

    class _Raw:
        Raw = "raw"

    class _NoEncryption:
        pass

    # just enough of cryptography.hazmat.primitives.serialization for
    # netapp's raw-bytes round trips
    serialization = types.SimpleNamespace(
        Encoding=_Raw, PrivateFormat=_Raw, PublicFormat=_Raw,
        NoEncryption=_NoEncryption,
    )

    class Ed25519PublicKey:
        __slots__ = ("_raw",)

        def __init__(self, raw: bytes):
            if len(raw) != 32:
                raise ValueError("ed25519 public key must be 32 bytes")
            self._raw = bytes(raw)

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
            return cls(raw)

        def public_bytes(self, *_a) -> bytes:
            return self._raw

        def verify(self, signature: bytes, message: bytes) -> None:
            rc = _lib.crypto_sign_verify_detached(
                bytes(signature), bytes(message),
                ctypes.c_ulonglong(len(message)), self._raw,
            )
            if rc != 0:
                raise InvalidSignature("ed25519 signature mismatch")

    class Ed25519PrivateKey:
        __slots__ = ("_seed", "_pk", "_sk")

        def __init__(self, seed: bytes):
            if len(seed) != 32:
                raise ValueError("ed25519 private key must be 32 bytes")
            self._seed = bytes(seed)
            pk = ctypes.create_string_buffer(32)
            sk = ctypes.create_string_buffer(64)
            _lib.crypto_sign_seed_keypair(pk, sk, self._seed)
            self._pk = pk.raw
            self._sk = sk.raw

        @classmethod
        def generate(cls) -> "Ed25519PrivateKey":
            return cls(os.urandom(32))

        @classmethod
        def from_private_bytes(cls, raw: bytes) -> "Ed25519PrivateKey":
            return cls(bytes(raw))

        def public_key(self) -> Ed25519PublicKey:
            return Ed25519PublicKey(self._pk)

        def private_bytes(self, *_a) -> bytes:
            return self._seed

        def sign(self, message: bytes) -> bytes:
            sig = ctypes.create_string_buffer(64)
            siglen = ctypes.c_ulonglong(0)
            _lib.crypto_sign_detached(
                sig, ctypes.byref(siglen), bytes(message),
                ctypes.c_ulonglong(len(message)), self._sk,
            )
            return sig.raw
