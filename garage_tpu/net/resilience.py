"""Degraded-mode RPC resilience primitives.

The quorum engine's value proposition (1 RTT in the common case, quorum
survives stragglers — PAPER.md) only holds while every peer is healthy:
with one fixed timeout, no retries and no hedging, a single slow or
blackholed peer drags every read that latency-orders it into the first
quorum wave into a full timeout.  This module holds the pure mechanisms
the RPC layer composes to act on the health data PRs 1–3 made visible
(peer RTT EWMA, failure streaks, per-endpoint latency histograms):

  - ``ResilienceTunables`` — the ``[rpc]`` config section, threaded into
    ``FullMeshPeering`` (breaker) and ``RpcHelper`` (timeouts / retries /
    hedging).
  - ``adaptive_timeout`` — clamped ``base + k·rtt`` per-peer timeout; the
    static strategy timeout remains both the fallback for unknown peers
    and the ceiling.
  - ``full_jitter_backoff`` — the AWS full-jitter schedule for bounded
    retries of idempotent calls (retry storms synchronize without the
    jitter; see PAPERS.md tail-at-scale discussion).
  - ``CircuitBreaker`` — per-peer closed → open → half-open machine with
    an injectable clock so state transitions unit-test without sleeping.

Everything here is deliberately dependency-free (stdlib only): the net
layer must not import config/ops, and tests drive it with fake clocks.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "ResilienceTunables",
    "adaptive_timeout",
    "full_jitter_backoff",
    "is_transport_error",
    "CircuitBreaker",
    "BREAKER_STATE_VALUES",
]


@dataclass
class ResilienceTunables:
    """``[rpc]`` tunables (defaults chosen for WAN RTTs ≤ ~300 ms).

    Adaptive timeout: ``clamp(base + k·rtt_ewma, floor, static)`` where
    ``static`` is the caller's RequestStrategy timeout — adaptive tuning
    may only ever SHRINK a timeout, never extend past what the caller
    budgeted."""

    # adaptive per-peer timeouts
    adaptive_timeout_base: float = 5.0      # seconds added on top of k·rtt
    adaptive_timeout_rtt_factor: float = 20.0
    adaptive_timeout_min: float = 0.5       # floor: never time out faster
    # bounded retries (idempotent calls only)
    retry_max: int = 2                      # extra attempts per node call
    retry_backoff_base: float = 0.05        # full-jitter base (seconds)
    retry_backoff_max: float = 2.0          # per-sleep cap
    # read hedging
    hedge_quantile: float = 0.9             # of rpc_duration_seconds{endpoint}
    hedge_min_samples: int = 20             # no hedging before this many obs
    # per-peer circuit breaker
    breaker_failure_threshold: int = 5      # consecutive failures to open
    breaker_open_secs: float = 10.0         # cooldown before half-open probe
    breaker_rtt_blowup: float = 10.0        # ping > blowup×EWMA counts as fail
    breaker_rtt_min: float = 1.0            # …but only above this floor
    breaker_failure_window: float = 0.25    # dedupe burst failures (one conn
    #                                         loss fails N in-flight RPCs at
    #                                         once; that is ONE event)
    # block transfer static timeout (the adaptive layer's fallback for
    # put_block/get_block/need_block — used to be hardcoded 60.0 in
    # block/resync.py and block/manager.py)
    block_rpc_timeout: float = 60.0
    # --- end-to-end deadline propagation (docs/ROBUSTNESS.md "Overload
    # & brownout"): the API front door stamps each client request with
    # deadline_default seconds of budget; every RPC hop carries the
    # REMAINING budget and clamps its timeout to it; a hop whose
    # remaining budget is at or under deadline_floor fast-fails typed
    # (DeadlineExceeded) instead of dispatching work whose client is
    # gone.  deadline_default <= 0 disables request deadlines entirely.
    deadline_default: float = 30.0
    deadline_floor: float = 0.01


def adaptive_timeout(
    rtt: Optional[float],
    static: Optional[float],
    tun: ResilienceTunables,
) -> Optional[float]:
    """Per-peer timeout from the ping RTT EWMA: ``base + k·rtt``, floored
    at ``adaptive_timeout_min`` and ceilinged at the static timeout.
    Unknown peers (no EWMA yet) and untimed calls (static None) fall back
    to the static value unchanged."""
    if rtt is None or static is None:
        return static
    t = tun.adaptive_timeout_base + tun.adaptive_timeout_rtt_factor * rtt
    return min(static, max(tun.adaptive_timeout_min, t))


def full_jitter_backoff(
    attempt: int,
    tun: ResilienceTunables,
    rng: random.Random = random,  # type: ignore[assignment]
) -> float:
    """AWS full-jitter: uniform over [0, min(cap, base·2^attempt)].
    ``attempt`` is 0-based (first retry = attempt 0)."""
    ceiling = min(tun.retry_backoff_max,
                  tun.retry_backoff_base * (2 ** attempt))
    return rng.uniform(0.0, ceiling)


def is_transport_error(e: BaseException) -> bool:
    """True for failures that indict the PATH to the peer, not the peer's
    answer: timeouts, connection loss/refusal, and local RpcErrors.  An
    error reconstructed from a K_ERR/K_RESP wire code (``remote_code``
    set) proves the peer answered — the transport is fine, so it neither
    feeds the breaker nor earns a retry to the same node.  Likewise a
    DeadlineExceeded indicts the CALLER's budget, not the path: no
    breaker feed, no retry (the budget is gone either way)."""
    from ..utils.error import DeadlineExceeded, RpcError

    if getattr(e, "remote_code", None):
        return False
    if isinstance(e, DeadlineExceeded):
        return False
    if isinstance(e, (TimeoutError, asyncio.TimeoutError)):
        return True
    if isinstance(e, (ConnectionError, OSError)):
        return True
    return isinstance(e, RpcError)


# peer_breaker_state gauge encoding (docs/ROBUSTNESS.md + dashboard
# mappings rely on these values)
BREAKER_STATE_VALUES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class CircuitBreaker:
    """Per-peer circuit breaker: closed → open on a consecutive-failure
    streak (or ping-RTT blowup), half-open probe after a cooldown, closed
    again on probe success.

    Listed behaviors the RPC layer depends on:
      - ``allow()`` is the request gate: True in closed state, True for
        exactly ONE in-flight probe once the open cooldown elapses, False
        otherwise (callers fast-fail instead of burning a timeout).
      - Failures within ``failure_window`` seconds of the previous one
        count as a single event: one TCP connection dying fails every
        in-flight RPC on it simultaneously, and that must not trip a
        threshold-5 breaker on its own.
      - A failure while OPEN does NOT re-arm the cooldown (pings keep
        failing against a dead peer; the half-open probe must still get
        its turn).  A failure while HALF_OPEN re-opens with a fresh
        cooldown.
      - Success from ANY source (ping or data plane) closes immediately.

    ``clock`` is injectable so every transition is unit-testable without
    real sleeps."""

    __slots__ = ("tun", "clock", "state", "failures", "opened_at",
                 "probe_in_flight", "probe_at", "last_failure_at", "trips")

    def __init__(self, tun: Optional[ResilienceTunables] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.tun = tun or ResilienceTunables()
        self.clock = clock
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.probe_in_flight = False
        self.probe_at = 0.0
        self.last_failure_at: Optional[float] = None
        self.trips = 0  # lifetime open transitions (metrics/debugging)

    # --- state queries (non-mutating) ---

    def state_now(self) -> str:
        """Current state accounting for elapsed cooldown, without
        consuming the half-open probe slot (safe for request_order and
        metric scrapes)."""
        if self.state == "open" and (
            self.clock() - self.opened_at >= self.tun.breaker_open_secs
        ):
            return "half_open"
        return self.state

    # --- the request gate ---

    def allow(self) -> bool:
        now = self.clock()
        if self.state == "open":
            if now - self.opened_at < self.tun.breaker_open_secs:
                return False
            self.state = "half_open"
            self.probe_in_flight = False
        if self.state == "half_open":
            # one probe at a time; a probe whose caller vanished (task
            # cancelled before reporting) expires after a cooldown so the
            # peer is not stuck un-probeable forever
            if self.probe_in_flight and (
                now - self.probe_at < self.tun.breaker_open_secs
            ):
                return False
            self.probe_in_flight = True
            self.probe_at = now
            return True
        return True

    def release_probe(self) -> None:
        """The in-flight probe was abandoned without a verdict (caller
        cancelled, e.g. a hedged read losing the race)."""
        self.probe_in_flight = False

    # --- outcome reporting ---

    def on_success(self) -> None:
        self.failures = 0
        self.last_failure_at = None
        self.probe_in_flight = False
        self.state = "closed"

    def on_failure(self) -> None:
        now = self.clock()
        if self.state == "half_open":
            # failed probe: back to open with a fresh cooldown.  Checked
            # BEFORE the burst dedupe — a probe verdict arriving within
            # the window of an earlier failure must never be swallowed,
            # or the breaker wedges half-open (probe slot consumed, gauge
            # reads 1, request_order does not demote) until the next
            # failure outside the window
            self.state = "open"
            self.opened_at = now
            self.probe_in_flight = False
            self.last_failure_at = now
            self.trips += 1
            return
        if (self.last_failure_at is not None
                and now - self.last_failure_at < self.tun.breaker_failure_window):
            return  # burst: same event as the previous failure
        self.last_failure_at = now
        if self.state == "open":
            return  # cooldown keeps running; do not starve the probe
        self.failures += 1
        if self.failures >= self.tun.breaker_failure_threshold:
            self.state = "open"
            self.opened_at = now
            self.trips += 1

    def on_rtt(self, rtt: float, baseline: Optional[float]) -> None:
        """Ping outcome: a blown-up RTT (>'blowup'× the pre-ping EWMA and
        above the absolute floor) counts as a failure even though the ping
        technically succeeded — a peer 10× slower than its own history is
        degraded for quorum purposes."""
        if baseline is not None and rtt > max(
            self.tun.breaker_rtt_min, self.tun.breaker_rtt_blowup * baseline
        ):
            self.on_failure()
        else:
            self.on_success()
