"""net — the cluster communication backend.

Equivalent of the reference's external `netapp 0.10` crate (SURVEY.md §2.3,
§5 "Distributed communication backend"): TCP transport, ed25519-keyed
authenticated handshake where the node ID *is* the public key, multiplexed
request streams with 4 priority levels so repair traffic yields to user
traffic, streaming message bodies, typed endpoints, and full-mesh peering
with ping-based latency estimation.

This is a new asyncio design, not a port: one writer task per connection
drains four bounded priority queues (strict priority, FIFO within a level,
16 KiB chunking so a background stream never blocks a high-priority frame
for more than one chunk), and every request/response is a msgpack blob plus
an optional byte stream.

Modules:
  frame.py    wire framing + priorities
  netapp.py   Connection (handshake, mux), NetApp (listener + endpoints)
  peering.py  FullMeshPeering: connect-to-all, pings, latency, liveness
"""

from .frame import (
    PRIO_BACKGROUND,
    PRIO_HIGH,
    PRIO_NORMAL,
    PRIO_SECONDARY,
)
from .netapp import Endpoint, NetApp, NodeID, gen_node_key
from .peering import FullMeshPeering

__all__ = [
    "PRIO_HIGH", "PRIO_NORMAL", "PRIO_SECONDARY", "PRIO_BACKGROUND",
    "NetApp", "Endpoint", "NodeID", "gen_node_key", "FullMeshPeering",
]
