"""Wire framing for the cluster RPC fabric.

Frame layout (all integers big-endian):

    kind:u8  prio:u8  stream_id:u32  length:u32  payload[length]

Stream IDs are allocated by the connection side that opens the request
(odd/even split by dialer/listener so both sides can open streams without
coordination).  Priorities (ref rpc/rpc_helper.rs:19-21): lower value =
more urgent; the connection writer drains queues in strict priority order,
chunking DATA frames at CHUNK so a bulk background body never delays a
high-priority frame by more than one chunk.
"""

from __future__ import annotations

import struct
from typing import NamedTuple

# Priorities (ref netapp PRIO_*): 0 is most urgent.
PRIO_HIGH = 0        # membership gossip, health
PRIO_NORMAL = 1      # user-facing metadata + block ops
PRIO_SECONDARY = 2   # offloading, read-repair pushes
PRIO_BACKGROUND = 3  # resync/scrub/rebalance bulk traffic

N_PRIO = 4

# Metric/display labels for the priority levels (index == PRIO_* value).
PRIO_NAMES = ("high", "normal", "secondary", "background")

# Frame kinds.
K_REQ = 1        # open stream: payload = msgpack request header + body blob
K_RESP = 2       # payload = msgpack response header + body blob
K_DATA = 3       # streaming body chunk
K_EOS = 4        # end of stream (clean)
K_ERR = 5        # stream aborted: payload = utf-8 error text
K_PING = 6       # payload = 8-byte token, echoed in PONG
K_PONG = 7
K_GOODBYE = 8    # clean shutdown notice
K_WIN = 9        # per-stream flow-control credit grant: payload = u32 chunks
K_CANCEL = 10    # receiver abandoned an incoming stream: sender stops pumping

CHUNK = 16 * 1024          # streaming body chunk size
MAX_FRAME = 16 * 1024 * 1024  # sanity bound on one frame payload

_HDR = struct.Struct(">BBII")
HDR_SIZE = _HDR.size


class Frame(NamedTuple):
    kind: int
    prio: int
    stream_id: int
    payload: bytes

    def encode(self) -> bytes:
        return _HDR.pack(self.kind, self.prio, self.stream_id, len(self.payload)) + self.payload


def decode_header(buf: bytes):
    """→ (kind, prio, stream_id, length)."""
    return _HDR.unpack(buf)
