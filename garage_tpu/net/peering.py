"""Full-mesh peering: keep a connection to every known peer, ping for
latency, expose liveness.

Equivalent of netapp's FullMeshPeeringStrategy (ref rpc/system.rs:329-332):
the latency estimates feed RpcHelper's request ordering
(ref rpc/rpc_helper.rs:392-435) and the ping liveness feeds `is_up`
(ref rpc/system.rs:405-426).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set, Tuple

from .netapp import NetApp, NodeID
from .resilience import BREAKER_STATE_VALUES, CircuitBreaker, ResilienceTunables

logger = logging.getLogger("garage_tpu.net.peering")

PING_INTERVAL = 15.0
RECONNECT_BASE = 2.0
RECONNECT_MAX = 60.0
EWMA_ALPHA = 0.3


@dataclass
class PeerState:
    addr: Optional[str] = None
    latency: Optional[float] = None       # EWMA RTT seconds
    last_seen: Optional[float] = None     # monotonic, last successful ping
    failures: int = 0                     # consecutive connect/ping failures
    reconnects: int = 0                   # successful re-establishments
    ping_failures: int = 0                # lifetime failed pings/dials
    addrs_tried: Set[str] = field(default_factory=set)

    @property
    def is_up(self) -> bool:
        return self.last_seen is not None and (
            time.monotonic() - self.last_seen < 2.5 * PING_INTERVAL
        )


class FullMeshPeering:
    """Dial every known peer, keep latency estimates fresh.

    `known_peers` accumulates from bootstrap config, the persisted peer
    list, and layout gossip (the rpc System layer feeds those in via
    `add_peer`)."""

    def __init__(self, netapp: NetApp, metrics=None,
                 tunables: Optional[ResilienceTunables] = None):
        self.netapp = netapp
        self.tunables = tunables or ResilienceTunables()
        # per-peer circuit breakers (closed → open on failure streak /
        # RTT blowup → half-open probe on timer).  Fed by the ping loop
        # here AND by data-plane call outcomes via RpcHelper; consulted
        # by request_order (broken peers sort last) and by every call
        # gate (fast-fail instead of burning a timeout).
        self.breakers: Dict[NodeID, CircuitBreaker] = {}
        self.peers: Dict[NodeID, PeerState] = {}
        self._addr_only: Set[str] = set()   # peers known only by address
        self._task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()
        # optional per-ping RTT tap (set by System): successful ping
        # RTTs feed the fail-slow scorer's "ping" endpoint class, so a
        # peer with NO data-plane traffic toward us is still judgeable
        # against its siblings (utils/health_score.py)
        self.rtt_note: Optional[Callable[[NodeID, float], None]] = None
        netapp.on_connected = self._on_connected
        netapp.on_disconnected = self._on_disconnected
        # per-peer health instruments: RTT EWMA / liveness / failure
        # streak are mirrored into gauges at scrape (observe_gauges);
        # reconnects and ping failures are counted at event time
        if metrics is not None:
            self._m = {
                "rtt": metrics.gauge(
                    "peer_rtt_ewma_seconds",
                    "Smoothed ping round-trip time per peer"),
                "up": metrics.gauge(
                    "peer_up", "1 when the peer answers pings"),
                "failures": metrics.gauge(
                    "peer_consecutive_failures",
                    "Consecutive failed dials/pings per peer"),
                "reconnect": metrics.counter(
                    "peer_reconnect_total",
                    "Connection re-establishments per peer"),
                "ping_fail": metrics.counter(
                    "peer_ping_failure_total",
                    "Failed pings/dials per peer"),
                "breaker": metrics.gauge(
                    "peer_breaker_state",
                    "Circuit breaker state per peer "
                    "(0=closed, 1=half_open, 2=open)"),
            }
        else:
            self._m = None

    @staticmethod
    def _label(node: NodeID) -> str:
        return bytes(node).hex()[:16]

    def observe_gauges(self) -> None:
        """Refresh the per-peer gauges from PeerState (called at scrape
        time by the admin /metrics handler).  Clear-then-set so forgotten
        peers drop out instead of freezing at their last value."""
        if self._m is None:
            return
        for g in ("rtt", "up", "failures", "breaker"):
            self._m[g].clear()
        for nid, st in self.peers.items():
            lbl = self._label(nid)
            if st.latency is not None:
                self._m["rtt"].set(st.latency, peer=lbl)
            self._m["up"].set(1.0 if st.is_up else 0.0, peer=lbl)
            self._m["failures"].set(float(st.failures), peer=lbl)
            self._m["breaker"].set(
                BREAKER_STATE_VALUES[self.breaker_state(nid)], peer=lbl)

    # --- peer book ---

    def add_peer(self, addr: str, node_id: Optional[NodeID] = None):
        if node_id is None:
            self._addr_only.add(addr)
            return
        if node_id == self.netapp.id:
            return
        st = self.peers.setdefault(node_id, PeerState())
        if addr:
            st.addr = addr

    def latency(self, node: NodeID) -> Optional[float]:
        st = self.peers.get(node)
        return st.latency if st else None

    def forget_peer(self, node: NodeID) -> None:
        """Drop a peer removed from the committed layout: peer-book
        entry (so the scrape-time gauge refresh stops emitting its
        series), breaker state (a re-added node must not inherit stale
        failure history), and the event-time counter series.  The live
        connection, if any, is left to die naturally — the peer may
        still be draining its own goodbye traffic."""
        self.peers.pop(node, None)
        self.breakers.pop(node, None)
        if self._m is not None:
            lbl = self._label(node)
            self._m["reconnect"].drop_label("peer", lbl)
            self._m["ping_fail"].drop_label("peer", lbl)

    # --- circuit breaker surface (consulted by RpcHelper) ---

    def breaker(self, node: NodeID) -> CircuitBreaker:
        br = self.breakers.get(node)
        if br is None:
            br = self.breakers[node] = CircuitBreaker(self.tunables)
        return br

    def breaker_state(self, node: NodeID) -> str:
        br = self.breakers.get(node)
        return br.state_now() if br is not None else "closed"

    def breaker_allows(self, node: NodeID) -> bool:
        """Request gate: may a call be dispatched to this peer right now?
        Consumes the half-open probe slot when it grants one — report the
        outcome via record_rpc_success/record_rpc_failure (or
        breaker_release if abandoned)."""
        return self.breaker(node).allow()

    def breaker_release(self, node: NodeID) -> None:
        br = self.breakers.get(node)
        if br is not None:
            br.release_probe()

    def record_rpc_success(self, node: NodeID) -> None:
        self.breaker(node).on_success()

    def record_rpc_failure(self, node: NodeID) -> None:
        self.breaker(node).on_failure()

    def is_up(self, node: NodeID) -> bool:
        if node == self.netapp.id:
            return True
        st = self.peers.get(node)
        return bool(st and st.is_up)

    def connected_nodes(self) -> Set[NodeID]:
        return set(self.netapp.conns.keys())

    def peer_info(self) -> Dict[NodeID, Tuple[Optional[str], bool, Optional[float]]]:
        return {
            nid: (st.addr, st.is_up, st.latency) for nid, st in self.peers.items()
        }

    # --- lifecycle ---

    def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self):
        self._stopped.set()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def _on_connected(self, node: NodeID, is_dialer: bool):
        # a completed handshake is bidirectional proof of life: an open
        # breaker (peer crashed / was partitioned) closes immediately
        # instead of waiting out its half-open probe timer
        self.breaker(node).on_success()
        st = self.peers.setdefault(node, PeerState())
        if st.last_seen is not None:
            # not the first contact: this is a RE-connection — the churn
            # counter operators alert on (flapping link, crash-looping
            # peer)
            st.reconnects += 1
            if self._m is not None:
                self._m["reconnect"].inc(peer=self._label(node))
        st.failures = 0
        st.last_seen = time.monotonic()
        logger.debug("connected to %s", node.hex_short())

    def _on_disconnected(self, node: NodeID):
        logger.debug("disconnected from %s", node.hex_short())
        # an inbound peer we know no address for (e.g. a CLI client with a
        # temp keypair) can never be redialed — forget it so transient
        # connections don't accumulate in the peer book / health counts
        st = self.peers.get(node)
        if st is not None and st.addr is None:
            del self.peers[node]
            self.breakers.pop(node, None)

    async def _run(self):
        """Main loop: every PING_INTERVAL, (re)dial missing peers and ping
        connected ones.  Reconnect backoff is per-peer exponential."""
        while not self._stopped.is_set():
            await self._tick()
            await asyncio.sleep(PING_INTERVAL * random.uniform(0.8, 1.2))

    async def _tick(self):
        # resolve addr-only bootstrap peers by dialing them once
        for addr in list(self._addr_only):
            try:
                conn = await self.netapp.connect(addr)
                self._addr_only.discard(addr)
                self.add_peer(addr, conn.remote_id)
            except Exception as e:
                logger.debug("bootstrap dial %s failed: %s", addr, e)

        tasks = []
        for nid, st in list(self.peers.items()):
            conn = self.netapp.conns.get(nid)
            if conn is None or conn._closed:
                if st.addr and self._should_retry(st):
                    tasks.append(self._dial(nid, st))
            else:
                tasks.append(self._ping(nid, st, conn))
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    def _should_retry(self, st: PeerState) -> bool:
        if st.failures == 0 or st.last_seen is None:
            return True
        backoff = min(RECONNECT_BASE * (2 ** min(st.failures, 6)), RECONNECT_MAX)
        return time.monotonic() - st.last_seen > backoff

    async def _dial(self, nid: NodeID, st: PeerState):
        try:
            await self.netapp.connect(st.addr, expected_id=nid)
            st.failures = 0
        except Exception as e:
            st.failures += 1
            st.ping_failures += 1
            self.breaker(nid).on_failure()
            if self._m is not None:
                self._m["ping_fail"].inc(peer=self._label(nid))
            logger.debug("dial %s (%s) failed: %s", nid.hex_short(), st.addr, e)

    async def _ping(self, nid: NodeID, st: PeerState, conn):
        try:
            rtt = await conn.ping()
            st.last_seen = time.monotonic()
            if self.rtt_note is not None:
                try:
                    self.rtt_note(nid, rtt)
                except Exception:  # noqa: BLE001 — scoring never breaks pings
                    pass
            # breaker judges the fresh RTT against the PRE-ping EWMA: a
            # 10× blowup on an established baseline counts as a failure
            # even though the ping came back
            self.breaker(nid).on_rtt(rtt, st.latency)
            st.latency = (
                rtt if st.latency is None
                else EWMA_ALPHA * rtt + (1 - EWMA_ALPHA) * st.latency
            )
            st.failures = 0
        except Exception as e:
            st.failures += 1
            st.ping_failures += 1
            self.breaker(nid).on_failure()
            if self._m is not None:
                self._m["ping_fail"].inc(peer=self._label(nid))
            logger.debug("ping %s failed: %s", nid.hex_short(), e)
