"""NetApp — authenticated, multiplexed, priority-scheduled RPC transport.

Equivalent of the reference's netapp crate (SURVEY.md §2.3): TCP transport
with an ed25519 handshake (node ID = public key; a cluster-wide shared
secret gates membership, ref rpc/system.rs:22-23,185-242), typed endpoints,
multiplexed request streams with 4 priorities, and streaming bodies.

Design notes (asyncio-native, not a port):
  - One reader task and one writer task per connection.  Outgoing frames sit
    in four bounded per-priority deques; the writer always drains the most
    urgent non-empty level, so PRIO_BACKGROUND bulk (resync/scrub) yields to
    PRIO_HIGH gossip at 16 KiB granularity.
  - A request = msgpack header + opaque payload + optional byte stream.
    Responses mirror that.  Stream frames of one stream are FIFO, which
    gives the reference's OrderTag ordering for free within a stream.
  - Handshake: both sides exchange pubkey+nonce, then prove (a) possession
    of the cluster secret (HMAC-SHA256 over the transcript) and (b) their
    node identity (ed25519 signature over the transcript).  The channel is
    authenticated, not encrypted — same trust model as deployments of the
    reference that run RPC on a private network.

Flow control (round 2): per-stream credit windows.  A sender may have at
most STREAM_WINDOW chunks of one stream in flight; the receiver grants
more credit (K_WIN frames at PRIO_HIGH) as the consumer drains the
stream.  A slow consumer therefore stalls only its own stream's sender —
the connection reader never blocks on a full stream buffer, so unrelated
RPCs on the same connection keep flowing (the reference's netapp has the
same property via per-stream channels).
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import logging
import os
import struct
import time
from collections import deque
from contextlib import nullcontext
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional, Tuple

import msgpack

from .ed25519_compat import Ed25519PrivateKey, Ed25519PublicKey, serialization

from ..utils.data import FixedBytes32
from ..utils.error import RpcError, error_code, remote_error
from ..utils.tracing import (
    TraceContext,
    arm_deadline,
    current_trace_context,
    deadline_expired,
    disarm_deadline,
    inherited_priority,
    remaining_budget,
    reset_remote_context,
    set_remote_context,
)
from .frame import (
    CHUNK,
    HDR_SIZE,
    K_CANCEL,
    K_DATA,
    K_EOS,
    K_ERR,
    K_GOODBYE,
    K_PING,
    K_PONG,
    K_REQ,
    K_RESP,
    K_WIN,
    MAX_FRAME,
    N_PRIO,
    PRIO_HIGH,
    PRIO_NORMAL,
    PRIO_NAMES,
    Frame,
    decode_header,
)

logger = logging.getLogger("garage_tpu.net")

NodeID = FixedBytes32

_NULL_CTX = nullcontext()

# Protocol v2: a length-prefixed version frame follows the auth proof
# (NetApp.version exchange).  The magic is BUMPED with the wire change
# so a v1 peer fails the handshake cleanly ("bad protocol magic")
# instead of desyncing on the frame it does not expect — version skew
# WITHIN v2 (the rolling-upgrade drill) is what the frame itself
# carries.
MAGIC = b"GTPU/2\n"
_OUT_QUEUE_LIMIT = 16       # frames buffered per priority level
_IN_STREAM_LIMIT = 128      # legacy bound (loopback streams only)
STREAM_WINDOW = 64          # flow-control window per stream (64 × 16 KiB = 1 MiB)


def gen_node_key() -> Ed25519PrivateKey:
    return Ed25519PrivateKey.generate()


def key_to_bytes(key: Ed25519PrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.Raw,
        serialization.PrivateFormat.Raw,
        serialization.NoEncryption(),
    )


def key_from_bytes(raw: bytes) -> Ed25519PrivateKey:
    return Ed25519PrivateKey.from_private_bytes(raw)


def node_id_of(key: Ed25519PrivateKey) -> NodeID:
    return NodeID(
        key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
    )


def load_or_gen_node_key(path: str) -> Ed25519PrivateKey:
    """Persisted node identity, file mode 0600 (ref rpc/system.rs:201-242)."""
    if os.path.exists(path):
        with open(path, "rb") as f:
            return key_from_bytes(f.read())
    key = gen_node_key()
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key_to_bytes(key))
    return key


class ByteStream:
    """Incoming streaming body: async-iterate 16 KiB chunks.

    The queue is BOUNDED (STREAM_WINDOW + 2 chunks ≈ 2 MiB): connection-fed
    streams stay within it because the remote sender respects the credit
    window (`on_consumed` grants credit back as the consumer drains; a
    sender that violates the window fails the stream instead of growing the
    buffer), and loopback streams get real backpressure because the local
    producer awaits `_push` on the full queue.

    A consumer that stops early MUST call `aclose()` — it tells the sender
    to stop pumping (K_CANCEL for connection streams, producer-task cancel
    for loopback); abandoning the object without it parks the remote pump
    in its credit window until the connection closes."""

    def __init__(self, on_consumed=None, on_cancel=None,
                 maxsize: int = STREAM_WINDOW + 2):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._err: Optional[str] = None
        self._err_code: Optional[str] = None  # structured K_ERR code
        self._on_consumed = on_consumed
        self._on_cancel = on_cancel
        self._consumed = 0
        self._done = False

    async def _push(self, chunk: Optional[bytes]):
        await self._q.put(chunk)

    def _push_nowait(self, chunk: Optional[bytes]):
        try:
            self._q.put_nowait(chunk)
        except asyncio.QueueFull:
            # only a sender ignoring the credit window can get here
            self._fail("flow-control window violated by sender")

    def _fail(self, err: str, code: Optional[str] = None):
        self._err = err
        self._err_code = code
        try:
            self._q.put_nowait(None)
        except asyncio.QueueFull:
            pass  # consumer drains the queue, then sees _err

    def _raise_err(self):
        """Stream failure as an exception: typed when the sender shipped
        a structured code in its K_ERR frame, plain RpcError otherwise."""
        if self._err_code is not None:
            raise remote_error(self._err_code, f"stream error: {self._err}")
        raise RpcError(f"stream error: {self._err}")

    def __aiter__(self):
        return self

    async def __anext__(self) -> bytes:
        if self._err is not None and self._q.empty():
            self._done = True
            self._raise_err()
        chunk = await self._q.get()
        if chunk is None:
            self._done = True
            if self._err is not None:
                self._raise_err()
            raise StopAsyncIteration
        if self._on_consumed is not None:
            self._consumed += 1
            if self._consumed >= STREAM_WINDOW // 2:
                n, self._consumed = self._consumed, 0
                try:
                    await self._on_consumed(n)
                except Exception:  # conn gone: the stream will fail anyway
                    pass
        return chunk

    async def aclose(self) -> None:
        """Abandon the stream: the sender stops pumping and both sides drop
        their per-stream state.  No-op after full consumption."""
        if self._done:
            return
        self._done = True
        self._err = "cancelled by receiver"
        if self._on_cancel is not None:
            try:
                await self._on_cancel()
            except Exception:
                pass

    async def read_all(self) -> bytes:
        return b"".join([c async for c in self])


# handler(remote_node, msg, body) -> (resp_msg, resp_body | None)
Handler = Callable[
    [NodeID, Any, Optional[ByteStream]],
    Awaitable[Tuple[Any, Optional[AsyncIterator[bytes]]]],
]


class Endpoint:
    """A typed RPC endpoint (ref netapp endpoint registration, e.g.
    table/table.rs:72-74).  Register a handler server-side; call remotely."""

    def __init__(self, netapp: "NetApp", path: str):
        self.netapp = netapp
        self.path = path
        self.handler: Optional[Handler] = None

    def set_handler(self, handler: Handler) -> "Endpoint":
        self.handler = handler
        return self

    async def call(
        self,
        node: NodeID,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
        body: Optional[AsyncIterator[bytes]] = None,
    ) -> Any:
        resp, stream = await self.call_streaming(node, msg, prio, timeout, body)
        if stream is not None:
            await stream.read_all()  # drain ignored body
        return resp

    async def call_streaming(
        self,
        node: NodeID,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
        body: Optional[AsyncIterator[bytes]] = None,
    ) -> Tuple[Any, Optional[ByteStream]]:
        return await self.netapp.call_streaming(node, self.path, msg, prio, timeout, body)


class _OutMux:
    """Bounded per-priority outgoing frame queues + strict-priority pop.

    Entries carry their enqueue timestamp so the writer can report how
    long each frame waited for the wire — the direct measure of
    priority-queue head-of-line blocking (a PRIO_HIGH gossip frame stuck
    behind bulk shows up as queue-wait, not as mystery RPC latency)."""

    def __init__(self):
        self.queues = [deque() for _ in range(N_PRIO)]
        self.cv = asyncio.Condition()
        self.closed = False
        # request frames dropped in-queue because their deadline passed
        # before they reached the wire (docs/ROBUSTNESS.md "Overload &
        # brownout"): under head-of-line pressure the doomed work is shed
        # HERE instead of burning wire bytes + a remote handler on it
        self.expired_drops = 0

    async def put(self, frame: Frame, deadline: Optional[float] = None,
                  on_drop=None):
        """`deadline` (absolute time.monotonic) marks a frame droppable
        once expired; `on_drop` (sync, no await) is invoked if the writer
        discards it — K_REQ senders fail their response future there so
        the caller sees a typed DeadlineExceeded immediately."""
        async with self.cv:
            while (
                len(self.queues[frame.prio]) >= _OUT_QUEUE_LIMIT and not self.closed
            ):
                await self.cv.wait()
            if self.closed:
                raise RpcError("connection closed")
            self.queues[frame.prio].append(
                (frame, time.perf_counter(), deadline, on_drop))
            self.cv.notify_all()

    async def pop(self) -> Optional[Tuple[Frame, float]]:
        """→ (frame, enqueue_perf_counter) or None when closed+drained.
        Queued frames whose deadline already passed are discarded (their
        on_drop hook runs) instead of being written — the client is gone;
        the wire slot goes to a frame someone still waits for."""
        async with self.cv:
            while True:
                popped = False
                for q in self.queues:
                    while q:
                        frame, t_enq, deadline, on_drop = q.popleft()
                        popped = True
                        if (deadline is not None
                                and time.monotonic() >= deadline):
                            self.expired_drops += 1
                            if on_drop is not None:
                                try:
                                    on_drop()
                                except Exception:  # noqa: BLE001
                                    pass
                            continue
                        self.cv.notify_all()
                        return frame, t_enq
                if popped:
                    # dropped expired entries freed queue slots: writers
                    # blocked in put() must recheck before we sleep
                    self.cv.notify_all()
                if self.closed:
                    return None
                await self.cv.wait()

    async def close(self):
        async with self.cv:
            self.closed = True
            self.cv.notify_all()


class _StreamCancelled(Exception):
    """Receiver abandoned the stream (K_CANCEL) — stop pumping, silently."""


async def _cancel_task(task: Optional[asyncio.Task]) -> None:
    """Loopback streams' on_cancel hook: stop the local producer task."""
    if task is not None and not task.done():
        task.cancel()


class _Credit:
    """Sender-side flow-control window for one outgoing stream."""

    __slots__ = ("n", "_ev", "_failed", "_cancelled")

    def __init__(self, n: int):
        self.n = n
        self._ev = asyncio.Event()
        self._failed = False
        self._cancelled = False

    async def take(self) -> None:
        while self.n <= 0:
            self._check()
            self._ev.clear()
            await self._ev.wait()
        self._check()
        self.n -= 1

    def _check(self) -> None:
        if self._cancelled:
            raise _StreamCancelled()
        if self._failed:
            raise RpcError("connection lost (flow control)")

    def grant(self, n: int) -> None:
        self.n += n
        self._ev.set()

    def fail(self) -> None:
        self._failed = True
        self._ev.set()

    def cancel(self) -> None:
        self._cancelled = True
        self._ev.set()


class Connection:
    """One authenticated, multiplexed peer connection."""

    def __init__(
        self,
        netapp: "NetApp",
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        remote_id: NodeID,
        is_dialer: bool,
    ):
        self.netapp = netapp
        self.reader = reader
        self.writer = writer
        self.remote_id = remote_id
        self.is_dialer = is_dialer
        self._next_stream = 1 if is_dialer else 2  # odd/even split
        self._out = _OutMux()
        self._pending: Dict[int, asyncio.Future] = {}   # stream -> resp future
        self._in_streams: Dict[int, ByteStream] = {}
        self._send_credit: Dict[int, "_Credit"] = {}    # outgoing stream windows
        self._pings: Dict[bytes, asyncio.Future] = {}
        self._tasks: list = []
        self._closed = False
        self.last_seen = time.monotonic()
        # per-peer per-priority traffic accounting, read by the Prometheus
        # counters below and by the `cluster stats` admin command
        self.tx_bytes = [0] * N_PRIO
        self.tx_frames = [0] * N_PRIO
        self.rx_bytes = [0] * N_PRIO
        self.rx_frames = [0] * N_PRIO
        self._peer_id_hex = bytes(remote_id).hex()[:16]
        self._peer_durable = False

    @property
    def _peer_label(self) -> str:
        """Metric label for this peer.  Connections from peers the node
        cannot redial (CLI clients with throwaway keypairs) aggregate
        under 'transient' — every `garage status` otherwise mints a new
        immortal counter series, unbounded over a daemon's lifetime.
        Once a peer proves durable (a dialable address is known) the
        real label sticks."""
        if self._peer_durable:
            return self._peer_id_hex
        fn = self.netapp.peer_durable_fn
        if fn is None or fn(self.remote_id):
            self._peer_durable = True
            return self._peer_id_hex
        return "transient"

    def traffic_stats(self) -> Dict[str, Dict[str, int]]:
        """{prio_name: {tx_bytes, tx_frames, rx_bytes, rx_frames}}."""
        return {
            PRIO_NAMES[p]: {
                "tx_bytes": self.tx_bytes[p],
                "tx_frames": self.tx_frames[p],
                "rx_bytes": self.rx_bytes[p],
                "rx_frames": self.rx_frames[p],
            }
            for p in range(N_PRIO)
        }

    def start(self):
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._read_loop()),
            loop.create_task(self._write_loop()),
        ]

    # --- outgoing ---

    def _alloc_stream(self) -> int:
        sid = self._next_stream
        self._next_stream += 2
        return sid

    async def request(
        self,
        path: str,
        msg_bytes: bytes,
        prio: int,
        timeout: Optional[float],
        body: Optional[AsyncIterator[bytes]],
    ) -> Tuple[bytes, Optional[ByteStream]]:
        if self._closed:
            raise RpcError(f"connection to {self.remote_id.hex_short()} closed")
        sid = self._alloc_stream()
        hdr_obj: Dict[str, Any] = {"p": path, "b": body is not None}
        # cross-node trace propagation: the caller's span identity rides
        # the request header, so the remote handler's spans join THIS
        # trace instead of starting an orphan one
        ctx = current_trace_context()
        if ctx is not None:
            hdr_obj["tc"] = TraceContext(
                ctx.trace_id, ctx.span_id, prio
            ).pack()
        # end-to-end deadline propagation: the REMAINING request budget
        # (relative seconds — peer clocks are not comparable) rides next
        # to the trace context; the serving node re-arms its task-local
        # deadline from it so further hops inherit an ever-shrinking
        # budget instead of a fresh 30 s per hop
        budget = remaining_budget()
        expires_at: Optional[float] = None
        if budget is not None:
            hdr_obj["dl"] = round(budget, 4)
            expires_at = time.monotonic() + budget
        header = msgpack.packb(hdr_obj, use_bin_type=True)
        fut = asyncio.get_running_loop().create_future()
        self._pending[sid] = fut

        def _expired_in_queue():
            # the writer dropped our K_REQ before it hit the wire: fail
            # the caller immediately with the typed budget error instead
            # of letting it burn its (already tiny) timeout
            if not fut.done():
                from ..utils.error import DeadlineExceeded

                fut.set_exception(DeadlineExceeded(
                    f"request {path} to {self.remote_id.hex_short()} "
                    f"expired in the outgoing queue"))

        try:
            await self._out.put(
                Frame(K_REQ, prio, sid, struct.pack(">I", len(header)) + header + msg_bytes),
                deadline=expires_at,
                on_drop=_expired_in_queue,
            )
            pump = None
            if body is not None:
                pump = asyncio.get_running_loop().create_task(
                    self._pump_body(sid, prio, body)
                )
            try:
                resp_payload, stream = await (
                    asyncio.wait_for(fut, timeout) if timeout else fut
                )
            finally:
                if pump is not None and not pump.done():
                    pump.cancel()
            hlen = struct.unpack(">I", resp_payload[:4])[0]
            rheader = msgpack.unpackb(resp_payload[4 : 4 + hlen], raw=False)
            rbody = resp_payload[4 + hlen :]
            if not rheader.get("ok", False):
                raise remote_error(
                    rheader.get("code"), rheader.get("err", "remote error")
                )
            return rbody, stream
        except asyncio.TimeoutError:
            # typed so the resilience layer can classify it (retryable,
            # breaker-feeding) without string matching
            from ..utils.error import TimeoutError_

            raise TimeoutError_(
                f"rpc timeout after {timeout}s calling {path} on "
                f"{self.remote_id.hex_short()}"
            )
        finally:
            self._pending.pop(sid, None)

    async def _pump_body(self, sid: int, prio: int, body: AsyncIterator[bytes]):
        credit = _Credit(STREAM_WINDOW)
        self._send_credit[sid] = credit
        try:
            async for chunk in body:
                for i in range(0, len(chunk), CHUNK):
                    # flow control: at most STREAM_WINDOW chunks of this
                    # stream in flight; the receiver grants more (K_WIN)
                    # as its consumer drains
                    await credit.take()
                    await self._out.put(Frame(K_DATA, prio, sid, bytes(chunk[i : i + CHUNK])))
            await self._out.put(Frame(K_EOS, prio, sid, b""))
        except asyncio.CancelledError:
            raise
        except _StreamCancelled:
            pass  # receiver already dropped its end; nothing to tell it
        except Exception as e:
            logger.debug("body pump error on stream %d: %s", sid, e)
            try:
                # structured abort: code + message, so the receiver can
                # re-raise the domain error type and label its metrics
                payload = msgpack.packb(
                    {"c": error_code(e), "m": str(e)}, use_bin_type=True
                )
                await self._out.put(Frame(K_ERR, prio, sid, payload))
            except RpcError:
                pass
        finally:
            self._send_credit.pop(sid, None)
            # release upstream resources (file handles, generators) promptly
            # — `async for` does not close a broken-out-of async generator
            aclose = getattr(body, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass

    async def ping(self, timeout: float = 10.0) -> float:
        token = os.urandom(8)
        fut = asyncio.get_running_loop().create_future()
        self._pings[token] = fut
        t0 = time.monotonic()
        try:
            await self._out.put(Frame(K_PING, PRIO_HIGH, 0, token))
            await asyncio.wait_for(fut, timeout)
            return time.monotonic() - t0
        finally:
            self._pings.pop(token, None)

    # --- loops ---

    async def _write_loop(self):
        nm = self.netapp._net_metrics
        try:
            while True:
                entry = await self._out.pop()
                if entry is None:
                    break
                frame, t_enq = entry
                self.tx_frames[frame.prio] += 1
                self.tx_bytes[frame.prio] += HDR_SIZE + len(frame.payload)
                waited = time.perf_counter() - t_enq
                hook = self.netapp.queue_wait_hook
                if hook is not None:
                    try:
                        hook(waited)
                    except Exception:  # noqa: BLE001 — governor must not kill IO
                        pass
                if nm is not None:
                    prio_name = PRIO_NAMES[frame.prio]
                    nm["queue_wait"].observe(waited, prio=prio_name)
                    nm["tx_frames"].inc(peer=self._peer_label, prio=prio_name)
                    nm["tx_bytes"].inc(
                        HDR_SIZE + len(frame.payload),
                        peer=self._peer_label, prio=prio_name,
                    )
                self.writer.write(frame.encode())
                await self.writer.drain()
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            await self._shutdown()

    async def _read_loop(self):
        nm = self.netapp._net_metrics
        try:
            while True:
                hdr = await self.reader.readexactly(HDR_SIZE)
                kind, prio, sid, length = decode_header(hdr)
                if length > MAX_FRAME:
                    raise RpcError(f"oversized frame: {length}")
                payload = await self.reader.readexactly(length) if length else b""
                self.last_seen = time.monotonic()
                if prio < N_PRIO:
                    self.rx_frames[prio] += 1
                    self.rx_bytes[prio] += HDR_SIZE + length
                    if nm is not None:
                        nm["rx_frames"].inc(
                            peer=self._peer_label, prio=PRIO_NAMES[prio])
                        nm["rx_bytes"].inc(
                            HDR_SIZE + length,
                            peer=self._peer_label, prio=PRIO_NAMES[prio])
                await self._dispatch(kind, prio, sid, payload)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            asyncio.CancelledError,
            OSError,
            RpcError,
        ):
            pass
        finally:
            await self._shutdown()

    def _make_in_stream(self, sid: int) -> ByteStream:
        """Flow-controlled incoming stream: grants window credit back to the
        sender as the consumer drains (K_WIN at PRIO_HIGH so grants are
        never stuck behind bulk data)."""

        async def grant(n: int, _sid=sid):
            await self._out.put(
                Frame(K_WIN, PRIO_HIGH, _sid, struct.pack(">I", n))
            )

        async def cancel(_sid=sid):
            # drop local state first so in-flight K_DATA frames are ignored,
            # then tell the sender to stop pumping
            self._in_streams.pop(_sid, None)
            try:
                await self._out.put(Frame(K_CANCEL, PRIO_HIGH, _sid, b""))
            except RpcError:
                pass  # connection gone — sender state died with it

        return ByteStream(on_consumed=grant, on_cancel=cancel)

    async def _dispatch(self, kind: int, prio: int, sid: int, payload: bytes):
        if kind == K_REQ:
            hlen = struct.unpack(">I", payload[:4])[0]
            header = msgpack.unpackb(payload[4 : 4 + hlen], raw=False)
            msg = payload[4 + hlen :]
            body = None
            if header.get("b"):
                body = self._make_in_stream(sid)
                self._in_streams[sid] = body
            asyncio.get_running_loop().create_task(
                self._handle_request(sid, prio, header, msg, body)
            )
        elif kind == K_RESP:
            # register the body stream before resolving the future, and hand
            # the stream object to the future directly — it may be fully
            # received (and deregistered) before the caller wakes up
            hlen = struct.unpack(">I", payload[:4])[0]
            rheader = msgpack.unpackb(payload[4 : 4 + hlen], raw=False)
            stream = None
            if rheader.get("b"):
                stream = self._make_in_stream(sid)
                self._in_streams[sid] = stream
            fut = self._pending.get(sid)
            if fut is not None and not fut.done():
                fut.set_result((payload, stream))
        elif kind == K_DATA:
            stream = self._in_streams.get(sid)
            if stream is not None:
                # never blocks: the sender respects the credit window, so
                # the queue holds at most ~STREAM_WINDOW chunks
                stream._push_nowait(payload)
        elif kind == K_WIN:
            credit = self._send_credit.get(sid)
            if credit is not None:
                credit.grant(struct.unpack(">I", payload[:4])[0])
        elif kind == K_CANCEL:
            credit = self._send_credit.get(sid)
            if credit is not None:
                credit.cancel()
        elif kind == K_EOS:
            stream = self._in_streams.pop(sid, None)
            if stream is not None:
                await stream._push(None)
        elif kind == K_ERR:
            stream = self._in_streams.pop(sid, None)
            if stream is not None:
                try:
                    err = msgpack.unpackb(payload, raw=False)
                    stream._fail(str(err.get("m", "remote error")),
                                 code=err.get("c"))
                except Exception:
                    # pre-structured peers sent bare utf-8 text
                    stream._fail(payload.decode("utf-8", "replace"))
        elif kind == K_PING:
            await self._out.put(Frame(K_PONG, PRIO_HIGH, 0, payload))
        elif kind == K_PONG:
            fut = self._pings.get(bytes(payload))
            if fut is not None and not fut.done():
                fut.set_result(True)
        elif kind == K_GOODBYE:
            raise RpcError("peer said goodbye")

    async def _handle_request(
        self, sid: int, prio: int, header: dict, msg: bytes,
        body: Optional[ByteStream],
    ):
        path = header["p"]
        # cross-node trace propagation, server side: extract the caller's
        # context and (a) wrap the handler in a span parented on it, so
        # every node an RPC touches contributes spans to ONE trace, and
        # (b) install it task-locally so deeper spans and further hops
        # inherit it.  This task is freshly created per request, so the
        # contextvar never leaks across requests.
        tctx = TraceContext.unpack(header.get("tc")) if header.get("tc") else None
        token = set_remote_context(tctx) if tctx is not None else None
        # deadline propagation, server side: re-arm the caller's remaining
        # budget task-locally so this handler's own work and further hops
        # clamp to it.  Malformed values from a hostile peer are ignored
        # (like a bad tc) — they must never break dispatch.
        dl = header.get("dl")
        dtoken = None
        if isinstance(dl, (int, float)) and not isinstance(dl, bool):
            budget = float(dl)
            if budget == budget and -1.0 <= budget <= 86400.0:  # finite, sane
                dtoken = arm_deadline(budget)
        tracer = self.netapp.tracer
        if tracer is not None and tctx is not None:
            span = tracer.span_from_context(
                f"RPC handler {path}", tctx,
                **{"from": self._peer_label, "prio": PRIO_NAMES[prio]
                   if prio < N_PRIO else prio},
            )
        else:
            span = _NULL_CTX
        try:
            with span:
                await self._handle_request_inner(sid, prio, path, msg, body)
        finally:
            if dtoken is not None:
                disarm_deadline(dtoken)
            if token is not None:
                reset_remote_context(token)

    async def _handle_request_inner(
        self, sid: int, prio: int, path: str, msg: bytes,
        body: Optional[ByteStream],
    ):
        ep = self.netapp.endpoints.get(path)
        try:
            if ep is None or ep.handler is None:
                raise RpcError(f"no handler for endpoint {path!r}")
            if deadline_expired():
                # the caller's budget ran out while this request sat in
                # queues: answer the typed error without running the
                # handler — the client is gone, the work would be waste
                from ..utils.error import DeadlineExceeded

                raise DeadlineExceeded(
                    f"budget exhausted before handler {path}")
            msg_obj = msgpack.unpackb(msg, raw=False)
            resp_obj, resp_body = await ep.handler(self.remote_id, msg_obj, body)
            resp = msgpack.packb(resp_obj, use_bin_type=True)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.debug("handler %s error: %s", path, e)
            header = msgpack.packb(
                {"ok": False, "err": str(e), "code": error_code(e)},
                use_bin_type=True,
            )
            try:
                await self._out.put(
                    Frame(K_RESP, prio, sid, struct.pack(">I", len(header)) + header)
                )
            except RpcError:
                pass
            return
        header = msgpack.packb({"ok": True, "b": resp_body is not None}, use_bin_type=True)
        try:
            await self._out.put(
                Frame(K_RESP, prio, sid, struct.pack(">I", len(header)) + header + resp)
            )
            if resp_body is not None:
                await self._pump_body(sid, prio, resp_body)
        except RpcError:
            pass

    async def _shutdown(self):
        if self._closed:
            return
        self._closed = True
        await self._out.close()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(RpcError("connection lost"))
        for stream in self._in_streams.values():
            stream._fail("connection lost")
        self._in_streams.clear()
        for credit in self._send_credit.values():
            credit.fail()  # release pumps blocked on flow control
        self._send_credit.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        self.netapp._conn_lost(self)

    async def close(self):
        try:
            await self._out.put(Frame(K_GOODBYE, PRIO_HIGH, 0, b""))
        except RpcError:
            pass
        await asyncio.sleep(0)
        await self._shutdown()
        for t in self._tasks:
            t.cancel()


class NetApp:
    """The node's RPC stack: listener, dialer, endpoint registry, conn map."""

    def __init__(self, privkey: Ed25519PrivateKey, secret: Optional[str] = None,
                 version: Optional[str] = None):
        self.privkey = privkey
        self.id: NodeID = node_id_of(privkey)
        self.secret = (secret or "").encode()
        # advertised in the post-auth handshake frame; peers record it as
        # Connection.remote_version / NetApp.peer_versions (the rolling-
        # upgrade drill's transport-level skew signal)
        self.version = version or ""
        self.peer_versions: Dict[NodeID, str] = {}
        self.endpoints: Dict[str, Endpoint] = {}
        self.conns: Dict[NodeID, Connection] = {}
        self.on_connected: Optional[Callable[[NodeID, bool], None]] = None
        self.on_disconnected: Optional[Callable[[NodeID], None]] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._dial_locks: Dict[str, asyncio.Lock] = {}
        self._addr_ids: Dict[str, NodeID] = {}  # addr -> last node seen there
        # set by System: server-side handler spans parent on the caller's
        # propagated trace context
        self.tracer = None
        self._net_metrics: Optional[Dict[str, Any]] = None
        # set by System: NodeID -> bool, True when the peer has a known
        # dialable address (metric series worth keeping per-peer)
        self.peer_durable_fn: Optional[Callable[[NodeID], bool]] = None
        # set by the model layer: per-frame queue-wait seconds feed the
        # load governor's HOL-pressure signal (utils/overload.py)
        self.queue_wait_hook: Optional[Callable[[float], None]] = None

    def set_metrics(self, registry) -> None:
        """Attach per-peer traffic + queue-wait instruments (called by
        System; bare NetApps — tests, the CLI's throwaway client — stay
        uninstrumented)."""
        self._net_metrics = {
            "tx_bytes": registry.counter(
                "net_peer_tx_bytes_total",
                "Frame bytes written per peer and priority"),
            "tx_frames": registry.counter(
                "net_peer_tx_frames_total",
                "Frames written per peer and priority"),
            "rx_bytes": registry.counter(
                "net_peer_rx_bytes_total",
                "Frame bytes read per peer and priority"),
            "rx_frames": registry.counter(
                "net_peer_rx_frames_total",
                "Frames read per peer and priority"),
            "queue_wait": registry.histogram(
                "net_queue_wait_seconds",
                "Time outgoing frames waited in the priority queues "
                "before hitting the wire (head-of-line blocking signal)"),
        }

    def endpoint(self, path: str) -> Endpoint:
        ep = self.endpoints.get(path)
        if ep is None:
            ep = Endpoint(self, path)
            self.endpoints[path] = ep
        return ep

    def forget_peer_series(self, node: NodeID) -> None:
        """Drop the per-peer traffic counter series of a peer removed
        from the committed layout (System calls this alongside
        FullMeshPeering.forget_peer): a removed node's tx/rx totals
        would otherwise scrape forever as frozen lines.  The live
        connection's durable-label latch resets too, so goodbye traffic
        (the node learning the layout that removed it, its final block
        offloads) aggregates under peer="transient" instead of
        re-minting the dropped series."""
        self.peer_versions.pop(node, None)
        conn = self.conns.get(node)
        if conn is not None:
            conn._peer_durable = False
        if self._net_metrics is None:
            return
        lbl = bytes(node).hex()[:16]
        for key in ("tx_bytes", "tx_frames", "rx_bytes", "rx_frames"):
            self._net_metrics[key].drop_label("peer", lbl)

    # --- handshake ---

    def _transcript_mac(self, transcript: bytes, label: bytes) -> bytes:
        return hmac.new(self.secret, transcript + label, hashlib.sha256).digest()

    async def _handshake(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, is_dialer: bool
    ) -> NodeID:
        my_pub = bytes(self.id)
        my_nonce = os.urandom(32)
        writer.write(MAGIC + my_pub + my_nonce)
        await writer.drain()
        hello = await asyncio.wait_for(reader.readexactly(len(MAGIC) + 64), 10.0)
        if hello[: len(MAGIC)] != MAGIC:
            raise RpcError("bad protocol magic")
        their_pub = hello[len(MAGIC) : len(MAGIC) + 32]
        their_nonce = hello[len(MAGIC) + 32 :]
        if is_dialer:
            transcript = MAGIC + my_pub + my_nonce + their_pub + their_nonce
            my_label, their_label = b"dialer", b"listener"
        else:
            transcript = MAGIC + their_pub + their_nonce + my_pub + my_nonce
            my_label, their_label = b"listener", b"dialer"
        sig = self.privkey.sign(transcript + my_label)
        mac = self._transcript_mac(transcript, my_label)
        writer.write(sig + mac)
        await writer.drain()
        proof = await asyncio.wait_for(reader.readexactly(64 + 32), 10.0)
        their_sig, their_mac = proof[:64], proof[64:]
        if not hmac.compare_digest(
            their_mac, self._transcript_mac(transcript, their_label)
        ):
            raise RpcError("peer does not know the cluster secret")
        Ed25519PublicKey.from_public_bytes(their_pub).verify(
            their_sig, transcript + their_label
        )
        # post-auth version advertisement: one length-prefixed frame each
        # way, so a mixed-version cluster (rolling upgrade in flight)
        # knows exactly which build sits on the other end of every
        # connection.  Exchanged AFTER authentication so an unauthorized
        # dialer learns nothing.
        vb = self.version.encode()[:255]
        writer.write(bytes([len(vb)]) + vb)
        await writer.drain()
        vlen = (await asyncio.wait_for(reader.readexactly(1), 10.0))[0]
        their_version = (
            await asyncio.wait_for(reader.readexactly(vlen), 10.0)
            if vlen else b""
        ).decode("utf-8", "replace")
        nid = NodeID(their_pub)
        self.peer_versions[nid] = their_version
        return nid

    # --- connection management ---

    def _register_conn(self, conn: Connection) -> bool:
        """Keep one connection per peer.  On a simultaneous-dial race the
        connection dialed by the lower node ID wins deterministically."""
        old = self.conns.get(conn.remote_id)
        if old is not None and not old._closed:
            new_dialer = self.id if conn.is_dialer else conn.remote_id
            old_dialer = self.id if old.is_dialer else old.remote_id
            if old_dialer == new_dialer:
                # same dialer re-dialed (e.g. reconnect we haven't noticed):
                # the newest connection is the live one — replace old
                asyncio.get_running_loop().create_task(old.close())
            elif old_dialer <= new_dialer:
                # simultaneous cross-dial: both sides deterministically keep
                # the connection dialed by the smaller node id
                return False
            else:
                asyncio.get_running_loop().create_task(old.close())
        self.conns[conn.remote_id] = conn
        if self.on_connected:
            self.on_connected(conn.remote_id, conn.is_dialer)
        return True

    def _conn_lost(self, conn: Connection):
        cur = self.conns.get(conn.remote_id)
        if cur is conn:
            del self.conns[conn.remote_id]
            # bound peer_versions to live + durable peers: throwaway CLI
            # connections would otherwise grow the map forever (same
            # rationale as the 'transient' metric label); cluster peers
            # re-advertise on reconnect and stay visible via gossip
            fn = self.peer_durable_fn
            if fn is not None and not fn(conn.remote_id):
                self.peer_versions.pop(conn.remote_id, None)
            if self.on_disconnected:
                self.on_disconnected(conn.remote_id)

    async def listen(self, bind_addr: str):
        host, port = bind_addr.rsplit(":", 1)
        self._server = await asyncio.start_server(
            self._accept, host or "0.0.0.0", int(port)
        )

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            remote = await self._handshake(reader, writer, is_dialer=False)
        except Exception as e:
            logger.debug("handshake failed (accept): %s", e)
            writer.close()
            return
        conn = Connection(self, reader, writer, remote, is_dialer=False)
        if self._register_conn(conn):
            conn.start()
        else:
            writer.close()

    async def connect(self, addr: str, expected_id: Optional[NodeID] = None) -> Connection:
        """Dial a peer.  Dials to one address are serialized and live
        connections reused, so concurrent discovery/peering dials can't
        create duplicate connections that then kill each other."""
        lock = self._dial_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            known = expected_id or self._addr_ids.get(addr)
            if known is not None:
                existing = self.conns.get(known)
                if existing is not None and not existing._closed:
                    return existing
            host, port = addr.rsplit(":", 1)
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), 10.0
            )
            try:
                remote = await self._handshake(reader, writer, is_dialer=True)
            except Exception:
                writer.close()
                raise
            if expected_id is not None and remote != expected_id:
                writer.close()
                raise RpcError(
                    f"peer at {addr} is {remote.hex_short()}, expected "
                    f"{expected_id.hex_short()}"
                )
            if remote == self.id:
                writer.close()
                raise RpcError("connected to self")
            self._addr_ids[addr] = remote
            conn = Connection(self, reader, writer, remote, is_dialer=True)
            if not self._register_conn(conn):
                writer.close()
                return self.conns[remote]
            conn.start()
            return conn

    # --- calls ---

    async def call_streaming(
        self,
        node: NodeID,
        path: str,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
        body: Optional[AsyncIterator[bytes]] = None,
    ) -> Tuple[Any, Optional[ByteStream]]:
        # priority inheritance (demote-only): work spawned while serving
        # a background-priority request never jumps ahead of it — a
        # resync-triggered nested fetch must not compete with user
        # traffic just because its call site asked for PRIO_NORMAL
        inherited = inherited_priority()
        if inherited is not None and inherited > prio:
            prio = inherited
        msg_bytes = msgpack.packb(msg, use_bin_type=True)
        if node == self.id:
            return await self._local_call(path, msg_bytes, body)
        conn = self.conns.get(node)
        if conn is None or conn._closed:
            raise RpcError(f"not connected to {node.hex_short()}")
        resp_bytes, stream = await conn.request(path, msg_bytes, prio, timeout, body)
        return msgpack.unpackb(resp_bytes, raw=False), stream

    async def _local_call(self, path, msg_bytes, body):
        """Self-calls short-circuit the network (the reference does the same
        via its own entry in the node list)."""
        ep = self.endpoints.get(path)
        if ep is None or ep.handler is None:
            raise RpcError(f"no handler for endpoint {path!r}")
        in_stream: Optional[ByteStream] = None
        pump = None
        if body is not None:
            in_stream = ByteStream(on_cancel=lambda: _cancel_task(pump))

            async def _feed():
                try:
                    async for chunk in body:
                        await in_stream._push(bytes(chunk))
                    await in_stream._push(None)
                except Exception as e:
                    in_stream._fail(str(e))
                finally:
                    aclose = getattr(body, "aclose", None)
                    if aclose is not None:
                        try:
                            await aclose()
                        except Exception:
                            pass

            pump = asyncio.get_running_loop().create_task(_feed())
        try:
            resp, resp_body = await ep.handler(
                self.id, msgpack.unpackb(msg_bytes, raw=False), in_stream
            )
        finally:
            if pump is not None and not pump.done():
                pump.cancel()
        out_stream = None
        if resp_body is not None:
            out_pump = None
            out_stream = ByteStream(on_cancel=lambda: _cancel_task(out_pump))

            async def _feed_out():
                try:
                    async for chunk in resp_body:
                        # backpressure: blocks on the bounded queue until
                        # the consumer drains (or cancels the task)
                        await out_stream._push(bytes(chunk))
                    await out_stream._push(None)
                except Exception as e:
                    out_stream._fail(str(e))
                finally:
                    aclose = getattr(resp_body, "aclose", None)
                    if aclose is not None:
                        try:
                            await aclose()
                        except Exception:
                            pass

            out_pump = asyncio.get_running_loop().create_task(_feed_out())
        return resp, out_stream

    async def shutdown(self):
        # stop accepting first, then close conns; only then wait_closed —
        # py3.12 Server.wait_closed blocks until every accepted transport
        # is closed, so the order matters
        if self._server is not None:
            self._server.close()
        for conn in list(self.conns.values()):
            await conn.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                logger.debug("server wait_closed timed out")
