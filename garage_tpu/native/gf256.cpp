// Native GF(2^8) Reed-Solomon kernel — CPU baseline of the BlockCodec.
//
// Equivalent role to the reference's native Rust block-codec path
// (ref src/block/block.rs DataBlock verify/encode run at native speed);
// the TPU build keeps a native CPU fallback per SURVEY.md §2.11 item 3.
//
// Strategy: per (row, col) of the small GF matrix, precompute the 256-entry
// product table; the inner loop is then a table-lookup-XOR sweep over the
// shard bytes, parallelized over batch with OpenMP.  Field: poly 0x11D.
//
// Build: make -C garage_tpu/native   (produces libgf256.so, loaded by
// garage_tpu/ops/native.py via ctypes; python falls back to numpy if absent).

#include <cstdint>
#include <cstring>

static uint8_t GF_EXP[512];
static int16_t GF_LOG[256];

static void init_tables() {
  static bool done = false;
  if (done) return;
  int x = 1;
  for (int i = 0; i < 255; i++) {
    GF_EXP[i] = (uint8_t)x;
    GF_LOG[x] = (int16_t)i;
    x <<= 1;
    if (x & 0x100) x ^= 0x11D;
  }
  for (int i = 255; i < 510; i++) GF_EXP[i] = GF_EXP[i - 255];
  GF_LOG[0] = 0;
  done = true;
}

static inline uint8_t gf_mul(uint8_t a, uint8_t b) {
  if (a == 0 || b == 0) return 0;
  return GF_EXP[GF_LOG[a] + GF_LOG[b]];
}

extern "C" {

// out (B, r, S) ^= mat (r, k) * shards (B, k, S) over GF(2^8).
// `out` must be zero-initialized by the caller.
void gf_matmul_blocks(const uint8_t* mat, const uint8_t* shards, uint8_t* out,
                      int64_t batch, int64_t r, int64_t k, int64_t s) {
  init_tables();
  // Precompute per-(i,j) multiplication tables: r*k*256 bytes.
  uint8_t* tables = new uint8_t[r * k * 256];
  for (int64_t i = 0; i < r; i++) {
    for (int64_t j = 0; j < k; j++) {
      uint8_t c = mat[i * k + j];
      uint8_t* t = tables + (i * k + j) * 256;
      if (c == 0) {
        memset(t, 0, 256);
      } else {
        int16_t lc = GF_LOG[c];
        t[0] = 0;
        for (int v = 1; v < 256; v++) t[v] = GF_EXP[lc + GF_LOG[v]];
      }
    }
  }
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < batch; b++) {
    const uint8_t* in_b = shards + b * k * s;
    uint8_t* out_b = out + b * r * s;
    for (int64_t i = 0; i < r; i++) {
      uint8_t* dst = out_b + i * s;
      for (int64_t j = 0; j < k; j++) {
        const uint8_t* t = tables + (i * k + j) * 256;
        const uint8_t* src = in_b + j * s;
        if (mat[i * k + j] == 0) continue;
        if (mat[i * k + j] == 1) {
          for (int64_t v = 0; v < s; v++) dst[v] ^= src[v];
        } else {
          for (int64_t v = 0; v < s; v++) dst[v] ^= t[src[v]];
        }
      }
    }
  }
  delete[] tables;
}

}  // extern "C"
