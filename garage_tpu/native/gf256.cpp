// Native GF(2^8) Reed-Solomon kernel — CPU baseline of the BlockCodec.
//
// Equivalent role to the reference's native Rust block-codec path
// (ref src/block/block.rs DataBlock verify/encode run at native speed);
// the TPU build keeps a native CPU fallback per SURVEY.md §2.11 item 3.
//
// Strategy: per (row, col) of the small GF matrix, precompute the 256-entry
// product table; the inner loop is then a table-lookup-XOR sweep over the
// shard bytes, parallelized over batch with OpenMP.  Field: poly 0x11D.
//
// Build: make -C garage_tpu/native   (produces libgf256.so, loaded by
// garage_tpu/ops/native.py via ctypes; python falls back to numpy if absent).

#include <cstdint>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

static uint8_t GF_EXP[512];
static int16_t GF_LOG[256];

static void init_tables() {
  static bool done = false;
  if (done) return;
  int x = 1;
  for (int i = 0; i < 255; i++) {
    GF_EXP[i] = (uint8_t)x;
    GF_LOG[x] = (int16_t)i;
    x <<= 1;
    if (x & 0x100) x ^= 0x11D;
  }
  for (int i = 255; i < 510; i++) GF_EXP[i] = GF_EXP[i - 255];
  GF_LOG[0] = 0;
  done = true;
}

static inline uint8_t gf_mul(uint8_t a, uint8_t b) {
  if (a == 0 || b == 0) return 0;
  return GF_EXP[GF_LOG[a] + GF_LOG[b]];
}

#if defined(__x86_64__)
// --- AVX2 split-nibble path (the ISA-L technique) -------------------------
//
// gfmul(c, x) = T_lo[x & 0xF] ^ T_hi[x >> 4] where T_lo[v] = gfmul(c, v)
// and T_hi[v] = gfmul(c, v<<4) — each table is 16 bytes, exactly one
// VPSHUFB operand.  32 input bytes per two shuffles + ors/xors; the
// column-chunked loop keeps the k source rows of the active chunk in L1
// while every output row consumes them.

__attribute__((target("avx2"))) static void gf_matmul_avx2(
    const uint8_t* mat, const uint8_t* nib_tables, const uint8_t* shards,
    uint8_t* out, int64_t batch, int64_t r, int64_t k, int64_t s) {
  const __m256i lo_mask = _mm256_set1_epi8(0x0F);
  int64_t svec = s & ~int64_t(31);
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < batch; b++) {
    const uint8_t* in_b = shards + b * k * s;
    uint8_t* out_b = out + b * r * s;
    // column-vector outer loop: each output row accumulates in ONE ymm
    // register across all k inputs, stored once — dst carries no
    // read-modify-write traffic (`out` is zero-initialized by contract,
    // so the accumulator starts empty)
    for (int64_t v = 0; v < svec; v += 32) {
      for (int64_t i = 0; i < r; i++) {
        __m256i acc = _mm256_setzero_si256();
        for (int64_t j = 0; j < k; j++) {
          uint8_t coef = mat[i * k + j];
          if (coef == 0) continue;
          __m256i x =
              _mm256_loadu_si256((const __m256i*)(in_b + j * s + v));
          if (coef == 1) {
            acc = _mm256_xor_si256(acc, x);
            continue;
          }
          const uint8_t* nt = nib_tables + (i * k + j) * 32;
          __m256i tlo = _mm256_broadcastsi128_si256(
              _mm_loadu_si128((const __m128i*)nt));
          __m256i thi = _mm256_broadcastsi128_si256(
              _mm_loadu_si128((const __m128i*)(nt + 16)));
          __m256i xl = _mm256_and_si256(x, lo_mask);
          __m256i xh = _mm256_and_si256(_mm256_srli_epi16(x, 4), lo_mask);
          acc = _mm256_xor_si256(
              acc, _mm256_xor_si256(_mm256_shuffle_epi8(tlo, xl),
                                    _mm256_shuffle_epi8(thi, xh)));
        }
        _mm256_storeu_si256((__m256i*)(out_b + i * s + v), acc);
      }
    }
    // scalar tail for the last s % 32 columns
    for (int64_t v = svec; v < s; v++) {
      for (int64_t i = 0; i < r; i++) {
        uint8_t acc = 0;
        for (int64_t j = 0; j < k; j++) {
          if (mat[i * k + j] == 0) continue;
          const uint8_t* nt = nib_tables + (i * k + j) * 32;
          uint8_t x = in_b[j * s + v];
          acc ^= (uint8_t)(nt[x & 0x0F] ^ nt[16 + (x >> 4)]);
        }
        out_b[i * s + v] = acc;
      }
    }
  }
}

static bool have_avx2() {
  return __builtin_cpu_supports("avx2");
}

// --- GFNI + AVX512 path ----------------------------------------------------
//
// Multiplication by a constant c in GF(2^8) is GF(2)-linear, so it is one
// 8x8 bit matrix — exactly what VGF2P8AFFINEQB applies to 64 bytes per
// instruction.  The instruction is polynomial-agnostic (it is a bit-matrix
// product; only GF2P8MULB hardwires 0x11B), so it serves our 0x11D field
// directly: 1 load + 1 affine + 1 xor per 64 bytes per coefficient,
// vs ~6 ops per 32 bytes on the AVX2 split-nibble path.

// Pack multiply-by-c as the VGF2P8AFFINEQB matrix operand:
// dst.bit[i] = parity(A.byte[7-i] AND x), so A.byte[7-i] must hold row i
// of the bit matrix M where M[i][k] = bit i of (c * x^k mod 0x11D).
static uint64_t gf_affine_matrix(uint8_t c) {
  uint8_t col[8];
  for (int k = 0; k < 8; k++) col[k] = gf_mul(c, (uint8_t)(1 << k));
  uint64_t A = 0;
  for (int b = 0; b < 8; b++) {
    int i = 7 - b;
    uint8_t row = 0;
    for (int k = 0; k < 8; k++) row |= (uint8_t)(((col[k] >> i) & 1) << k);
    A |= (uint64_t)row << (8 * b);
  }
  return A;
}

// Register-blocking cap: preloading each 64-byte input column ONCE and
// keeping all k lanes live in zmm registers cuts loads r-fold (the
// column was re-read per output row).  k ≤ 16 covers every supported RS
// geometry with registers to spare (16 inputs + r accumulators < 32
// zmm); larger k falls back to the unblocked loop.
//
// NOTE the per-row accumulate (coef==0 skip / coef==1 xor / affine)
// appears FOUR times below — blocked+fallback in both the packed and
// the ptrs kernel.  Deliberate: the fallbacks are the pre-blocking
// loops kept verbatim, and templating target-attributed functions
// risks codegen drift.  A change to the GF math must touch all four.
#define GF_KMAX 16

__attribute__((target("gfni,avx512f,avx512bw"))) static void gf_matmul_gfni(
    const uint8_t* mat, const uint64_t* affine, const uint8_t* shards,
    uint8_t* out, int64_t batch, int64_t r, int64_t k, int64_t s) {
  int64_t svec = s & ~int64_t(63);
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < batch; b++) {
    const uint8_t* in_b = shards + b * k * s;
    uint8_t* out_b = out + b * r * s;
    if (k <= GF_KMAX) {
      for (int64_t v = 0; v < svec; v += 64) {
        __m512i x[GF_KMAX];
        for (int64_t j = 0; j < k; j++)
          x[j] = _mm512_loadu_si512((const void*)(in_b + j * s + v));
        for (int64_t i = 0; i < r; i++) {
          __m512i acc = _mm512_setzero_si512();
          for (int64_t j = 0; j < k; j++) {
            uint8_t coef = mat[i * k + j];
            if (coef == 0) continue;
            if (coef == 1) {
              acc = _mm512_xor_si512(acc, x[j]);
              continue;
            }
            __m512i A = _mm512_set1_epi64((long long)affine[i * k + j]);
            acc = _mm512_xor_si512(
                acc, _mm512_gf2p8affine_epi64_epi8(x[j], A, 0));
          }
          _mm512_storeu_si512((void*)(out_b + i * s + v), acc);
        }
      }
    } else {
      for (int64_t v = 0; v < svec; v += 64) {
        for (int64_t i = 0; i < r; i++) {
          __m512i acc = _mm512_setzero_si512();
          for (int64_t j = 0; j < k; j++) {
            uint8_t coef = mat[i * k + j];
            if (coef == 0) continue;
            __m512i x = _mm512_loadu_si512((const void*)(in_b + j * s + v));
            if (coef == 1) {
              acc = _mm512_xor_si512(acc, x);
              continue;
            }
            __m512i A = _mm512_set1_epi64((long long)affine[i * k + j]);
            acc = _mm512_xor_si512(acc,
                                   _mm512_gf2p8affine_epi64_epi8(x, A, 0));
          }
          _mm512_storeu_si512((void*)(out_b + i * s + v), acc);
        }
      }
    }
    for (int64_t v = svec; v < s; v++) {
      for (int64_t i = 0; i < r; i++) {
        uint8_t acc = 0;
        for (int64_t j = 0; j < k; j++) {
          uint8_t coef = mat[i * k + j];
          if (coef == 0) continue;
          acc ^= gf_mul(coef, in_b[j * s + v]);
        }
        out_b[i * s + v] = acc;
      }
    }
  }
}

static bool have_gfni512() {
  return __builtin_cpu_supports("gfni") &&
         __builtin_cpu_supports("avx512f") &&
         __builtin_cpu_supports("avx512bw");
}

// Pointer-gather variant: shard (b, j) is its own buffer ptrs[b*k+j] of
// lens[b*k+j] bytes, zero-extended to the codeword width s.  This is the
// scrub/put encode hot path — blocks arrive as separate Python bytes
// objects, and packing them into one (B, k, S) array first costs a full
// extra pass over the data (measured: the pack memcpy alone was slower
// than the GFNI encode it fed).  Masked AVX512 loads zero-extend the
// ragged tails for free.
__attribute__((target("gfni,avx512f,avx512bw"))) static void gf_matmul_ptrs_gfni(
    const uint8_t* mat, const uint64_t* affine, const uint8_t* const* ptrs,
    const uint64_t* lens, uint8_t* out, int64_t B, int64_t r, int64_t k,
    int64_t s) {
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < B; b++) {
    const uint8_t* const* in_p = ptrs + b * k;
    const uint64_t* in_l = lens + b * k;
    uint8_t* out_b = out + b * r * s;
    for (int64_t v = 0; v < s; v += 64) {
      int64_t w = s - v < 64 ? s - v : 64;
      __mmask64 outmask =
          w == 64 ? ~(__mmask64)0 : ((((__mmask64)1) << w) - 1);
      if (k <= GF_KMAX) {
        // register-blocked: each (masked) input column loaded once,
        // reused across all r output rows
        __m512i x[GF_KMAX];
        bool zero[GF_KMAX];
        for (int64_t j = 0; j < k; j++) {
          uint64_t len = in_l[j];
          if ((uint64_t)v >= len) {  // zero-extended region
            zero[j] = true;
            continue;
          }
          zero[j] = false;
          uint64_t avail = len - (uint64_t)v;
          x[j] = avail >= 64
                     ? _mm512_loadu_si512((const void*)(in_p[j] + v))
                     : _mm512_maskz_loadu_epi8(
                           ((((__mmask64)1) << avail) - 1),
                           (const void*)(in_p[j] + v));
        }
        for (int64_t i = 0; i < r; i++) {
          __m512i acc = _mm512_setzero_si512();
          for (int64_t j = 0; j < k; j++) {
            uint8_t coef = mat[i * k + j];
            if (coef == 0 || zero[j]) continue;
            if (coef == 1) {
              acc = _mm512_xor_si512(acc, x[j]);
            } else {
              __m512i A = _mm512_set1_epi64((long long)affine[i * k + j]);
              acc = _mm512_xor_si512(
                  acc, _mm512_gf2p8affine_epi64_epi8(x[j], A, 0));
            }
          }
          _mm512_mask_storeu_epi8((void*)(out_b + i * s + v), outmask, acc);
        }
        continue;
      }
      for (int64_t i = 0; i < r; i++) {
        __m512i acc = _mm512_setzero_si512();
        for (int64_t j = 0; j < k; j++) {
          uint8_t coef = mat[i * k + j];
          if (coef == 0) continue;
          uint64_t len = in_l[j];
          if ((uint64_t)v >= len) continue;  // zero-extended region
          uint64_t avail = len - (uint64_t)v;
          __m512i x;
          if (avail >= 64) {
            x = _mm512_loadu_si512((const void*)(in_p[j] + v));
          } else {
            x = _mm512_maskz_loadu_epi8(((((__mmask64)1) << avail) - 1),
                                        (const void*)(in_p[j] + v));
          }
          if (coef == 1) {
            acc = _mm512_xor_si512(acc, x);
          } else {
            __m512i A = _mm512_set1_epi64((long long)affine[i * k + j]);
            acc = _mm512_xor_si512(acc,
                                   _mm512_gf2p8affine_epi64_epi8(x, A, 0));
          }
        }
        _mm512_mask_storeu_epi8((void*)(out_b + i * s + v), outmask, acc);
      }
    }
  }
}
#endif  // __x86_64__

extern "C" {

// out (B, r, S) = mat (r, k) * shards (B, k, S) over GF(2^8).
// `out` MUST be zero-initialized by the caller — under that contract the
// scalar path (which XOR-accumulates into out) and the AVX2 path (which
// overwrites it) are equivalent; passing a pre-populated buffer is NOT
// supported and would give machine-dependent results.
void gf_matmul_blocks(const uint8_t* mat, const uint8_t* shards, uint8_t* out,
                      int64_t batch, int64_t r, int64_t k, int64_t s) {
  init_tables();
#if defined(__x86_64__)
  if (have_gfni512()) {
    uint64_t* affine = new uint64_t[r * k];
    for (int64_t i = 0; i < r * k; i++) affine[i] = gf_affine_matrix(mat[i]);
    gf_matmul_gfni(mat, affine, shards, out, batch, r, k, s);
    delete[] affine;
    return;
  }
  if (have_avx2()) {
    // per-(i,j) nibble tables: 16 low-nibble products + 16 high-nibble
    // products (the two VPSHUFB operands)
    uint8_t* nib = new uint8_t[r * k * 32];
    for (int64_t i = 0; i < r; i++) {
      for (int64_t j = 0; j < k; j++) {
        uint8_t c = mat[i * k + j];
        uint8_t* t = nib + (i * k + j) * 32;
        for (int v = 0; v < 16; v++) {
          t[v] = gf_mul(c, (uint8_t)v);
          t[16 + v] = gf_mul(c, (uint8_t)(v << 4));
        }
      }
    }
    gf_matmul_avx2(mat, nib, shards, out, batch, r, k, s);
    delete[] nib;
    return;
  }
#endif
  // Precompute per-(i,j) multiplication tables: r*k*256 bytes.
  uint8_t* tables = new uint8_t[r * k * 256];
  for (int64_t i = 0; i < r; i++) {
    for (int64_t j = 0; j < k; j++) {
      uint8_t c = mat[i * k + j];
      uint8_t* t = tables + (i * k + j) * 256;
      if (c == 0) {
        memset(t, 0, 256);
      } else {
        int16_t lc = GF_LOG[c];
        t[0] = 0;
        for (int v = 1; v < 256; v++) t[v] = GF_EXP[lc + GF_LOG[v]];
      }
    }
  }
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < batch; b++) {
    const uint8_t* in_b = shards + b * k * s;
    uint8_t* out_b = out + b * r * s;
    for (int64_t i = 0; i < r; i++) {
      uint8_t* dst = out_b + i * s;
      for (int64_t j = 0; j < k; j++) {
        const uint8_t* t = tables + (i * k + j) * 256;
        const uint8_t* src = in_b + j * s;
        if (mat[i * k + j] == 0) continue;
        if (mat[i * k + j] == 1) {
          for (int64_t v = 0; v < s; v++) dst[v] ^= src[v];
        } else {
          for (int64_t v = 0; v < s; v++) dst[v] ^= t[src[v]];
        }
      }
    }
  }
  delete[] tables;
}

// Fast pointer-gather support probe: the Python wrapper only routes the
// per-buffer path here when the GFNI kernel backs it (the scalar fallback
// below exists for correctness on old hosts, but packing + the AVX2 block
// kernel is faster there).
int gf_ptrs_fast() {
#if defined(__x86_64__)
  return have_gfni512() ? 1 : 0;
#else
  return 0;
#endif
}

// out (B, r, S) = mat (r, k) applied to B codewords of k separate,
// zero-extended buffers.  Same zero-initialized-out contract as
// gf_matmul_blocks.
void gf_matmul_ptrs(const uint8_t* mat, const uint8_t* const* ptrs,
                    const uint64_t* lens, uint8_t* out, int64_t B, int64_t r,
                    int64_t k, int64_t s) {
  init_tables();
#if defined(__x86_64__)
  if (have_gfni512()) {
    uint64_t* affine = new uint64_t[r * k];
    for (int64_t i = 0; i < r * k; i++) affine[i] = gf_affine_matrix(mat[i]);
    gf_matmul_ptrs_gfni(mat, affine, ptrs, lens, out, B, r, k, s);
    delete[] affine;
    return;
  }
#endif
  for (int64_t b = 0; b < B; b++) {
    const uint8_t* const* in_p = ptrs + b * k;
    const uint64_t* in_l = lens + b * k;
    uint8_t* out_b = out + b * r * s;
    for (int64_t i = 0; i < r; i++) {
      uint8_t* dst = out_b + i * s;
      for (int64_t j = 0; j < k; j++) {
        uint8_t coef = mat[i * k + j];
        if (coef == 0) continue;
        int64_t n = (int64_t)in_l[j] < s ? (int64_t)in_l[j] : s;
        const uint8_t* src = in_p[j];
        if (coef == 1) {
          for (int64_t v = 0; v < n; v++) dst[v] ^= src[v];
        } else {
          int16_t lc = GF_LOG[coef];
          for (int64_t v = 0; v < n; v++)
            if (src[v]) dst[v] ^= GF_EXP[lc + GF_LOG[src[v]]];
        }
      }
    }
  }
}

}  // extern "C"
