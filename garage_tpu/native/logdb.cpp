// logdb — native log-structured metadata engine (the default engine slot).
//
// Role equivalent of the reference's LMDB adapter (ref db/lmdb_adapter.rs:
// 1-354): the fast native engine behind the Db/Tree/Transaction facade.
// LMDB itself is not available in this environment (no liblmdb, no
// network), so this is an original bitcask-style design with the
// properties the metadata layer needs:
//
//   - append-only log file; every mutation group ends with a COMMIT
//     record, so a torn write never exposes a partial transaction
//     (recovery truncates to the last committed group);
//   - CRC32-protected records;
//   - in-RAM ordered index per tree: key -> (file offset, length) of the
//     live value; values are pread() on demand (RAM holds keys only);
//   - ordered range iteration with snapshot-of-keys semantics (same
//     contract as the other engines' adapters);
//   - automatic compaction when dead bytes dominate.
//
// Exposed as a C ABI consumed by db/native_adapter.py over ctypes.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <unistd.h>
#include <sys/stat.h>

namespace {

constexpr char MAGIC[8] = {'G','T','L','O','G','D','B','1'};
constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;
constexpr uint8_t OP_COMMIT = 3;
constexpr uint8_t OP_TREEDEF = 4;
constexpr uint8_t OP_CLEAR = 5;

// CRC-32 (IEEE, reflected) — table-driven
uint32_t crc_table[256];
struct CrcInit {
    CrcInit() {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            crc_table[i] = c;
        }
    }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n, uint32_t crc = 0) {
    crc = ~crc;
    while (n--) crc = crc_table[(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

void put_u32(std::string& s, uint32_t v) {
    char b[4] = {(char)(v), (char)(v >> 8), (char)(v >> 16), (char)(v >> 24)};
    s.append(b, 4);
}

uint32_t get_u32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

struct Loc { uint64_t off; uint32_t len; };

struct Tree {
    std::string name;
    std::map<std::string, Loc> index;
};

struct LogDb {
    int fd = -1;
    std::string path;
    uint64_t file_size = 0;      // logical end (committed + pending appended)
    uint64_t live_bytes = 0;     // bytes of live values (compaction heuristic)
    std::vector<Tree> trees;
    std::mutex mu;
    std::string err;
    bool fsync_commits = false;
    bool broken = false;   // unrecoverable append failure: refuse writes

    int tree_by_name(const std::string& n) {
        for (size_t i = 0; i < trees.size(); i++)
            if (trees[i].name == n) return (int)i;
        return -1;
    }
};

struct Iter {
    LogDb* db;
    int tree;
    std::vector<std::string> keys;  // snapshot of the range
    size_t pos = 0;
    std::string cur_key, cur_val;
};

// serialize one record into out; returns offset-of-value within record
size_t append_record(std::string& out, uint8_t type, uint32_t tree,
                     const uint8_t* k, uint32_t klen,
                     const uint8_t* v, uint32_t vlen) {
    std::string body;
    body.push_back((char)type);
    put_u32(body, tree);
    put_u32(body, klen);
    put_u32(body, vlen);
    if (klen) body.append((const char*)k, klen);
    size_t val_off_in_body = body.size();
    if (vlen) body.append((const char*)v, vlen);
    uint32_t crc = crc32((const uint8_t*)body.data(), body.size());
    put_u32(out, crc);
    out.append(body);
    return 4 + val_off_in_body;  // +4 for the crc prefix
}

bool write_all(int fd, const void* buf, size_t n) {
    const char* p = (const char*)buf;
    while (n) {
        ssize_t w = ::write(fd, p, n);
        if (w < 0) { if (errno == EINTR) continue; return false; }
        p += w; n -= (size_t)w;
    }
    return true;
}

// Replay the log, building indexes. Returns the offset of the end of the
// last committed group (file is truncated there if shorter than size).
bool replay(LogDb* db) {
    struct stat st;
    if (fstat(db->fd, &st) != 0) { db->err = "fstat failed"; return false; }
    uint64_t size = (uint64_t)st.st_size;
    if (size < 8) {
        // torn initial write (1-7 byte file): reset before re-writing the
        // magic — fd is O_APPEND, a bare write would land past the tear
        if (size > 0 && ftruncate(db->fd, 0) != 0) {
            db->err = "truncate torn header";
            return false;
        }
        if (!write_all(db->fd, MAGIC, 8)) { db->err = "write magic"; return false; }
        db->file_size = 8;
        return true;
    }
    char magic[8];
    if (pread(db->fd, magic, 8, 0) != 8 || memcmp(magic, MAGIC, 8) != 0) {
        db->err = "bad magic";
        return false;
    }

    // pending (uncommitted) group: list of (type, tree, key, val_loc)
    struct Pending { uint8_t type; uint32_t tree; std::string key; Loc loc; };
    std::vector<Pending> pending;
    std::vector<std::pair<uint32_t, std::string>> pending_trees;

    uint64_t off = 8, committed_end = 8;
    std::vector<uint8_t> buf;
    while (off + 17 <= size) {
        uint8_t hdr[17];
        if (pread(db->fd, hdr, 17, (off_t)off) != 17) break;
        uint32_t crc = get_u32(hdr);
        uint8_t type = hdr[4];
        uint32_t tree = get_u32(hdr + 5);
        uint32_t klen = get_u32(hdr + 9);
        uint32_t vlen = get_u32(hdr + 13);
        uint64_t rec_len = 17ull + klen + vlen;
        if (off + rec_len > size || klen > (64u << 20) || vlen > (256u << 20))
            break;
        buf.resize(13 + klen + vlen);
        if (pread(db->fd, buf.data() + 13, klen + vlen, (off_t)(off + 17))
            != (ssize_t)(klen + vlen)) break;
        memcpy(buf.data(), hdr + 4, 13);
        if (crc32(buf.data(), buf.size()) != crc) break;

        const char* kp = (const char*)buf.data() + 13;
        switch (type) {
        case OP_PUT:
            pending.push_back({type, tree, std::string(kp, klen),
                               {off + 17 + klen, vlen}});
            break;
        case OP_DEL:
            pending.push_back({type, tree, std::string(kp, klen), {0, 0}});
            break;
        case OP_CLEAR:
            pending.push_back({type, tree, std::string(), {0, 0}});
            break;
        case OP_TREEDEF:
            pending_trees.push_back({tree, std::string(kp, klen)});
            break;
        case OP_COMMIT: {
            for (auto& pt : pending_trees) {
                while (db->trees.size() <= pt.first)
                    db->trees.push_back(Tree{});
                db->trees[pt.first].name = pt.second;
            }
            pending_trees.clear();
            for (auto& p : pending) {
                if (p.tree >= db->trees.size()) continue;  // corrupt ref
                auto& idx = db->trees[p.tree].index;
                if (p.type == OP_PUT) {
                    auto it = idx.find(p.key);
                    if (it != idx.end()) db->live_bytes -= it->second.len;
                    idx[p.key] = p.loc;
                    db->live_bytes += p.loc.len;
                } else if (p.type == OP_DEL) {
                    auto it = idx.find(p.key);
                    if (it != idx.end()) {
                        db->live_bytes -= it->second.len;
                        idx.erase(it);
                    }
                } else if (p.type == OP_CLEAR) {
                    for (auto& kv : idx) db->live_bytes -= kv.second.len;
                    idx.clear();
                }
            }
            pending.clear();
            committed_end = off + rec_len;
            break;
        }
        default:
            goto done;  // unknown type: stop (future format)
        }
        off += rec_len;
    }
done:
    db->file_size = committed_end;
    if (committed_end < size) {
        if (ftruncate(db->fd, (off_t)committed_end) != 0) {
            db->err = "truncate failed";
            return false;
        }
    }
    if (lseek(db->fd, (off_t)committed_end, SEEK_SET) < 0) {
        db->err = "seek failed";
        return false;
    }
    return true;
}

// append a group (records already serialized, commit included); updates
// file_size; group offsets in locs were pre-computed relative to start
bool append_group(LogDb* db, const std::string& group) {
    if (!write_all(db->fd, group.data(), group.size())) {
        // a partial append left bytes past the committed end; truncate
        // back so O_APPEND keeps physical EOF == logical file_size (value
        // offsets of later commits depend on it).  If even that fails the
        // handle is poisoned: every later write would corrupt offsets.
        if (ftruncate(db->fd, (off_t)db->file_size) != 0)
            db->broken = true;
        db->err = "append failed";
        return false;
    }
    db->file_size += group.size();
    if (db->fsync_commits) fdatasync(db->fd);
    return true;
}

// Rewrite only live records into a fresh log and atomically replace the
// old file; indexes are rebuilt by replaying the new file (replay is the
// single source of truth for offsets).
bool compact(LogDb* db) {
    std::string tmp = db->path + ".compact";
    int nfd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (nfd < 0) return false;
    std::string out(MAGIC, 8);
    std::string val;
    for (uint32_t t = 0; t < db->trees.size(); t++)
        append_record(out, OP_TREEDEF, t,
                      (const uint8_t*)db->trees[t].name.data(),
                      (uint32_t)db->trees[t].name.size(), nullptr, 0);
    for (uint32_t t = 0; t < db->trees.size(); t++) {
        for (auto& kv : db->trees[t].index) {
            val.resize(kv.second.len);
            if (kv.second.len &&
                pread(db->fd, &val[0], kv.second.len, (off_t)kv.second.off)
                    != (ssize_t)kv.second.len) {
                ::close(nfd); ::unlink(tmp.c_str()); return false;
            }
            append_record(out, OP_PUT, t, (const uint8_t*)kv.first.data(),
                          (uint32_t)kv.first.size(),
                          (const uint8_t*)val.data(), (uint32_t)val.size());
            if (out.size() > (8u << 20)) {  // keep the staging buffer bounded
                if (!write_all(nfd, out.data(), out.size())) {
                    ::close(nfd); ::unlink(tmp.c_str()); return false;
                }
                out.clear();
            }
        }
    }
    append_record(out, OP_COMMIT, 0, nullptr, 0, nullptr, 0);
    if (!write_all(nfd, out.data(), out.size())) {
        ::close(nfd); ::unlink(tmp.c_str()); return false;
    }
    fdatasync(nfd);
    ::close(nfd);
    if (rename(tmp.c_str(), db->path.c_str()) != 0) {
        ::unlink(tmp.c_str());
        return false;
    }
    ::close(db->fd);
    db->fd = ::open(db->path.c_str(), O_RDWR | O_APPEND, 0644);
    if (db->fd < 0) return false;
    db->trees.clear();
    db->live_bytes = 0;
    db->file_size = 0;
    return replay(db);
}

}  // namespace

extern "C" {

LogDb* ldb_open(const char* path, int fsync_commits) {
    LogDb* db = new LogDb();
    db->path = path;
    db->fsync_commits = fsync_commits != 0;
    db->fd = ::open(path, O_RDWR | O_CREAT | O_APPEND, 0644);
    if (db->fd < 0) { delete db; return nullptr; }
    if (!replay(db)) { ::close(db->fd); delete db; return nullptr; }
    // startup compaction when the log is dominated by dead records
    struct stat st;
    if (fstat(db->fd, &st) == 0 && (uint64_t)st.st_size > (4u << 20) &&
        (uint64_t)st.st_size > 4 * (db->live_bytes + (16u << 10)))
        compact(db);
    return db;
}

int ldb_open_tree(LogDb* db, const char* name, uint32_t namelen) {
    std::lock_guard<std::mutex> g(db->mu);
    std::string n(name, namelen);
    int i = db->tree_by_name(n);
    if (i >= 0) return i;
    uint32_t idx = (uint32_t)db->trees.size();
    std::string group;
    append_record(group, OP_TREEDEF, idx, (const uint8_t*)n.data(),
                  (uint32_t)n.size(), nullptr, 0);
    append_record(group, OP_COMMIT, 0, nullptr, 0, nullptr, 0);
    if (!append_group(db, group)) return -1;
    db->trees.push_back(Tree{n, {}});
    return (int)idx;
}

int ldb_tree_count(LogDb* db) {
    std::lock_guard<std::mutex> g(db->mu);
    return (int)db->trees.size();
}

// copies the name into out (cap bytes); returns the name length
int ldb_tree_name(LogDb* db, int tree, char* out, uint32_t cap) {
    std::lock_guard<std::mutex> g(db->mu);
    if (tree < 0 || (size_t)tree >= db->trees.size()) return -1;
    const std::string& n = db->trees[tree].name;
    if (n.size() <= cap) memcpy(out, n.data(), n.size());
    return (int)n.size();
}

// returns value length, -1 if absent, -2 on error; value copied into out
// if it fits cap (call twice: probe with cap=0 then read)
long ldb_get(LogDb* db, int tree, const uint8_t* key, uint32_t klen,
             uint8_t* out, uint32_t cap) {
    std::lock_guard<std::mutex> g(db->mu);
    if (tree < 0 || (size_t)tree >= db->trees.size()) return -2;
    auto& idx = db->trees[tree].index;
    auto it = idx.find(std::string((const char*)key, klen));
    if (it == idx.end()) return -1;
    if (it->second.len <= cap && it->second.len > 0) {
        if (pread(db->fd, out, it->second.len, (off_t)it->second.off)
            != (ssize_t)it->second.len)
            return -2;
    }
    return (long)it->second.len;
}

long ldb_len(LogDb* db, int tree) {
    std::lock_guard<std::mutex> g(db->mu);
    if (tree < 0 || (size_t)tree >= db->trees.size()) return -1;
    return (long)db->trees[tree].index.size();
}

// Apply a batch of operations atomically (one commit record).
// ops buffer: repeated [u8 op(1=put,2=del,5=clear), u32 tree, u32 klen,
// u32 vlen, key, val].  Returns 0 on success.
int ldb_apply(LogDb* db, const uint8_t* ops, uint64_t ops_len) {
    std::lock_guard<std::mutex> g(db->mu);
    if (db->broken) return -3;
    std::string group;
    struct Staged { uint8_t op; uint32_t tree; std::string key; uint64_t voff; uint32_t vlen; };
    std::vector<Staged> staged;
    uint64_t base = db->file_size;
    uint64_t p = 0;
    while (p + 13 <= ops_len) {
        uint8_t op = ops[p];
        uint32_t tree = get_u32(ops + p + 1);
        uint32_t klen = get_u32(ops + p + 5);
        uint32_t vlen = get_u32(ops + p + 9);
        if (p + 13 + klen + vlen > ops_len) return -1;
        if (tree >= db->trees.size()) return -1;
        const uint8_t* k = ops + p + 13;
        const uint8_t* v = k + klen;
        uint8_t rec_type = op == 5 ? OP_CLEAR : (op == 2 ? OP_DEL : OP_PUT);
        uint64_t rec_start = group.size();
        append_record(group, rec_type, tree, k, klen,
                      op == 1 ? v : nullptr, op == 1 ? vlen : 0);
        // record layout: crc(4) type(1) tree(4) klen(4) vlen(4) key val
        staged.push_back({op, tree, std::string((const char*)k, klen),
                          base + rec_start + 17 + klen, op == 1 ? vlen : 0});
        p += 13ull + klen + vlen;
    }
    if (p != ops_len) return -1;
    append_record(group, OP_COMMIT, 0, nullptr, 0, nullptr, 0);
    if (!append_group(db, group)) return -2;
    for (auto& s : staged) {
        auto& idx = db->trees[s.tree].index;
        if (s.op == 1) {
            auto it = idx.find(s.key);
            if (it != idx.end()) db->live_bytes -= it->second.len;
            idx[s.key] = {s.voff, s.vlen};
            db->live_bytes += s.vlen;
        } else if (s.op == 2) {
            auto it = idx.find(s.key);
            if (it != idx.end()) { db->live_bytes -= it->second.len; idx.erase(it); }
        } else if (s.op == 5) {
            for (auto& kv : idx) db->live_bytes -= kv.second.len;
            idx.clear();
        }
    }
    // runtime compaction: reclaim space once dead records dominate (the
    // open-time check alone would let a long-running daemon's log grow
    // without bound).  Amortized: cost is O(live bytes), triggered only
    // after ≥4× that much has been written.
    if (db->file_size > (4u << 20) &&
        db->file_size > 4 * (db->live_bytes + (16u << 10)))
        compact(db);
    return 0;
}

Iter* ldb_iter_new(LogDb* db, int tree, const uint8_t* start, uint32_t slen,
                   int has_start, const uint8_t* end, uint32_t elen,
                   int has_end, int reverse) {
    std::lock_guard<std::mutex> g(db->mu);
    if (tree < 0 || (size_t)tree >= db->trees.size()) return nullptr;
    Iter* it = new Iter();
    it->db = db;
    it->tree = tree;
    auto& idx = db->trees[tree].index;
    auto lo = has_start ? idx.lower_bound(std::string((const char*)start, slen))
                        : idx.begin();
    auto hi = has_end ? idx.lower_bound(std::string((const char*)end, elen))
                      : idx.end();
    for (auto i = lo; i != hi; ++i) it->keys.push_back(i->first);
    if (reverse) std::reverse(it->keys.begin(), it->keys.end());
    return it;
}

// advances; returns 1 and fills pointers (valid until next call/free),
// 0 at end, -1 on error.  Keys deleted since the snapshot are skipped.
int ldb_iter_next(Iter* it, const uint8_t** k, uint32_t* klen,
                  const uint8_t** v, uint32_t* vlen) {
    LogDb* db = it->db;
    std::lock_guard<std::mutex> g(db->mu);
    auto& idx = db->trees[it->tree].index;
    while (it->pos < it->keys.size()) {
        const std::string& key = it->keys[it->pos++];
        auto f = idx.find(key);
        if (f == idx.end()) continue;  // deleted since snapshot
        it->cur_key = key;
        it->cur_val.resize(f->second.len);
        if (f->second.len &&
            pread(db->fd, &it->cur_val[0], f->second.len,
                  (off_t)f->second.off) != (ssize_t)f->second.len)
            return -1;
        *k = (const uint8_t*)it->cur_key.data();
        *klen = (uint32_t)it->cur_key.size();
        *v = (const uint8_t*)it->cur_val.data();
        *vlen = (uint32_t)it->cur_val.size();
        return 1;
    }
    return 0;
}

void ldb_iter_free(Iter* it) { delete it; }

int ldb_sync(LogDb* db) {
    std::lock_guard<std::mutex> g(db->mu);
    return fdatasync(db->fd) == 0 ? 0 : -1;
}


int ldb_compact(LogDb* db) {
    std::lock_guard<std::mutex> g(db->mu);
    return compact(db) ? 0 : -1;
}

// flush + fsync + copy the log to `dest`
int ldb_snapshot(LogDb* db, const char* dest) {
    std::lock_guard<std::mutex> g(db->mu);
    if (fdatasync(db->fd) != 0) return -1;
    int out = ::open(dest, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (out < 0) return -1;
    uint64_t off = 0;
    char buf[1 << 16];
    while (off < db->file_size) {
        size_t want = (size_t)std::min<uint64_t>(sizeof buf, db->file_size - off);
        ssize_t r = pread(db->fd, buf, want, (off_t)off);
        if (r <= 0) { ::close(out); return -1; }
        if (!write_all(out, buf, (size_t)r)) { ::close(out); return -1; }
        off += (uint64_t)r;
    }
    fdatasync(out);
    ::close(out);
    return 0;
}

void ldb_close(LogDb* db) {
    if (db->fd >= 0) { fdatasync(db->fd); ::close(db->fd); }
    delete db;
}

const char* ldb_error(LogDb* db) { return db->err.c_str(); }

}  // extern "C"
