// Multi-buffer BLAKE2s-256: hash independent byte streams in the uint32
// lanes of one SIMD register file (lane-major, the same layout
// ops/tpu_blake2s.py uses on the TPU VPU) — 16 lanes on AVX-512 (native
// vprord rotates), 8 on AVX2, runtime-dispatched.  This is the CPU-floor
// answer to the reference's strictly sequential per-block scrub hashing
// (ref src/block/repair.rs:438-490 → block.rs:66-78 verify): on the
// 1-core hosts this framework targets, thread pools cannot add
// parallelism, but SIMD lanes can (~2.9 GiB/s 16-lane vs 0.38 hashlib
// on the dev host).
//
// RFC 7693 exactly (digest_size=32, no key, no salt/personal);
// bit-identity against hashlib.blake2s is enforced by
// tests/test_native_blake2s.py.
//
// Lanes may have DIFFERENT lengths: the message counter t, the final-block
// flag f0, and the "still active" mask are all per-lane vectors, so a lane
// that finishes early simply stops updating its state words (blend) while
// the remaining lanes keep compressing.  The uniform interior of the
// streams (every lane still has a full non-final chunk) runs a fast loop
// with no per-lane bookkeeping.

#include <immintrin.h>
#include <stdint.h>
#include <string.h>

namespace {

const uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};

const uint8_t SIGMA[10][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
};

// Every SIMD function carries target("avx2") and the .so is built WITHOUT
// -march=native (see Makefile): a prebuilt binary carried to a non-AVX2
// host must dlopen cleanly and report unsupported via blake2s_mb_supported
// instead of SIGILLing on first use.
#define B2_TARGET __attribute__((target("avx2")))

B2_TARGET inline __m256i ror16(__m256i x) {
    const __m256i m = _mm256_setr_epi8(
        2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13,
        2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13);
    return _mm256_shuffle_epi8(x, m);
}

B2_TARGET inline __m256i ror12(__m256i x) {
    return _mm256_or_si256(_mm256_srli_epi32(x, 12), _mm256_slli_epi32(x, 20));
}

B2_TARGET inline __m256i ror8(__m256i x) {
    const __m256i m = _mm256_setr_epi8(
        1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12,
        1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12);
    return _mm256_shuffle_epi8(x, m);
}

B2_TARGET inline __m256i ror7(__m256i x) {
    return _mm256_or_si256(_mm256_srli_epi32(x, 7), _mm256_slli_epi32(x, 25));
}

// Transpose 8 lanes x 8 consecutive uint32 (from ptrs[l] + off) into
// word-major vectors m[w], lane l of m[w] = word w of stream l.
B2_TARGET inline void transpose8x8(const uint8_t *const ptrs[8], size_t off,
                         __m256i m[8]) {
    __m256i r0 = _mm256_loadu_si256((const __m256i *)(ptrs[0] + off));
    __m256i r1 = _mm256_loadu_si256((const __m256i *)(ptrs[1] + off));
    __m256i r2 = _mm256_loadu_si256((const __m256i *)(ptrs[2] + off));
    __m256i r3 = _mm256_loadu_si256((const __m256i *)(ptrs[3] + off));
    __m256i r4 = _mm256_loadu_si256((const __m256i *)(ptrs[4] + off));
    __m256i r5 = _mm256_loadu_si256((const __m256i *)(ptrs[5] + off));
    __m256i r6 = _mm256_loadu_si256((const __m256i *)(ptrs[6] + off));
    __m256i r7 = _mm256_loadu_si256((const __m256i *)(ptrs[7] + off));
    __m256i t0 = _mm256_unpacklo_epi32(r0, r1);
    __m256i t1 = _mm256_unpackhi_epi32(r0, r1);
    __m256i t2 = _mm256_unpacklo_epi32(r2, r3);
    __m256i t3 = _mm256_unpackhi_epi32(r2, r3);
    __m256i t4 = _mm256_unpacklo_epi32(r4, r5);
    __m256i t5 = _mm256_unpackhi_epi32(r4, r5);
    __m256i t6 = _mm256_unpacklo_epi32(r6, r7);
    __m256i t7 = _mm256_unpackhi_epi32(r6, r7);
    __m256i u0 = _mm256_unpacklo_epi64(t0, t2);
    __m256i u1 = _mm256_unpackhi_epi64(t0, t2);
    __m256i u2 = _mm256_unpacklo_epi64(t1, t3);
    __m256i u3 = _mm256_unpackhi_epi64(t1, t3);
    __m256i u4 = _mm256_unpacklo_epi64(t4, t6);
    __m256i u5 = _mm256_unpackhi_epi64(t4, t6);
    __m256i u6 = _mm256_unpacklo_epi64(t5, t7);
    __m256i u7 = _mm256_unpackhi_epi64(t5, t7);
    m[0] = _mm256_permute2x128_si256(u0, u4, 0x20);
    m[4] = _mm256_permute2x128_si256(u0, u4, 0x31);
    m[1] = _mm256_permute2x128_si256(u1, u5, 0x20);
    m[5] = _mm256_permute2x128_si256(u1, u5, 0x31);
    m[2] = _mm256_permute2x128_si256(u2, u6, 0x20);
    m[6] = _mm256_permute2x128_si256(u2, u6, 0x31);
    m[3] = _mm256_permute2x128_si256(u3, u7, 0x20);
    m[7] = _mm256_permute2x128_si256(u3, u7, 0x31);
}

#define G(r, i, a, b, c, d)                                   \
    do {                                                      \
        a = _mm256_add_epi32(_mm256_add_epi32(a, b),          \
                             m[SIGMA[r][2 * (i)]]);           \
        d = ror16(_mm256_xor_si256(d, a));                    \
        c = _mm256_add_epi32(c, d);                           \
        b = ror12(_mm256_xor_si256(b, c));                    \
        a = _mm256_add_epi32(_mm256_add_epi32(a, b),          \
                             m[SIGMA[r][2 * (i) + 1]]);       \
        d = ror8(_mm256_xor_si256(d, a));                     \
        c = _mm256_add_epi32(c, d);                           \
        b = ror7(_mm256_xor_si256(b, c));                     \
    } while (0)

// One compression over 8 lanes; chunk pointers must each reference 64
// readable bytes.  t_lo/t_hi/f0 are per-lane vectors.
B2_TARGET inline void compress8(__m256i h[8], const uint8_t *const chunk[8],
                      __m256i t_lo, __m256i t_hi, __m256i f0) {
    __m256i m[16];
    transpose8x8(chunk, 0, m);
    transpose8x8(chunk, 32, m + 8);
    __m256i v0 = h[0], v1 = h[1], v2 = h[2], v3 = h[3];
    __m256i v4 = h[4], v5 = h[5], v6 = h[6], v7 = h[7];
    __m256i v8 = _mm256_set1_epi32((int)IV[0]);
    __m256i v9 = _mm256_set1_epi32((int)IV[1]);
    __m256i v10 = _mm256_set1_epi32((int)IV[2]);
    __m256i v11 = _mm256_set1_epi32((int)IV[3]);
    __m256i v12 = _mm256_xor_si256(_mm256_set1_epi32((int)IV[4]), t_lo);
    __m256i v13 = _mm256_xor_si256(_mm256_set1_epi32((int)IV[5]), t_hi);
    __m256i v14 = _mm256_xor_si256(_mm256_set1_epi32((int)IV[6]), f0);
    __m256i v15 = _mm256_set1_epi32((int)IV[7]);
    for (int r = 0; r < 10; ++r) {
        G(r, 0, v0, v4, v8, v12);
        G(r, 1, v1, v5, v9, v13);
        G(r, 2, v2, v6, v10, v14);
        G(r, 3, v3, v7, v11, v15);
        G(r, 4, v0, v5, v10, v15);
        G(r, 5, v1, v6, v11, v12);
        G(r, 6, v2, v7, v8, v13);
        G(r, 7, v3, v4, v9, v14);
    }
    h[0] = _mm256_xor_si256(h[0], _mm256_xor_si256(v0, v8));
    h[1] = _mm256_xor_si256(h[1], _mm256_xor_si256(v1, v9));
    h[2] = _mm256_xor_si256(h[2], _mm256_xor_si256(v2, v10));
    h[3] = _mm256_xor_si256(h[3], _mm256_xor_si256(v3, v11));
    h[4] = _mm256_xor_si256(h[4], _mm256_xor_si256(v4, v12));
    h[5] = _mm256_xor_si256(h[5], _mm256_xor_si256(v5, v13));
    h[6] = _mm256_xor_si256(h[6], _mm256_xor_si256(v6, v14));
    h[7] = _mm256_xor_si256(h[7], _mm256_xor_si256(v7, v15));
}

// Hash 8 streams of independent lengths; outs[l] receives 32 bytes.
B2_TARGET void hash8(const uint8_t *const ptrs[8], const uint64_t lens[8],
           uint8_t *const outs[8]) {
    __m256i h[8];
    // Parameter block word 0: digest_length=32 | fanout=1<<16 | depth=1<<24.
    h[0] = _mm256_set1_epi32((int)(IV[0] ^ 0x01010020u));
    for (int i = 1; i < 8; ++i) h[i] = _mm256_set1_epi32((int)IV[i]);

    uint64_t chunks[8], min_interior = UINT64_MAX, max_chunks = 0;
    for (int l = 0; l < 8; ++l) {
        chunks[l] = lens[l] == 0 ? 1 : (lens[l] + 63) / 64;
        uint64_t interior = lens[l] == 0 ? 0 : (lens[l] - 1) / 64;
        if (interior < min_interior) min_interior = interior;
        if (chunks[l] > max_chunks) max_chunks = chunks[l];
    }

    // Fast path: every lane has a full, non-final chunk at index c, so t is
    // uniform and f0 = 0 — no per-lane bookkeeping, no masking.
    uint64_t c = 0;
    for (; c < min_interior; ++c) {
        const uint8_t *cp[8];
        for (int l = 0; l < 8; ++l) cp[l] = ptrs[l] + c * 64;
        uint64_t t = (c + 1) * 64;
        compress8(h, cp, _mm256_set1_epi32((int)(uint32_t)t),
                  _mm256_set1_epi32((int)(uint32_t)(t >> 32)),
                  _mm256_setzero_si256());
    }

    // Tail: lanes diverge (final/partial chunks, early finishers).
    alignas(32) uint8_t padbuf[8][64];
    static const uint8_t zeros[64] = {0};
    for (; c < max_chunks; ++c) {
        const uint8_t *cp[8];
        alignas(32) uint32_t tl[8], th[8], fl[8], act[8];
        for (int l = 0; l < 8; ++l) {
            if (c >= chunks[l]) {  // lane already finished: freeze its state
                cp[l] = zeros;
                tl[l] = th[l] = fl[l] = 0;
                act[l] = 0;
                continue;
            }
            act[l] = 0xFFFFFFFFu;
            uint64_t off = c * 64;
            uint64_t remain = lens[l] - off;
            bool final_chunk = (c == chunks[l] - 1);
            if (remain >= 64) {
                cp[l] = ptrs[l] + off;
            } else {
                memset(padbuf[l], 0, 64);
                if (remain) memcpy(padbuf[l], ptrs[l] + off, remain);
                cp[l] = padbuf[l];
            }
            uint64_t t = final_chunk ? lens[l] : off + 64;
            tl[l] = (uint32_t)t;
            th[l] = (uint32_t)(t >> 32);
            fl[l] = final_chunk ? 0xFFFFFFFFu : 0;
        }
        __m256i mask = _mm256_load_si256((const __m256i *)act);
        __m256i hold[8];
        for (int i = 0; i < 8; ++i) hold[i] = h[i];
        compress8(h, cp, _mm256_load_si256((const __m256i *)tl),
                  _mm256_load_si256((const __m256i *)th),
                  _mm256_load_si256((const __m256i *)fl));
        for (int i = 0; i < 8; ++i)
            h[i] = _mm256_blendv_epi8(hold[i], h[i], mask);
    }

    // Output: word-major state → per-lane 32-byte digests (one more 8x8
    // transpose, through memory — negligible vs the stream itself).
    alignas(32) uint32_t words[8][8];
    for (int i = 0; i < 8; ++i)
        _mm256_store_si256((__m256i *)words[i], h[i]);
    for (int l = 0; l < 8; ++l) {
        uint32_t d[8];
        for (int w = 0; w < 8; ++w) d[w] = words[w][l];
        memcpy(outs[l], d, 32);
    }
}

// ---------------------------------------------------------------------------
// 16-lane AVX-512 path.  Same lane-major design, double the width, and the
// ISA gives native 32-bit rotates (vprord) so the G function drops the
// shuffle-based rotate emulation entirely.
// ---------------------------------------------------------------------------

#define B2_TARGET512 __attribute__((target("avx512f,avx512bw")))

// Transpose 16 lanes × 16 consecutive uint32 (one 64-byte chunk per lane)
// into word-major vectors m[w]: lane l of m[w] = word w of stream l.
// Classic 4-stage 16x16: epi32 unpack, epi64 unpack, then two rounds of
// 128-bit block shuffles (shuffle_i32x4).
B2_TARGET512 inline void transpose16x16(const uint8_t *const ptrs[16],
                                        __m512i m[16]) {
    __m512i r[16], t[16], u[16];
    for (int l = 0; l < 16; ++l)
        r[l] = _mm512_loadu_si512((const void *)ptrs[l]);
    for (int i = 0; i < 8; ++i) {
        t[2 * i] = _mm512_unpacklo_epi32(r[2 * i], r[2 * i + 1]);
        t[2 * i + 1] = _mm512_unpackhi_epi32(r[2 * i], r[2 * i + 1]);
    }
    for (int i = 0; i < 4; ++i) {
        u[4 * i + 0] = _mm512_unpacklo_epi64(t[4 * i + 0], t[4 * i + 2]);
        u[4 * i + 1] = _mm512_unpackhi_epi64(t[4 * i + 0], t[4 * i + 2]);
        u[4 * i + 2] = _mm512_unpacklo_epi64(t[4 * i + 1], t[4 * i + 3]);
        u[4 * i + 3] = _mm512_unpackhi_epi64(t[4 * i + 1], t[4 * i + 3]);
    }
    // u[g*4+k] now holds, for the 4 streams of group g (lanes 4g..4g+3),
    // words {k of sub-block j} across its four 128-bit sub-blocks j.
    // Gather equal 128-bit sub-blocks across groups:
    __m512i v[16];
    for (int k = 0; k < 4; ++k) {
        v[k + 0] = _mm512_shuffle_i32x4(u[k], u[4 + k], 0x88);      // j=0,2
        v[k + 4] = _mm512_shuffle_i32x4(u[8 + k], u[12 + k], 0x88); // j=0,2
        v[k + 8] = _mm512_shuffle_i32x4(u[k], u[4 + k], 0xDD);      // j=1,3
        v[k + 12] = _mm512_shuffle_i32x4(u[8 + k], u[12 + k], 0xDD);
    }
    for (int k = 0; k < 4; ++k) {
        m[k + 0] = _mm512_shuffle_i32x4(v[k + 0], v[k + 4], 0x88);   // j=0
        m[k + 8] = _mm512_shuffle_i32x4(v[k + 0], v[k + 4], 0xDD);   // j=2
        m[k + 4] = _mm512_shuffle_i32x4(v[k + 8], v[k + 12], 0x88);  // j=1
        m[k + 12] = _mm512_shuffle_i32x4(v[k + 8], v[k + 12], 0xDD); // j=3
    }
}

#define G16(r, i, a, b, c, d)                                  \
    do {                                                       \
        a = _mm512_add_epi32(_mm512_add_epi32(a, b),           \
                             m[SIGMA[r][2 * (i)]]);            \
        d = _mm512_ror_epi32(_mm512_xor_si512(d, a), 16);      \
        c = _mm512_add_epi32(c, d);                            \
        b = _mm512_ror_epi32(_mm512_xor_si512(b, c), 12);      \
        a = _mm512_add_epi32(_mm512_add_epi32(a, b),           \
                             m[SIGMA[r][2 * (i) + 1]]);        \
        d = _mm512_ror_epi32(_mm512_xor_si512(d, a), 8);       \
        c = _mm512_add_epi32(c, d);                            \
        b = _mm512_ror_epi32(_mm512_xor_si512(b, c), 7);       \
    } while (0)

B2_TARGET512 inline void compress16(__m512i h[8],
                                    const uint8_t *const chunk[16],
                                    __m512i t_lo, __m512i t_hi, __m512i f0) {
    __m512i m[16];
    transpose16x16(chunk, m);
    __m512i v0 = h[0], v1 = h[1], v2 = h[2], v3 = h[3];
    __m512i v4 = h[4], v5 = h[5], v6 = h[6], v7 = h[7];
    __m512i v8 = _mm512_set1_epi32((int)IV[0]);
    __m512i v9 = _mm512_set1_epi32((int)IV[1]);
    __m512i v10 = _mm512_set1_epi32((int)IV[2]);
    __m512i v11 = _mm512_set1_epi32((int)IV[3]);
    __m512i v12 = _mm512_xor_si512(_mm512_set1_epi32((int)IV[4]), t_lo);
    __m512i v13 = _mm512_xor_si512(_mm512_set1_epi32((int)IV[5]), t_hi);
    __m512i v14 = _mm512_xor_si512(_mm512_set1_epi32((int)IV[6]), f0);
    __m512i v15 = _mm512_set1_epi32((int)IV[7]);
    for (int r = 0; r < 10; ++r) {
        G16(r, 0, v0, v4, v8, v12);
        G16(r, 1, v1, v5, v9, v13);
        G16(r, 2, v2, v6, v10, v14);
        G16(r, 3, v3, v7, v11, v15);
        G16(r, 4, v0, v5, v10, v15);
        G16(r, 5, v1, v6, v11, v12);
        G16(r, 6, v2, v7, v8, v13);
        G16(r, 7, v3, v4, v9, v14);
    }
    h[0] = _mm512_xor_si512(h[0], _mm512_xor_si512(v0, v8));
    h[1] = _mm512_xor_si512(h[1], _mm512_xor_si512(v1, v9));
    h[2] = _mm512_xor_si512(h[2], _mm512_xor_si512(v2, v10));
    h[3] = _mm512_xor_si512(h[3], _mm512_xor_si512(v3, v11));
    h[4] = _mm512_xor_si512(h[4], _mm512_xor_si512(v4, v12));
    h[5] = _mm512_xor_si512(h[5], _mm512_xor_si512(v5, v13));
    h[6] = _mm512_xor_si512(h[6], _mm512_xor_si512(v6, v14));
    h[7] = _mm512_xor_si512(h[7], _mm512_xor_si512(v7, v15));
}

B2_TARGET512 void hash16(const uint8_t *const ptrs[16],
                         const uint64_t lens[16], uint8_t *const outs[16]) {
    __m512i h[8];
    h[0] = _mm512_set1_epi32((int)(IV[0] ^ 0x01010020u));
    for (int i = 1; i < 8; ++i) h[i] = _mm512_set1_epi32((int)IV[i]);

    uint64_t chunks[16], min_interior = UINT64_MAX, max_chunks = 0;
    for (int l = 0; l < 16; ++l) {
        chunks[l] = lens[l] == 0 ? 1 : (lens[l] + 63) / 64;
        uint64_t interior = lens[l] == 0 ? 0 : (lens[l] - 1) / 64;
        if (interior < min_interior) min_interior = interior;
        if (chunks[l] > max_chunks) max_chunks = chunks[l];
    }

    uint64_t c = 0;
    for (; c < min_interior; ++c) {
        const uint8_t *cp[16];
        for (int l = 0; l < 16; ++l) cp[l] = ptrs[l] + c * 64;
        uint64_t t = (c + 1) * 64;
        compress16(h, cp, _mm512_set1_epi32((int)(uint32_t)t),
                   _mm512_set1_epi32((int)(uint32_t)(t >> 32)),
                   _mm512_setzero_si512());
    }

    alignas(64) uint8_t padbuf[16][64];
    static const uint8_t zeros[64] = {0};
    for (; c < max_chunks; ++c) {
        const uint8_t *cp[16];
        alignas(64) uint32_t tl[16], th[16], fl[16];
        uint16_t act = 0;
        for (int l = 0; l < 16; ++l) {
            if (c >= chunks[l]) {
                cp[l] = zeros;
                tl[l] = th[l] = fl[l] = 0;
                continue;
            }
            act |= (uint16_t)(1u << l);
            uint64_t off = c * 64;
            uint64_t remain = lens[l] - off;
            bool final_chunk = (c == chunks[l] - 1);
            if (remain >= 64) {
                cp[l] = ptrs[l] + off;
            } else {
                memset(padbuf[l], 0, 64);
                if (remain) memcpy(padbuf[l], ptrs[l] + off, remain);
                cp[l] = padbuf[l];
            }
            uint64_t t = final_chunk ? lens[l] : off + 64;
            tl[l] = (uint32_t)t;
            th[l] = (uint32_t)(t >> 32);
            fl[l] = final_chunk ? 0xFFFFFFFFu : 0;
        }
        __m512i hold[8];
        for (int i = 0; i < 8; ++i) hold[i] = h[i];
        compress16(h, cp, _mm512_load_si512((const void *)tl),
                   _mm512_load_si512((const void *)th),
                   _mm512_load_si512((const void *)fl));
        for (int i = 0; i < 8; ++i)  // finished lanes keep frozen state
            h[i] = _mm512_mask_blend_epi32((__mmask16)act, hold[i], h[i]);
    }

    alignas(64) uint32_t words[8][16];
    for (int i = 0; i < 8; ++i)
        _mm512_store_si512((void *)words[i], h[i]);
    for (int l = 0; l < 16; ++l) {
        uint32_t d[8];
        for (int w = 0; w < 8; ++w) d[w] = words[w][l];
        memcpy(outs[l], d, 32);
    }
}

B2_TARGET512 void multi16(const uint8_t *const *ptrs, const uint64_t *lens,
                          uint8_t *out, int64_t n) {
    for (int64_t i = 0; i < n; i += 16) {
        const uint8_t *p[16];
        uint64_t L[16];
        uint8_t *o[16];
        uint8_t scratch[16][32];
        int64_t last = (i + 15 < n ? i + 15 : n - 1);
        for (int l = 0; l < 16; ++l) {
            int64_t j = i + l;
            if (j < n) {
                p[l] = ptrs[j];
                L[l] = lens[j];
                o[l] = out + j * 32;
            } else {  // pad lane: replay the last real stream (see multi8)
                p[l] = ptrs[last];
                L[l] = lens[last];
                o[l] = scratch[l];
            }
        }
        hash16(p, L, o);
    }
}

B2_TARGET void multi8(const uint8_t *const *ptrs, const uint64_t *lens,
                      uint8_t *out, int64_t n) {
    for (int64_t i = 0; i < n; i += 8) {
        const uint8_t *p[8];
        uint64_t L[8];
        uint8_t *o[8];
        uint8_t scratch[8][32];
        int64_t last = (i + 7 < n ? i + 7 : n - 1);
        for (int l = 0; l < 8; ++l) {
            int64_t j = i + l;
            if (j < n) {
                p[l] = ptrs[j];
                L[l] = lens[j];
                o[l] = out + j * 32;
            } else {
                // Pad lane: REPLAY the group's last real stream and discard
                // the digest.  An empty-string pad (len 0) would pull
                // min_interior to 0 and push the whole group — which the
                // caller's ascending length sort fills with the LONGEST
                // blocks — onto the masked per-lane tail path for every
                // chunk; replaying a real lane keeps the uniform fast loop
                // at zero extra compress cost.
                p[l] = ptrs[last];
                L[l] = lens[last];
                o[l] = scratch[l];
            }
        }
        hash8(p, L, o);
    }
}

}  // namespace

// Runtime support probe: the Python wrapper must call this before using
// blake2s256_multi and treat 0 as "kernel unavailable" (hashlib fallback).
// Returns the SIMD lane count (16 = AVX-512, 8 = AVX2); callers need only
// truthiness — blake2s256_multi dispatches on width internally.
extern "C" int blake2s_mb_supported() {
    if (__builtin_cpu_supports("avx512f") &&
        __builtin_cpu_supports("avx512bw"))
        return 16;
    return __builtin_cpu_supports("avx2") ? 8 : 0;
}

extern "C" void blake2s256_multi(const uint8_t *const *ptrs,
                                 const uint64_t *lens, uint8_t *out,
                                 int64_t n) {
    static const int lanes = blake2s_mb_supported();
    if (lanes == 16 && n > 8)
        multi16(ptrs, lens, out, n);
    else
        multi8(ptrs, lens, out, n);
}
