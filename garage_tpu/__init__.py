"""garage_tpu — a TPU-native distributed object storage framework.

A brand-new implementation of the capabilities of Garage (reference:
/root/reference, an S3-compatible leaderless CRDT-reconciled object store
written in Rust): quorum replication, CRDT metadata tables with Merkle
anti-entropy, content-addressed blocks, and background scrub/resync/repair
workers — re-architected TPU-first so the block layer's integrity hashing and
erasure-coding math runs as batched JAX/Pallas device ops.

Layer map (mirrors reference SURVEY.md §1):
  utils/     L1 foundation  (ref: src/util)
  db/        L2 metadata DB (ref: src/db)
  net/       L3 comm backend (ref: external crate netapp 0.10)
  rpc/       L3 cluster/RPC (ref: src/rpc)
  parallel/  L3 replication & sharding strategies + layout optimizer
             (ref: src/rpc/ring.rs, layout.rs, graph_algo.rs,
              src/table/replication)
  table/     L4b replicated CRDT table engine (ref: src/table)
  block/     L4a content-addressed block store (ref: src/block)
  ops/       the genuinely new layer: BlockCodec — batched device ops
             (BLAKE2 hashing, Reed-Solomon GF(2^8) encode/decode-repair,
              compression) with CPU and TPU (JAX) implementations
  models/    L5 data model (ref: src/model)
  api/       L6 HTTP APIs: S3, admin, web (ref: src/api, src/web)
  cli/       L7 daemon + CLI (ref: src/garage)
"""

__version__ = "0.9.5"

# feature registry (ref util/version.rs garage_features): what this build
# ships, surfaced by `garage_tpu --version` and node stats
FEATURES = [
    "k2v", "lmdb-equivalent-logdb", "sqlite", "consul-discovery",
    "kubernetes-discovery", "metrics", "telemetry-otlp",
    "codec-cpu", "codec-tpu", "codec-hybrid", "repair-tree",
]
