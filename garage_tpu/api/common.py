"""Shared API error model + helpers.

Equivalent of reference src/api/common_error.rs + helpers.rs + encoding.rs
(SURVEY.md §2.7): a typed error enum rendered uniformly to S3-style XML
error bodies, host→bucket parsing for vhost-style requests, and URI
encoding helpers.
"""

from __future__ import annotations

import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, Optional, Tuple

from ..utils.error import GarageError


class ApiError(GarageError):
    status = 500
    code = "InternalError"

    def __init__(self, message: str = "", status: Optional[int] = None,
                 code: Optional[str] = None):
        super().__init__(message)
        if status is not None:
            self.status = status
        if code is not None:
            self.code = code
        self.message = message


class NoSuchBucketError(ApiError):
    status = 404
    code = "NoSuchBucket"


class NoSuchKeyError(ApiError):
    status = 404
    code = "NoSuchKey"


class NoSuchUploadError(ApiError):
    status = 404
    code = "NoSuchUpload"


class BucketNotEmptyError(ApiError):
    status = 409
    code = "BucketNotEmpty"


class BucketAlreadyExistsError(ApiError):
    status = 409
    code = "BucketAlreadyExists"


class AccessDeniedError(ApiError):
    status = 403
    code = "AccessDenied"


class BadRequestError(ApiError):
    status = 400
    code = "InvalidRequest"


class EntityTooSmallError(ApiError):
    status = 400
    code = "EntityTooSmall"


class InvalidPartError(ApiError):
    status = 400
    code = "InvalidPart"


class PreconditionFailedError(ApiError):
    status = 412
    code = "PreconditionFailed"


class InvalidRangeError(ApiError):
    status = 416
    code = "InvalidRange"


class NotImplementedError_(ApiError):
    status = 501
    code = "NotImplemented"


class SlowDownError(ApiError):
    """Node past its admission watermarks: the request was shed at
    intake, unserved (S3's throttle answer — clients back off and
    retry).  `retry_after` rides the Retry-After header via
    error_response."""

    status = 503
    code = "SlowDown"

    def __init__(self, message: str = "service is overloaded; slow down",
                 retry_after: int = 1):
        super().__init__(message)
        self.retry_after = retry_after


def error_xml(err: Exception, resource: str = "", request_id: str = "") -> bytes:
    """S3 error body (ref common_error.rs rendering)."""
    code = getattr(err, "code", "InternalError")
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = code
    ET.SubElement(root, "Message").text = str(err)
    ET.SubElement(root, "Resource").text = resource
    ET.SubElement(root, "RequestId").text = request_id
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def body_claim(tun, request):
    """→ (bytes to admit against, estimated?).  Declared Content-Length
    when present; a chunked/streaming body with NO declared length is
    admitted against the conservative ``streaming_body_estimate`` (and
    reconciled to actual bytes as it streams — AdmissionToken
    note_body_bytes/body_done) instead of bypassing the bytes watermark
    entirely.  Body-less requests claim nothing."""
    cl = request.headers.get("Content-Length")
    if cl is not None:
        try:
            return max(int(cl), 0), False
        except ValueError:
            return 0, False
    te = request.headers.get("Transfer-Encoding", "")
    if "chunked" in te.lower():
        return max(getattr(tun, "streaming_body_estimate", 0), 0), True
    return 0, False


_SHED_MESSAGES = {
    "over_share": "tenant is past its fair share of the admission gate; "
                  "retry with backoff",
    "queue_full": "tenant admission queue is full; retry with backoff",
    "queue_timeout": "no admission slot freed within the queueing bound; "
                     "retry with backoff",
    "remote_pressure": "a storage node this request must touch is "
                       "saturated; shed at the gateway on its behalf",
}


async def admit_request(gate, request, tenant: Optional[str] = None,
                        remote_pressure: float = 0.0,
                        bucket: Optional[str] = None):
    """Admission-gate intake shared by the S3 and K2V servers →
    ``(token, None)`` when admitted (release the token when the request
    FULLY finishes, streaming included) or ``(None, response)`` when
    shed — the ready-to-return 503 SlowDown with a load-derived
    Retry-After and a minted RequestId.  Requests are classified into
    per-tenant WDRR queues by access key (fallback: bucket); sheds are
    per-tenant, never gate-wide.  Gate None (overload protection
    unwired, e.g. bare test servers) admits everything."""
    if gate is None:
        return None, None
    from .admission import classify_tenant

    nbytes, estimated = body_claim(gate.tun, request)
    token, verdict = await gate.admit(
        nbytes, tenant=tenant or classify_tenant(request, bucket),
        remote_pressure=remote_pressure, estimated=estimated)
    if token is not None:
        return token, None
    msg = _SHED_MESSAGES.get(
        verdict, "node is past its admission watermarks; retry with backoff")
    return None, error_response(
        SlowDownError(msg, retry_after=gate.retry_after_hint()),
        request.path)


def slo_service_latency(request, token, t_intake_ns: int
                        ) -> Tuple[float, bool]:
    """``(seconds, client_paced)`` for the latency SLO (utils/slo.py),
    shared by the S3 and K2V middlewares at request completion.
    Client-paced durations (streamed GET responses, long-polls — the
    admission token's CoDel sojourn exclusion, or the request-level
    ``slo_client_paced`` flag the streaming/poll handlers set so the
    exclusion holds with admission disabled) count toward availability
    but must never mark slow.  Uploads anchor at body completion, like
    the adaptive watermark (subtracting the client-paced body
    transfer); everything else keeps the INTAKE anchor — it includes
    the admission (WDRR) queue wait, which is server-side latency the
    client observes and must burn the budget."""
    import time

    lat_s = (time.time_ns() - t_intake_ns) / 1e9
    paced = bool(request.get("slo_client_paced"))
    if token is not None and not paced:
        sl = token.service_latency()
        if sl is None:
            paced = True
        elif token.body_anchored():
            lat_s = sl
    return lat_s, paced


def request_deadline_budget(config) -> Optional[float]:
    """The per-request deadline budget the API servers arm, from
    ``[rpc] deadline_default``; None = deadlines disabled."""
    rpc_tun = getattr(config, "rpc", None)
    if rpc_tun is not None and rpc_tun.deadline_default > 0:
        return rpc_tun.deadline_default
    return None


def client_deadline_budget(default_s: Optional[float],
                           request) -> Optional[float]:
    """Fold a client-supplied ``X-Request-Timeout`` (seconds) into the
    request's deadline budget: it may TIGHTEN the default, never extend
    it — and when deadlines are disabled a client may still arm its own.
    Malformed / non-finite / non-positive values are ignored (header
    values are client-controlled fuzz targets; a bad one must not
    disable or poison the budget)."""
    raw = request.headers.get("X-Request-Timeout")
    if raw is None:
        return default_s
    try:
        t = float(raw)
    except (TypeError, ValueError):
        return default_s
    if not (t == t) or t == float("inf") or t <= 0:
        return default_s
    return t if default_s is None else min(default_s, t)


def gen_request_id() -> str:
    """A fresh x-amz-request-id.  request_trace mints one per traced
    request; error paths that answer BEFORE a trace exists (the
    admission gate's shed) mint one here so every response — even a
    rejection — carries a RequestId a support ticket can quote."""
    return os.urandom(16).hex()


def error_response(err: Exception, resource: str = "",
                   request_id: str = ""):
    """The ONE way an API server renders an error to the client: S3
    error XML body + the `x-amz-request-id` header (always — error
    responses are never prepared streaming responses, so the header can
    always be set here instead of relying on each caller's post-hoc
    header pass) + `Retry-After` on every 503 (SlowDown sheds, deadline
    expiries) so well-behaved clients back off instead of hammering an
    overloaded node."""
    from aiohttp import web

    status = int(getattr(err, "status", 500))
    rid = request_id or gen_request_id()
    headers = {"x-amz-request-id": rid}
    if status == 503:
        headers["Retry-After"] = str(int(getattr(err, "retry_after", 1)))
    return web.Response(
        status=status,
        body=error_xml(err, resource, rid),
        content_type="application/xml",
        headers=headers,
    )


def xml_to_bytes(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


S3_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def s3_xml_root(tag: str) -> ET.Element:
    return ET.Element(tag, {"xmlns": S3_XMLNS})


def iso_timestamp(ts_ms: int) -> str:
    """ms epoch → S3-style ISO8601 (shared by list/bucket/copy XML)."""
    import datetime

    return datetime.datetime.fromtimestamp(
        ts_ms / 1000, tz=datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")


def int_param(value, name: str, default: Optional[int] = None) -> Optional[int]:
    """Parse an integer query parameter; malformed → 400 InvalidArgument
    (not a 500) — S3 clients fuzz these freely."""
    if value is None:
        return default
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ApiError(
            f"invalid integer for {name}: {value!r}",
            status=400, code="InvalidArgument",
        )


async def start_site(runner, bind_addr: str, unix_mode: int = 0o222):
    """Bind an aiohttp runner to `bind_addr` — "host:port" for TCP, an
    absolute path or "unix:/path" for a unix domain socket (ref
    util/socket_address.rs UnixOrTCPSocketAddress; every API server in
    the reference accepts both).  Returns the started site.

    Unix sockets are chmod'd to `unix_mode` after bind (ref
    api/generic_server.rs:150-152, default 0o222): connecting requires
    write permission, and the daemon's umask would otherwise leave the
    socket unreachable for clients running as other users."""
    from aiohttp import web

    is_unix = bind_addr.startswith("unix:")
    if is_unix:
        bind_addr = bind_addr[len("unix:"):]
    if is_unix or bind_addr.startswith("/"):
        # a previous run's socket file survives shutdown and would make
        # bind fail EADDRINUSE; only ever unlink an actual socket
        import os
        import stat

        try:
            if stat.S_ISSOCK(os.stat(bind_addr).st_mode):
                os.unlink(bind_addr)
        except FileNotFoundError:
            pass
        site = web.UnixSite(runner, bind_addr)
        await site.start()
        try:
            os.chmod(bind_addr, unix_mode)
        except OSError:
            import logging

            logging.getLogger("garage_tpu.api").warning(
                "cannot chmod unix socket %s to %s — clients running as "
                "other users will get EACCES", bind_addr, oct(unix_mode),
                exc_info=True,
            )
        return site
    host, port = bind_addr.rsplit(":", 1)
    site = web.TCPSite(runner, host, int(port))
    await site.start()
    return site


def client_addr(request) -> str:
    """Advertised client address for logs/spans (ref
    util/forwarded_headers.rs handle_forwarded_for_headers +
    generic_server.rs:172-177): when X-Forwarded-For holds exactly one
    valid IP literal it is used, like the reference does; anything else
    (absent, hostname, list) falls back to the TCP peer address.  The
    header is client-controlled, so spans record the TCP peer TOO
    (request_trace below) — a spoofed header can't erase the real peer
    from the audit trail."""
    import ipaddress

    xff = request.headers.get("X-Forwarded-For")
    if xff is not None:
        try:
            return str(ipaddress.ip_address(xff.strip()))
        except ValueError:
            pass
    return request.remote or ""


def request_trace(tracer, title: str, api: str, request,
                  start_ns: Optional[int] = None):
    """Per-request trace root shared by the S3/K2V/Web servers (ref
    api/generic_server.rs:187-200 creates one span per request with a
    fresh trace id).  Records method/path, the TCP peer, and the
    forwarded client address when it differs.

    → (span, request_id).  The request id IS the trace id (it seeds the
    root span), so the `x-amz-request-id` a client quotes in a support
    ticket is the exact key to look the distributed trace up by.  The
    id exists even with tracing off — clients always get one.

    `start_ns` backdates the root to request INTAKE: admission runs
    before the trace can be minted (sheds must stay cheap), but its
    time belongs to the request — the waterfall's segments then sum to
    the duration the client actually saw."""
    rid = os.urandom(16).hex()
    attrs = {
        "api": api,
        "method": request.method,
        "path": request.path,
        "peer": request.remote or "",
    }
    fwd = client_addr(request)
    if fwd != attrs["peer"]:
        attrs["forwarded_for"] = fwd
    return tracer.new_trace(
        f"{title} {request.method}", trace_id=rid, start_ns=start_ns,
        **attrs
    ), rid


def host_to_bucket(host: str, root_domain: Optional[str]) -> Optional[str]:
    """vhost-style bucket extraction (ref helpers.rs host_to_bucket):
    `bucket.root_domain` → bucket; bare root_domain or unrelated host →
    None (path-style)."""
    if root_domain is None:
        return None
    host = host.split(":")[0].lower()
    rd = root_domain.lstrip(".").lower()
    if host == rd:
        return None
    suffix = "." + rd
    if host.endswith(suffix):
        return host[: -len(suffix)]
    return None


def parse_bucket_key(path: str, vhost_bucket: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """(bucket, key) from the URI path (ref api_server.rs:79-103).
    Key of "" (trailing slash) is a valid S3 key distinct from None."""
    path = urllib.parse.unquote(path)
    if not path.startswith("/"):
        path = "/" + path
    if vhost_bucket is not None:
        key = path[1:]
        return vhost_bucket, (key if key != "" else None)
    parts = path[1:].split("/", 1)
    bucket = parts[0] if parts[0] != "" else None
    key = parts[1] if len(parts) > 1 else None
    return bucket, key
