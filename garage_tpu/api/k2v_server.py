"""K2V HTTP API.

Equivalent of reference src/api/k2v/ (SURVEY.md §2.7, ≈2100 LoC):
  - item ops (item.rs): GET/PUT/DELETE /{bucket}/{partition}/{sort}; reads
    return the causality token in X-Garage-Causality-Token and either a
    single raw value (octet-stream; 409 on conflict) or a JSON array of
    base64 values / null tombstones; writes take the token to supersede.
  - PollItem (long-poll) via ?causality_token=…&timeout=… on GET.
  - ReadIndex (index.rs): GET /{bucket}?start&end&limit over the partition
    counter table.
  - batch ops (batch.rs): POST /{bucket} = InsertBatch, ?search =
    ReadBatch, ?delete = DeleteBatch.
SigV4-authenticated like the S3 API, same key/bucket permission model.
"""

from __future__ import annotations

import base64
import json
import logging
from typing import Optional

from aiohttp import web

from ..model.helper import NoSuchBucket, NoSuchKey
from ..model.k2v.causality import CausalContext
from ..utils.error import GarageError
from ..utils.tracing import deadline_scope
from .common import (
    AccessDeniedError,
    ApiError,
    BadRequestError,
    NoSuchBucketError,
    NoSuchKeyError,
    admit_request,
    client_deadline_budget,
    error_response,
    int_param,
    request_deadline_budget,
    request_trace,
    slo_service_latency,
    start_site,
)
from .signature import check_signature, raw_query_pairs

logger = logging.getLogger("garage_tpu.api.k2v")

CAUSALITY_HEADER = "X-Garage-Causality-Token"


def parse_poll_timeout(raw) -> float:
    """Client long-poll window → seconds in (0, 600].  The value is
    client-controlled: non-numeric raises a typed 400 (not a 500 out of
    float()), and NaN/non-positive are rejected too — nan would poison
    every downstream deadline comparison and the event loop's timer
    heap (same invariant as the budget-extension parse above)."""
    try:
        t = float(raw)
    except (TypeError, ValueError):
        raise BadRequestError(f"invalid poll timeout: {raw!r}")
    if not (t == t) or t <= 0:
        raise BadRequestError(f"invalid poll timeout: {raw!r}")
    return min(t, 600.0)


class K2VApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.helper = garage.helper()
        self.region = garage.config.s3_region
        # node-wide admission gate + request deadline budget, shared with
        # the S3 server (docs/ROBUSTNESS.md "Overload & brownout")
        self.gate = getattr(garage, "admission", None)
        # SLO burn-rate tracker (utils/slo.py): K2V requests classify
        # by method ("K2V:GET", …) — sheds included
        self.slo = getattr(garage, "slo", None)
        self.deadline_s = request_deadline_budget(garage.config)
        self._runner: Optional[web.AppRunner] = None

    async def start(self, bind_addr: str) -> None:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self.handle_request)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = await start_site(self._runner, bind_addr)
        logger.info("K2V API listening on %s", bind_addr)

    @property
    def port(self) -> int:
        return self._site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def handle_request(self, request: web.Request) -> web.StreamResponse:
        # admission first, before signature/trace/body — shed typed
        # (503 SlowDown + Retry-After + RequestId) instead of queueing.
        # Tenant-classified (access key, fallback bucket) with the
        # gossiped pressure of the bucket's placement nodes folded in,
        # exactly like the S3 front door.
        remote_p = 0.0
        probe = getattr(self.garage, "admission_probe", None)
        seg = request.rel_url.raw_path.lstrip("/").split("/", 1)[0]
        import urllib.parse as _up

        bname = _up.unquote(seg) if seg else None
        if probe is not None:
            remote_p, _hot = probe.pressure(bname)
        # long polls legitimately outlive the default request budget:
        # give them their requested window on top of it.  The value is
        # client-controlled: only FINITE values in [0, 600] extend —
        # nan would poison every downstream deadline comparison and
        # the event loop's timer heap, and a negative value must not
        # silently shrink the budget
        budget = self.deadline_s
        if budget is not None and "timeout" in request.query:
            try:
                t = float(request.query["timeout"])
            except ValueError:
                t = 0.0
            if t == t and t > 0:
                budget += min(t, 600.0)
        # a client-supplied X-Request-Timeout tightens the final budget
        # (never extends — even a long poll honors an explicit tighter
        # client bound); armed BEFORE admission so WDRR queue time
        # spends the budget instead of stacking on top of it
        budget = client_deadline_budget(budget, request)
        import time as _time

        t_intake_ns = _time.time_ns()
        with deadline_scope(budget):
            token, shed = await admit_request(
                self.gate, request, remote_pressure=remote_p, bucket=bname)
            t_admitted_ns = _time.time_ns()
            if shed is not None:
                if self.slo is not None:
                    self.slo.note(f"K2V:{request.method}",
                                  (_time.time_ns() - t_intake_ns) / 1e9,
                                  ok=False)
                return shed
            if token is not None:
                # the long-poll handlers park this token while waiting so
                # pollers don't starve the in-flight watermark
                request["admission_token"] = token
            try:
                tracer = self.garage.system.tracer
                trace, rid = request_trace(
                    tracer, "K2V", "k2v", request, start_ns=t_intake_ns)
                if t_admitted_ns > t_intake_ns:
                    # the waterfall's `admission` segment (root is
                    # backdated to intake, so this lands inside it)
                    tracer.record_span(
                        "admission", trace.trace_id, trace.span_id,
                        t_intake_ns, t_admitted_ns)
                with trace:
                    resp = await self._handle_with_errors(request, rid)
                    trace.set_attr("status", resp.status)
                    if self.slo is not None:
                        # long-polls (PollItem/PollRange) wait out the
                        # CLIENT's chosen window — excluded from the
                        # latency SLO by the shared helper
                        lat_s, paced = slo_service_latency(
                            request, token, t_intake_ns)
                        self.slo.note(
                            f"K2V:{request.method}", lat_s,
                            ok=resp.status < 500, client_paced=paced)
                    if not resp.prepared:
                        resp.headers["x-amz-request-id"] = rid
                    return resp
            finally:
                if token is not None:
                    token.release()

    async def _handle_with_errors(self, request, rid: str) -> web.StreamResponse:
        try:
            return await self._handle(request)
        except (ApiError, NoSuchBucket, NoSuchKey, GarageError) as e:
            status = getattr(e, "status", 500)
            if status >= 500 and status != 503:
                logger.exception("K2V API internal error")
            else:
                logger.debug("K2V API error %s: %s", status, e)
            return error_response(e, request.path, rid)
        except ConnectionError as e:  # incl. ConnectionResetError
            logger.debug("client disconnected mid-request: %s", e)
            raise
        except Exception as e:  # noqa: BLE001
            logger.exception("K2V API error")
            return error_response(e, request.path, rid)

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        headers = {k.lower(): v for k, v in request.headers.items()}

        async def get_key(key_id: str):
            k = await self.garage.key_table.get(key_id, "")
            if k is None or k.is_deleted():
                return None
            return k

        query = [(k, v) for k, v in request.query.items()]
        verified = await check_signature(
            get_key, self.region, request.method, request.path, query, headers,
            raw_path=request.rel_url.raw_path,
            raw_query=raw_query_pairs(request.rel_url.raw_query_string),
        )
        api_key = verified.key

        import urllib.parse

        parts = [
            urllib.parse.unquote(p)
            for p in request.rel_url.raw_path.lstrip("/").split("/")
        ]
        if not parts or parts[0] == "":
            raise BadRequestError("missing bucket in path")
        bucket_name = parts[0]
        pk = parts[1] if len(parts) > 1 and parts[1] != "" else None
        sk = parts[2] if len(parts) > 2 else None

        bucket_id = await self.helper.resolve_bucket(bucket_name, api_key)
        probe = getattr(self.garage, "admission_probe", None)
        if probe is not None:
            probe.note_bucket(bucket_name, bytes(bucket_id))
        m = request.method
        # Classify the endpoint BEFORE the permission check (ref
        # src/api/k2v/router.rs authorization_type): ReadBatch (POST
        # ?search) and PollRange are reads even though they are POSTs;
        # everything else follows the method (GET=read, PUT/POST/DELETE
        # mutations=write).
        qk = request.query
        if m == "GET":
            needs = "read"
        elif m == "POST" and (
            (pk is None and "search" in qk)
            or (pk is not None and sk is None and "poll_range" in qk)
        ):
            needs = "read"
        else:
            needs = "write"
        allowed = (
            api_key.allow_read(bucket_id) if needs == "read"
            else api_key.allow_write(bucket_id)
        )
        if not allowed:
            raise AccessDeniedError(f"no {needs} permission on {bucket_name}")

        q = request.query
        if pk is None:
            if m == "GET":
                return await self.read_index(bucket_id, q)
            if m == "POST":
                if "search" in q:
                    return await self.read_batch(bucket_id, request)
                if "delete" in q:
                    return await self.delete_batch(bucket_id, request)
                return await self.insert_batch(bucket_id, request)
            raise BadRequestError(f"no such K2V endpoint: {m} /bucket")
        if sk is None and "poll_range" in q:
            # POST only (ref router.rs); the permission classification
            # above treats only the POST form as a read
            if m != "POST":
                raise BadRequestError("PollRange is POST")
            return await self.poll_range(bucket_id, pk, request)
        if sk is None:
            raise BadRequestError("missing sort key")
        if m == "GET":
            if "causality_token" in q and "timeout" in q:
                return await self.poll_item(bucket_id, pk, sk, q, headers,
                                            request)
            return await self.read_item(bucket_id, pk, sk, headers)
        if m == "PUT":
            return await self.insert_item(bucket_id, pk, sk, request, headers)
        if m == "DELETE":
            return await self.delete_item(bucket_id, pk, sk, headers)
        raise BadRequestError(f"no such K2V endpoint: {m} on item")

    # --- item ops (ref api/k2v/item.rs) ---

    async def _get_item(self, bucket_id, pk, sk):
        return await self.garage.k2v_item_table.get((bytes(bucket_id), pk), sk)

    def _item_response(self, item, headers) -> web.Response:
        token = item.causal_context().serialize()
        vals = item.values()
        accept = headers.get("accept", "*/*")
        wants_json = "application/json" in accept
        wants_raw = "application/octet-stream" in accept
        live = [v for v in vals if v is not None]
        if not live:
            raise NoSuchKeyError("item is deleted")
        if wants_raw or (not wants_json and len(live) == 1 and len(vals) == 1):
            if len(vals) > 1:
                raise ApiError(
                    "multiple concurrent values; use Accept: application/json",
                    status=409, code="Conflict",
                )
            return web.Response(
                status=200, body=live[0],
                headers={CAUSALITY_HEADER: token},
                content_type="application/octet-stream",
            )
        body = json.dumps([
            base64.b64encode(v).decode() if v is not None else None
            for v in vals
        ])
        return web.Response(
            status=200, body=body.encode(),
            headers={CAUSALITY_HEADER: token},
            content_type="application/json",
        )

    async def read_item(self, bucket_id, pk, sk, headers) -> web.Response:
        item = await self._get_item(bucket_id, pk, sk)
        if item is None:
            raise NoSuchKeyError(f"no such K2V item: {pk}/{sk}")
        return self._item_response(item, headers)

    async def insert_item(self, bucket_id, pk, sk, request, headers) -> web.Response:
        value = await request.read()
        ct = headers.get(CAUSALITY_HEADER.lower())
        context = CausalContext.parse(ct) if ct else None
        await self.garage.k2v_rpc.insert(bucket_id, pk, sk, context, value)
        return web.Response(status=204)

    async def delete_item(self, bucket_id, pk, sk, headers) -> web.Response:
        ct = headers.get(CAUSALITY_HEADER.lower())
        context = CausalContext.parse(ct) if ct else None
        await self.garage.k2v_rpc.insert(bucket_id, pk, sk, context, None)
        return web.Response(status=204)

    async def poll_item(self, bucket_id, pk, sk, q, headers,
                        request=None) -> web.Response:
        context = CausalContext.parse(q["causality_token"])
        timeout = parse_poll_timeout(q.get("timeout", "300"))
        # park the admission slot for the poll window: a long poll holds
        # no node resources while waiting, and N pollers must not brown
        # out PUT/GET admission for up to 600 s each
        token = request.get("admission_token") if request is not None else None
        if request is not None:
            # poll duration is the client's chosen window, not service
            # latency: keep it out of the latency SLO even when no
            # admission token exists to carry the CoDel exclusion
            request["slo_client_paced"] = True
        if token is not None:
            token.park()
        try:
            item = await self.garage.k2v_rpc.poll_item(
                bucket_id, pk, sk, context, timeout
            )
        finally:
            if token is not None:
                token.unpark()
        if item is None:
            return web.Response(status=304)  # not modified within timeout
        return self._item_response(item, headers)

    # --- index (ref api/k2v/index.rs) ---

    async def read_index(self, bucket_id, q) -> web.Response:
        start = q.get("start")
        end = q.get("end")
        prefix = q.get("prefix")
        limit = min(int_param(q.get("limit"), "limit", 1000), 1000)
        ent = await self.garage.k2v_counter_table.get_range(
            bytes(bucket_id), start, filter=None, limit=limit + 1,
        )
        partitions = []
        for ce in ent:
            pk = ce.sk
            if prefix and not pk.startswith(prefix):
                continue
            if end is not None and pk >= end:
                break
            t = ce.totals()
            if t.get("items", 0) <= 0:
                continue
            partitions.append({
                "pk": pk,
                "entries": t.get("items", 0),
                "conflicts": t.get("conflicts", 0),
                "values": t.get("values", 0),
                "bytes": t.get("bytes", 0),
            })
        truncated = len(partitions) > limit
        partitions = partitions[:limit]
        return web.json_response({
            "prefix": prefix,
            "start": start,
            "end": end,
            "limit": limit,
            "partitionKeys": partitions,
            "more": truncated,
            "nextStart": partitions[-1]["pk"] if truncated else None,
        })

    # --- batch ops (ref api/k2v/batch.rs) ---

    async def insert_batch(self, bucket_id, request) -> web.Response:
        try:
            body = json.loads(await request.read())
            items = [
                (
                    it["pk"], it["sk"],
                    CausalContext.parse(it["ct"]) if it.get("ct") else None,
                    base64.b64decode(it["v"]) if it.get("v") is not None else None,
                )
                for it in body
            ]
        except (ValueError, KeyError, TypeError) as e:
            raise BadRequestError(f"malformed InsertBatch body: {e}")
        await self.garage.k2v_rpc.insert_many(bucket_id, items)
        return web.Response(status=204)

    async def read_batch(self, bucket_id, request) -> web.Response:
        try:
            queries = json.loads(await request.read())
            assert isinstance(queries, list)
        except (ValueError, AssertionError) as e:
            raise BadRequestError(f"malformed ReadBatch body: {e}")
        out = []
        for sq in queries:
            out.append(await self._search(bucket_id, sq))
        return web.json_response(out)

    async def _search(self, bucket_id, sq) -> dict:
        pk = sq.get("partitionKey")
        if pk is None:
            raise BadRequestError("search missing partitionKey")
        limit = max(1, min(int(sq.get("limit") or 1000), 1000))
        start = sq.get("start")
        end = sq.get("end")
        prefix = sq.get("prefix")
        if start is None and prefix is not None:
            # seed the scan at the prefix (ref batch.rs start.unwrap_or
            # (prefix)): scanning from the partition head and post-
            # filtering would return an empty not-truncated page when the
            # first window holds no matching keys
            start = prefix
        single = sq.get("singleItem", False)
        conflicts_only = sq.get("conflictsOnly", False)
        tombstones = sq.get("tombstones", False)

        if single:
            item = await self._get_item(bucket_id, pk, start or "")
            items = [item] if item is not None else []
        else:
            # ALWAYS range-read with filter="any" and filter AFTER the
            # quorum merge: a liveness filter pushed to the replicas makes
            # a node that already holds a tombstone return nothing while a
            # lagging node returns the stale live value — the merge then
            # RESURRECTS deleted items (the reference's ItemFilter is
            # applied post-merge for the same reason, k2v/batch.rs:171).
            # Pagination stays raw-entry-based (nextStart may be a
            # tombstone), so pages can carry fewer visible items; clients
            # follow `more`/nextStart as usual.
            raw = await self.garage.k2v_item_table.get_range(
                (bytes(bucket_id), pk), start, filter="any", limit=limit + 1,
            )
            if prefix:
                raw = [i for i in raw if i.sort_key_str.startswith(prefix)]
            if end is not None:
                raw = [i for i in raw if i.sort_key_str < end]
            # pagination over RAW entries (a tombstone-heavy page must
            # still report more/nextStart or clients stop early)
            truncated = len(raw) > limit
            raw = raw[:limit]
            if conflicts_only:
                items = [i for i in raw if len(i.values()) > 1]
            elif tombstones:
                items = raw
            else:
                items = [i for i in raw if i.live_values()]
            return self._search_result(pk, prefix, start, end, limit,
                                       single, items, truncated,
                                       raw[-1].sort_key_str if truncated
                                       else None)
        return self._search_result(pk, prefix, start, end, limit, single,
                                   items, False, None)

    @staticmethod
    def _search_result(pk, prefix, start, end, limit, single, items, more,
                       next_start) -> dict:
        return {
            "partitionKey": pk,
            "prefix": prefix,
            "start": start,
            "end": end,
            "limit": limit,
            "singleItem": single,
            "items": [
                {
                    "sk": i.sort_key_str,
                    "ct": i.causal_context().serialize(),
                    "v": [
                        base64.b64encode(v).decode() if v is not None else None
                        for v in i.values()
                    ],
                }
                for i in items
            ],
            "more": more,
            "nextStart": next_start,
        }

    async def delete_batch(self, bucket_id, request) -> web.Response:
        try:
            queries = json.loads(await request.read())
            assert isinstance(queries, list)
        except (ValueError, AssertionError) as e:
            raise BadRequestError(f"malformed DeleteBatch body: {e}")
        out = []
        for dq in queries:
            pk = dq.get("partitionKey")
            if pk is None:
                raise BadRequestError("delete missing partitionKey")
            if dq.get("singleItem"):
                sk = dq.get("start") or ""
                item = await self._get_item(bucket_id, pk, sk)
                n = 0
                if item is not None and item.live_values():
                    await self.garage.k2v_rpc.insert(
                        bucket_id, pk, sk, item.causal_context(), None
                    )
                    n = 1
                out.append({"partitionKey": pk, "singleItem": True, "deletedItems": n})
            else:
                # Walk the WHOLE range (the reference reads it unbounded,
                # batch.rs:209-220) in raw pages: filter="any" + post-merge
                # liveness so a lagging replica can't resurrect deleted
                # items (see _search), and only LIVE items are tombstoned —
                # re-killing tombstones would make deletedItems never
                # converge to zero.  Each page's kills go out as ONE
                # batched insert (a sequential per-item quorum insert makes
                # a 1000-item range delete take minutes).
                end = dq.get("end")
                prefix = dq.get("prefix")
                start = dq.get("start")
                n = 0
                while True:
                    items = await self.garage.k2v_item_table.get_range(
                        (bytes(bucket_id), pk), start, filter="any",
                        limit=1000,
                    )
                    doomed = [
                        (pk, i.sort_key_str, i.causal_context(), None)
                        for i in items
                        if i.live_values()
                        and not (prefix
                                 and not i.sort_key_str.startswith(prefix))
                        and not (end is not None and i.sort_key_str >= end)
                    ]
                    if doomed:
                        await self.garage.k2v_rpc.insert_many(
                            bucket_id, doomed)
                        n += len(doomed)
                    if len(items) < 1000:
                        break
                    last = items[-1].sort_key_str
                    if end is not None and last >= end:
                        break
                    start = last + "\x00"
                out.append({"partitionKey": pk, "singleItem": False,
                            "deletedItems": n})
        return web.json_response(out)

    # --- poll range (ref api/k2v/range.rs + k2v/seen.rs) ---

    async def poll_range(self, bucket_id, pk, request) -> web.Response:
        try:
            body = json.loads(await request.read() or b"{}")
        except ValueError as e:
            raise BadRequestError(f"malformed PollRange body: {e}")
        timeout = parse_poll_timeout(body.get("timeout", 300))
        prefix = body.get("prefix")
        start = body.get("start")
        end = body.get("end")
        seen = body.get("seenMarker")
        # seen marker = {sort_key: causality token} of what the client saw
        seen_map = {}
        if seen:
            try:
                seen_map = {
                    k: CausalContext.parse(v)
                    for k, v in json.loads(
                        base64.urlsafe_b64decode(seen.encode()).decode()
                    ).items()
                }
            except Exception:
                raise BadRequestError("invalid seenMarker")

        def matches(i):
            if prefix and not i.sort_key_str.startswith(prefix):
                return False
            if start is not None and i.sort_key_str < start:
                return False
            if end is not None and i.sort_key_str >= end:
                return False
            return True

        def is_new(i):
            old = seen_map.get(i.sort_key_str)
            return old is None or i.causal_context().is_newer_than(old)

        subs = self.garage.k2v_subscriptions
        q = subs.subscribe_range(bucket_id, pk)
        try:
            items = await self.garage.k2v_item_table.get_range(
                (bytes(bucket_id), pk), start, filter="any", limit=1000,
            )
            fresh = [i for i in items if matches(i) and is_new(i)]
            if not fresh:
                import time as _time

                # park the admission slot for the wait (same rationale as
                # poll_item: a parked poller must not starve the gate)
                request["slo_client_paced"] = True
                token = request.get("admission_token")
                if token is not None:
                    token.park()
                try:
                    deadline = _time.monotonic() + timeout
                    while not fresh:
                        remain = deadline - _time.monotonic()
                        if remain <= 0:
                            return web.Response(status=304)
                        try:
                            import asyncio as _asyncio

                            cand = await _asyncio.wait_for(
                                q.get(), timeout=remain)
                        except Exception:
                            return web.Response(status=304)
                        if matches(cand) and is_new(cand):
                            fresh = [cand]
                finally:
                    if token is not None:
                        token.unpark()
            for i in fresh:
                seen_map[i.sort_key_str] = i.causal_context()
            marker = base64.urlsafe_b64encode(json.dumps({
                k: v.serialize() for k, v in seen_map.items()
            }).encode()).decode()
            return web.json_response({
                "items": [
                    {
                        "sk": i.sort_key_str,
                        "ct": i.causal_context().serialize(),
                        "v": [
                            base64.b64encode(v).decode() if v is not None else None
                            for v in i.values()
                        ],
                    }
                    for i in fresh
                ],
                "seenMarker": marker,
            })
        finally:
            subs.unsubscribe_range(bucket_id, pk, q)
