"""AdmissionGate — bounded, tenant-fair in-flight work at the API front door.

Past saturation a storage node has exactly two choices per new request:
queue it (converting overload into a timeout storm — every queued
request ages toward its client's deadline while making every other
request slower) or shed it immediately with a typed, retryable answer.
Garage answers 503 SlowDown; so do we, at the earliest possible point —
before signature verification, before the request trace, before a byte
of body is read — with correct S3 error XML, a RequestId (minted here,
since the shed happens before request_trace runs) and a Retry-After
hint derived from live load, not a constant.

On top of the PR-10 watermarks the gate is now a multi-tenant QoS layer
(docs/ROBUSTNESS.md "Multi-tenant fairness & noisy neighbors"):

  - requests are CLASSIFIED by access key (fallback: bucket, then
    "anon") into per-tenant accounting.  While the gate is contended, a
    tenant already holding at least its fair share (limit / active
    tenants) is shed typed — per-tenant, never gate-wide — so one
    abusive tenant can exhaust only its own share.
  - under-share tenants whose request finds the gate full wait in a
    BOUNDED per-tenant queue and are dispatched by weighted deficit
    round-robin with byte-sized deficits (cost = declared body bytes +
    a per-request base cost): released capacity interleaves tenants
    fairly instead of draining whoever queued first.  The wait itself
    is bounded (`tenant_queue_wait`); a waiter whose turn never comes
    sheds typed rather than aging toward its client's timeout.
  - CLUSTER-AWARE admission: the caller folds the max gossiped
    `governor_pressure` of the layout nodes the request must touch
    (RemotePressureProbe below) into the admit decision, so a gateway
    sheds at the front door on behalf of a saturated storage node
    instead of forwarding doomed work three hops (verdict
    `remote_pressure`).
  - CoDel-style ADAPTIVE watermark: the effective in-flight limit is
    derived from admitted-latency drift — sojourn above `codel_target`
    for a full `codel_interval` tightens the limit, sustained sojourn
    below it relaxes back toward the configured `max_inflight` ceiling.
  - requests with no Content-Length (chunked/streaming PUTs) are
    admitted against a conservative `streaming_body_estimate` claim and
    RECONCILED to actual bytes as the body streams (AdmissionToken
    note_body_bytes/body_done), so they no longer bypass the bytes
    watermark.
  - K2V long-polls park their slot while waiting (token.park/unpark →
    a separate long-poll pool, `api_longpoll_parked`), so N pollers
    cannot brown out PUT/GET admission for their full poll window.

Admission is still checked ONCE at intake: an admitted request is never
shed mid-flight, so streaming bodies always run to completion; the
token is released when the handler finishes, transfer included.

Single-threaded by construction (the aiohttp handlers run on one event
loop), so the counters need no locks.
"""

from __future__ import annotations

import asyncio
import math
import re
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from ..utils.overload import OverloadTunables

__all__ = ["AdmissionGate", "AdmissionToken", "RemotePressureProbe",
           "classify_tenant"]


# access key id out of a SigV4 Authorization header / presigned query —
# a cheap string parse, NO verification: classification only picks which
# queue a request waits in, so a forged key id merely moves the forger
# into a different (empty) queue.  Auth still happens after admission.
_CRED_RE = re.compile(r"Credential=([A-Za-z0-9._-]{1,64})/")


def classify_tenant(request, bucket: Optional[str] = None) -> str:
    """Tenant id for QoS accounting: the access key id from the
    Authorization header (or presigned X-Amz-Credential), falling back
    to the bucket for unsigned requests, then "anon".  `bucket` is the
    caller's already-parsed bucket (vhost-aware — for a vhost-style
    request the first PATH segment is the object key, not the bucket);
    without it the first path segment is used.  Pure string work — runs
    before signature verification."""
    auth = request.headers.get("Authorization", "")
    m = _CRED_RE.search(auth)
    if m:
        return m.group(1)
    try:
        cred = request.query.get("X-Amz-Credential")
    except Exception:  # noqa: BLE001 — fake requests without .query
        cred = None
    if cred:
        return cred.split("/", 1)[0][:64]
    if bucket:
        return "bucket:" + bucket[:64]
    path = getattr(request, "path", "") or ""
    seg = path.lstrip("/").split("/", 1)[0]
    if seg:
        return "bucket:" + seg[:64]
    return "anon"


class _Tenant:
    """Per-tenant accounting + the WDRR queue."""

    __slots__ = ("name", "inflight", "inflight_bytes", "deficit",
                 "queue", "parked", "admitted_total", "shed_total")

    def __init__(self, name: str):
        self.name = name
        self.inflight = 0
        self.inflight_bytes = 0
        self.deficit = 0          # WDRR byte deficit
        self.queue: deque = deque()
        self.parked = 0           # long-polls parked outside the watermark
        self.admitted_total = 0
        self.shed_total = 0

    def idle(self) -> bool:
        # a parked long-poll is LIVE state: evicting its tenant at the
        # cardinality cap would split accounting across two objects
        return self.inflight == 0 and self.parked == 0 and not self.queue


class _Waiter:
    __slots__ = ("future", "nbytes", "cost", "estimated", "t0")

    def __init__(self, future, nbytes: int, cost: int, estimated: bool,
                 t0: float):
        self.future = future
        self.nbytes = nbytes
        self.cost = cost
        self.estimated = estimated
        self.t0 = t0


# uploads bigger than this are excluded from the CoDel control law:
# their duration is dominated by the client-paced body transfer, the
# same "client-chosen duration" class as long-polls — feeding it in
# would let a healthy large-object workload strangle the limit
_CODEL_MAX_BYTES = 1 << 20


class AdmissionToken:
    """One admitted request's claim on the gate; release exactly once
    (idempotent — a finally block racing an explicit release is fine)."""

    __slots__ = ("_gate", "_tenant", "nbytes", "_released", "_parked",
                 "_estimated", "_observed", "_sojourn_excluded", "_t0",
                 "_t_body")

    def __init__(self, gate: "AdmissionGate", tenant: _Tenant, nbytes: int,
                 estimated: bool = False):
        self._gate = gate
        self._tenant = tenant
        self.nbytes = nbytes          # bytes currently accounted
        self._released = False
        self._parked = False
        self._estimated = estimated
        self._observed = 0
        self._sojourn_excluded = False
        self._t0 = gate.clock()
        self._t_body: Optional[float] = None

    def exclude_sojourn(self) -> None:
        """Keep this request out of the CoDel law: its duration is
        client-paced (streamed GET response, long-poll), not service
        latency.  Called by the streaming handlers."""
        self._sojourn_excluded = True

    def service_latency(self) -> Optional[float]:
        """Server-side service seconds so far, for the latency SLO
        (utils/slo.py) — None when this request's duration is
        client-paced (streamed response / long-poll: the client's drain
        pace must not burn the latency budget, exactly the CoDel
        exclusion).  Uploads anchor at body completion (`body_done`),
        like the CoDel sojourn, so a trickled body measures only its
        post-body service time."""
        if self._sojourn_excluded:
            return None
        start = self._t_body if self._t_body is not None else self._t0
        return self._gate.clock() - start

    def body_anchored(self) -> bool:
        """True once ``body_done`` stamped the post-body anchor — the
        only case where ``service_latency`` is a BETTER latency-SLO
        measurement than intake-to-completion (it subtracts the
        client-paced body transfer).  For everything else the intake
        anchor wins: it includes the admission queue wait, which is
        server-side latency and must burn the budget."""
        return self._t_body is not None

    # --- byte reconciliation (Content-Length-less bodies) ---------------

    def note_body_bytes(self, n: int) -> None:
        """Body bytes observed streaming in: an estimate-admitted
        request that turns out BIGGER than its claim grows its
        accounting live, so a storm of undeclared huge uploads cannot
        hide from the bytes watermark behind one conservative guess."""
        if not self._estimated or self._released:
            return
        self._observed += n
        if self._observed > self.nbytes and not self._parked:
            delta = self._observed - self.nbytes
            self._gate._inflight_bytes += delta
            self._tenant.inflight_bytes += delta
            self.nbytes = self._observed

    def body_done(self) -> None:
        """Body fully streamed.  Marks the sojourn anchor — CoDel then
        measures admit->release MINUS the body transfer, i.e. the
        server-side service latency, so a client trickling a small body
        over many seconds cannot feed its own pace into the adaptive
        watermark.  Also reconciles an estimate-admitted claim DOWN to
        the actual size so the unused claim stops blocking admits."""
        if self._released:
            return
        self._t_body = self._gate.clock()
        if not self._estimated:
            return
        self._estimated = False
        if self._observed < self.nbytes and not self._parked:
            delta = self.nbytes - self._observed
            self._gate._inflight_bytes -= delta
            self._tenant.inflight_bytes -= delta
            self.nbytes = self._observed
            self._gate._dispatch()

    # --- long-poll parking ----------------------------------------------

    def park(self) -> None:
        """Release this request's slot while it sits in a long poll: the
        parked request moves to a separate BOUNDED pool
        (`api_longpoll_parked`) so pollers do not starve the in-flight
        watermark for up to their whole poll window.  When the pool is
        full the poll simply KEEPS its admission slot — total poll
        concurrency stays bounded by the gate either way; an uncapped
        pool would let one tenant hold unbounded 600 s polls."""
        if self._released or self._parked:
            return
        g = self._gate
        cap = g._longpoll_cap()
        if cap and g._parked >= cap:
            self._sojourn_excluded = True
            return
        self._parked = True
        self._sojourn_excluded = True
        g._inflight -= 1
        g._inflight_bytes -= self.nbytes
        g._parked += 1
        self._tenant.inflight -= 1
        self._tenant.inflight_bytes -= self.nbytes
        self._tenant.parked += 1
        g._dispatch()

    def unpark(self) -> None:
        """Re-acquire after the poll wakes.  Deliberately unconditional:
        an admitted request is never shed mid-flight, so re-entry may
        transiently exceed the watermark while the (cheap) response is
        written — the alternative is a parked poller that can never
        answer on a hot gate."""
        if self._released or not self._parked:
            return
        self._parked = False
        g = self._gate
        g._parked -= 1
        g._inflight += 1
        g._inflight_bytes += self.nbytes
        self._tenant.inflight += 1
        self._tenant.inflight_bytes += self.nbytes
        self._tenant.parked -= 1

    # --- release ---------------------------------------------------------

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        g = self._gate
        if self._parked:
            self._parked = False
            g._parked -= 1
            self._tenant.parked -= 1
        else:
            g._inflight -= 1
            g._inflight_bytes -= self.nbytes
            self._tenant.inflight -= 1
            self._tenant.inflight_bytes -= self.nbytes
        # admitted-latency drift feeds the adaptive watermark — but a
        # long-poll's (or streamed transfer's) sojourn is the CLIENT's
        # chosen duration, not service latency; folding those in would
        # let a healthy slow-client workload strangle the limit.  For
        # uploads the anchor is body completion (body_done), so a
        # trickled body measures only its post-body service time.
        if not self._sojourn_excluded and self.nbytes <= _CODEL_MAX_BYTES:
            start = self._t_body if self._t_body is not None else self._t0
            g._note_sojourn(g.clock() - start)
        g._gc_tenant(self._tenant)
        g._dispatch()


class AdmissionGate:
    def __init__(self, tun: Optional[OverloadTunables] = None, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.tun = tun or OverloadTunables()
        self.clock = clock
        self._inflight = 0
        self._inflight_bytes = 0
        self._parked = 0
        self._waiters_total = 0
        self.admitted_total = 0
        self.shed_total = 0
        self._tenants: Dict[str, _Tenant] = {}
        self._shed_series: set = set()  # tenant labels minted in metrics
        self._rr = 0                  # WDRR round-robin start offset
        # CoDel adaptive watermark state
        self._limit = self.tun.max_inflight
        self._above_since: Optional[float] = None
        self._last_relax = clock()
        # optional live-load input for the Retry-After hint (wired to
        # LoadGovernor.pressure by model/garage.py)
        self.pressure_fn: Optional[Callable[[], float]] = None
        if metrics is not None:
            metrics.gauge(
                "api_inflight_requests",
                "Client requests currently admitted and in flight "
                "(admission-gate occupancy numerator)",
                fn=lambda: float(self._inflight))
            self.m_admission = metrics.counter(
                "api_admission_total",
                "Admission-gate verdicts at the API front door (verdict = "
                "admit | shed | over_share | queue_full | queue_timeout | "
                "remote_pressure)")
            metrics.gauge(
                "api_admission_limit",
                "Effective in-flight request limit (CoDel-adaptive, "
                "bounded by the configured max_inflight ceiling; 0 = "
                "unlimited)",
                fn=lambda: float(self.limit))
            metrics.gauge(
                "api_admission_queue_depth",
                "Requests parked in per-tenant WDRR admission queues",
                fn=lambda: float(self._waiters_total))
            metrics.gauge(
                "api_longpoll_parked",
                "Admitted long-poll requests currently parked outside "
                "the in-flight watermark",
                fn=lambda: float(self._parked))
            metrics.gauge(
                "api_tenant_inflight",
                "Admitted in-flight requests per tenant (access key or "
                "bucket fallback)",
                labeled_fn=lambda: [
                    ({"tenant": te.name}, float(te.inflight))
                    for te in self._tenants.values() if te.inflight
                ])
            self.m_tenant_shed = metrics.counter(
                "api_tenant_shed_total",
                "Requests shed per tenant at the admission gate (all "
                "shed verdicts)")
            self.m_queue_wait = metrics.histogram(
                "api_admission_queue_wait_seconds",
                "Time requests waited in the WDRR admission queue "
                "(outcome = admitted | timeout)")
        else:
            self.m_admission = None
            self.m_tenant_shed = None
            self.m_queue_wait = None

    # --- tenant bookkeeping ----------------------------------------------

    def _tenant(self, name: str) -> _Tenant:
        te = self._tenants.get(name)
        if te is None:
            # metric-cardinality bound: tenant ids come from
            # client-controlled headers, so past the cap newcomers share
            # one overflow bucket instead of minting unbounded series
            if len(self._tenants) >= max(self.tun.max_tracked_tenants, 1):
                for cand, known in list(self._tenants.items()):
                    if known.idle():
                        del self._tenants[cand]
                        break
                else:
                    return self._tenants.setdefault(
                        "~overflow", _Tenant("~overflow"))
            te = _Tenant(name)
            self._tenants[name] = te
        return te

    def _gc_tenant(self, te: _Tenant) -> None:
        # drop idle tenants so the dict (and the labelled gauge) tracks
        # the live population, not every key ever seen
        if te.idle() and self._tenants.get(te.name) is te:
            del self._tenants[te.name]

    def _active_tenants(self) -> int:
        return sum(1 for te in self._tenants.values() if not te.idle())

    def _fair_share(self, te: _Tenant) -> int:
        """This tenant's fair slice of the in-flight limit while the
        gate is contended: limit / active tenants (the requester counts
        as active even before its first admit), at least 1."""
        limit = self.limit
        if not limit:
            return 1 << 30
        active = self._active_tenants()
        if te.idle():
            active += 1
        return max(1, math.ceil(limit / max(active, 1)))

    # --- CoDel adaptive watermark ----------------------------------------

    @property
    def limit(self) -> int:
        """Effective in-flight limit: the configured ceiling, tightened
        by admitted-latency drift when CoDel is enabled.  0 = unlimited."""
        ceiling = self.tun.max_inflight
        if not ceiling or self.tun.codel_target <= 0:
            return ceiling
        return min(self._limit, ceiling)

    def _codel_floor(self) -> int:
        return max(1, self.tun.max_inflight // 8)

    def _longpoll_cap(self) -> int:
        """Parked-pool bound: configured, else 4x the inflight ceiling
        (0 only when both are unlimited)."""
        if self.tun.longpoll_max_parked:
            return self.tun.longpoll_max_parked
        return 4 * self.tun.max_inflight

    def _note_sojourn(self, sojourn: float) -> None:
        """CoDel control law on admitted-request latency: persistently
        above target for an interval → tighten the limit; persistently
        below → relax back toward the configured ceiling."""
        tun = self.tun
        if tun.codel_target <= 0 or not tun.max_inflight:
            return
        now = self.clock()
        self._limit = min(self._limit, tun.max_inflight)
        if sojourn > tun.codel_target:
            if self._above_since is None:
                self._above_since = now
            elif now - self._above_since >= tun.codel_interval:
                self._limit = max(self._codel_floor(),
                                  min(self._limit - 1,
                                      int(self._limit * 0.9)))
                self._above_since = now
                self._last_relax = now
        else:
            self._above_since = None
            if (self._limit < tun.max_inflight
                    and now - self._last_relax >= tun.codel_interval):
                self._limit = min(tun.max_inflight,
                                  self._limit
                                  + max(1, tun.max_inflight // 10))
                self._last_relax = now

    # --- the gate ---------------------------------------------------------

    def _capacity_free(self, nbytes: int) -> bool:
        limit = self.limit
        if limit and self._inflight >= limit:
            return False
        t = self.tun
        if (t.max_inflight_bytes and self._inflight > 0
                and self._inflight_bytes + nbytes > t.max_inflight_bytes):
            return False
        return True

    def _admit_now(self, te: _Tenant, nbytes: int,
                   estimated: bool = False) -> AdmissionToken:
        self._inflight += 1
        self._inflight_bytes += nbytes
        te.inflight += 1
        te.inflight_bytes += nbytes
        te.admitted_total += 1
        self.admitted_total += 1
        if self.m_admission is not None:
            self.m_admission.inc(verdict="admit")
        return AdmissionToken(self, te, nbytes, estimated=estimated)

    def _shed(self, te: Optional[_Tenant], verdict: str) -> None:
        self.shed_total += 1
        if te is not None:
            te.shed_total += 1
            if self.m_tenant_shed is not None:
                # counter series are immortal, so the cardinality bound
                # must hold over every tenant name EVER shed, not just
                # the live dict (which GCs idle tenants immediately):
                # forged rotating key ids collapse into ~overflow
                label = te.name
                if label not in self._shed_series:
                    if (len(self._shed_series)
                            >= max(self.tun.max_tracked_tenants, 1)):
                        label = "~overflow"
                    else:
                        self._shed_series.add(label)
                self.m_tenant_shed.inc(tenant=label)
            self._gc_tenant(te)
        if self.m_admission is not None:
            self.m_admission.inc(verdict=verdict)

    def try_admit(self, nbytes: int = 0,
                  tenant: str = "anon") -> Optional[AdmissionToken]:
        """Synchronous fast path (legacy PR-10 semantics): admit when the
        watermarks allow and nobody is queued, shed otherwise.  Watermark
        0 = unlimited.  The bytes watermark never sheds when the gate is
        empty — one over-sized request must degrade to "admitted alone",
        not to a permanently unservable request class."""
        te = self._tenant(tenant)
        if self._waiters_total == 0 and self._capacity_free(nbytes):
            return self._admit_now(te, nbytes)
        self._shed(te, "shed")
        return None

    async def admit(self, nbytes: int = 0, tenant: str = "anon",
                    remote_pressure: float = 0.0,
                    estimated: bool = False,
                    ) -> Tuple[Optional[AdmissionToken], str]:
        """Full tenant-fair admission → (token, verdict).  token None
        means shed; verdict names why (`remote_pressure`, `over_share`,
        `queue_full`, `queue_timeout`).  An under-share tenant that
        finds the gate contended waits in its bounded queue and is
        dispatched by WDRR as capacity frees."""
        tun = self.tun
        # cluster-aware shed BEFORE any local accounting: the layout
        # nodes this request must touch are saturated, so forwarding is
        # doomed work — shed on their behalf at the front door
        if (tun.remote_pressure_shed > 0
                and remote_pressure >= tun.remote_pressure_shed):
            self._shed(self._tenant(tenant), "remote_pressure")
            return None, "remote_pressure"
        te = self._tenant(tenant)
        if self._waiters_total == 0 and self._capacity_free(nbytes):
            return self._admit_now(te, nbytes, estimated=estimated), "admit"
        # contended: a tenant at/over its fair share is shed typed — the
        # per-tenant isolation invariant (never gate-wide).  Parked
        # long-polls count as usage here: a tenant hogging the parked
        # pool must not ALSO claim fresh slots while others queue.
        if self.limit and te.inflight + te.parked >= self._fair_share(te):
            self._shed(te, "over_share")
            return None, "over_share"
        if len(te.queue) >= max(tun.tenant_queue_len, 1):
            self._shed(te, "queue_full")
            return None, "queue_full"
        fut = asyncio.get_running_loop().create_future()
        w = _Waiter(fut, nbytes,
                    nbytes + max(tun.wdrr_request_cost, 1),
                    estimated, self.clock())
        te.queue.append(w)
        self._waiters_total += 1
        self._dispatch()
        # the queue wait SPENDS the request's deadline budget (armed by
        # the API servers before admission): a 0.5 s budget must bound
        # the whole server-side latency, queueing included — never add
        # tenant_queue_wait on top of it
        from ..utils.tracing import remaining_budget

        wait = max(tun.tenant_queue_wait, 0.001)
        rem = remaining_budget()
        if rem is not None:
            wait = min(wait, max(rem, 0.001))
        try:
            if not fut.done():
                await asyncio.wait({fut}, timeout=wait)
        except asyncio.CancelledError:
            # the client gave up while we were queued — but _dispatch may
            # have fulfilled the future in the same window: that token
            # already holds a slot and nobody else will release it
            if fut.done() and not fut.cancelled():
                fut.result().release()
            else:
                self._discard_waiter(te, w)
            raise
        if fut.done() and not fut.cancelled():
            if self.m_queue_wait is not None:
                self.m_queue_wait.observe(self.clock() - w.t0,
                                          outcome="admitted")
            return fut.result(), "admit"
        # our turn never came within the bounded wait: shed typed
        # instead of aging toward the client's timeout
        self._discard_waiter(te, w)
        if self.m_queue_wait is not None:
            self.m_queue_wait.observe(self.clock() - w.t0, outcome="timeout")
        self._shed(te, "queue_timeout")
        return None, "queue_timeout"

    def _discard_waiter(self, te: _Tenant, w: _Waiter) -> None:
        try:
            te.queue.remove(w)
            self._waiters_total -= 1
        except ValueError:
            pass                      # already dispatched
        w.future.cancel()

    def _dispatch(self) -> None:
        """WDRR over the tenants with queued waiters: each visited
        tenant's deficit grows by the quantum (clamped so an idle wait
        cannot bank unbounded credit) and its queue head is served while
        the deficit covers the request's byte cost and capacity is free.
        Serving order rotates so no tenant owns the first visit."""
        if not self._waiters_total:
            return
        quantum = max(self.tun.wdrr_quantum_bytes, 1)
        while True:
            served = False
            starved: list = []        # (tenant, head) blocked on deficit only
            names = [n for n, te in self._tenants.items() if te.queue]
            if not names:
                break
            r = self._rr % len(names)
            for name in names[r:] + names[:r]:
                te = self._tenants.get(name)
                if te is None:
                    continue
                # drop waiters whose clients already gave up
                while te.queue and (te.queue[0].future.cancelled()
                                    or te.queue[0].future.done()):
                    te.queue.popleft()
                    self._waiters_total -= 1
                if not te.queue:
                    te.deficit = 0
                    continue
                # the deficit grows only on a genuine SERVICE OPPORTUNITY
                # (capacity available for this head): a full gate must
                # not bank credit for whoever enqueued first, or byte
                # weighting degenerates into FIFO
                if not self._capacity_free(te.queue[0].nbytes):
                    continue
                te.deficit = min(te.deficit + quantum,
                                 te.queue[0].cost + quantum)
                while te.queue:
                    w = te.queue[0]
                    if w.future.cancelled() or w.future.done():
                        te.queue.popleft()
                        self._waiters_total -= 1
                        continue
                    if w.cost > te.deficit or not self._capacity_free(
                            w.nbytes):
                        break
                    te.queue.popleft()
                    self._waiters_total -= 1
                    te.deficit -= w.cost
                    w.future.set_result(self._admit_now(
                        te, w.nbytes, estimated=w.estimated))
                    served = True
                if not te.queue:
                    te.deficit = 0
                elif self._capacity_free(te.queue[0].nbytes):
                    # capacity is free but this head still lacks deficit:
                    # more WDRR rounds will grow it — stopping here would
                    # strand a big request behind free capacity forever
                    starved.append((te, te.queue[0]))
            self._rr += 1
            if not served:
                if not starved:
                    break
                # every remaining eligible head is blocked on deficit
                # alone, and nothing changes between such rounds — so
                # fast-forward the k identical rounds it would take the
                # closest head to afford service, in one step (crediting
                # k quanta to EVERY starved tenant keeps the round-by-
                # round ordering exactly), instead of spinning
                # O(cost/quantum) synchronous loop iterations on the
                # event loop for one large body
                k = max(1, min(
                    -(-(h.cost - te.deficit) // quantum)
                    for te, h in starved))
                for te, h in starved:
                    te.deficit = min(te.deficit + k * quantum,
                                     h.cost + quantum)

    # --- shed backoff hint -----------------------------------------------

    def retry_after_hint(self) -> int:
        """Retry-After seconds derived from live load — governor
        pressure (when wired) or gate occupancy, plus queued depth — so
        client backoff tracks actual saturation instead of a constant;
        clamped to [retry_after, retry_after_max]."""
        base = max(int(self.tun.retry_after), 1)
        load = self.occupancy()
        if self.pressure_fn is not None:
            try:
                load = max(load, float(self.pressure_fn()))
            except Exception:  # noqa: BLE001 — a dead signal is no signal
                pass
        limit = self.limit or 64
        hint = base + int(base * 2 * min(load, 2.0)) \
            + self._waiters_total // max(limit, 1)
        return max(base, min(hint, max(self.tun.retry_after_max, base)))

    # --- introspection (governor signal + admin API) ----------------------

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def inflight_bytes(self) -> int:
        return self._inflight_bytes

    @property
    def longpoll_parked(self) -> int:
        return self._parked

    def occupancy(self) -> float:
        """Gate fullness in [0, 1] — the load governor's primary
        foreground-pressure signal.  Max of the two watermark ratios
        (against the EFFECTIVE, CoDel-adjusted limit); 0 when both
        watermarks are disabled."""
        t = self.tun
        occ = 0.0
        limit = self.limit
        if limit:
            occ = self._inflight / limit
        if t.max_inflight_bytes:
            occ = max(occ, self._inflight_bytes / t.max_inflight_bytes)
        return occ

    def tenant_stats(self) -> dict:
        return {
            te.name: {
                "inflight": te.inflight,
                "inflight_bytes": te.inflight_bytes,
                "queued": len(te.queue),
                "admitted_total": te.admitted_total,
                "shed_total": te.shed_total,
            }
            for te in self._tenants.values()
        }

    def stats(self) -> dict:
        return {
            "inflight": self._inflight,
            "inflight_bytes": self._inflight_bytes,
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "max_inflight": self.tun.max_inflight,
            "max_inflight_bytes": self.tun.max_inflight_bytes,
            "effective_limit": self.limit,
            "queued": self._waiters_total,
            "longpoll_parked": self._parked,
            "tenants": self._active_tenants(),
        }


class RemotePressureProbe:
    """Bucket name → the max gossiped `governor_pressure` of the layout
    nodes that bucket's metadata partition lives on.

    The gateway cannot know a bucket's id before authentication resolves
    it, so the probe keeps a small name → id cache populated by the
    dispatch path after each successful resolve; the FIRST request for a
    bucket pays no remote check, every later one folds the gossiped
    pressure of its placement nodes into admission — cheap (a dict get
    plus a ring lookup), before any signature/body work."""

    def __init__(self, system, cache_max: int = 4096):
        self.system = system
        self.cache_max = cache_max
        self._ids: Dict[str, bytes] = {}

    def note_bucket(self, name: str, bucket_id) -> None:
        bid = bytes(bucket_id)
        if self._ids.get(name) == bid:
            return
        # overwrite on a changed id: a bucket deleted and recreated
        # under the same name moves to a new placement — keeping the
        # stale mapping would shed for the wrong nodes forever
        if name not in self._ids and len(self._ids) >= self.cache_max:
            self._ids.pop(next(iter(self._ids)))
        self._ids[name] = bid

    def pressure(self, bucket_name: Optional[str]) -> Tuple[float, str]:
        """→ (max remote pressure, hex id of the hottest node); (0, "")
        when the bucket is unknown or no peer has gossiped pressure."""
        if not bucket_name:
            return 0.0, ""
        bid = self._ids.get(bucket_name)
        if bid is None:
            return 0.0, ""
        sys_ = self.system
        try:
            nodes = sys_.ring.get_nodes(
                bid, sys_.replication_mode.replication_factor)
        except Exception:  # noqa: BLE001 — ring not ready yet
            return 0.0, ""
        worst, who = 0.0, ""
        for n in nodes:
            if bytes(n) == bytes(sys_.id):
                continue              # local pressure is the local gate's job
            p = sys_.peer_pressure(n)
            if p > worst:
                worst, who = p, bytes(n).hex()[:16]
        return worst, who
