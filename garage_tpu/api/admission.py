"""AdmissionGate — bounded in-flight work at the API front door.

Past saturation a storage node has exactly two choices per new request:
queue it (converting overload into a timeout storm — every queued
request ages toward its client's deadline while making every other
request slower) or shed it immediately with a typed, retryable answer.
Garage answers 503 SlowDown; so do we, at the earliest possible point —
before signature verification, before the request trace, before a byte
of body is read — with correct S3 error XML, a RequestId (minted here,
since the shed happens before request_trace runs) and a Retry-After
hint.

The gate bounds two things: concurrent requests (``max_inflight``) and
committed request-body bytes (``max_inflight_bytes``, from the declared
Content-Length — the memory watermark).  Admission is checked ONCE at
intake: an admitted request is never shed mid-flight, so streaming
bodies (upload or download) always run to completion; the token is
released when the handler finishes, transfer included.

Single-threaded by construction (the aiohttp handlers run on one event
loop), so the counters need no locks.
"""

from __future__ import annotations

from typing import Optional

from ..utils.overload import OverloadTunables

__all__ = ["AdmissionGate", "AdmissionToken"]


class AdmissionToken:
    """One admitted request's claim on the gate; release exactly once
    (idempotent — a finally block racing an explicit release is fine)."""

    __slots__ = ("_gate", "nbytes", "_released")

    def __init__(self, gate: "AdmissionGate", nbytes: int):
        self._gate = gate
        self.nbytes = nbytes
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._gate._inflight -= 1
        self._gate._inflight_bytes -= self.nbytes


class AdmissionGate:
    def __init__(self, tun: Optional[OverloadTunables] = None, metrics=None):
        self.tun = tun or OverloadTunables()
        self._inflight = 0
        self._inflight_bytes = 0
        self.admitted_total = 0
        self.shed_total = 0
        if metrics is not None:
            metrics.gauge(
                "api_inflight_requests",
                "Client requests currently admitted and in flight "
                "(admission-gate occupancy numerator)",
                fn=lambda: float(self._inflight))
            self.m_admission = metrics.counter(
                "api_admission_total",
                "Admission-gate verdicts at the API front door "
                "(verdict = admit | shed)")
        else:
            self.m_admission = None

    # --- the gate ---------------------------------------------------------

    def try_admit(self, nbytes: int = 0) -> Optional[AdmissionToken]:
        """Admit (→ token, release when the request FULLY finishes) or
        shed (→ None; caller answers 503 SlowDown).  Watermark 0 =
        unlimited.  The bytes watermark never sheds when the gate is
        empty — one over-sized request must degrade to "admitted alone",
        not to a permanently unservable request class."""
        t = self.tun
        shed = False
        if t.max_inflight and self._inflight >= t.max_inflight:
            shed = True
        elif (t.max_inflight_bytes and self._inflight > 0
              and self._inflight_bytes + nbytes > t.max_inflight_bytes):
            shed = True
        if shed:
            self.shed_total += 1
            if self.m_admission is not None:
                self.m_admission.inc(verdict="shed")
            return None
        self._inflight += 1
        self._inflight_bytes += nbytes
        self.admitted_total += 1
        if self.m_admission is not None:
            self.m_admission.inc(verdict="admit")
        return AdmissionToken(self, nbytes)

    # --- introspection (governor signal + admin API) ----------------------

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def inflight_bytes(self) -> int:
        return self._inflight_bytes

    def occupancy(self) -> float:
        """Gate fullness in [0, 1] — the load governor's primary
        foreground-pressure signal.  Max of the two watermark ratios;
        0 when both watermarks are disabled."""
        t = self.tun
        occ = 0.0
        if t.max_inflight:
            occ = self._inflight / t.max_inflight
        if t.max_inflight_bytes:
            occ = max(occ, self._inflight_bytes / t.max_inflight_bytes)
        return occ

    def stats(self) -> dict:
        return {
            "inflight": self._inflight,
            "inflight_bytes": self._inflight_bytes,
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "max_inflight": self.tun.max_inflight,
            "max_inflight_bytes": self.tun.max_inflight_bytes,
        }
