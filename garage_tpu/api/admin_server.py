"""Admin HTTP API — health, metrics, cluster/bucket/key REST.

Equivalent of reference src/api/admin/ (SURVEY.md §2.7): `/health` (no
auth), `/metrics` (Prometheus text format, guarded by the metrics token),
and the v1 REST endpoints for status/layout/buckets/keys guarded by the
admin token (api_server.rs:32-60,271-335).
"""

from __future__ import annotations

import hmac
import json
import logging
import time
from typing import Optional

from aiohttp import web

from .common import start_site

logger = logging.getLogger("garage_tpu.api.admin")


def metrics_body(garage, openmetrics: bool = False) -> str:
    """The full Prometheus exposition for one node: the ad-hoc cluster
    gauges + the refreshed registry.  Module-level so the metrics-docs
    lint (tests + smoke) checks exactly what /metrics serves."""
    lines = []

    def gauge(name, value, help_=""):
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")

    h = garage.system.health()
    gauge("cluster_healthy", 1 if h.status == "healthy" else 0)
    gauge("cluster_available", 1 if h.status != "unavailable" else 0)
    gauge("cluster_connected_nodes", h.connected_nodes)
    gauge("cluster_known_nodes", h.known_nodes)
    # refresh scrape-time observed gauges (per-table backlogs, the
    # per-worker status registry, per-peer health), then render the
    # registry that the rpc/table/block/api layers record into.
    # Each subsystem's sweep is timed (metrics_gauge_sweep_seconds):
    # the ROADMAP 128-node wall is exactly these sweeps growing with
    # the fleet, so the scrape's self-cost must be a datapoint.
    reg = garage.system.metrics
    sweep_g = reg.gauge(
        "metrics_gauge_sweep_seconds",
        "Scrape-time gauge sweep cost per subsystem (last scrape)")
    render_g = reg.gauge(
        "metrics_render_seconds",
        "Wall time of the previous /metrics registry render")

    def timed_sweep(subsystem, fn):
        t0 = time.perf_counter()
        try:
            fn()
        finally:
            sweep_g.set(time.perf_counter() - t0, subsystem=subsystem)

    timed_sweep("tables", lambda: [t.observe_gauges()
                                   for t in garage.tables])
    timed_sweep("workers", lambda: garage.bg.observe_gauges(reg))
    timed_sweep("peering",
                lambda: garage.system.peering.observe_gauges())
    # the render gauge necessarily reports the PREVIOUS scrape's render
    # cost: its own value must land inside the body it measures
    t0 = time.perf_counter()
    body = reg.render(openmetrics=openmetrics)
    render_g.set(time.perf_counter() - t0)
    return "\n".join(lines) + "\n" + body


class AdminApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.helper = garage.helper()
        self._runner: Optional[web.AppRunner] = None
        # v1 endpoints share their implementations with the CLI's admin
        # RPC handler (one semantics for both operator surfaces)
        from ..admin.handler import AdminRpcHandler

        self._rpc = AdminRpcHandler(garage, register_endpoint=False)

    async def start(self, bind_addr: str) -> None:
        @web.middleware
        async def bad_request_guard(request, handler):
            """Malformed admin requests (missing required query params,
            invalid JSON bodies) render as 400 JSON, not bare 500s."""
            from ..utils.error import GarageError

            try:
                return await handler(request)
            except web.HTTPException:
                raise
            except (KeyError, ValueError) as e:  # incl. JSONDecodeError
                return web.json_response(
                    {"error": f"bad request: {e!r}"}, status=400
                )
            except GarageError as e:
                # domain errors raised by handlers that bypass _rpc_json
                # (e.g. NoSuchBucket from a direct helper call) must
                # render as JSON 400s like every other admin error
                return web.json_response({"error": str(e)}, status=400)

        app = web.Application(middlewares=[bad_request_guard])
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/v1/status", self.handle_status)
        app.router.add_get("/v1/health", self.handle_health_detailed)
        app.router.add_post("/v1/connect", self.handle_connect)
        app.router.add_post("/v1/layout", self.handle_layout_update)
        app.router.add_get("/v1/layout", self.handle_layout_get)
        app.router.add_post("/v1/layout/apply", self.handle_layout_apply)
        app.router.add_post("/v1/layout/revert", self.handle_layout_revert)
        app.router.add_get("/v1/bucket", self.handle_bucket_get)
        app.router.add_post("/v1/bucket", self.handle_bucket_create)
        app.router.add_delete("/v1/bucket", self.handle_bucket_delete)
        app.router.add_put("/v1/bucket", self.handle_bucket_update)
        app.router.add_post("/v1/bucket/allow", self.handle_bucket_allow)
        app.router.add_post("/v1/bucket/deny", self.handle_bucket_deny)
        app.router.add_put("/v1/bucket/alias/global", self.handle_alias_global)
        app.router.add_delete(
            "/v1/bucket/alias/global", self.handle_unalias_global)
        app.router.add_get("/v1/key", self.handle_key_get)
        app.router.add_post("/v1/key", self.handle_key_post)
        app.router.add_post("/v1/key/import", self.handle_key_import)
        app.router.add_delete("/v1/key", self.handle_key_delete)
        app.router.add_put("/v1/bucket/alias/local", self.handle_alias_local)
        app.router.add_delete(
            "/v1/bucket/alias/local", self.handle_unalias_local)
        app.router.add_get("/check", self.handle_check_domain)
        app.router.add_get("/v1/timeline", self.handle_timeline)
        # v0 compat surface (ref api/admin/router_v0.rs:88-122): thin
        # aliases onto the v1 handlers — upstream v0 and v1 share their
        # request/response shapes for these routes (key.rs serves both);
        # the one behavioral difference is GetKeyInfo's secret default
        # (v0 always returned it; handle_key_get_v0 restores that).
        app.router.add_get("/v0/status", self.handle_status)
        app.router.add_get("/v0/health", self.handle_health_detailed)
        app.router.add_post("/v0/connect", self.handle_connect)
        app.router.add_get("/v0/layout", self.handle_layout_get)
        app.router.add_post("/v0/layout", self.handle_layout_update)
        app.router.add_post("/v0/layout/apply", self.handle_layout_apply)
        app.router.add_post("/v0/layout/revert", self.handle_layout_revert)
        app.router.add_get("/v0/bucket", self.handle_bucket_get)
        app.router.add_post("/v0/bucket", self.handle_bucket_create)
        app.router.add_delete("/v0/bucket", self.handle_bucket_delete)
        app.router.add_put("/v0/bucket", self.handle_bucket_update)
        app.router.add_post("/v0/bucket/allow", self.handle_bucket_allow)
        app.router.add_post("/v0/bucket/deny", self.handle_bucket_deny)
        app.router.add_put("/v0/bucket/alias/global", self.handle_alias_global)
        app.router.add_delete(
            "/v0/bucket/alias/global", self.handle_unalias_global)
        app.router.add_put("/v0/bucket/alias/local", self.handle_alias_local)
        app.router.add_delete(
            "/v0/bucket/alias/local", self.handle_unalias_local)
        app.router.add_get("/v0/key", self.handle_key_get_v0)
        app.router.add_post("/v0/key", self.handle_key_post)
        app.router.add_post("/v0/key/import", self.handle_key_import)
        app.router.add_delete("/v0/key", self.handle_key_delete)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = await start_site(self._runner, bind_addr)
        logger.info("Admin API listening on %s", bind_addr)

    @property
    def port(self) -> int:
        return self._site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # --- auth ---

    def _check_token(self, request: web.Request, token: Optional[str]) -> None:
        if token is None:
            raise web.HTTPForbidden(text="admin token not configured")
        auth = request.headers.get("Authorization", "")
        # compare bytes: compare_digest raises TypeError on non-ASCII str
        if not hmac.compare_digest(
            auth.encode("utf-8", "surrogateescape"),
            f"Bearer {token}".encode("utf-8", "surrogateescape"),
        ):
            raise web.HTTPForbidden(text="invalid bearer token")

    def _admin(self, request) -> None:
        self._check_token(request, self.garage.config.admin_token)

    # --- handlers ---

    async def handle_health(self, request) -> web.Response:
        """Quick liveness: 200 if we can serve quorum ops (ref
        api_server.rs /health)."""
        h = self.garage.system.health()
        status = 200 if h.status in ("healthy", "degraded") else 503
        return web.Response(status=status, text=h.status)

    async def handle_health_detailed(self, request) -> web.Response:
        self._admin(request)
        h = self.garage.system.health()
        return web.json_response({
            "status": h.status,
            "knownNodes": h.known_nodes,
            "connectedNodes": h.connected_nodes,
            "storageNodes": h.storage_nodes,
            "storageNodesOk": h.storage_nodes_ok,
            "partitions": h.partitions,
            "partitionsQuorum": h.partitions_quorum,
            "partitionsAllOk": h.partitions_all_ok,
        })

    async def handle_metrics(self, request) -> web.Response:
        """Prometheus exposition of every layer's metrics (ref
        api/admin/api_server.rs:271-335 + rpc/table/block/api metric
        structs).  `?exemplars=1` appends histogram exemplars — trace
        ids on max buckets — in the OpenMetrics suffix syntax.  This is
        an EXPLICIT opt-in only, never Accept-header sniffing: a stock
        Prometheus server advertises openmetrics-text on every scrape
        but selects its parser by the response Content-Type, and an
        exemplar suffix under text/plain would fail the whole scrape."""
        tok = self.garage.config.admin_metrics_token
        if tok is not None:
            self._check_token(request, tok)
        om = request.query.get("exemplars") == "1"
        body = metrics_body(self.garage, openmetrics=om)
        return web.Response(text=body, content_type="text/plain")

    async def handle_timeline(self, request) -> web.Response:
        """Chrome-trace (catapult) JSON of the device/transport
        pipeline timeline — load into chrome://tracing / Perfetto."""
        self._admin(request)
        limit = request.query.get("limit")
        tl = self.garage.block_manager.codec.obs.timeline
        return web.json_response(
            tl.chrome_trace(int(limit) if limit else None))

    async def handle_status(self, request) -> web.Response:
        self._admin(request)
        sys = self.garage.system
        return web.json_response({
            "node": bytes(sys.id).hex(),
            "garageVersion": "garage-tpu-0.1",
            "layoutVersion": sys.layout.version,
            "knownNodes": sys.get_known_nodes(),
            "roles": {
                nid.hex(): {"zone": r.zone, "capacity": r.capacity, "tags": r.tags}
                for nid, r in sys.layout.node_roles().items()
            },
        })

    async def handle_layout_get(self, request) -> web.Response:
        self._admin(request)
        sys = self.garage.system
        return web.json_response({
            "version": sys.layout.version,
            "roles": {
                nid.hex(): {"zone": r.zone, "capacity": r.capacity, "tags": r.tags}
                for nid, r in sys.layout.node_roles().items()
            },
            "stagedRoleChanges": {
                nid.hex(): (
                    {"zone": r.zone, "capacity": r.capacity, "tags": r.tags}
                    if r is not None else None
                )
                for nid, r in sys.layout.staged_roles().items()
            },
        })

    async def handle_layout_update(self, request) -> web.Response:
        self._admin(request)
        from ..rpc.layout import NodeRole

        body = json.loads(await request.read())
        sys = self.garage.system
        for nid_hex, role in body.get("roles", {}).items():
            nid = bytes.fromhex(nid_hex)
            if role is None:
                sys.layout.stage_role(nid, None)
            else:
                sys.layout.stage_role(nid, NodeRole(
                    zone=role["zone"], capacity=role.get("capacity"),
                    tags=role.get("tags", []),
                ))
        sys.save_layout()
        return web.json_response({"ok": True})

    async def handle_layout_apply(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read() or b"{}")
        sys = self.garage.system
        msgs = sys.layout.apply_staged_changes(body.get("version"))
        sys.save_layout()
        sys._rebuild_ring()
        await sys.broadcast_layout()
        return web.json_response({"messages": msgs})

    async def handle_bucket_list(self, request) -> web.Response:
        self._admin(request)
        return await self._rpc_json(self._rpc._cmd_bucket_list, {})

    async def handle_bucket_create(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read())
        b = await self.helper.create_bucket(body["globalAlias"])
        return web.json_response({"id": bytes(b.id).hex()})

    async def handle_key_list(self, request) -> web.Response:
        self._admin(request)
        return await self._rpc_json(self._rpc._cmd_key_list, {})

    async def handle_key_create(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read() or b"{}")
        k = await self.helper.create_key(body.get("name", "unnamed"))
        return web.json_response({
            "accessKeyId": k.key_id,
            "secretAccessKey": k.params().secret_key,
        })

    # --- v1 endpoints delegating to the shared admin command set
    #     (ref api/admin/router_v1.rs:95-131) ---

    async def _rpc_json(self, fn, msg) -> web.Response:
        """Run one AdminRpcHandler command, render errors as 400 JSON."""
        try:
            return web.json_response(await fn(msg))
        except Exception as e:  # noqa: BLE001 — admin surface: report, 400
            logger.debug("admin v1 op failed: %s", e)
            return web.json_response({"error": str(e)}, status=400)

    async def handle_connect(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read())
        # body = ["<id>@<addr>", ...] (ref ConnectClusterNodes)
        out = []
        for spec in body:
            nid, _, addr = spec.partition("@")
            try:
                await self._rpc._cmd_connect({"addr": addr, "node_id": nid})
                out.append({"success": True, "error": None})
            except Exception as e:  # noqa: BLE001
                out.append({"success": False, "error": str(e)})
        return web.json_response(out)

    async def handle_layout_revert(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read() or b"{}")
        return await self._rpc_json(
            self._rpc._cmd_layout_revert, {"version": body.get("version")}
        )

    async def handle_bucket_get(self, request) -> web.Response:
        self._admin(request)
        bid = request.query.get("id")
        alias = request.query.get("globalAlias")
        if bid is None and alias is None:
            return await self.handle_bucket_list(request)
        return await self._rpc_json(
            self._rpc._cmd_bucket_info, {"bucket": bid or alias}
        )

    async def handle_bucket_delete(self, request) -> web.Response:
        self._admin(request)
        return await self._rpc_json(
            self._rpc._cmd_bucket_delete, {"bucket": request.query["id"]}
        )

    async def handle_bucket_update(self, request) -> web.Response:
        """UpdateBucket: websiteAccess and/or quotas (ref router_v1 PUT
        /v1/bucket?id=)."""
        self._admin(request)
        bid = request.query["id"]
        body = json.loads(await request.read() or b"{}")
        if "websiteAccess" in body:
            wa = body["websiteAccess"] or {}
            r = await self._rpc_json(self._rpc._cmd_bucket_website, {
                "bucket": bid,
                "allow": bool(wa.get("enabled")),
                "index_document": wa.get("indexDocument", "index.html"),
                "error_document": wa.get("errorDocument"),
            })
            if r.status != 200:
                return r
        if "quotas" in body:
            q = body["quotas"] or {}
            r = await self._rpc_json(self._rpc._cmd_bucket_set_quotas, {
                "bucket": bid,
                "max_size": q.get("maxSize"),
                "max_objects": q.get("maxObjects"),
            })
            if r.status != 200:
                return r
        return await self._rpc_json(self._rpc._cmd_bucket_info,
                                    {"bucket": bid})

    async def _bucket_perm(self, request, op: str) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read())
        perms = body.get("permissions", {})
        return await self._rpc_json(
            getattr(self._rpc, f"_cmd_bucket_{op}"), {
                "bucket": body["bucketId"],
                "key": body["accessKeyId"],
                "read": perms.get("read"),
                "write": perms.get("write"),
                "owner": perms.get("owner"),
            }
        )

    async def handle_bucket_allow(self, request) -> web.Response:
        return await self._bucket_perm(request, "allow")

    async def handle_bucket_deny(self, request) -> web.Response:
        return await self._bucket_perm(request, "deny")

    async def handle_alias_global(self, request) -> web.Response:
        self._admin(request)
        return await self._rpc_json(self._rpc._cmd_bucket_alias, {
            "bucket": request.query["id"], "alias": request.query["alias"],
        })

    async def handle_unalias_global(self, request) -> web.Response:
        self._admin(request)
        return await self._rpc_json(self._rpc._cmd_bucket_unalias, {
            "alias": request.query["alias"],
        })

    async def handle_alias_local(self, request) -> web.Response:
        """PUT /v{0,1}/bucket/alias/local?id&accessKeyId&alias — a bucket
        name visible only through one access key (ref router_v0.rs:121,
        bucket_alias semantics in the bucket/key tables)."""
        self._admin(request)
        from ..utils.data import Uuid

        bid = bytes.fromhex(request.query["id"])
        kid = request.query["accessKeyId"]
        alias = request.query["alias"]
        helper = self.garage.helper()
        b = await helper.get_existing_bucket(Uuid(bid))
        key = await self.garage.key_table.get(kid, "")
        if key is None or key.is_deleted():
            return web.json_response(
                {"error": f"no such key {kid!r}"}, status=404)
        # refuse to repoint an in-use alias (mirror of the global-alias
        # guard): silently moving it would strand the old bucket's
        # local_aliases entry, inflating its name count past the
        # last-alias guard and making the stale entry undeletable
        cur = key.params().local_aliases.get(alias)
        if cur is not None and bytes(cur) != bytes(b.id):
            return web.json_response(
                {"error": f"alias {alias!r} already in use by this key "
                          "for another bucket"}, status=400)
        key.params().local_aliases.update(alias, bytes(b.id))
        b.params().local_aliases.update((kid, alias), True)
        await self.garage.key_table.insert(key)
        await self.garage.bucket_table.insert(b)
        return web.json_response({"ok": True})

    async def handle_unalias_local(self, request) -> web.Response:
        self._admin(request)
        from ..utils.data import Uuid

        bid = bytes.fromhex(request.query["id"])
        kid = request.query["accessKeyId"]
        alias = request.query["alias"]
        helper = self.garage.helper()
        b = await helper.get_existing_bucket(Uuid(bid))
        key = await self.garage.key_table.get(kid, "")
        if key is None or key.is_deleted():
            return web.json_response(
                {"error": f"no such key {kid!r}"}, status=404)
        cur = key.params().local_aliases.get(alias)
        if cur is None or bytes(cur) != bytes(b.id):
            return web.json_response(
                {"error": f"key has no local alias {alias!r} for this "
                          "bucket"}, status=400)
        # refuse to strip the bucket's last name (same rule as global
        # unalias: an unreachable bucket is an operator trap)
        if helper.bucket_name_count(b) <= 1:
            return web.json_response(
                {"error": "cannot remove the last alias of a bucket"},
                status=400)
        key.params().local_aliases.update(alias, None)
        b.params().local_aliases.update((kid, alias), False)
        await self.garage.key_table.insert(key)
        await self.garage.bucket_table.insert(b)
        return web.json_response({"ok": True})

    async def handle_key_get_v0(self, request) -> web.Response:
        """v0 GetKeyInfo always returned the secret key (v1 gates it
        behind showSecretKey=true; ref router_v0.rs:101-102)."""
        self._admin(request)
        kid = request.query.get("id")
        search = request.query.get("search")
        if kid is None and search is None:
            return await self.handle_key_list(request)
        return await self._rpc_json(self._rpc._cmd_key_info, {
            "key": kid or search, "show_secret": True,
        })

    async def handle_key_get(self, request) -> web.Response:
        self._admin(request)
        kid = request.query.get("id")
        search = request.query.get("search")
        if kid is None and search is None:
            return await self.handle_key_list(request)
        show_secret = request.query.get("showSecretKey") == "true"
        return await self._rpc_json(self._rpc._cmd_key_info, {
            "key": kid or search, "show_secret": show_secret,
        })

    async def handle_key_post(self, request) -> web.Response:
        """POST /v1/key?id= = UpdateKey; POST /v1/key = CreateKey."""
        kid = request.query.get("id")
        if kid is None:
            return await self.handle_key_create(request)
        self._admin(request)
        body = json.loads(await request.read() or b"{}")
        msg = {"key": kid, "name": body.get("name")}
        # allow/deny translate to the single tri-state handler field; an
        # absent directive must leave the flag untouched
        if (body.get("allow") or {}).get("createBucket"):
            msg["allow_create_bucket"] = True
        elif (body.get("deny") or {}).get("createBucket"):
            msg["allow_create_bucket"] = False
        return await self._rpc_json(self._rpc._cmd_key_set, msg)

    async def handle_key_import(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read())
        return await self._rpc_json(self._rpc._cmd_key_import, {
            "id": body["accessKeyId"],
            "secret": body["secretAccessKey"],
            "name": body.get("name", "imported"),
        })

    async def handle_key_delete(self, request) -> web.Response:
        self._admin(request)
        return await self._rpc_json(self._rpc._cmd_key_delete, {
            "key": request.query["id"],
        })

    async def handle_check_domain(self, request) -> web.Response:
        """/check?domain= — used by reverse proxies to validate website
        domains (ref api_server.rs handle_check_website)."""
        domain = request.query.get("domain", "")
        from .common import host_to_bucket

        bucket_name = host_to_bucket(domain, self.garage.config.web_root_domain) or domain
        bid = await self.helper.resolve_global_bucket_name(bucket_name)
        if bid is None:
            return web.Response(status=404, text="no such bucket")
        b = await self.helper.get_existing_bucket(bid)
        if b.params().website_config.value is None:
            return web.Response(status=404, text="website not enabled")
        return web.Response(status=200, text="ok")
