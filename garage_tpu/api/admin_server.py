"""Admin HTTP API — health, metrics, cluster/bucket/key REST.

Equivalent of reference src/api/admin/ (SURVEY.md §2.7): `/health` (no
auth), `/metrics` (Prometheus text format, guarded by the metrics token),
and the v1 REST endpoints for status/layout/buckets/keys guarded by the
admin token (api_server.rs:32-60,271-335).
"""

from __future__ import annotations

import hmac
import json
import logging
from typing import Optional

from aiohttp import web

logger = logging.getLogger("garage_tpu.api.admin")


class AdminApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.helper = garage.helper()
        self._runner: Optional[web.AppRunner] = None

    async def start(self, bind_addr: str) -> None:
        app = web.Application()
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/v1/status", self.handle_status)
        app.router.add_get("/v1/health", self.handle_health_detailed)
        app.router.add_post("/v1/layout", self.handle_layout_update)
        app.router.add_get("/v1/layout", self.handle_layout_get)
        app.router.add_post("/v1/layout/apply", self.handle_layout_apply)
        app.router.add_get("/v1/bucket", self.handle_bucket_list)
        app.router.add_post("/v1/bucket", self.handle_bucket_create)
        app.router.add_get("/v1/key", self.handle_key_list)
        app.router.add_post("/v1/key", self.handle_key_create)
        app.router.add_get("/check", self.handle_check_domain)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        host, port = bind_addr.rsplit(":", 1)
        self._site = web.TCPSite(self._runner, host, int(port))
        await self._site.start()
        logger.info("Admin API listening on %s", bind_addr)

    @property
    def port(self) -> int:
        return self._site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # --- auth ---

    def _check_token(self, request: web.Request, token: Optional[str]) -> None:
        if token is None:
            raise web.HTTPForbidden(text="admin token not configured")
        auth = request.headers.get("Authorization", "")
        # compare bytes: compare_digest raises TypeError on non-ASCII str
        if not hmac.compare_digest(
            auth.encode("utf-8", "surrogateescape"),
            f"Bearer {token}".encode("utf-8", "surrogateescape"),
        ):
            raise web.HTTPForbidden(text="invalid bearer token")

    def _admin(self, request) -> None:
        self._check_token(request, self.garage.config.admin_token)

    # --- handlers ---

    async def handle_health(self, request) -> web.Response:
        """Quick liveness: 200 if we can serve quorum ops (ref
        api_server.rs /health)."""
        h = self.garage.system.health()
        status = 200 if h.status in ("healthy", "degraded") else 503
        return web.Response(status=status, text=h.status)

    async def handle_health_detailed(self, request) -> web.Response:
        self._admin(request)
        h = self.garage.system.health()
        return web.json_response({
            "status": h.status,
            "knownNodes": h.known_nodes,
            "connectedNodes": h.connected_nodes,
            "storageNodes": h.storage_nodes,
            "storageNodesOk": h.storage_nodes_ok,
            "partitions": h.partitions,
            "partitionsQuorum": h.partitions_quorum,
            "partitionsAllOk": h.partitions_all_ok,
        })

    async def handle_metrics(self, request) -> web.Response:
        """Prometheus exposition of every layer's metrics (ref
        api/admin/api_server.rs:271-335 + rpc/table/block/api metric
        structs)."""
        tok = self.garage.config.admin_metrics_token
        if tok is not None:
            self._check_token(request, tok)
        g = self.garage
        lines = []

        def gauge(name, value, help_=""):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {value}")

        h = g.system.health()
        gauge("cluster_healthy", 1 if h.status == "healthy" else 0)
        gauge("cluster_available", 1 if h.status != "unavailable" else 0)
        gauge("cluster_connected_nodes", h.connected_nodes)
        gauge("cluster_known_nodes", h.known_nodes)
        # refresh per-table observed gauges, then render the registry that
        # the rpc/table/block/api layers record into
        for t in g.tables:
            t.observe_gauges()
        body = "\n".join(lines) + "\n" + g.system.metrics.render()
        return web.Response(text=body, content_type="text/plain")

    async def handle_status(self, request) -> web.Response:
        self._admin(request)
        sys = self.garage.system
        return web.json_response({
            "node": bytes(sys.id).hex(),
            "garageVersion": "garage-tpu-0.1",
            "layoutVersion": sys.layout.version,
            "knownNodes": sys.get_known_nodes(),
            "roles": {
                nid.hex(): {"zone": r.zone, "capacity": r.capacity, "tags": r.tags}
                for nid, r in sys.layout.node_roles().items()
            },
        })

    async def handle_layout_get(self, request) -> web.Response:
        self._admin(request)
        sys = self.garage.system
        return web.json_response({
            "version": sys.layout.version,
            "roles": {
                nid.hex(): {"zone": r.zone, "capacity": r.capacity, "tags": r.tags}
                for nid, r in sys.layout.node_roles().items()
            },
            "stagedRoleChanges": {
                nid.hex(): (
                    {"zone": r.zone, "capacity": r.capacity, "tags": r.tags}
                    if r is not None else None
                )
                for nid, r in sys.layout.staged_roles().items()
            },
        })

    async def handle_layout_update(self, request) -> web.Response:
        self._admin(request)
        from ..rpc.layout import NodeRole

        body = json.loads(await request.read())
        sys = self.garage.system
        for nid_hex, role in body.get("roles", {}).items():
            nid = bytes.fromhex(nid_hex)
            if role is None:
                sys.layout.stage_role(nid, None)
            else:
                sys.layout.stage_role(nid, NodeRole(
                    zone=role["zone"], capacity=role.get("capacity"),
                    tags=role.get("tags", []),
                ))
        sys.save_layout()
        return web.json_response({"ok": True})

    async def handle_layout_apply(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read() or b"{}")
        sys = self.garage.system
        msgs = sys.layout.apply_staged_changes(body.get("version"))
        sys.save_layout()
        sys._rebuild_ring()
        await sys.broadcast_layout()
        return web.json_response({"messages": msgs})

    async def handle_bucket_list(self, request) -> web.Response:
        self._admin(request)
        out = []
        for b in await self.helper.list_buckets():
            p = b.params()
            out.append({
                "id": bytes(b.id).hex(),
                "globalAliases": [n for n, l in p.aliases.items.items() if l.value],
            })
        return web.json_response(out)

    async def handle_bucket_create(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read())
        b = await self.helper.create_bucket(body["globalAlias"])
        return web.json_response({"id": bytes(b.id).hex()})

    async def handle_key_list(self, request) -> web.Response:
        self._admin(request)
        return web.json_response([
            {"id": k.key_id, "name": k.params().name.value}
            for k in await self.helper.list_keys()
        ])

    async def handle_key_create(self, request) -> web.Response:
        self._admin(request)
        body = json.loads(await request.read() or b"{}")
        k = await self.helper.create_key(body.get("name", "unnamed"))
        return web.json_response({
            "accessKeyId": k.key_id,
            "secretAccessKey": k.params().secret_key,
        })

    async def handle_check_domain(self, request) -> web.Response:
        """/check?domain= — used by reverse proxies to validate website
        domains (ref api_server.rs handle_check_website)."""
        domain = request.query.get("domain", "")
        from .common import host_to_bucket

        bucket_name = host_to_bucket(domain, self.garage.config.web_root_domain) or domain
        bid = await self.helper.resolve_global_bucket_name(bucket_name)
        if bid is None:
            return web.Response(status=404, text="no such bucket")
        b = await self.helper.get_existing_bucket(bid)
        if b.params().website_config.value is None:
            return web.Response(status=404, text="website not enabled")
        return web.Response(status=200, text="ok")
