"""AWS Signature Version 4 verification.

Equivalent of reference src/api/signature/ (SURVEY.md §2.7):
  - header authentication: `Authorization: AWS4-HMAC-SHA256 Credential=…,
    SignedHeaders=…, Signature=…` (payload.rs:20-100+): rebuild the
    canonical request from the raw request, derive the signing key from
    the API key's secret, compare signatures, check scope (date/region/
    service).
  - presigned query authentication: `X-Amz-Algorithm=…&X-Amz-Credential=…`
    with expiry check (payload.rs presigned branch).
  - streaming payload signatures: `STREAMING-AWS4-HMAC-SHA256-PAYLOAD`
    bodies arrive as `<hex-size>;chunk-signature=<sig>\\r\\n<data>\\r\\n`
    chunks, each signed over the previous signature (streaming.rs:17-60+),
    exposed here as an async stream transformer.

Secret lookup goes through the key table; the caller passes an async
`get_key(key_id) -> Optional[Key]`.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
from typing import AsyncIterator, Dict, List, Optional, Tuple

from ..utils.error import GarageError

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
SERVICE = "s3"


class AuthError(GarageError):
    """403 Forbidden (ref common_error.rs Forbidden)."""

    status = 403
    code = "AccessDenied"


class InvalidRequest(GarageError):
    status = 400
    code = "InvalidRequest"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = SERVICE) -> bytes:
    """AWS4 key derivation chain."""
    k = _hmac(b"AWS4" + secret.encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def uri_encode(s: str, encode_slash: bool = True) -> str:
    """AWS canonical URI encoding (ref encoding.rs)."""
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query_string(query: List[Tuple[str, str]], skip_sig: bool = False) -> str:
    items = [
        (uri_encode(k), uri_encode(v))
        for k, v in query
        if not (skip_sig and k == "X-Amz-Signature")
    ]
    items.sort()
    return "&".join(f"{k}={v}" for k, v in items)


def canonical_query_string_raw(
    raw_query: List[Tuple[str, str]], skip_sig: bool = False
) -> str:
    """Canonical query from the RAW (still percent-encoded, as sent) pairs:
    sort and join without re-encoding, so the signature covers exactly the
    client's wire encoding (the reference signs the raw query, payload.rs).
    X-Amz-Signature is unreserved-only so the raw name matches literally."""
    items = sorted(
        p for p in raw_query if not (skip_sig and p[0] == "X-Amz-Signature")
    )
    return "&".join(f"{k}={v}" for k, v in items)


def canonical_request(
    method: str,
    path: str,
    query: List[Tuple[str, str]],
    headers: Dict[str, str],
    signed_headers: List[str],
    payload_hash: str,
    skip_sig_param: bool = False,
    raw_path: Optional[str] = None,
    raw_query: Optional[List[Tuple[str, str]]] = None,
) -> str:
    # Sign over the URI exactly as the client sent it (raw_path) when the
    # server can supply it; clients whose wire encoding differs from our
    # re-encoding of the decoded path (literal %2F in keys, '+' in values)
    # would otherwise get spurious SignatureDoesNotMatch.  The re-encoding
    # branch remains for client-side signing, where `path` is logical.
    canon_uri = raw_path if raw_path is not None else uri_encode(
        path, encode_slash=False
    )
    if raw_query is not None:
        canon_query = canonical_query_string_raw(raw_query, skip_sig=skip_sig_param)
    else:
        canon_query = canonical_query_string(query, skip_sig=skip_sig_param)
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join([
        method.upper(),
        canon_uri,
        canon_query,
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign(timestamp: str, scope: str, canon_req: str) -> str:
    return "\n".join([
        ALGORITHM,
        timestamp,
        scope,
        hashlib.sha256(canon_req.encode()).hexdigest(),
    ])


class Credential:
    __slots__ = ("key_id", "date", "region", "service")

    def __init__(self, raw: str):
        parts = raw.split("/")
        if len(parts) != 5 or parts[4] != "aws4_request":
            raise InvalidRequest(f"invalid credential {raw!r}")
        self.key_id, self.date, self.region, self.service = parts[:4]

    @property
    def scope(self) -> str:
        return f"{self.date}/{self.region}/{self.service}/aws4_request"


class VerifiedRequest:
    """Result of signature verification."""

    __slots__ = ("key", "content_sha256", "signature", "credential", "timestamp")

    def __init__(self, key, content_sha256: Optional[str], signature: str,
                 credential: Credential, timestamp: str):
        self.key = key                      # model Key entry (None = anonymous)
        self.content_sha256 = content_sha256  # None=unsigned, "STREAMING"=chunked
        self.signature = signature
        self.credential = credential
        self.timestamp = timestamp


def _parse_auth_header(auth: str) -> Dict[str, str]:
    if not auth.startswith(ALGORITHM):
        raise InvalidRequest("unsupported authorization algorithm")
    out = {}
    for item in auth[len(ALGORITHM):].split(","):
        item = item.strip()
        if "=" in item:
            k, v = item.split("=", 1)
            out[k.strip()] = v.strip()
    for req in ("Credential", "SignedHeaders", "Signature"):
        if req not in out:
            raise InvalidRequest(f"missing {req} in Authorization")
    return out


def raw_query_pairs(raw_query_string: str) -> List[Tuple[str, str]]:
    """Split a raw (still-encoded) query string into (name, value) pairs
    without decoding, preserving the client's exact wire encoding."""
    out: List[Tuple[str, str]] = []
    for part in raw_query_string.split("&"):
        if not part:
            continue
        if "=" in part:
            k, v = part.split("=", 1)
            out.append((k, v))
        else:
            out.append((part, ""))
    return out


async def check_signature(
    get_key,
    region: str,
    method: str,
    path: str,
    query: List[Tuple[str, str]],
    headers: Dict[str, str],
    raw_path: Optional[str] = None,
    raw_query: Optional[List[Tuple[str, str]]] = None,
) -> VerifiedRequest:
    """Verify header or presigned-query SigV4 (ref payload.rs:20-100+).
    `headers` keys must be lowercase.  `raw_path`/`raw_query` are the
    still-encoded wire forms; when given, the canonical request is built
    from them (decoded `path`/`query` stay for parameter lookups)."""
    qdict = dict(query)
    if "Authorization" in headers or "authorization" in headers:
        return await _check_header_signature(
            get_key, region, method, path, query, headers, raw_path, raw_query
        )
    if qdict.get("X-Amz-Algorithm") == ALGORITHM:
        return await _check_presigned_signature(
            get_key, region, method, path, query, headers, raw_path, raw_query
        )
    raise AuthError("no signature: anonymous access denied")


async def _lookup(get_key, cred: Credential, region: str):
    if cred.region != region and cred.region != "":
        raise AuthError(
            f"scope region {cred.region!r} does not match {region!r}"
        )
    key = await get_key(cred.key_id)
    if key is None:
        raise AuthError(f"no such key: {cred.key_id}")
    return key


async def _check_header_signature(
    get_key, region, method, path, query, headers,
    raw_path=None, raw_query=None,
) -> VerifiedRequest:
    auth = _parse_auth_header(headers.get("authorization", headers.get("Authorization", "")))
    cred = Credential(auth["Credential"])
    signed_headers = auth["SignedHeaders"].split(";")
    if "host" not in signed_headers:
        raise InvalidRequest("host must be a signed header")
    timestamp = headers.get("x-amz-date")
    if not timestamp:
        raise InvalidRequest("missing x-amz-date")
    if timestamp[:8] != cred.date:
        raise AuthError("x-amz-date does not match credential scope date")
    content_sha256 = headers.get("x-amz-content-sha256")
    if content_sha256 is None:
        raise InvalidRequest("missing x-amz-content-sha256")

    key = await _lookup(get_key, cred, region)
    canon = canonical_request(
        method, path, query, headers, signed_headers, content_sha256,
        raw_path=raw_path, raw_query=raw_query,
    )
    sts = string_to_sign(timestamp, cred.scope, canon)
    sk = signing_key(key.params().secret_key, cred.date, cred.region, cred.service)
    expected = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expected, auth["Signature"]):
        raise AuthError("signature mismatch")

    if content_sha256 == UNSIGNED_PAYLOAD:
        sha = None
    elif content_sha256 == STREAMING_PAYLOAD:
        sha = "STREAMING"
    else:
        sha = content_sha256
    return VerifiedRequest(key, sha, auth["Signature"], cred, timestamp)


async def _check_presigned_signature(
    get_key, region, method, path, query, headers,
    raw_path=None, raw_query=None,
) -> VerifiedRequest:
    q = dict(query)
    cred = Credential(q.get("X-Amz-Credential", ""))
    timestamp = q.get("X-Amz-Date", "")
    if not timestamp:
        raise InvalidRequest("missing X-Amz-Date")
    try:
        t0 = datetime.datetime.strptime(timestamp, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
    except ValueError:
        raise InvalidRequest("bad X-Amz-Date")
    try:
        expires = int(q.get("X-Amz-Expires", "86400"))
    except ValueError:
        raise InvalidRequest("bad X-Amz-Expires")
    if not 1 <= expires <= 7 * 86400:
        # AWS caps presigned validity at 7 days
        raise InvalidRequest("X-Amz-Expires out of range")
    now = datetime.datetime.now(datetime.timezone.utc)
    if now > t0 + datetime.timedelta(seconds=expires):
        raise AuthError("presigned URL expired")
    signed_headers = q.get("X-Amz-SignedHeaders", "host").split(";")
    signature = q.get("X-Amz-Signature", "")

    key = await _lookup(get_key, cred, region)
    canon = canonical_request(
        method, path, query, headers, signed_headers, UNSIGNED_PAYLOAD,
        skip_sig_param=True, raw_path=raw_path, raw_query=raw_query,
    )
    sts = string_to_sign(timestamp, cred.scope, canon)
    sk = signing_key(key.params().secret_key, cred.date, cred.region, cred.service)
    expected = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expected, signature):
        raise AuthError("presigned signature mismatch")
    return VerifiedRequest(key, None, signature, cred, timestamp)


# --- streaming chunked payloads (ref signature/streaming.rs) ---------------


class StreamingPayloadError(GarageError):
    status = 403
    code = "SignatureDoesNotMatch"


async def decode_streaming_body(
    body: AsyncIterator[bytes],
    secret: str,
    cred: Credential,
    seed_signature: str,
    timestamp: str,
) -> AsyncIterator[bytes]:
    """Decode `aws-chunked` content, verifying each chunk signature
    (ref streaming.rs:17-60+).  Chunk string-to-sign:
    AWS4-HMAC-SHA256-PAYLOAD \\n ts \\n scope \\n prev_sig \\n
    sha256("") \\n sha256(chunk)."""
    sk = signing_key(secret, cred.date, cred.region, cred.service)
    prev_sig = seed_signature
    empty_sha = hashlib.sha256(b"").hexdigest()

    buf = bytearray()
    it = body.__aiter__()
    eof = False

    async def fill(n: int) -> None:
        nonlocal eof
        while len(buf) < n and not eof:
            try:
                buf.extend(await it.__anext__())
            except StopAsyncIteration:
                eof = True

    async def read_line() -> bytes:
        while True:
            i = buf.find(b"\r\n")
            if i >= 0:
                line = bytes(buf[:i])
                del buf[: i + 2]
                return line
            if eof:
                raise StreamingPayloadError("truncated chunk stream")
            await fill(len(buf) + 1)

    while True:
        header = await read_line()
        if b";" in header:
            size_hex, rest = header.split(b";", 1)
            if not rest.startswith(b"chunk-signature="):
                raise StreamingPayloadError("missing chunk-signature")
            chunk_sig = rest[len(b"chunk-signature="):].decode()
        else:
            raise StreamingPayloadError("malformed chunk header")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise StreamingPayloadError(f"bad chunk size {size_hex!r}")
        await fill(size + 2)
        if len(buf) < size + 2:
            raise StreamingPayloadError("truncated chunk data")
        data = bytes(buf[:size])
        if bytes(buf[size : size + 2]) != b"\r\n":
            raise StreamingPayloadError("missing chunk trailer CRLF")
        del buf[: size + 2]

        sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD",
            timestamp,
            cred.scope,
            prev_sig,
            empty_sha,
            hashlib.sha256(data).hexdigest(),
        ])
        expected = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expected, chunk_sig):
            raise StreamingPayloadError("chunk signature mismatch")
        prev_sig = expected
        if size == 0:
            return
        yield data


# --- client-side signing (for tests, CLI and the web/k2v clients) ----------


def sign_request(
    key_id: str,
    secret: str,
    region: str,
    method: str,
    path: str,
    query: List[Tuple[str, str]],
    headers: Dict[str, str],
    payload: bytes = b"",
    timestamp: Optional[str] = None,
    path_is_raw: bool = False,
) -> Dict[str, str]:
    """Produce the headers for a header-authenticated request (the
    reference keeps an equivalent in tests/common/custom_requester.rs).
    Returns headers to add; input `headers` must include host.
    With `path_is_raw`, `path` is the exact wire form (already
    percent-encoded) and is signed verbatim — required for keys whose
    decoded form re-encodes differently (literal %2F), since the server
    verifies against the raw wire path."""
    now = timestamp or datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ"
    )
    date = now[:8]
    payload_hash = hashlib.sha256(payload).hexdigest()
    hdrs = {k.lower(): v for k, v in headers.items()}
    hdrs["x-amz-date"] = now
    hdrs["x-amz-content-sha256"] = payload_hash
    signed = sorted(set(list(hdrs.keys()) + ["host"]))
    cred = Credential(f"{key_id}/{date}/{region}/{SERVICE}/aws4_request")
    canon = canonical_request(
        method, path, query, hdrs, signed, payload_hash,
        raw_path=path if path_is_raw else None,
    )
    sts = string_to_sign(now, cred.scope, canon)
    sk = signing_key(secret, date, region)
    sig = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": now,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"{ALGORITHM} Credential={cred.key_id}/{cred.scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        ),
    }
