"""HTTP API layer.

Equivalent of reference src/api/ (SURVEY.md §2.7): generic HTTP server
plumbing, AWS SigV4 authentication (header, presigned query, and streaming
chunk signatures), the S3 API, the Admin API, and shared error rendering.
The HTTP engine is aiohttp — the analogue of the reference's hyper.
"""
