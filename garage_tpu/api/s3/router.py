"""S3 endpoint router.

Equivalent of reference src/api/s3/router.rs (SURVEY.md §2.7): maps
(method, bucket?, key?, query params, headers) to a named endpoint with
its required authorization level (Read / Write / Owner).  The reference
implements ~60 endpoints via the router_match! macro; here the dispatch
table is explicit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common import BadRequestError, NotImplementedError_

READ, WRITE, OWNER, NONE = "read", "write", "owner", "none"


@dataclass
class Endpoint:
    name: str
    authorization: str
    bucket: Optional[str] = None
    key: Optional[str] = None
    query: Dict[str, str] = field(default_factory=dict)


# bucket-level subresources: query param → (GET endpoint, PUT, DELETE)
_BUCKET_SUBRESOURCES = {
    "website": ("GetBucketWebsite", "PutBucketWebsite", "DeleteBucketWebsite", OWNER),
    "cors": ("GetBucketCors", "PutBucketCors", "DeleteBucketCors", OWNER),
    "lifecycle": ("GetBucketLifecycle", "PutBucketLifecycle", "DeleteBucketLifecycle", OWNER),
    "versioning": ("GetBucketVersioning", None, None, READ),
    "location": ("GetBucketLocation", None, None, READ),
    "acl": ("GetBucketAcl", None, None, READ),
    "policy": ("GetBucketPolicy", None, None, OWNER),
}

# recognized-but-unimplemented subresources (ref router.rs parses all of
# these into named endpoints; the dispatch answers 501 NotImplemented).
# Without this table `GET /bucket?tagging` would silently route to
# ListObjects — a misroute, not a NotImplemented.
_BUCKET_SUBRESOURCES_UNIMPL = {
    "accelerate": "BucketAccelerateConfiguration",
    "analytics": "BucketAnalyticsConfiguration",
    "encryption": "BucketEncryption",
    "intelligent-tiering": "BucketIntelligentTieringConfiguration",
    "inventory": "BucketInventoryConfiguration",
    "logging": "BucketLogging",
    "metrics": "BucketMetricsConfiguration",
    "notification": "BucketNotificationConfiguration",
    "object-lock": "ObjectLockConfiguration",
    "ownershipControls": "BucketOwnershipControls",
    "policyStatus": "BucketPolicyStatus",
    "publicAccessBlock": "PublicAccessBlock",
    "replication": "BucketReplication",
    "requestPayment": "BucketRequestPayment",
    "tagging": "BucketTagging",
    "versions": "ListObjectVersions",
}

_OBJECT_SUBRESOURCES_UNIMPL = {
    "acl": "ObjectAcl",
    "legal-hold": "ObjectLegalHold",
    "retention": "ObjectRetention",
    "tagging": "ObjectTagging",
    "torrent": "ObjectTorrent",
    "restore": "RestoreObject",
    "select": "SelectObjectContent",
}


def parse_endpoint(
    method: str,
    bucket: Optional[str],
    key: Optional[str],
    query: List[Tuple[str, str]],
    headers: Dict[str, str],
) -> Endpoint:
    """ref router.rs Endpoint::from_request."""
    q = {k: v for k, v in query}
    m = method.upper()

    # CORS preflight: unauthenticated, handled before signature checks
    # (ref api_server.rs Endpoint::Options + cors.rs handle_options_s3api)
    if m == "OPTIONS":
        return Endpoint("Options", NONE, bucket, key, q)

    if bucket is None:
        if m == "GET":
            return Endpoint("ListBuckets", NONE)
        raise BadRequestError(f"no such API endpoint: {m} /")

    if key is None:
        return _bucket_endpoint(m, bucket, q, headers)
    return _object_endpoint(m, bucket, key, q, headers)


def _bucket_endpoint(m: str, bucket: str, q: Dict[str, str], headers) -> Endpoint:
    for sub, (get_ep, put_ep, del_ep, auth) in _BUCKET_SUBRESOURCES.items():
        if sub in q:
            if m == "GET" and get_ep:
                return Endpoint(get_ep, auth, bucket, query=q)
            if m == "PUT" and put_ep:
                return Endpoint(put_ep, OWNER, bucket, query=q)
            if m == "DELETE" and del_ep:
                return Endpoint(del_ep, OWNER, bucket, query=q)
            raise NotImplementedError_(f"{m} ?{sub} not supported")
    for sub, name in _BUCKET_SUBRESOURCES_UNIMPL.items():
        if sub in q:
            # All of these 501 at the catch-all, but verb/auth must still
            # match per-method intent: the List special-case (?versions)
            # applies to GET only — a PUT/DELETE on ?versions is a
            # mutation and keeps OWNER auth, not ListObjectVersions/READ.
            verb = {"GET": "Get", "PUT": "Put", "DELETE": "Delete"}.get(m, m)
            auth = READ if m == "GET" else OWNER
            if m == "GET" and name.startswith("List"):
                verb = ""
            return Endpoint(verb + name, auth, bucket, query=q)
    if m == "GET":
        if "uploads" in q:
            return Endpoint("ListMultipartUploads", READ, bucket, query=q)
        if q.get("list-type") == "2":
            return Endpoint("ListObjectsV2", READ, bucket, query=q)
        return Endpoint("ListObjects", READ, bucket, query=q)
    if m == "HEAD":
        return Endpoint("HeadBucket", READ, bucket)
    if m == "PUT":
        return Endpoint("CreateBucket", NONE, bucket)
    if m == "DELETE":
        return Endpoint("DeleteBucket", OWNER, bucket)
    if m == "POST":
        if "delete" in q:
            return Endpoint("DeleteObjects", WRITE, bucket, query=q)
        return Endpoint("PostObject", NONE, bucket)
    raise BadRequestError(f"no such API endpoint: {m} on bucket")


def _object_endpoint(m: str, bucket: str, key: str, q: Dict[str, str], headers) -> Endpoint:
    copy_source = headers.get("x-amz-copy-source")
    for sub, name in _OBJECT_SUBRESOURCES_UNIMPL.items():
        if sub in q:
            if name in ("RestoreObject", "SelectObjectContent"):
                return Endpoint(name, WRITE if name == "RestoreObject" else READ,
                                bucket, key, q)
            verb = {"GET": "Get", "PUT": "Put", "DELETE": "Delete"}.get(m, m)
            return Endpoint(verb + name, READ if m == "GET" else WRITE,
                            bucket, key, q)
    if m == "GET":
        if "uploadId" in q:
            return Endpoint("ListParts", READ, bucket, key, q)
        return Endpoint("GetObject", READ, bucket, key, q)
    if m == "HEAD":
        return Endpoint("HeadObject", READ, bucket, key, q)
    if m == "PUT":
        if "partNumber" in q and "uploadId" in q:
            if copy_source is not None:
                return Endpoint("UploadPartCopy", WRITE, bucket, key, q)
            return Endpoint("UploadPart", WRITE, bucket, key, q)
        if copy_source is not None:
            return Endpoint("CopyObject", WRITE, bucket, key, q)
        return Endpoint("PutObject", WRITE, bucket, key, q)
    if m == "POST":
        if "uploads" in q:
            return Endpoint("CreateMultipartUpload", WRITE, bucket, key, q)
        if "uploadId" in q:
            return Endpoint("CompleteMultipartUpload", WRITE, bucket, key, q)
    if m == "DELETE":
        if "uploadId" in q:
            return Endpoint("AbortMultipartUpload", WRITE, bucket, key, q)
        return Endpoint("DeleteObject", WRITE, bucket, key, q)
    raise BadRequestError(f"no such API endpoint: {m} on object")
