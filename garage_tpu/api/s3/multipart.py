"""Multipart upload endpoints.

Equivalent of reference src/api/s3/multipart.rs (SURVEY.md §2.7):
create (object Uploading version + MPU row), upload-part (own Version row
per part, streamed through the same block pipeline as PutObject),
complete (renumber listed parts 1..N, splice their blocks into the final
Version keyed by the upload id, etag = md5-of-part-md5s "-N"), abort
(aborted object version → MPU tombstone cascade via hooks).
"""

from __future__ import annotations

import hashlib
import xml.etree.ElementTree as ET

from aiohttp import web

from ...model.s3.mpu_table import MpuPart, MultipartUpload
from ...model.s3.object_table import (
    Object,
    ObjectVersion,
    ObjectVersionData,
    ObjectVersionMeta,
)
from ...model.s3.version_table import Version
from ...ops.codec import mhash_stream
from ...utils.crdt import now_msec
from ...utils.data import Uuid, gen_uuid
from ..common import (
    ApiError,
    BadRequestError,
    EntityTooSmallError,
    InvalidPartError,
    NoSuchUploadError,
    s3_xml_root,
    xml_to_bytes,
)
from .put import (
    Chunker,
    check_quotas,
    headers_from_request,
    read_and_put_blocks,
    request_scope,
)


def decode_upload_id(s: str) -> Uuid:
    try:
        b = bytes.fromhex(s)
        if len(b) != 32:
            raise ValueError
        return Uuid(b)
    except ValueError:
        raise NoSuchUploadError(f"invalid upload id {s!r}")


async def get_upload(ctx, key: str, upload_id: Uuid):
    """(object_version, mpu) for an ongoing upload (ref multipart.rs
    get_upload)."""
    garage = ctx.garage
    obj = await garage.object_table.get(ctx.bucket_id, key)
    ov = None
    if obj is not None:
        for v in obj.versions():
            if bytes(v.uuid) == bytes(upload_id) and v.is_uploading(True):
                ov = v
                break
    mpu = await garage.mpu_table.get(upload_id, "")
    if ov is None or mpu is None or mpu.deleted.value:
        raise NoSuchUploadError("no such ongoing multipart upload")
    return ov, mpu


async def get_existing_mpu(ctx, upload_id_str: str) -> MultipartUpload:
    upload_id = decode_upload_id(upload_id_str)
    mpu = await ctx.garage.mpu_table.get(upload_id, "")
    if mpu is None or mpu.deleted.value:
        raise NoSuchUploadError("no such multipart upload")
    return mpu


async def handle_create_mpu(ctx) -> web.Response:
    garage = ctx.garage
    key = ctx.key_name
    upload_id = gen_uuid()
    ts = now_msec()
    headers = headers_from_request(ctx)

    ov = ObjectVersion.uploading(upload_id, ts, True, headers)
    await garage.object_table.insert(Object(ctx.bucket_id, key, [ov]))
    mpu = MultipartUpload(upload_id, ts, bytes(ctx.bucket_id), key)
    await garage.mpu_table.insert(mpu)

    out = s3_xml_root("InitiateMultipartUploadResult")
    ET.SubElement(out, "Bucket").text = ctx.bucket_name
    ET.SubElement(out, "Key").text = key
    ET.SubElement(out, "UploadId").text = bytes(upload_id).hex()
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_upload_part(ctx) -> web.Response:
    garage = ctx.garage
    key = ctx.key_name
    from ..common import int_param

    q = ctx.request.query
    part_number = int_param(q.get("partNumber"), "partNumber")
    if part_number is None or not 1 <= part_number <= 10000:
        raise BadRequestError("partNumber must be in [1, 10000]")
    upload_id = decode_upload_id(q["uploadId"])
    _ov, mpu = await get_upload(ctx, key, upload_id)

    # register the part (ref multipart.rs:69-120)
    ts = now_msec()
    part_version_uuid = gen_uuid()
    mpu.parts[(part_number, ts)] = MpuPart.new(bytes(part_version_uuid), None, None)
    await garage.mpu_table.insert(mpu)

    version = Version(
        part_version_uuid, bytes(ctx.bucket_id), key,
        mpu_upload_id=bytes(upload_id),
    )
    await garage.version_table.insert(version)

    md5 = hashlib.md5()
    sha256 = hashlib.sha256()
    # incremental BLAKE2b-256 over THIS part's bytes, advanced in the
    # same off-loop digest hop as md5/sha256 (put.py
    # update_stream_digests): a 1 GiB part is hashed exactly once, while
    # it streams — completing the upload never rereads or rehashes the
    # assembled object
    mhash = mhash_stream()
    # on error the part is left unfinished; abort/lifecycle reaps it
    with request_scope(garage):
        chunker = Chunker(ctx.body_stream(), garage.config.block_size)
        first = await chunker.next() or b""
        total_size, _fh = await read_and_put_blocks(
            ctx, version, part_number, first, chunker, md5, sha256,
            mhash=mhash,
        )
    etag = md5.hexdigest()
    content_sha256 = ctx.verified.content_sha256
    if content_sha256 not in (None, "STREAMING") and \
            content_sha256 != sha256.hexdigest():
        raise ApiError("x-amz-content-sha256 mismatch", status=400, code="BadDigest")

    mpu.parts[(part_number, ts)] = MpuPart.new(bytes(part_version_uuid), etag, total_size)
    await garage.mpu_table.insert(mpu)
    return web.Response(status=200, headers={
        "ETag": f'"{etag}"',
        "x-garage-part-blake2b": mhash.hexdigest(),
    })


def _parse_complete_body(body: bytes):
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequestError(f"malformed CompleteMultipartUpload XML: {e}")
    ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
    parts = []
    for p in root.findall(f"{ns}Part"):
        pn = p.findtext(f"{ns}PartNumber")
        etag = (p.findtext(f"{ns}ETag") or "").strip().strip('"')
        if pn is None:
            raise BadRequestError("Part missing PartNumber")
        parts.append((int(pn), etag))
    return parts


async def handle_complete_mpu(ctx) -> web.Response:
    garage = ctx.garage
    key = ctx.key_name
    upload_id = decode_upload_id(ctx.request.query["uploadId"])
    body = await ctx.read_body_verified()
    listed = _parse_complete_body(body)
    if not listed:
        raise EntityTooSmallError("no parts listed")
    if any(a >= b for (a, _), (b, _) in zip(listed, listed[1:])):
        raise ApiError("part order invalid", status=400, code="InvalidPartOrder")

    ov, mpu = await get_upload(ctx, key, upload_id)
    if not mpu.parts:
        raise BadRequestError("no data was uploaded")

    # match listed parts against stored ones (multipart.rs:261-275)
    have = {}
    for (pn, ts), p in mpu.sorted_parts():
        if p.get("etag") is not None:
            have[pn] = p
    chosen = []
    for pn, etag in listed:
        p = have.get(pn)
        if p is None or p["etag"] != etag or p["size"] is None:
            raise InvalidPartError(f"part {pn} not found or etag mismatch")
        chosen.append((pn, p))

    total_size = sum(p["size"] for _pn, p in chosen)
    # quota check FIRST, before any final-version metadata exists: on
    # failure the upload stays intact and retryable (the reference aborts
    # the whole upload here, destroying all parts — deliberately kinder)
    await check_quotas(ctx, total_size, key)

    # splice part blocks into the final version, renumbered 1..N
    # (multipart.rs:286-309)
    final_version = Version(upload_id, bytes(ctx.bucket_id), key)
    for i, (_pn, p) in enumerate(chosen):
        pv = await garage.version_table.get(Uuid(p["version"]), "")
        if pv is None or pv.deleted.value:
            raise InvalidPartError("part version missing")
        for (pk, (h, sz)) in pv.sorted_blocks():
            final_version.blocks[(i + 1, pk[1])] = (h, sz)
        final_version.parts_etags[i + 1] = p["etag"]
    await garage.version_table.insert(final_version)

    # aws multipart etag = md5 of the concatenated BINARY part digests
    # (multipart.rs:319-329 hex-decodes each part etag first)
    md5 = hashlib.md5()
    for _pn, p in chosen:
        try:
            md5.update(bytes.fromhex(p["etag"]))
        except ValueError:
            md5.update(p["etag"].encode())
    etag = f"{md5.hexdigest()}-{len(chosen)}"

    blocks = final_version.sorted_blocks()
    meta = ObjectVersionMeta.new(ov.state[2], total_size, etag)
    first_hash = blocks[0][1][0] if blocks else b"\x00" * 32
    ov_done = ObjectVersion(
        upload_id, ov.timestamp,
        ["complete", ObjectVersionData.first_block(meta, first_hash)],
    )
    await garage.object_table.insert(Object(ctx.bucket_id, key, [ov_done]))

    out = s3_xml_root("CompleteMultipartUploadResult")
    ET.SubElement(out, "Bucket").text = ctx.bucket_name
    ET.SubElement(out, "Key").text = key
    ET.SubElement(out, "ETag").text = f'"{etag}"'
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_abort_mpu(ctx) -> web.Response:
    garage = ctx.garage
    key = ctx.key_name
    upload_id = decode_upload_id(ctx.request.query["uploadId"])
    ov, _mpu = await get_upload(ctx, key, upload_id)
    ov_abort = ObjectVersion(upload_id, ov.timestamp, ["aborted"])
    await garage.object_table.insert(Object(ctx.bucket_id, key, [ov_abort]))
    return web.Response(status=204)
