"""Bucket configuration endpoints: website, CORS, lifecycle.

Equivalent of reference src/api/s3/website.rs + cors.rs + lifecycle.rs
(SURVEY.md §2.7): XML get/put/delete of per-bucket configs stored as LWW
CRDTs in the bucket params, plus `find_matching_cors_rule` used by both
the S3 server and the static web server.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Any, Dict, List, Optional

from aiohttp import web

from ..common import ApiError, BadRequestError, s3_xml_root, xml_to_bytes


def _ns(root) -> str:
    return root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""


async def _update_bucket(ctx, mutate) -> None:
    bucket = await ctx.server.helper.get_existing_bucket(ctx.bucket_id)
    mutate(bucket.params())
    await ctx.garage.bucket_table.insert(bucket)


# --- website ---------------------------------------------------------------


async def handle_get_website(ctx) -> web.Response:
    wc = ctx.bucket.params().website_config.value
    if wc is None:
        raise ApiError(
            "no website configuration", status=404,
            code="NoSuchWebsiteConfiguration",
        )
    out = s3_xml_root("WebsiteConfiguration")
    idx = ET.SubElement(out, "IndexDocument")
    ET.SubElement(idx, "Suffix").text = wc.get("index_document", "index.html")
    if wc.get("error_document"):
        err = ET.SubElement(out, "ErrorDocument")
        ET.SubElement(err, "Key").text = wc["error_document"]
    return web.Response(status=200, body=xml_to_bytes(out), content_type="application/xml")


async def handle_put_website(ctx) -> web.Response:
    body = await ctx.read_body_verified()
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequestError(f"malformed WebsiteConfiguration: {e}")
    ns = _ns(root)
    suffix = root.findtext(f"{ns}IndexDocument/{ns}Suffix")
    if suffix is None:
        raise BadRequestError("IndexDocument.Suffix is required")
    error_doc = root.findtext(f"{ns}ErrorDocument/{ns}Key")
    wc = {"index_document": suffix, "error_document": error_doc}
    await _update_bucket(ctx, lambda p: p.website_config.update(wc))
    return web.Response(status=200)


async def handle_delete_website(ctx) -> web.Response:
    await _update_bucket(ctx, lambda p: p.website_config.update(None))
    return web.Response(status=204)


# --- CORS ------------------------------------------------------------------


async def handle_get_cors(ctx) -> web.Response:
    rules = ctx.bucket.params().cors_config.value
    if rules is None:
        raise ApiError(
            "no CORS configuration", status=404, code="NoSuchCORSConfiguration"
        )
    out = s3_xml_root("CORSConfiguration")
    for r in rules:
        el = ET.SubElement(out, "CORSRule")
        if r.get("id"):
            ET.SubElement(el, "ID").text = r["id"]
        for o in r.get("allow_origins", []):
            ET.SubElement(el, "AllowedOrigin").text = o
        for m in r.get("allow_methods", []):
            ET.SubElement(el, "AllowedMethod").text = m
        for hh in r.get("allow_headers", []):
            ET.SubElement(el, "AllowedHeader").text = hh
        for e in r.get("expose_headers", []):
            ET.SubElement(el, "ExposeHeader").text = e
        if r.get("max_age_seconds") is not None:
            ET.SubElement(el, "MaxAgeSeconds").text = str(r["max_age_seconds"])
    return web.Response(status=200, body=xml_to_bytes(out), content_type="application/xml")


async def handle_put_cors(ctx) -> web.Response:
    body = await ctx.read_body_verified()
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequestError(f"malformed CORSConfiguration: {e}")
    ns = _ns(root)
    rules = []
    for el in root.findall(f"{ns}CORSRule"):
        rule = {
            "id": el.findtext(f"{ns}ID"),
            "allow_origins": [x.text or "" for x in el.findall(f"{ns}AllowedOrigin")],
            "allow_methods": [x.text or "" for x in el.findall(f"{ns}AllowedMethod")],
            "allow_headers": [x.text or "" for x in el.findall(f"{ns}AllowedHeader")],
            "expose_headers": [x.text or "" for x in el.findall(f"{ns}ExposeHeader")],
        }
        ma = el.findtext(f"{ns}MaxAgeSeconds")
        rule["max_age_seconds"] = int(ma) if ma is not None else None
        rules.append(rule)
    await _update_bucket(ctx, lambda p: p.cors_config.update(rules))
    return web.Response(status=200)


async def handle_delete_cors(ctx) -> web.Response:
    await _update_bucket(ctx, lambda p: p.cors_config.update(None))
    return web.Response(status=204)


def cors_request_headers(request) -> List[str]:
    """Parse Access-Control-Request-Headers into a list (ref cors.rs
    split(',')+trim) — shared by the S3 dispatch, preflight, and web
    server so the parsing can't diverge."""
    return [
        h.strip()
        for h in request.headers.get(
            "Access-Control-Request-Headers", "").split(",")
        if h.strip()
    ]


def find_matching_cors_rule(
    rules: Optional[List[Dict]], method: str, origin: Optional[str],
    request_headers: List[str],
) -> Optional[Dict]:
    """ref cors.rs find_matching_cors_rule."""
    if not rules or origin is None:
        return None
    for r in rules:
        if method not in r.get("allow_methods", []) and "*" not in r.get("allow_methods", []):
            continue
        origins = r.get("allow_origins", [])
        ok = any(
            o == "*" or o == origin
            or (o.count("*") == 1 and _glob_match(o, origin))
            for o in origins
        )
        if not ok:
            continue
        allowed = [h.lower() for h in r.get("allow_headers", [])]
        if "*" not in allowed and any(h.lower() not in allowed for h in request_headers):
            continue
        return r
    return None


def _glob_match(pattern: str, s: str) -> bool:
    pre, _, post = pattern.partition("*")
    return s.startswith(pre) and s.endswith(post) and len(s) >= len(pre) + len(post)


def apply_cors_headers(resp_headers: Dict[str, str], rule: Dict, origin: str) -> None:
    resp_headers["Access-Control-Allow-Origin"] = (
        "*" if "*" in rule.get("allow_origins", []) else origin
    )
    if rule.get("expose_headers"):
        resp_headers["Access-Control-Expose-Headers"] = ", ".join(rule["expose_headers"])


def add_cors_headers(resp_headers: Dict[str, str], rule: Dict,
                     origin: str) -> None:
    """Full CORS header set on a matched rule.  Allow-Origin must be ONE
    origin or '*' (browsers reject lists — the reference comma-joins the
    configured origins, cors.rs add_cors_headers, which no browser
    accepts for multi-origin rules); we echo the matched request origin
    like apply_cors_headers does."""
    resp_headers["Access-Control-Allow-Origin"] = (
        "*" if "*" in rule.get("allow_origins", []) else origin
    )
    resp_headers["Access-Control-Allow-Methods"] = ", ".join(
        rule.get("allow_methods", []))
    resp_headers["Access-Control-Allow-Headers"] = ", ".join(
        rule.get("allow_headers", []))
    resp_headers["Access-Control-Expose-Headers"] = ", ".join(
        rule.get("expose_headers", []))


async def handle_options_s3api(server, request, bucket_name) -> web.Response:
    """Unauthenticated CORS preflight (ref cors.rs:90-136
    handle_options_s3api): a global bucket's CORS rules apply; an
    unresolvable name gets the permissive response (could be a local
    alias — preflights can't authenticate); no bucket = ListBuckets,
    open to GET from anywhere."""
    if bucket_name is not None:
        bid = await server.helper.resolve_global_bucket_name(bucket_name)
        if bid is not None:
            bucket = await server.helper.get_existing_bucket(bid)
            return handle_options_for_bucket(request, bucket)
        return web.Response(status=200, headers={
            "Access-Control-Allow-Origin": "*",
            "Access-Control-Allow-Methods": "*",
        })
    return web.Response(status=200, headers={
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Methods": "GET",
    })


def handle_options_for_bucket(request, bucket) -> web.Response:
    """ref cors.rs:138-170 handle_options_for_bucket."""
    origin = request.headers.get("Origin")
    if origin is None:
        raise BadRequestError("Missing Origin header")
    req_method = request.headers.get("Access-Control-Request-Method")
    if req_method is None:
        raise BadRequestError("Missing Access-Control-Request-Method header")
    req_headers = cors_request_headers(request)
    rules = bucket.params().cors_config.value
    rule = find_matching_cors_rule(rules, req_method, origin, req_headers)
    if rule is not None:
        headers: Dict[str, str] = {}
        add_cors_headers(headers, rule, origin)
        return web.Response(status=200, headers=headers)
    raise ApiError("This CORS request is not allowed.", status=403,
                   code="AccessDenied")


# --- lifecycle -------------------------------------------------------------


async def handle_get_lifecycle(ctx) -> web.Response:
    rules = ctx.bucket.params().lifecycle_config.value
    if rules is None:
        raise ApiError(
            "no lifecycle configuration", status=404,
            code="NoSuchLifecycleConfiguration",
        )
    out = s3_xml_root("LifecycleConfiguration")
    for r in rules:
        el = ET.SubElement(out, "Rule")
        if r.get("id"):
            ET.SubElement(el, "ID").text = r["id"]
        ET.SubElement(el, "Status").text = "Enabled" if r.get("enabled", True) else "Disabled"
        f = ET.SubElement(el, "Filter")
        preds = [
            (tag, r[k])
            for tag, k in (
                ("Prefix", "prefix"),
                ("ObjectSizeGreaterThan", "size_gt"),
                ("ObjectSizeLessThan", "size_lt"),
            )
            if r.get(k) not in (None, "")
        ]
        # AWS XML: 2+ predicates must be wrapped in <And>
        parent = ET.SubElement(f, "And") if len(preds) > 1 else f
        for tag, v in preds:
            ET.SubElement(parent, tag).text = str(v)
        if r.get("expiration_days") is not None or r.get("expiration_date"):
            ex = ET.SubElement(el, "Expiration")
            if r.get("expiration_days") is not None:
                ET.SubElement(ex, "Days").text = str(r["expiration_days"])
            if r.get("expiration_date"):
                ET.SubElement(ex, "Date").text = r["expiration_date"]
        if r.get("abort_incomplete_days") is not None:
            ab = ET.SubElement(el, "AbortIncompleteMultipartUpload")
            ET.SubElement(ab, "DaysAfterInitiation").text = str(r["abort_incomplete_days"])
    return web.Response(status=200, body=xml_to_bytes(out), content_type="application/xml")


async def handle_put_lifecycle(ctx) -> web.Response:
    body = await ctx.read_body_verified()
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequestError(f"malformed LifecycleConfiguration: {e}")
    ns = _ns(root)
    rules = []
    for el in root.findall(f"{ns}Rule"):
        status = el.findtext(f"{ns}Status") or "Enabled"
        # AWS wraps multiple Filter predicates in <And>; single predicates
        # sit directly under <Filter>; Prefix may also be legacy top-level
        prefix = (
            el.findtext(f"{ns}Filter/{ns}And/{ns}Prefix")
            or el.findtext(f"{ns}Filter/{ns}Prefix")
            or el.findtext(f"{ns}Prefix")  # legacy top-level form
            or ""
        )
        days = el.findtext(f"{ns}Expiration/{ns}Days")
        date = el.findtext(f"{ns}Expiration/{ns}Date")
        abort_days = el.findtext(
            f"{ns}AbortIncompleteMultipartUpload/{ns}DaysAfterInitiation"
        )
        size_gt = (
            el.findtext(f"{ns}Filter/{ns}And/{ns}ObjectSizeGreaterThan")
            or el.findtext(f"{ns}Filter/{ns}ObjectSizeGreaterThan")
        )
        size_lt = (
            el.findtext(f"{ns}Filter/{ns}And/{ns}ObjectSizeLessThan")
            or el.findtext(f"{ns}Filter/{ns}ObjectSizeLessThan")
        )

        def _int(v, what):
            if v is None:
                return None
            try:
                return int(v)
            except ValueError:
                raise BadRequestError(f"{what} must be an integer, got {v!r}")

        days = _int(days, "Expiration Days")
        if days is not None and days <= 0:
            raise BadRequestError("Expiration Days must be positive")
        rules.append({
            "id": el.findtext(f"{ns}ID"),
            "enabled": status == "Enabled",
            "prefix": prefix,
            "size_gt": _int(size_gt, "ObjectSizeGreaterThan"),
            "size_lt": _int(size_lt, "ObjectSizeLessThan"),
            "expiration_days": days,
            "expiration_date": date,
            "abort_incomplete_days": _int(abort_days, "DaysAfterInitiation"),
        })
    await _update_bucket(ctx, lambda p: p.lifecycle_config.update(rules))
    return web.Response(status=200)


async def handle_delete_lifecycle(ctx) -> web.Response:
    await _update_bucket(ctx, lambda p: p.lifecycle_config.update(None))
    return web.Response(status=204)


HANDLERS = {
    "GetBucketWebsite": handle_get_website,
    "PutBucketWebsite": handle_put_website,
    "DeleteBucketWebsite": handle_delete_website,
    "GetBucketCors": handle_get_cors,
    "PutBucketCors": handle_put_cors,
    "DeleteBucketCors": handle_delete_cors,
    "GetBucketLifecycle": handle_get_lifecycle,
    "PutBucketLifecycle": handle_put_lifecycle,
    "DeleteBucketLifecycle": handle_delete_lifecycle,
}
