"""PutObject — the S3 write path.

Equivalent of reference src/api/s3/put.rs (SURVEY.md §3.2): the body is
chunked into `block_size` blocks (put.rs:392-426); payloads under the
inline threshold are stored directly in the object row (put.rs:84-119);
larger objects create an Uploading version + Version row, then per block
pipeline {put-block RPC, version-meta insert, next-chunk read} with
running md5/sha256 hashing (put.rs:286-360), finishing with the
Complete{FirstBlock} object row.  Block refs are created by the version
table's updated() hook.  On failure the version is aborted and a cleanup
tombstone inserted (put.rs:436-466).
"""

from __future__ import annotations

import asyncio
import binascii
import contextlib
import hashlib
from typing import AsyncIterator, Dict, Optional, Tuple

from aiohttp import web

from ...block.manager import INLINE_THRESHOLD
from ...ops.codec import mhash_stream
from ...model.s3.object_table import (
    Object,
    ObjectVersion,
    ObjectVersionData,
    ObjectVersionHeaders,
    ObjectVersionMeta,
)
from ...model.s3.version_table import Version
from ...utils.crdt import now_msec
from ...utils.data import Hash, block_hash, gen_uuid
from ...utils.tracing import refresh_deadline
from ..common import ApiError, BadRequestError


def request_scope(garage):
    """Bracket one client WRITE request for the codec feeder's in-flight
    count (ops/feeder.py).  Entered at request INTAKE — before quota
    checks, metadata inserts and body streaming — so that by the time
    any request's first hash window is submitted, every concurrent
    writer is already counted and the submit's `peers` hint tells the
    dispatcher how many submissions it may expect to coalesce.  A no-op
    context manager when the feeder is disabled or draining."""
    feeder = getattr(garage.block_manager, "feeder", None)
    if feeder is None or feeder.closed:
        return contextlib.nullcontext()
    return feeder.request_scope()


class Chunker:
    """Re-chunk an async byte stream into fixed-size blocks
    (ref put.rs:392-426 StreamChunker)."""

    def __init__(self, stream: AsyncIterator[bytes], block_size: int):
        self.stream = stream.__aiter__()
        self.block_size = block_size
        self.buf = bytearray()
        self.eof = False

    async def next(self) -> Optional[bytes]:
        while not self.eof and len(self.buf) < self.block_size:
            try:
                self.buf.extend(await self.stream.__anext__())
            except StopAsyncIteration:
                self.eof = True
        if not self.buf:
            return None
        out = bytes(self.buf[: self.block_size])
        del self.buf[: self.block_size]
        return out


def headers_from_request(ctx) -> Dict:
    """Collect stored headers (ref put.rs get_headers)."""
    req = ctx.request
    other = {}
    for h in (
        "cache-control", "content-disposition", "content-encoding",
        "content-language", "expires",
    ):
        if h in req.headers:
            other[h] = req.headers[h]
    for k, v in req.headers.items():
        if k.lower().startswith("x-amz-meta-"):
            other[k.lower()] = v
    return ObjectVersionHeaders.new(
        req.headers.get("Content-Type", "application/octet-stream"), other
    )


async def check_quotas(ctx, add_size: int, key: Optional[str] = None) -> None:
    """ref put.rs check_quotas: max_size/max_objects from bucket params,
    crediting back the object being overwritten."""
    quotas = ctx.bucket.params().quotas.value or {}
    if not (quotas.get("max_size") or quotas.get("max_objects")):
        return
    counters = await ctx.garage.object_counter.get_totals(bytes(ctx.bucket_id))
    prev_objects, prev_size = 0, 0
    if key is not None:
        cur = await ctx.garage.object_table.get(ctx.bucket_id, key)
        lv = cur.last_data_version() if cur is not None else None
        if lv is not None:
            prev_objects, prev_size = 1, lv.size()
    if quotas.get("max_objects") is not None:
        if counters.get("objects", 0) - prev_objects + 1 > quotas["max_objects"]:
            raise ApiError("object quota exceeded", status=403, code="QuotaExceeded")
    if quotas.get("max_size") is not None:
        if counters.get("bytes", 0) - prev_size + add_size > quotas["max_size"]:
            raise ApiError("size quota exceeded", status=403, code="QuotaExceeded")


async def save_stream(
    ctx,
    stream: AsyncIterator[bytes],
    headers: Dict,
    key: str,
    content_md5: Optional[str] = None,
    content_sha256: Optional[str] = None,
    mhash=None,
) -> Tuple[str, int]:
    """Store a full object body; returns (etag, size) (ref put.rs:66-199).

    `mhash` is an optional IncrementalHash (ops/codec.py mhash_stream):
    when provided it advances over the body bytes IN THE SAME off-loop
    hop as md5/sha256, so the whole-object BLAKE2b-256 digest exists at
    stream end without ever rehashing the assembled object — O(1) extra
    state per request, zero extra passes over the data."""
    garage = ctx.garage
    bucket_id = ctx.bucket_id
    chunker = Chunker(stream, garage.config.block_size)
    first = await chunker.next() or b""

    md5 = hashlib.md5()
    sha256 = hashlib.sha256()

    # small payload: store inline in the object row (put.rs:84-119)
    if len(first) < INLINE_THRESHOLD and chunker.eof and not chunker.buf:
        md5.update(first)
        sha256.update(first)
        if mhash is not None:
            mhash.update(first)
        etag = md5.hexdigest()
        _check_digests(etag, sha256.hexdigest(), content_md5, content_sha256)
        await check_quotas(ctx, len(first), key)
        meta = ObjectVersionMeta.new(headers, len(first), etag)
        ov = ObjectVersion(
            gen_uuid(), now_msec(), ["complete", ObjectVersionData.inline(meta, first)]
        )
        await garage.object_table.insert(Object(bucket_id, key, [ov]))
        return etag, len(first)

    # large payload: streaming multi-block write (put.rs:120-199)
    # Pre-check quotas against the declared Content-Length so an over-quota
    # upload is rejected before consuming bandwidth and disk churn (the
    # reference pre-checks with the announced size, put.rs:76-82); the
    # post-stream check below still covers chunked bodies with no length.
    declared = ctx.request.headers.get(
        "x-amz-decoded-content-length",  # payload size under aws-chunked
        ctx.request.headers.get("Content-Length"),
    )
    if declared is not None:
        try:
            declared_n = int(declared)
        except ValueError:
            declared_n = None
        if declared_n is not None:
            await check_quotas(ctx, declared_n, key)
    version_uuid = gen_uuid()
    ts = now_msec()
    ov = ObjectVersion.uploading(version_uuid, ts, False, headers)
    await garage.object_table.insert(Object(bucket_id, key, [ov]))
    version = Version.new(version_uuid, bytes(bucket_id), key)
    await garage.version_table.insert(version)

    try:
        total_size, first_hash = await read_and_put_blocks(
            ctx, version, 0, first, chunker, md5, sha256, mhash=mhash
        )
        etag = md5.hexdigest()
        _check_digests(etag, sha256.hexdigest(), content_md5, content_sha256)
        await check_quotas(ctx, total_size, key)
        meta = ObjectVersionMeta.new(headers, total_size, etag)
        ov_done = ObjectVersion(
            version_uuid, ts,
            ["complete", ObjectVersionData.first_block(meta, first_hash)],
        )
        await garage.object_table.insert(Object(bucket_id, key, [ov_done]))
        return etag, total_size
    except BaseException:
        # cleanup: mark the version aborted (put.rs:436-466); the object
        # hook will tombstone the version row → drop block refs
        try:
            ov_abort = ObjectVersion(version_uuid, ts, ["aborted"])
            await garage.object_table.insert(
                Object(bucket_id, key, [ov_abort])
            )
        except Exception:
            pass
        raise


# Streaming write batching knobs.  HASH_WINDOW: blocks hashed per worker
# hop — ≥4 engages the 8-way SIMD BLAKE2s kernel (4.6× hashlib), and one
# to_thread hop amortizes over the window.  META_BATCH: the version row
# (whole-row CRDT re-insert whose hook creates block refs) lands every
# N blocks instead of every block — ~9 metadata commits/block measured
# down to ~1/block.  Cost: a crash mid-upload can orphan up to
# META_BATCH written-but-unreferenced blocks (the reference's concurrent
# block/meta writes have the same window at 1 block); `repair blocks`
# reclaims them, and the final insert still precedes the Complete row.
HASH_WINDOW = 8
META_BATCH = 8


async def read_and_put_blocks(
    ctx, version: Version, part_number: int, first_block: bytes,
    chunker: Chunker, md5, sha256, mhash=None,
) -> Tuple[int, Hash]:
    """Windowed streaming loop (ref put.rs:286-360 is strictly per-block):
    read up to HASH_WINDOW blocks ahead, hash the window in one worker
    hop (SIMD multi-buffer BLAKE2s; md5/sha256 advance sequentially in
    the same hop), pipeline the per-block quorum writes, and batch the
    version-meta inserts.  Returns (total_size, first_block_hash)."""
    garage = ctx.garage
    algo = garage.block_manager.hash_algo
    codec = garage.block_manager.codec
    # continuous-batching feeder (ops/feeder.py): BLAKE2s block-id
    # hashing SUBMITS here instead of running inline, so K concurrent
    # puts coalesce into one ragged SIMD/device batch while each
    # request's md5/sha256 (stream-sequential, unbatchable) advance in
    # parallel with the feeder wait — the deadline is effectively free
    # whenever the stream digests take longer than the SLO.
    feeder = getattr(garage.block_manager, "feeder", None)
    if feeder is not None and feeder.closed:
        feeder = None
    offset = 0
    first_hash: Optional[Hash] = None
    put_task: Optional[asyncio.Task] = None
    unflushed = 0

    async def put_one(h: Hash, data: bytes, off: int, flush_meta: bool,
                      started: asyncio.Event):
        # add_block runs HERE, not in the dispatch loop: a concurrent
        # flush insert must never encode a version row referencing a
        # block whose quorum write has not started (crash would leave
        # replicas holding rc for a hash no node stores).  Inside the
        # task, the row only ever includes blocks whose write is at
        # least concurrent with the insert — the reference's window.
        version.add_block(part_number, off, bytes(h), len(data))
        started.set()
        if flush_meta:
            # version row (hook creates the block refs) in parallel with
            # the block quorum write (put.rs:362-390)
            await asyncio.gather(
                garage.block_manager.rpc_put_block(h, data),
                garage.version_table.insert(version),
            )
        else:
            await garage.block_manager.rpc_put_block(h, data)

    def update_stream_digests(window):
        # one sequential pass shared by every stream digest: md5/sha256
        # for S3 semantics, plus the optional incremental BLAKE2b state
        # (satellite channel — the content digest is finished the moment
        # the last body byte arrives, no second pass over a 1 GiB body)
        for b in window:
            md5.update(b)
            sha256.update(b)
            if mhash is not None:
                mhash.update(b)

    def hash_window(window):
        update_stream_digests(window)
        if len(window) >= 4:
            return codec.batch_hash(window)
        return [block_hash(b, algo) for b in window]

    try:
        block = first_block
        while block:
            window = [block]
            while len(window) < HASH_WINDOW:
                nb = await chunker.next()
                if nb is None:
                    break
                window.append(nb)
            # the client delivered another window of body bytes: it is
            # demonstrably alive, so the request deadline renews — the
            # budget bounds time-since-progress, never total upload time
            # (a multi-GiB PUT must not be shed at the 30 s mark).  The
            # per-block put_one tasks spawned below inherit the renewed
            # budget at creation.
            refresh_deadline(garage.config.rpc.deadline_default)
            fut = _try_submit(feeder, window)
            if fut is not None:
                # feeder path: the block-id hash is already submitted —
                # run the stream digests OFF the loop and await both.
                # Keeping md5/sha256 off-loop matters beyond latency:
                # an inline digest would hold the event loop for ~4 ms
                # per put, serializing concurrent puts' submissions past
                # each other's SLO window so no batch ever formed; with
                # the hop, K in-flight puts all submit within the
                # deadline and coalesce into one ragged SIMD/device
                # dispatch.  The feeder wait overlaps the digest work.
                await asyncio.to_thread(update_stream_digests, window)
                hashes = list(await asyncio.wrap_future(fut))
            elif (offset == 0 and len(window) == 1 and chunker.eof
                    and not chunker.buf and len(window[0]) <= (1 << 20)):
                # no feeder, truly single-block body (the p50 latency
                # case): hash inline — nothing follows to overlap with,
                # and ≤1 MiB bounds the loop stall to less than an
                # executor hop
                hashes = [hash_window(window)[0]]
            else:
                hashes = await asyncio.to_thread(hash_window, window)
            for b, h in zip(window, hashes):
                if first_hash is None:
                    first_hash = h
                unflushed += 1
                if put_task is not None:
                    await put_task
                flush = unflushed >= META_BATCH
                if flush:
                    unflushed = 0
                started = asyncio.Event()
                put_task = asyncio.ensure_future(
                    put_one(h, b, offset, flush, started))
                offset += len(b)
            block = await chunker.next()
        # the version row must hold every block before the caller lands
        # the Complete object row (a racing GET could miss the tail);
        # gathering with the final block write keeps the small-object
        # overlap the per-block path always had
        if put_task is not None and unflushed:
            # the explicit event (set right after add_block) guarantees
            # the row encodes the tail block regardless of event-loop
            # scheduling policy
            await started.wait()
            await asyncio.gather(
                put_task, garage.version_table.insert(version))
        elif put_task is not None:
            await put_task
    except BaseException:
        if put_task is not None:
            put_task.cancel()
            try:
                await put_task
            except (asyncio.CancelledError, Exception):
                pass
        raise
    return offset, first_hash if first_hash is not None else Hash(b"\x00" * 32)


def _try_submit(feeder, window):
    """Submit a hash window to the codec feeder with the current
    in-flight write-request count (request_scope brackets at the
    handlers) as the `peers` hint; an unbracketed caller reads 0 and
    passes None = unknown, which the dispatcher treats as "wait out the
    SLO".  Returns None when the feeder is absent or closing (shutdown
    race) — the caller hashes inline, exactly the pre-feeder
    behavior."""
    if feeder is None:
        return None
    from ...ops.feeder import FeederClosed

    try:
        return feeder.submit_hash(window,
                                  peers=feeder.inflight_requests or None)
    except FeederClosed:
        return None


def _hash_block(md5, sha256, block: bytes, algo: str) -> Hash:
    md5.update(block)
    sha256.update(block)
    return block_hash(block, algo)


def _check_digests(md5_hex, sha256_hex, content_md5, content_sha256):
    """ref put.rs:200-240 ensure_checksum_matches."""
    if content_md5 is not None:
        expected = binascii.hexlify(binascii.a2b_base64(content_md5)).decode()
        if expected != md5_hex:
            raise ApiError("Content-MD5 mismatch", status=400, code="BadDigest")
    if content_sha256 is not None and content_sha256 != sha256_hex:
        raise ApiError("x-amz-content-sha256 mismatch", status=400, code="BadDigest")


async def handle_put_object(ctx) -> web.Response:
    key = ctx.key_name
    headers = headers_from_request(ctx)
    content_md5 = ctx.request.headers.get("Content-MD5")
    content_sha256 = ctx.verified.content_sha256
    if content_sha256 in (None, "STREAMING"):
        content_sha256 = None
    # incremental whole-object BLAKE2b-256 (utils.data.blake2sum family,
    # the metadata/merkle digest): advanced alongside md5/sha256 during
    # streaming so the digest is free at stream end — surfaced to the
    # client as a response header (schema-safe: no table changes)
    mhash = mhash_stream()
    with request_scope(ctx.garage):
        etag, _size = await save_stream(
            ctx, ctx.body_stream(), headers, key, content_md5, content_sha256,
            mhash=mhash,
        )
    return web.Response(status=200, headers={
        "ETag": f'"{etag}"',
        "x-garage-content-blake2b": mhash.hexdigest(),
    })
