"""S3 API server — request routing, auth, and dispatch.

Equivalent of reference src/api/s3/api_server.rs + generic_server.rs
(SURVEY.md §2.7): an aiohttp server (the hyper analogue) that parses
vhost- or path-style bucket addressing, verifies the SigV4 signature
against the key table, resolves the bucket and checks the endpoint's
required permission level, then dispatches to the per-endpoint handler.
Errors render as S3 XML bodies (generic_server.rs:165-266).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import web

from ...model.helper import (
    BucketAlreadyExists,
    BucketNotEmpty,
    NoSuchBucket,
    NoSuchKey,
)
from ...utils.metrics import maybe_time
from ...utils.tracing import deadline_scope
from ..common import (
    AccessDeniedError,
    ApiError,
    BadRequestError,
    BucketAlreadyExistsError,
    BucketNotEmptyError,
    NoSuchBucketError,
    SlowDownError,
    admit_request,
    client_deadline_budget,
    error_response,
    host_to_bucket,
    parse_bucket_key,
    request_deadline_budget,
    request_trace,
    slo_service_latency,
    start_site,
)
from ..signature import (
    AuthError,
    GarageError,
    InvalidRequest,
    check_signature,
    raw_query_pairs,
)
from .router import NONE, OWNER, READ, WRITE, parse_endpoint

logger = logging.getLogger("garage_tpu.api.s3")


class S3ApiServer:
    def __init__(self, garage):
        self.garage = garage
        self.helper = garage.helper()
        self.region = garage.config.s3_region
        self.root_domain = garage.config.root_domain
        # overload protection (docs/ROBUSTNESS.md "Overload & brownout"):
        # the node-wide admission gate (shared with the K2V server — one
        # node, one capacity), the cluster-aware pressure probe (shed at
        # the front door on behalf of a gossiped-hot storage node) and
        # the per-request deadline budget
        self.gate = getattr(garage, "admission", None)
        self.probe = getattr(garage, "admission_probe", None)
        # SLO burn-rate tracker (utils/slo.py): every finished request —
        # sheds included — lands in it, so admission verdicts burn the
        # availability budget like any other server-side failure
        self.slo = getattr(garage, "slo", None)
        self.deadline_s = request_deadline_budget(garage.config)
        self._runner: Optional[web.AppRunner] = None
        # graceful drain (docs/ROBUSTNESS.md "Geo-WAN & gateway
        # failover"): once draining, NEW requests are shed with a typed
        # 503 while the in-flight set runs to completion inside a
        # bounded window; the state rides NodeStatus gossip
        # (system.drain_state) so sibling gateways absorb load before
        # this socket closes
        self._draining = False
        self._inflight = 0
        self._idle = asyncio.Event()
        self._idle.set()
        # metrics (ref generic_server.rs:63-95)
        self.request_counter = 0
        self.error_counter = 0
        m = getattr(garage.system, "metrics", None)
        if m is not None:
            # families shared across API servers via registry name-dedup;
            # each server records with its own api= label
            self._m = {
                "requests": m.counter(
                    "api_request_counter", "API requests received"),
                "errors": m.counter(
                    "api_error_counter", "API requests answered with an error"),
                "duration": m.histogram(
                    "api_request_duration_seconds", "API request latency",
                    exemplars=True),
            }
        else:
            self._m = None

    # --- server lifecycle ---

    async def start(self, bind_addr: str) -> None:
        app = web.Application(client_max_size=1024**4)
        app.router.add_route("*", "/{tail:.*}", self.handle_request)
        # short shutdown_timeout: drain() already waited for the
        # in-flight set, so cleanup only has idle keep-alives (and an
        # abrupt kill_gateway must not hang 60 s on aborted conns)
        self._runner = web.AppRunner(app, access_log=None,
                                     shutdown_timeout=1.0)
        await self._runner.setup()
        self._site = await start_site(self._runner, bind_addr)
        logger.info("S3 API listening on %s", bind_addr)

    @property
    def port(self) -> int:
        return self._site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            runner, self._runner = self._runner, None  # drain() then
            # Server.stop() may both come through here — clean up once
            await runner.cleanup()

    async def drain(self, timeout: Optional[float] = None) -> float:
        """Graceful drain: stop admitting (typed 503 shed), publish
        "draining" via NodeStatus gossip, wait up to `timeout` for the
        in-flight set to finish, then close the socket and publish
        "drained".  Returns the observed drain window in seconds.  The
        SIGTERM path (server.py) and the gateway_failover drill both
        come through here."""
        import time as _time

        if timeout is None:
            timeout = self.garage.config.api.drain_timeout
        t0 = _time.monotonic()
        self._draining = True
        system = self.garage.system
        system.drain_state = "draining"
        try:
            # push the state to siblings NOW — the whole point is that
            # they learn before the socket goes away
            await system.advertise_status()
        except Exception:  # noqa: BLE001 — drain must finish regardless
            logger.exception("drain: status advertisement failed")
        try:
            await asyncio.wait_for(self._idle.wait(), timeout)
        except asyncio.TimeoutError:
            logger.warning(
                "drain: %d requests still in flight after %.1fs window; "
                "closing anyway", self._inflight, timeout)
        # handlers are done, but a client-paced download's final bytes
        # may still sit in user-space transport buffers (the handler
        # returns as soon as the kernel accepts the writes): closing
        # now would truncate an already-acked response.  Flush inside
        # the same window — kernel-buffered bytes survive the graceful
        # close (FIN sequences after data), user-space ones do not.
        runner = self._runner
        if runner is not None and runner.server is not None:
            deadline = t0 + timeout
            while _time.monotonic() < deadline:
                if not any(c.transport is not None
                           and c.transport.get_write_buffer_size() > 0
                           for c in runner.server.connections):
                    break
                await asyncio.sleep(0.05)
        await self.stop()
        system.drain_state = "drained"
        try:
            await system.advertise_status()
        except Exception:  # noqa: BLE001
            logger.exception("drain: final status advertisement failed")
        return _time.monotonic() - t0

    # --- request handling (ref generic_server.rs:165-266) ---

    async def handle_request(self, request: web.Request) -> web.StreamResponse:
        if self._draining:
            # typed shed, same surface as an admission reject: XML 503
            # SlowDown with RequestId + Retry-After, so pool clients
            # back off and fail over without special-casing drain
            self.request_counter += 1
            self.error_counter += 1
            if self._m is not None:
                self._m["requests"].inc(api="s3")
                self._m["errors"].inc(api="s3", status="503")
            return error_response(
                SlowDownError("gateway is draining; retry against a "
                              "sibling", retry_after=1),
                request.path)
        self._inflight += 1
        self._idle.clear()
        try:
            return await self._serve(request)
        finally:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.set()

    async def _serve(self, request: web.Request) -> web.StreamResponse:
        self.request_counter += 1
        if self._m is not None:
            self._m["requests"].inc(api="s3")
        # admission control BEFORE any per-request work (signature, trace,
        # body): past the watermarks the request is shed with a typed
        # 503 SlowDown + Retry-After instead of queueing toward its
        # client's timeout.  Requests classify into per-tenant WDRR
        # queues (by access key, fallback bucket), and the gossiped
        # pressure of the bucket's placement nodes is folded in so a
        # saturated storage node sheds HERE, not three hops later.
        # Admission is decided once — an admitted request (streaming
        # bodies included) is never shed mid-transfer.
        remote_p = 0.0
        vb = host_to_bucket(
            request.headers.get("Host", ""), self.root_domain)
        bname, key = parse_bucket_key(request.rel_url.raw_path, vb)
        # routing (_handle) reuses THIS parse: classification and
        # dispatch must never disagree about which bucket a request is
        request["s3_bucket_key"] = (bname, key)
        if self.probe is not None:
            remote_p, _hot = self.probe.pressure(bname)
        # the deadline scope arms the request's end-to-end budget —
        # tightened (never extended) by a client-supplied
        # X-Request-Timeout — BEFORE admission, so time queued in the
        # WDRR gate spends the budget instead of stacking on top of it;
        # every nested RPC hop carries what is left and sheds typed
        # once it runs out.
        budget = client_deadline_budget(self.deadline_s, request)
        import time as _time

        t_intake_ns = _time.time_ns()
        with deadline_scope(budget):
            token, shed = await admit_request(
                self.gate, request, remote_pressure=remote_p, bucket=bname)
            t_admitted_ns = _time.time_ns()
            if shed is not None:
                self.error_counter += 1
                if self._m is not None:
                    self._m["errors"].inc(api="s3", status="503")
                if self.slo is not None:
                    # the shed verdict burns the ENDPOINT's availability
                    # budget, not a generic bucket: classify the request
                    # the same way routing would have
                    self.slo.note(
                        self._slo_endpoint(request, bname, key),
                        (_time.time_ns() - t_intake_ns) / 1e9, ok=False)
                return shed
            if token is not None:
                # streaming handlers reconcile Content-Length-less bodies
                # against the token (RequestContext.body_stream)
                request["admission_token"] = token
            try:
                # fresh trace per request (ref generic_server.rs:187-200);
                # child spans (table ops, quorum RPCs, block IO — on
                # EVERY node the request touches, via the propagated
                # context) parent under it.  The request id returned to
                # the client IS the trace id, so a quoted
                # x-amz-request-id is the trace lookup key.  The root is
                # backdated to intake and the admission wait recorded as
                # a child, so the waterfall's segments cover the whole
                # client-observed duration.
                tracer = self.garage.system.tracer
                trace, rid = request_trace(
                    tracer, "S3", "s3", request, start_ns=t_intake_ns)
                if t_admitted_ns > t_intake_ns:
                    tracer.record_span(
                        "admission", trace.trace_id, trace.span_id,
                        t_intake_ns, t_admitted_ns)
                with trace, maybe_time(
                        self._m and self._m["duration"], api="s3"):
                    resp = await self._handle_with_errors(request, rid)
                    trace.set_attr("status", resp.status)
                    ep = request.get("s3_endpoint")
                    if ep is not None:
                        # the waterfall groups by this (PutObject,
                        # GetObject, …), not by raw method
                        trace.set_attr("endpoint", ep)
                    if self.slo is not None:
                        # 5xx burns availability; 4xx is the client's
                        # problem; a SLOW success burns the latency SLO
                        # (client-paced exclusion + body-completion
                        # anchor shared with K2V in slo_service_latency)
                        lat_s, paced = slo_service_latency(
                            request, token, t_intake_ns)
                        self.slo.note(
                            ep or self._slo_endpoint(request, bname, key),
                            lat_s, ok=resp.status < 500,
                            client_paced=paced)
                    if not resp.prepared:
                        resp.headers["x-amz-request-id"] = rid
                    return resp
            finally:
                if token is not None:
                    token.release()

    def _slo_endpoint(self, request, bname, key) -> str:
        """Endpoint classification for requests that never reached the
        router (sheds): the same parse routing uses, degraded to
        'Unknown' on malformed input — classification must stay cheap
        and must never raise on a request we are rejecting anyway."""
        try:
            ep = parse_endpoint(
                request.method, bname, key,
                [(k, v) for k, v in request.query.items()],
                {k.lower(): v for k, v in request.headers.items()})
            return ep.name
        except Exception:  # noqa: BLE001
            return "Unknown"

    async def _handle_with_errors(self, request, rid: str) -> web.StreamResponse:
        try:
            return await self._handle(request)
        except ConnectionError as e:  # incl. ConnectionResetError
            # the CLIENT hung up mid-response (aborted download, closed
            # tab) — normal operation, not a server error; nothing can
            # be written back on a dead transport anyway
            logger.debug("client disconnected mid-request: %s", e)
            raise
        except (ApiError, GarageError, NoSuchBucket, NoSuchKey) as e:
            self.error_counter += 1
            status = getattr(e, "status", 500)
            if self._m is not None:
                self._m["errors"].inc(api="s3", status=str(status))
            if status >= 500 and status != 503:
                logger.exception("S3 API internal error")
            else:
                # 503s (deadline expiry, overload shed) are the defined
                # past-saturation behavior, not an internal fault — a
                # stack trace per shed would melt the log under exactly
                # the load the gate exists to survive
                logger.debug("S3 API error %s: %s", status, e)
            return error_response(e, request.path, rid)
        except Exception as e:  # noqa: BLE001 — uniform 500 rendering
            self.error_counter += 1
            if self._m is not None:
                self._m["errors"].inc(api="s3", status="500")
            logger.exception("S3 API unexpected error")
            return error_response(e, request.path, rid)

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        headers = {k.lower(): v for k, v in request.headers.items()}
        # bucket/key come from the RAW (still-encoded) path, decoded
        # exactly once in parse_bucket_key (request.path is already
        # decoded and would double-decode keys containing %XX); normally
        # handle_request already parsed for admission — reuse it so
        # classification and routing can never disagree
        parsed = request.get("s3_bucket_key")
        if parsed is not None:
            bucket_name, key_name = parsed
        else:
            vhost_bucket = host_to_bucket(
                headers.get("host", ""), self.root_domain)
            bucket_name, key_name = parse_bucket_key(
                request.rel_url.raw_path, vhost_bucket
            )
        query = [(k, v) for k, v in request.query.items()]
        endpoint = parse_endpoint(
            request.method, bucket_name, key_name, query, headers
        )
        # the per-endpoint label the request root (and the waterfall
        # recorder keyed on it) carries
        request["s3_endpoint"] = endpoint.name

        # PostObject authenticates via the signed policy document inside
        # the form, not an Authorization header (ref post_object.rs:1-507)
        if endpoint.name == "PostObject":
            from .post_object import handle_post_object

            return await handle_post_object(self, request, bucket_name)

        # CORS preflight is unauthenticated too (ref api_server.rs:119-121)
        if endpoint.name == "Options":
            from .bucket_config import handle_options_s3api

            return await handle_options_s3api(self, request, bucket_name)

        # authentication (ref api_server.rs:105-130 + signature/)
        async def get_key(key_id: str):
            k = await self.garage.key_table.get(key_id, "")
            if k is None or k.is_deleted():
                return None
            return k

        with self.garage.system.tracer.span("signature verify"):
            verified = await check_signature(
                get_key, self.region, request.method, request.path, query,
                headers,
                raw_path=request.rel_url.raw_path,
                raw_query=raw_query_pairs(request.rel_url.raw_query_string),
            )
        api_key = verified.key

        ctx = RequestContext(
            self, request, verified, endpoint, bucket_name, key_name
        )

        try:
            return await self._dispatch(ctx, endpoint, bucket_name, api_key)
        except BucketAlreadyExists as e:
            raise BucketAlreadyExistsError(str(e))
        except BucketNotEmpty as e:
            raise BucketNotEmptyError(str(e))
        except NoSuchBucket as e:
            raise NoSuchBucketError(str(e))

    async def _dispatch(self, ctx, endpoint, bucket_name, api_key):
        handlers = _handlers()
        if endpoint.name == "ListBuckets":
            return await handlers["ListBuckets"](ctx)
        if endpoint.name == "CreateBucket":
            return await handlers["CreateBucket"](ctx)

        # all other endpoints address an existing bucket
        bucket_id = await self.helper.resolve_bucket(bucket_name, api_key)
        bucket = await self.helper.get_existing_bucket(bucket_id)
        ctx.bucket_id, ctx.bucket = bucket_id, bucket
        if self.probe is not None and bucket_name:
            # teach the admission probe this bucket's placement so the
            # NEXT request can fold the gossiped pressure of its layout
            # nodes into the admit decision
            self.probe.note_bucket(bucket_name, bytes(bucket_id))

        allowed = {
            READ: api_key.allow_read(bucket_id),
            WRITE: api_key.allow_write(bucket_id),
            OWNER: api_key.allow_owner(bucket_id),
            NONE: True,
        }[endpoint.authorization]
        if not allowed:
            raise AccessDeniedError(
                f"key {api_key.key_id} lacks {endpoint.authorization} on bucket"
            )

        h = handlers.get(endpoint.name)
        if h is None:
            # recognized S3 endpoint with no implementation → 501, the
            # reference's catch-all (api_server.rs Err(NotImplemented))
            from ..common import NotImplementedError_

            raise NotImplementedError_(
                f"endpoint {endpoint.name} is not implemented")
        # cross-origin browser callers need the bucket's CORS rule echoed
        # on the actual response too, not just the preflight (ref
        # api_server.rs:170,379-381).  Matched BEFORE the handler runs:
        # streaming handlers (GetObject) send headers on prepare(), after
        # which they are immutable — they merge ctx.cors_headers early.
        origin = ctx.request.headers.get("Origin")
        if origin is not None:
            from .bucket_config import (
                add_cors_headers,
                cors_request_headers,
                find_matching_cors_rule,
            )

            req_headers = cors_request_headers(ctx.request)
            rule = find_matching_cors_rule(
                ctx.bucket.params().cors_config.value,
                ctx.request.method, origin, req_headers,
            )
            if rule is not None:
                add_cors_headers(ctx.cors_headers, rule, origin)

        resp = await h(ctx)
        if ctx.cors_headers and not resp.prepared:
            for k, v in ctx.cors_headers.items():
                resp.headers[k] = v
        return resp


_HANDLERS = None


def _handlers():
    """Endpoint-name → handler table, built once on first request (the
    handler modules import api_server, so module-level would cycle)."""
    global _HANDLERS
    if _HANDLERS is None:
        from . import bucket as b
        from . import bucket_config
        from . import copy as c
        from . import delete as d
        from . import get as g
        from . import list as l
        from . import multipart as m
        from . import put as p

        _HANDLERS = {
            "ListBuckets": b.handle_list_buckets,
            "CreateBucket": b.handle_create_bucket,
            "HeadBucket": b.handle_head_bucket,
            "DeleteBucket": b.handle_delete_bucket,
            "GetBucketLocation": b.handle_get_location,
            "GetBucketVersioning": b.handle_get_versioning,
            "GetBucketAcl": b.handle_get_acl,
            "ListObjects": l.handle_list_objects,
            "ListObjectsV2": l.handle_list_objects_v2,
            "ListMultipartUploads": l.handle_list_multipart_uploads,
            "ListParts": l.handle_list_parts,
            "PutObject": p.handle_put_object,
            "GetObject": g.handle_get_object,
            "HeadObject": g.handle_head_object,
            "DeleteObject": d.handle_delete_object,
            "DeleteObjects": d.handle_delete_objects,
            "CreateMultipartUpload": m.handle_create_mpu,
            "UploadPart": m.handle_upload_part,
            "CompleteMultipartUpload": m.handle_complete_mpu,
            "AbortMultipartUpload": m.handle_abort_mpu,
            "CopyObject": c.handle_copy_object,
            "UploadPartCopy": c.handle_upload_part_copy,
            **bucket_config.HANDLERS,
        }
    return _HANDLERS


class RequestContext:
    """Per-request state handed to endpoint handlers."""

    __slots__ = (
        "server", "request", "verified", "endpoint",
        "bucket_name", "key_name", "bucket_id", "bucket", "cors_headers",
    )

    def __init__(self, server, request, verified, endpoint, bucket_name, key_name):
        self.server = server
        self.request = request
        self.verified = verified
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.key_name = key_name
        self.bucket_id = None
        self.bucket = None
        # CORS headers matched for this request (merged into the response
        # by _dispatch, or by streaming handlers before prepare())
        self.cors_headers = {}

    @property
    def garage(self):
        return self.server.garage

    @property
    def api_key(self):
        return self.verified.key

    async def read_body_verified(self) -> bytes:
        """Read the whole body and check it against the signed
        x-amz-content-sha256 (ref signature verify_signed_content) —
        required for XML-body endpoints so a tampered body can't ride a
        valid header signature."""
        import hashlib

        body = await self.request.read()
        sha = self.verified.content_sha256
        if sha not in (None, "STREAMING"):
            if hashlib.sha256(body).hexdigest() != sha:
                from ..common import ApiError

                raise ApiError(
                    "body does not match signed x-amz-content-sha256",
                    status=403, code="SignatureDoesNotMatch",
                )
        return body

    def body_stream(self):
        """The (possibly chunk-signed) request body as an async byte
        iterator (ref signature/streaming.rs wrapping).  Bodies admitted
        against the Content-Length-less ESTIMATE reconcile the admission
        gate's byte accounting to the actual bytes as they stream."""
        from ..signature import decode_streaming_body

        token = self.request.get("admission_token")

        async def raw():
            async for chunk in self.request.content.iter_any():
                if token is not None:
                    token.note_body_bytes(len(chunk))
                yield chunk
            if token is not None:
                token.body_done()

        if self.verified.content_sha256 == "STREAMING":
            return decode_streaming_body(
                raw(),
                self.api_key.params().secret_key,
                self.verified.credential,
                self.verified.signature,
                self.verified.timestamp,
            )
        return raw()
