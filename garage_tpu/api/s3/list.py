"""List endpoints: ListObjects v1/v2, ListMultipartUploads, ListParts.

Equivalent of reference src/api/s3/list.rs (1286 LoC, SURVEY.md §2.7):
iterative quorum range-reads over the object table with prefix/delimiter
aggregation into common prefixes (jumping past a completed common prefix
instead of scanning its contents), marker/continuation-token pagination.
Multipart uploads are listed from uploading object versions; parts come
from the MPU row.
"""

from __future__ import annotations

import asyncio
import base64
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple

from aiohttp import web

from ..common import (
    BadRequestError,
    int_param,
    iso_timestamp as _iso,
    s3_xml_root,
    xml_to_bytes,
)
from ..signature import uri_encode

PAGE = 1000


def _encoder(q) -> "tuple":
    """encoding-type=url support (ref list.rs:881-887 uriencode_maybe +
    router.rs encoding_type): returns (enc_fn, encoding_type|None).  Keys,
    prefixes, delimiters and markers in the RESPONSE are uri-encoded
    (slash included, uri_encode(s, true)) when the client asked for it —
    how AWS SDKs transport keys with control characters safely."""
    et = q.get("encoding-type")
    if et is None:
        return (lambda v: v), None
    if et != "url":
        raise BadRequestError(f"invalid encoding-type: {et!r}")
    return (lambda v: uri_encode(v, encode_slash=True)), "url"


def _after_prefix(p: str) -> str:
    """Smallest string greater than every string with prefix p (valid in
    both str and utf-8 byte order: increment the last code point)."""
    for i in range(len(p) - 1, -1, -1):
        c = ord(p[i])
        if c < 0x10FFFF:
            return p[:i] + chr(c + 1)
    return p + "\x00"


class _ShardScanner:
    """Bucket-sharded listing driver ([table] list_shards).

    Serves ordered pages of a bucket enumeration.  The first page is the
    serial walk (a listing that fits one page pays zero extra RPCs); once
    it comes back full, the remaining keyspace fans out across disjoint
    sub-ranges whose first pages fetch CONCURRENTLY — each sub-range is
    its own quorum read with its own continuation cursor, so a deep
    enumeration pipelines its round-trips instead of paying one at a
    time.  Pages are consumed strictly in boundary order (shard i only
    serves after shards < i exhausted), so emission order and
    continuation semantics are identical to the serial walk; skewed key
    distributions only lose the prefetch win, never correctness."""

    def __init__(self, ctx, prefix: str):
        g = ctx.garage
        self.table = g.object_table
        self.bucket_id = ctx.bucket_id
        self.prefix = prefix
        tcfg = getattr(getattr(g, "config", None), "table", None)
        self.n = max(1, int(getattr(tcfg, "list_shards", 1) or 1))
        self.shards = None  # lazy fan-out after the first full page
        self.pages = 0
        self.fanned_out = False
        # adaptive speculation: sequential consumers keep the next page
        # in flight; a walk whose jumps outrun whole pages (delimiter
        # strides wider than PAGE keys) turns it off and seeks straight
        # to each requested position instead of paying a mostly-missed
        # page per jump
        self._prefetch_on = True
        m = getattr(g.system, "metrics", None)
        if m is not None:
            self._m_pages = m.histogram(
                "api_list_pages",
                "Table range pages fetched per ListObjects-family "
                "enumeration",
                buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0))
            self._m_fanout = m.counter(
                "api_list_fanout_total",
                "Listings that fanned out across sharded sub-range "
                "scans (vs served by the serial first page)")
        else:
            self._m_pages = self._m_fanout = None

    async def _fetch(self, pos: str, end=None):
        self.pages += 1
        return await self.table.get_range(
            self.bucket_id, pos, filter="any", limit=PAGE,
            end_sort_key=end)

    def _fan_out(self, batch, fetch_pos: str) -> None:
        # split points on the first code point after the user prefix,
        # evenly spaced over printable ASCII — correctness never depends
        # on balance (the last shard is unbounded above, the first
        # starts at the serial cursor), only the prefetch win does
        start_pos = batch[-1].key + "\x00"
        lo, hi = 0x21, 0x7F
        bounds = sorted({
            self.prefix + chr(lo + (hi - lo) * i // self.n)
            for i in range(1, self.n)
        })
        bounds = [b for b in bounds if b > start_pos]
        ends = bounds + [None]
        # the serial first page becomes the first shard's buffer: a
        # delimiter jump back into its discarded tail is served from it,
        # never skipped past
        self.shards = [
            {
                "start": fetch_pos,
                "end": ends[0],
                "buf": batch,            # last completed page, servable
                "buf_start": fetch_pos,  # position it was fetched from
                "task_start": start_pos,
                "task": asyncio.ensure_future(
                    self._fetch(start_pos, ends[0])),
            }
        ] + [
            {
                "start": s,
                "end": e,
                "buf": None,
                "buf_start": None,
                "task_start": s,
                "task": asyncio.ensure_future(self._fetch(s, e)),
            }
            for s, e in zip(bounds, ends[1:])
        ]
        # everything below this key is proven fully enumerated (chained
        # through exhausted shards) — what makes a boundary-anchored
        # speculative page safe to serve at a shard handoff
        self._covered_to = fetch_pos
        self.fanned_out = True
        if self._m_fanout is not None:
            self._m_fanout.inc()

    def _usable_from(self, sh, pos: str, at) -> bool:
        # a page fetched from `at` serves `pos` when it starts at or
        # before it (anything it skipped is < pos, which the caller
        # already consumed), or at this shard's boundary with everything
        # below the boundary proven enumerated — anything else would
        # silently skip every key in [pos, at)
        return at is not None and (
            at <= pos
            or (at == sh["start"] and self._covered_to == sh["start"]))

    async def page(self, pos: str):
        """(objects with key >= pos in key order, done) — `done` means
        the enumeration is complete after this (possibly empty) page."""
        if self.shards is None:
            batch = await self._fetch(pos)
            if len(batch) < PAGE or self.n <= 1:
                return batch, len(batch) < PAGE
            self._fan_out(batch, pos)
            return batch, False
        # The caller may re-request from ANY pos after the one it last
        # asked for (a delimiter jump discards the tail of the returned
        # batch and resumes after the common prefix — possibly BEHIND
        # keys it was already handed).  So each shard KEEPS its last
        # fetched page: re-requests into the tail are served from the
        # buffer instead of paying a fresh quorum fetch per jump, and a
        # shard only retires once its buffered tail proves there is no
        # key at or after pos — never while a jump could still land in
        # it.
        while self.shards:
            sh = self.shards[0]
            if sh["end"] is not None and pos >= sh["end"]:
                # the jump moved past this whole shard
                self._cancel(sh)
                self.shards.pop(0)
                continue
            buf = sh["buf"]
            if buf is not None:
                if not self._usable_from(sh, pos, sh["buf_start"]):
                    # pos regressed behind the buffer — start over at pos
                    sh["buf"] = sh["buf_start"] = None
                    self._cancel(sh)
                    continue
                out = [o for o in buf if o.key >= pos]
                if out:
                    return out, False
                if len(buf) < PAGE:
                    # bounded partial page: no key in [pos, end) at all
                    self._cancel(sh)
                    if sh["end"] is None:
                        return [], True
                    self._covered_to = sh["end"]
                    self.shards.pop(0)
                    continue
                # pos is past the full buffered page
                if pos == buf[-1].key + "\x00":
                    # pure sequential continuation: speculation pays
                    self._prefetch_on = True
                else:
                    # long jump: the next sequential page mostly misses
                    # — stop speculating and seek straight to pos,
                    # unless a speculative page already finished (then
                    # trying it is free)
                    self._prefetch_on = False
                    t0 = sh["task"]
                    if t0 is not None and not t0.done():
                        self._cancel(sh)
            t = sh["task"]
            if t is not None and not self._usable_from(
                    sh, pos, sh["task_start"]):
                self._cancel(sh)
                t = None
            if t is None:
                sh["task_start"] = pos
                sh["task"] = asyncio.ensure_future(
                    self._fetch(pos, sh["end"]))
            fetched_from = sh["task_start"]
            page = await sh["task"]
            sh["task"] = None
            sh["buf"], sh["buf_start"] = page, fetched_from
            if len(page) == PAGE and self._prefetch_on:
                # prefetch the next page while the caller consumes this
                # one — jumps within the buffer don't invalidate it, and
                # a short jump past it lands inside the prefetched
                # page's range, so speculation is almost always consumed
                nxt = page[-1].key + "\x00"
                sh["task_start"] = nxt
                sh["task"] = asyncio.ensure_future(
                    self._fetch(nxt, sh["end"]))
            # loop: the buffer branch serves (or retires) from the new
            # page
        return [], True

    @staticmethod
    def _cancel(sh) -> None:
        t = sh.get("task")
        if t is not None and not t.done():
            t.cancel()
        sh["task"] = None

    def close(self) -> None:
        if self._m_pages is not None and self.pages:
            self._m_pages.observe(float(self.pages))
        for sh in self.shards or ():
            self._cancel(sh)


async def _collect(
    ctx,
    prefix: str,
    delimiter: Optional[str],
    pos: Optional[str],
    max_keys: int,
    marker: Optional[str] = None,
    uploads: bool = False,
    upload_id_marker: Optional[str] = None,
):
    """Enumeration core (ref list.rs).  `pos` = inclusive resume position
    (None → start of prefix); `marker` = last key/prefix already returned
    to the client (v1 semantics — suppresses a re-emitted common prefix).
    Returns (entries, prefixes, truncated, last_returned) where entries =
    [(key, version…)] in key order."""
    entries: List[Tuple[str, object]] = []
    prefixes: List[str] = []
    last_returned: Optional[str] = None
    if pos is None:
        pos = prefix

    scanner = _ShardScanner(ctx, prefix)
    try:
        return await _collect_inner(
            scanner, prefix, delimiter, pos, max_keys, marker,
            uploads, upload_id_marker, entries, prefixes, last_returned,
        )
    finally:
        scanner.close()


async def _collect_inner(
    scanner, prefix, delimiter, pos, max_keys, marker, uploads,
    upload_id_marker, entries, prefixes, last_returned,
):
    while True:
        batch, done = await scanner.page(pos)
        jumped = False
        for obj in batch:
            k = obj.key
            if k < pos:
                continue
            if not k.startswith(prefix):
                if k > prefix:
                    return entries, prefixes, False, last_returned
                continue
            if uploads:
                # uuid order, NOT timestamp order: upload-id-marker
                # pagination resumes by uuid, so emission order must match
                relevant = sorted(
                    (v for v in obj.versions() if v.is_uploading(True)),
                    key=lambda v: bytes(v.uuid),
                )
                if upload_id_marker is not None and k == marker:
                    # resume INSIDE the marker key: only uploads after the
                    # one last returned (filtered BEFORE capacity counting,
                    # or a page could come back empty yet truncated)
                    relevant = [
                        v for v in relevant
                        if bytes(v.uuid).hex() > upload_id_marker
                    ]
            else:
                lv = obj.last_data_version()
                relevant = [lv] if lv is not None else []
            if not relevant:
                continue
            if delimiter:
                rest = k[len(prefix):]
                di = rest.find(delimiter)
                if di >= 0:
                    cp = prefix + rest[: di + len(delimiter)]
                    if marker is not None and cp <= marker:
                        # already returned on a previous page — skip it
                        pos, jumped = _after_prefix(cp), True
                        break
                    if len(entries) + len(prefixes) >= max_keys:
                        return entries, prefixes, True, last_returned
                    prefixes.append(cp)
                    last_returned = ("cp", cp)
                    pos, jumped = _after_prefix(cp), True
                    break
            for v in relevant:
                # capacity check PER VERSION: a key with many concurrent
                # uploads must truncate mid-key (resumed via
                # upload-id-marker), not blow past max_keys
                if len(entries) + len(prefixes) >= max_keys:
                    return entries, prefixes, True, last_returned
                entries.append((k, v))
                last_returned = ("key", k)
        if jumped:
            continue
        if done:
            return entries, prefixes, False, last_returned
        pos = batch[-1].key + "\x00"


async def handle_list_objects(ctx) -> web.Response:
    q = ctx.request.query
    enc, enc_type = _encoder(q)
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter") or None
    marker = q.get("marker") or None
    max_keys = max(0, min(int_param(q.get("max-keys"), "max-keys", 1000), 1000))
    pos = (marker + "\x00") if marker is not None else None

    entries, prefixes, truncated, last = await _collect(
        ctx, prefix, delimiter, pos, max_keys, marker=marker
    )
    out = s3_xml_root("ListBucketResult")
    ET.SubElement(out, "Name").text = ctx.bucket_name
    ET.SubElement(out, "Prefix").text = enc(prefix)
    if marker is not None:
        ET.SubElement(out, "Marker").text = enc(marker)
    if delimiter:
        ET.SubElement(out, "Delimiter").text = enc(delimiter)
    if enc_type:
        ET.SubElement(out, "EncodingType").text = enc_type
    ET.SubElement(out, "MaxKeys").text = str(max_keys)
    ET.SubElement(out, "IsTruncated").text = "true" if truncated else "false"
    if truncated and last is not None:
        ET.SubElement(out, "NextMarker").text = enc(last[1])
    _append_contents(out, entries, prefixes, enc)
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_list_objects_v2(ctx) -> web.Response:
    q = ctx.request.query
    enc, enc_type = _encoder(q)
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter") or None
    max_keys = max(0, min(int_param(q.get("max-keys"), "max-keys", 1000), 1000))
    token = q.get("continuation-token")
    start_after = q.get("start-after")
    marker = None
    if token is not None:
        try:
            decoded = base64.urlsafe_b64decode(token.encode()).decode()
            kind, sep, marker = decoded.partition(":")
            if kind not in ("key", "cp") or not sep:
                raise ValueError(decoded)
        except Exception:
            raise BadRequestError("bad continuation-token")
        # resume exclusively after the last returned item: past the whole
        # prefix if it was a common prefix, just after the key otherwise
        pos = _after_prefix(marker) if kind == "cp" else marker + "\x00"
    elif start_after is not None:
        marker = start_after
        pos = start_after + "\x00"
    else:
        pos = None

    entries, prefixes, truncated, last = await _collect(
        ctx, prefix, delimiter, pos, max_keys, marker=marker
    )
    out = s3_xml_root("ListBucketResult")
    ET.SubElement(out, "Name").text = ctx.bucket_name
    ET.SubElement(out, "Prefix").text = enc(prefix)
    if delimiter:
        ET.SubElement(out, "Delimiter").text = enc(delimiter)
    if enc_type:
        ET.SubElement(out, "EncodingType").text = enc_type
    ET.SubElement(out, "MaxKeys").text = str(max_keys)
    ET.SubElement(out, "KeyCount").text = str(len(entries) + len(prefixes))
    ET.SubElement(out, "IsTruncated").text = "true" if truncated else "false"
    if token is not None:
        ET.SubElement(out, "ContinuationToken").text = token
    if start_after is not None:
        ET.SubElement(out, "StartAfter").text = enc(start_after)
    if truncated and last is not None:
        # the token records WHAT the last item was (key vs common prefix)
        # so resumption can't conflate a key that merely ends with the
        # delimiter with a completed prefix
        kind, value = last
        ET.SubElement(out, "NextContinuationToken").text = (
            base64.urlsafe_b64encode(f"{kind}:{value}".encode()).decode()
        )
    _append_contents(out, entries, prefixes, enc)
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


def _append_contents(out, entries, prefixes, enc=lambda v: v):
    for key, v in entries:
        c = ET.SubElement(out, "Contents")
        ET.SubElement(c, "Key").text = enc(key)
        ET.SubElement(c, "LastModified").text = _iso(v.timestamp)
        ET.SubElement(c, "ETag").text = f'"{v.etag()}"'
        ET.SubElement(c, "Size").text = str(v.size())
        ET.SubElement(c, "StorageClass").text = "STANDARD"
    for cp in prefixes:
        p = ET.SubElement(out, "CommonPrefixes")
        ET.SubElement(p, "Prefix").text = enc(cp)


async def handle_list_multipart_uploads(ctx) -> web.Response:
    q = ctx.request.query
    enc, enc_type = _encoder(q)
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter") or None
    max_uploads = max(0, min(int_param(q.get("max-uploads"), "max-uploads", 1000), 1000))
    key_marker = q.get("key-marker") or None
    upload_id_marker = q.get("upload-id-marker") or None

    # upload-id-marker refines key-marker (ref list.rs:49,208-236): resume
    # INSIDE the marker key, after the given upload id — without it, two
    # pages could never split a key with many concurrent uploads
    if key_marker is not None and upload_id_marker is not None:
        pos = key_marker  # re-scan the marker key, filter below
    elif key_marker is not None:
        pos = key_marker + "\x00"
    else:
        pos = None

    entries, prefixes, truncated, last = await _collect(
        ctx, prefix, delimiter, pos, max_uploads, marker=key_marker,
        uploads=True,
        upload_id_marker=(upload_id_marker if key_marker is not None
                          else None),
    )
    out = s3_xml_root("ListMultipartUploadsResult")
    ET.SubElement(out, "Bucket").text = ctx.bucket_name
    ET.SubElement(out, "Prefix").text = enc(prefix)
    if key_marker is not None:
        ET.SubElement(out, "KeyMarker").text = enc(key_marker)
    if upload_id_marker is not None:
        ET.SubElement(out, "UploadIdMarker").text = upload_id_marker
    if delimiter:
        ET.SubElement(out, "Delimiter").text = enc(delimiter)
    if enc_type:
        ET.SubElement(out, "EncodingType").text = enc_type
    ET.SubElement(out, "MaxUploads").text = str(max_uploads)
    ET.SubElement(out, "IsTruncated").text = "true" if truncated else "false"
    if truncated and last is not None:
        ET.SubElement(out, "NextKeyMarker").text = enc(last[1])
        if entries and last[0] == "key" and entries[-1][0] == last[1]:
            ET.SubElement(out, "NextUploadIdMarker").text = (
                bytes(entries[-1][1].uuid).hex()
            )
    for key, v in entries:
        u = ET.SubElement(out, "Upload")
        ET.SubElement(u, "Key").text = enc(key)
        ET.SubElement(u, "UploadId").text = bytes(v.uuid).hex()
        ET.SubElement(u, "Initiated").text = _iso(v.timestamp)
        ET.SubElement(u, "StorageClass").text = "STANDARD"
    for cp in prefixes:
        p = ET.SubElement(out, "CommonPrefixes")
        ET.SubElement(p, "Prefix").text = enc(cp)
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_list_parts(ctx) -> web.Response:
    from .multipart import get_existing_mpu

    q = ctx.request.query
    upload_id = q.get("uploadId", "")
    max_parts = max(0, min(int_param(q.get("max-parts"), "max-parts", 1000), 1000))
    pmarker = int_param(q.get("part-number-marker"), "part-number-marker", 0)

    mpu = await get_existing_mpu(ctx, upload_id)
    out = s3_xml_root("ListPartsResult")
    ET.SubElement(out, "Bucket").text = ctx.bucket_name
    ET.SubElement(out, "Key").text = ctx.key_name
    ET.SubElement(out, "UploadId").text = upload_id
    ET.SubElement(out, "MaxParts").text = str(max_parts)
    if pmarker:
        ET.SubElement(out, "PartNumberMarker").text = str(pmarker)

    # newest registration per part number, completed parts only
    per_part = {}
    for (pn, ts), p in mpu.sorted_parts():
        if p.get("etag") is not None:
            per_part[pn] = (ts, p)
    items = sorted((pn, tp) for pn, tp in per_part.items() if pn > pmarker)
    truncated = len(items) > max_parts
    items = items[:max_parts]
    ET.SubElement(out, "IsTruncated").text = "true" if truncated else "false"
    if truncated:
        ET.SubElement(out, "NextPartNumberMarker").text = str(items[-1][0])
    for pn, (ts, p) in items:
        el = ET.SubElement(out, "Part")
        ET.SubElement(el, "PartNumber").text = str(pn)
        ET.SubElement(el, "ETag").text = f'"{p["etag"]}"'
        ET.SubElement(el, "Size").text = str(p["size"] or 0)
        ET.SubElement(el, "LastModified").text = _iso(ts)
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )
