"""PostObject — browser form uploads (multipart/form-data).

Equivalent of reference src/api/s3/post_object.rs:1-507: parse the
multipart form (fields before the `file` part), verify the POST policy
document — signature = hex(HMAC-SHA256(SigV4 signing key, base64 policy))
(ref signature/payload.rs:322-359 verify_v4) — check its expiration and
match every provided form field against the policy's eq / starts-with /
content-length-range conditions, then stream the file through the same
save_stream path as PutObject.  Responds per success_action_redirect /
success_action_status (204 default / 200 / 201-with-XML / 303 redirect).
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import urllib.parse
import xml.etree.ElementTree as ET
from typing import AsyncIterator, Dict, Optional, Tuple

from aiohttp import web

from ...model.s3.object_table import ObjectVersionHeaders
from ..common import (
    AccessDeniedError,
    ApiError,
    BadRequestError,
    s3_xml_root,
    xml_to_bytes,
)
from ..signature import AuthError, Credential, signing_key
from .put import request_scope, save_stream

FIELD_LIMIT = 16 * 1024          # per-field size (ref post_object.rs:37-41)
FILE_LIMIT = 5 * 1024**3         # max file part

# fields the policy never needs to cover (ref post_object.rs:158-160)
ALWAYS_ALLOWED = {"policy", "x-amz-signature"}


class _PolicyConditions:
    """Parsed policy conditions (ref post_object.rs Policy::into_conditions):
    params: lowercased field -> [("eq"|"starts-with", value)];
    content_length: inclusive (min, max)."""

    def __init__(self, raw: list):
        self.params: Dict[str, list] = {}
        lo, hi = 0, (1 << 63)
        for cond in raw:
            if isinstance(cond, dict):
                if len(cond) != 1:
                    raise BadRequestError("invalid policy item")
                (k, v), = cond.items()
                self.params.setdefault(k.lower(), []).append(("eq", str(v)))
            elif isinstance(cond, list) and len(cond) == 3 and \
                    cond[0] == "content-length-range":
                lo = max(lo, int(cond[1]))
                hi = min(hi, int(cond[2]))
            elif isinstance(cond, list) and len(cond) == 3:
                op, key, value = cond
                if not isinstance(key, str) or not key.startswith("$"):
                    raise BadRequestError("invalid policy item")
                if op not in ("eq", "starts-with"):
                    raise BadRequestError("invalid policy item")
                self.params.setdefault(key[1:].lower(), []).append(
                    (op, str(value))
                )
            else:
                raise BadRequestError("invalid policy item")
        self.content_length = (lo, hi)

    def check(self, field: str, value: str, override_value: Optional[str] = None):
        """Consume and verify the conditions for one provided field
        (ref post_object.rs:154-220)."""
        if field in ALWAYS_ALLOWED:
            return
        if field.startswith("x-ignore-"):
            # AWS quirk: x-ignore-* fields skip checking but their policy
            # entries are NOT consumed (so they fail the required-check)
            return
        conds = self.params.pop(field, None)
        if conds is None:
            raise BadRequestError(f"key {field!r} is not allowed in policy")
        v = override_value if override_value is not None else value
        for op, s in conds:
            if op == "eq":
                ok = s == v
            elif field == "content-type":
                ok = all(part.startswith(s) for part in v.split(","))
            else:
                ok = v.startswith(s)
            if not ok:
                raise BadRequestError(
                    f"key {field!r} has value not allowed in policy"
                )


async def handle_post_object(server, request: web.Request,
                             bucket_name: str) -> web.Response:
    garage = server.garage
    try:
        reader = await request.multipart()
    except (ValueError, AssertionError) as e:
        raise BadRequestError(f"could not parse multipart body: {e}")

    params: Dict[str, str] = {}
    file_part = None
    async for part in reader:
        name = (part.name or "").lower()
        if name == "file":
            file_part = part
            break
        text = (await part.read_chunk(FIELD_LIMIT + 1)).decode(
            "utf-8", "replace"
        )
        if len(text) > FIELD_LIMIT:
            raise BadRequestError(f"field {name!r} too large")
        if name == "tag":
            continue  # tags unsupported, match reference behavior
        if name == "acl":
            name = "x-amz-acl"
        if name in params:
            raise BadRequestError(f"field {name!r} provided more than once")
        params[name] = text
    if file_part is None:
        raise BadRequestError("request did not contain a file")

    key = params.get("key")
    if key is None:
        raise BadRequestError("no key was provided")
    credential = params.get("x-amz-credential")
    if credential is None:
        raise AccessDeniedError("anonymous access is not supported")
    policy_b64 = params.get("policy")
    if policy_b64 is None:
        raise BadRequestError("no policy was provided")
    signature = params.get("x-amz-signature")
    if signature is None:
        raise BadRequestError("no signature was provided")
    if "x-amz-date" not in params:
        raise BadRequestError("no date was provided")

    if "${filename}" in key and file_part.filename:
        key = key.replace("${filename}", file_part.filename)

    # --- verify the policy signature (ref payload.rs:322-359) ---
    cred = Credential(credential)
    if cred.region not in (server.region, ""):
        raise AuthError(f"scope region {cred.region!r} mismatch")
    api_key = await garage.key_table.get(cred.key_id, "")
    if api_key is None or api_key.is_deleted():
        raise AuthError(f"no such key: {cred.key_id}")
    sk = signing_key(
        api_key.params().secret_key, cred.date, cred.region, cred.service
    )
    expected = hmac.new(sk, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expected, signature):
        raise AuthError("invalid policy signature")

    bucket_id = await server.helper.resolve_bucket(bucket_name, api_key)
    if not api_key.allow_write(bucket_id):
        raise AccessDeniedError("no write permission on bucket")
    bucket = await server.helper.get_existing_bucket(bucket_id)

    # --- decode + check the policy document ---
    try:
        policy = json.loads(base64.b64decode(policy_b64))
        expiration = policy["expiration"]
        if not isinstance(expiration, str):
            raise TypeError("expiration must be a string")
        conditions = _PolicyConditions(policy["conditions"])
    except (ValueError, KeyError, TypeError) as e:
        raise BadRequestError(f"invalid policy: {e}")
    try:
        exp = datetime.datetime.fromisoformat(expiration.replace("Z", "+00:00"))
    except ValueError:
        raise BadRequestError("invalid expiration date")
    if exp.tzinfo is None:
        exp = exp.replace(tzinfo=datetime.timezone.utc)
    if datetime.datetime.now(datetime.timezone.utc) > exp:
        raise BadRequestError("policy expired")

    for field, value in params.items():
        # the `key` condition checks the post-${filename} substitution
        conditions.check(field, value, override_value=key if field == "key" else None)
    if conditions.params:
        missing = next(iter(conditions.params))
        raise BadRequestError(
            f"key {missing!r} is required in policy but no value was provided"
        )

    headers = _headers_from_params(params)
    lo, hi = conditions.content_length
    hi = min(hi, FILE_LIMIT)  # 5 GiB single-part cap regardless of policy

    class _Ctx:
        """Minimal RequestContext stand-in for save_stream."""
        pass

    ctx = _Ctx()
    ctx.garage = garage
    ctx.request = request
    ctx.bucket_id = bucket_id
    ctx.bucket = bucket

    # size violations raise from inside the stream (over-max early,
    # under-min at EOF) so save_stream's cleanup aborts the version
    with request_scope(garage):
        etag, _size = await save_stream(
            ctx, _limited_stream(file_part, lo, hi), headers, key
        )

    etag_q = f'"{etag}"'
    redirect = params.get("success_action_redirect")
    if redirect is not None:
        u = urllib.parse.urlparse(redirect)
        if u.scheme in ("http", "https"):
            sep = "&" if u.query else "?"
            target = (
                redirect + sep + urllib.parse.urlencode(
                    {"bucket": bucket_name, "key": key, "etag": etag_q}
                )
            )
            return web.Response(
                status=303, headers={"Location": target, "ETag": etag_q},
                body=target.encode(),
            )

    host = request.headers.get("Host", "")
    base_path = request.path.rstrip("/") + "/"
    key_part = urllib.parse.quote(key)
    location = f"https://{host}{base_path}{key_part}" \
        if host else base_path + key_part
    action = params.get("success_action_status", "204")
    if action == "200":
        return web.Response(
            status=200, headers={"Location": location, "ETag": etag_q}
        )
    if action == "201":
        out = s3_xml_root("PostResponse")
        ET.SubElement(out, "Location").text = location
        ET.SubElement(out, "Bucket").text = bucket_name
        ET.SubElement(out, "Key").text = key
        ET.SubElement(out, "ETag").text = etag_q
        return web.Response(
            status=201, headers={"Location": location, "ETag": etag_q},
            body=xml_to_bytes(out), content_type="application/xml",
        )
    return web.Response(status=204, headers={"Location": location, "ETag": etag_q})


def _headers_from_params(params: Dict[str, str]) -> Dict:
    """Stored headers from the form fields (ref put.rs get_headers over the
    collected param HeaderMap)."""
    other = {}
    for h in (
        "cache-control", "content-disposition", "content-encoding",
        "content-language", "expires",
    ):
        if h in params:
            other[h] = params[h]
    for k, v in params.items():
        if k.startswith("x-amz-meta-"):
            other[k] = v
    return ObjectVersionHeaders.new(
        params.get("content-type", "application/octet-stream"), other
    )


async def _limited_stream(part, lo: int, hi: int) -> AsyncIterator[bytes]:
    """Stream the file part, failing early once the max length is exceeded
    (ref post_object.rs StreamLimiter)."""
    read = 0
    while True:
        chunk = await part.read_chunk(64 * 1024)
        if not chunk:
            if read < lo:
                raise BadRequestError("file size does not match policy")
            break
        read += len(chunk)
        if read > hi:
            raise BadRequestError("file size does not match policy")
        yield chunk
