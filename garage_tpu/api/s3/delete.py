"""DeleteObject(s).

Equivalent of reference src/api/s3/delete.rs: deletion inserts a new
complete version holding a DeleteMarker; the object merge prunes all
older versions, cascading through the version table hook to block-ref
deletion (delete.rs:20-80).  DeleteObjects handles the XML batch form
(delete.rs:82-169).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

from aiohttp import web

from ...model.s3.object_table import Object, ObjectVersion, ObjectVersionData
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ..common import BadRequestError, s3_xml_root, xml_to_bytes


async def delete_object_inner(ctx, key: str):
    """Returns (deleted_something, delete_marker_uuid) (ref delete.rs:20-60)."""
    garage = ctx.garage
    obj = await garage.object_table.get(ctx.bucket_id, key)
    if obj is None or obj.last_data_version() is None:
        return False, None
    del_uuid = gen_uuid()
    ov = ObjectVersion(
        del_uuid, now_msec(), ["complete", ObjectVersionData.delete_marker()]
    )
    await garage.object_table.insert(Object(ctx.bucket_id, key, [ov]))
    return True, del_uuid


async def handle_delete_object(ctx) -> web.Response:
    await delete_object_inner(ctx, ctx.key_name)
    # S3 returns 204 regardless of prior existence
    return web.Response(status=204)


async def handle_delete_objects(ctx) -> web.Response:
    """POST /?delete with <Delete><Object><Key>…</Key></Object>…</Delete>."""
    body = await ctx.read_body_verified()
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError as e:
        raise BadRequestError(f"malformed Delete XML: {e}")
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[: root.tag.index("}") + 1]
    quiet = (root.findtext(f"{ns}Quiet") or "").lower() == "true"

    out = s3_xml_root("DeleteResult")
    for obj_el in root.findall(f"{ns}Object"):
        key = obj_el.findtext(f"{ns}Key")
        if key is None:
            continue
        try:
            deleted, _uuid = await delete_object_inner(ctx, key)
            if not quiet:
                d = ET.SubElement(out, "Deleted")
                ET.SubElement(d, "Key").text = key
        except Exception as e:  # noqa: BLE001 — per-key error entries
            err = ET.SubElement(out, "Error")
            ET.SubElement(err, "Key").text = key
            ET.SubElement(err, "Code").text = getattr(e, "code", "InternalError")
            ET.SubElement(err, "Message").text = str(e)
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )
