"""S3-compatible API (ref src/api/s3/)."""
