"""CopyObject / UploadPartCopy — server-side copies.

Equivalent of reference src/api/s3/copy.rs (693 LoC, SURVEY.md §2.7):
CopyObject duplicates metadata and re-references the source blocks in a
new version (no data movement — refcounts do the sharing); UploadPartCopy
splices a byte range of the source into an upload part, re-referencing
whole blocks where aligned and re-writing only the cut edges.
"""

from __future__ import annotations

import hashlib
import urllib.parse
import xml.etree.ElementTree as ET

from aiohttp import web

from ...model.s3.mpu_table import MpuPart
from ...model.s3.object_table import (
    Object,
    ObjectVersion,
    ObjectVersionData,
    ObjectVersionMeta,
)
from ...model.s3.version_table import Version
from ...utils.crdt import now_msec
from ...utils.data import Hash, Uuid, block_hash, gen_uuid
from ..common import (
    iso_timestamp as _iso,
    AccessDeniedError,
    BadRequestError,
    NoSuchKeyError,
    s3_xml_root,
    xml_to_bytes,
)
from .get import parse_range
from .multipart import decode_upload_id, get_upload


def _parse_http_date(value: str, header: str) -> float:
    """HTTP-date → epoch seconds; malformed → 400 (ref copy.rs parse).
    Timezone-less forms (asctime, -0000) are UTC per RFC 9110."""
    import datetime
    from email.utils import parsedate_to_datetime

    try:
        dt = parsedate_to_datetime(value)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        return dt.timestamp()
    except (TypeError, ValueError):
        raise BadRequestError(f"Invalid date in {header}")


def _etag_list(value: str):
    return [m.strip().strip('"') for m in value.split(",")]


def check_copy_preconditions(ctx, src_version) -> None:
    """x-amz-copy-source-if-{match,none-match,modified-since,
    unmodified-since} (ref copy.rs:496-585 CopyPreconditionHeaders).
    Combination rules follow the reference: if-match overrides
    if-unmodified-since; if-none-match AND if-modified-since must both
    hold; other mixes are rejected as 400."""
    h = ctx.request.headers
    im = h.get("x-amz-copy-source-if-match")
    inm = h.get("x-amz-copy-source-if-none-match")
    ims = h.get("x-amz-copy-source-if-modified-since")
    ius = h.get("x-amz-copy-source-if-unmodified-since")
    if im is None and inm is None and ims is None and ius is None:
        return
    etag = src_version.etag()
    # second granularity: clients echo Last-Modified (whole seconds) back
    # into these headers; sub-second remainder must not flip the outcome
    v_date = src_version.timestamp // 1000
    ims_t = (_parse_http_date(ims, "x-amz-copy-source-if-modified-since")
             if ims is not None else None)
    ius_t = (_parse_http_date(ius, "x-amz-copy-source-if-unmodified-since")
             if ius is not None else None)

    if im is not None and inm is None and ims is None:
        ok = any(x == etag or x == "*" for x in _etag_list(im))
    elif ius is not None and im is None and inm is None and ims is None:
        ok = v_date <= ius_t
    elif inm is not None and im is None and ius is None:
        ok = not any(x == etag or x == "*" for x in _etag_list(inm))
        if ims is not None:
            ok = ok and v_date > ims_t
    elif ims is not None and im is None and inm is None and ius is None:
        ok = v_date > ims_t
    else:
        raise BadRequestError(
            "Invalid combination of x-amz-copy-source-if-xxxxx headers"
        )
    if not ok:
        from ..common import PreconditionFailedError

        raise PreconditionFailedError("copy source precondition failed")


async def _resolve_copy_source(ctx):
    """x-amz-copy-source → (bucket_id, key, object, data version)."""
    src = ctx.request.headers.get("x-amz-copy-source", "")
    src = urllib.parse.unquote(src)
    if src.startswith("/"):
        src = src[1:]
    if "/" not in src:
        raise BadRequestError(f"bad x-amz-copy-source {src!r}")
    src_bucket_name, src_key = src.split("/", 1)
    helper = ctx.server.helper
    src_bucket_id = await helper.resolve_bucket(src_bucket_name, ctx.api_key)
    if not ctx.api_key.allow_read(src_bucket_id):
        raise AccessDeniedError("no read permission on copy source bucket")
    obj = await ctx.garage.object_table.get(src_bucket_id, src_key)
    if obj is None:
        raise NoSuchKeyError(f"no such key: {src_key}")
    version = obj.last_data_version()
    if version is None:
        raise NoSuchKeyError(f"no such key: {src_key}")
    check_copy_preconditions(ctx, version)
    return src_bucket_id, src_key, obj, version


async def handle_copy_object(ctx) -> web.Response:
    garage = ctx.garage
    _sb, _sk, _sobj, src_version = await _resolve_copy_source(ctx)
    dest_key = ctx.key_name
    meta = src_version.meta()
    data = src_version.data()
    new_uuid = gen_uuid()
    ts = now_msec()

    # x-amz-metadata-directive=REPLACE takes the new object's headers from
    # the request instead of the source (ref copy.rs:52); default is COPY
    directive = ctx.request.headers.get(
        "x-amz-metadata-directive", "COPY"
    ).upper()
    if directive == "REPLACE":
        from .put import headers_from_request

        stored_headers = headers_from_request(ctx)
    elif directive == "COPY":
        stored_headers = meta["headers"]
    else:
        raise BadRequestError(
            f"bad x-amz-metadata-directive {directive!r} (COPY or REPLACE)"
        )

    if data[0] == "inline":
        new_meta = ObjectVersionMeta.new(stored_headers, meta["size"], meta["etag"])
        ov = ObjectVersion(
            new_uuid, ts, ["complete", ObjectVersionData.inline(new_meta, bytes(data[2]))]
        )
        await garage.object_table.insert(Object(ctx.bucket_id, dest_key, [ov]))
    else:
        src_ver_row = await garage.version_table.get(src_version.uuid, "")
        if src_ver_row is None:
            raise NoSuchKeyError("source version metadata missing")
        # re-reference all source blocks under a fresh version uuid
        # (copy.rs: no payload bytes move; the version hook increfs)
        new_version = Version(new_uuid, bytes(ctx.bucket_id), dest_key)
        for (pk, (h, sz)) in src_ver_row.sorted_blocks():
            new_version.blocks[pk] = (h, sz)
        new_version.parts_etags = dict(src_ver_row.parts_etags)
        await garage.version_table.insert(new_version)
        new_meta = ObjectVersionMeta.new(stored_headers, meta["size"], meta["etag"])
        ov = ObjectVersion(
            new_uuid, ts,
            ["complete", ObjectVersionData.first_block(new_meta, bytes(data[2]))],
        )
        await garage.object_table.insert(Object(ctx.bucket_id, dest_key, [ov]))

    out = s3_xml_root("CopyObjectResult")
    ET.SubElement(out, "LastModified").text = _iso(ts)
    ET.SubElement(out, "ETag").text = f'"{meta["etag"]}"'
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_upload_part_copy(ctx) -> web.Response:
    garage = ctx.garage
    from ..common import int_param

    q = ctx.request.query
    part_number = int_param(q.get("partNumber"), "partNumber")
    if part_number is None or not 1 <= part_number <= 10000:
        raise BadRequestError("partNumber must be in [1, 10000]")
    upload_id = decode_upload_id(q["uploadId"])
    _ov, mpu = await get_upload(ctx, ctx.key_name, upload_id)

    _sb, _sk, _sobj, src_version = await _resolve_copy_source(ctx)
    meta = src_version.meta()
    data = src_version.data()
    size = meta["size"]

    rng_header = ctx.request.headers.get("x-amz-copy-source-range")
    if rng_header is not None:
        r = parse_range(rng_header, size, clamp=False)
        if r is None:
            raise BadRequestError(f"bad x-amz-copy-source-range {rng_header!r}")
        begin, end = r
    else:
        begin, end = 0, size

    ts = now_msec()
    part_version_uuid = gen_uuid()
    mpu.parts[(part_number, ts)] = MpuPart.new(bytes(part_version_uuid), None, None)
    await garage.mpu_table.insert(mpu)
    version = Version(
        part_version_uuid, bytes(ctx.bucket_id), ctx.key_name,
        mpu_upload_id=bytes(upload_id),
    )

    md5 = hashlib.md5()
    algo = garage.block_manager.hash_algo

    if data[0] == "inline":
        piece = bytes(data[2])[begin:end]
        md5.update(piece)
        if piece:
            h = block_hash(piece, algo)
            await garage.block_manager.rpc_put_block(h, piece)
            version.add_block(part_number, 0, bytes(h), len(piece))
        await garage.version_table.insert(version)
    else:
        src_ver_row = await garage.version_table.get(src_version.uuid, "")
        if src_ver_row is None:
            raise NoSuchKeyError("source version metadata missing")
        # whole blocks inside [begin,end) are re-referenced; cut edges are
        # re-read, sliced, re-hashed and re-written (copy.rs block splice)
        abs_off = 0
        out_off = 0
        for (_pk, (h, sz)) in src_ver_row.sorted_blocks():
            b0, b1 = abs_off, abs_off + sz
            abs_off = b1
            if b1 <= begin or b0 >= end:
                continue
            if b0 >= begin and b1 <= end:
                version.add_block(part_number, out_off, h, sz)
                chunk = await garage.block_manager.rpc_get_block(Hash(h))
                md5.update(chunk)
                out_off += sz
            else:
                chunk = await garage.block_manager.rpc_get_block(Hash(h))
                piece = chunk[max(0, begin - b0): min(sz, end - b0)]
                md5.update(piece)
                nh = block_hash(piece, algo)
                await garage.block_manager.rpc_put_block(nh, piece)
                version.add_block(part_number, out_off, bytes(nh), len(piece))
                out_off += len(piece)
        # single metadata write with the complete block map (a per-block
        # insert would quorum-write the whole growing map O(n²) times)
        await garage.version_table.insert(version)

    etag = md5.hexdigest()
    mpu.parts[(part_number, ts)] = MpuPart.new(
        bytes(part_version_uuid), etag, end - begin
    )
    await garage.mpu_table.insert(mpu)

    out = s3_xml_root("CopyPartResult")
    ET.SubElement(out, "LastModified").text = _iso(ts)
    ET.SubElement(out, "ETag").text = f'"{etag}"'
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )
