"""GetObject / HeadObject — the S3 read path.

Equivalent of reference src/api/s3/get.rs (SURVEY.md §3.3): quorum read
of the object row, conditional headers (If-None-Match / If-Modified-Since
→ 304, get.rs:27-89), range and partNumber reads touching only the
intersecting blocks (get.rs:432-512), and a streaming body assembled from
per-block RPC streams with order tags and prefetch of the next block.
"""

from __future__ import annotations

import asyncio
import email.utils
import logging
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from ...utils.data import Hash, Uuid
from ...utils.tracing import refresh_deadline
from ..common import (
    ApiError,
    BadRequestError,
    InvalidRangeError,
    NoSuchKeyError,
    PreconditionFailedError,
)

PREFETCH = 2  # buffered(2) block prefetch (ref get.rs:458-466)

logger = logging.getLogger("garage_tpu.api.s3")


async def get_object_version(ctx, key: str):
    """Object row → newest complete data version, else NoSuchKey."""
    obj = await ctx.garage.object_table.get(ctx.bucket_id, key)
    last = obj.last_data_version() if obj is not None else None
    if last is None:
        raise NoSuchKeyError(f"no such key: {key}")
    return obj, last


def object_headers(version, meta: Dict) -> Dict[str, str]:
    """Response headers from stored meta (ref get.rs:60-90)."""
    hdrs = {
        "Content-Type": meta["headers"].get("content_type", "application/octet-stream"),
        "ETag": f'"{meta["etag"]}"',
        "Last-Modified": email.utils.formatdate(version.timestamp / 1000, usegmt=True),
        "Accept-Ranges": "bytes",
        "x-amz-version-id": bytes(version.uuid).hex(),
    }
    for k, v in meta["headers"].get("other", {}).items():
        hdrs[k] = v
    return hdrs


def check_conditions(ctx, version, meta) -> Optional[int]:
    """Conditional request handling; returns an HTTP status to short-
    circuit with, or None (ref get.rs:27-58 try_answer_cached)."""
    req = ctx.request
    etag = f'"{meta["etag"]}"'
    inm = req.headers.get("If-None-Match")
    if inm is not None:
        tags = [t.strip() for t in inm.split(",")]
        if etag in tags or "*" in tags:
            return 304
    ims = req.headers.get("If-Modified-Since")
    if ims is not None and inm is None:
        t = email.utils.parsedate_to_datetime(ims)
        if t is not None and version.timestamp / 1000 <= t.timestamp():
            return 304
    im = req.headers.get("If-Match")
    if im is not None:
        tags = [t.strip() for t in im.split(",")]
        if etag not in tags and "*" not in tags:
            raise PreconditionFailedError("If-Match failed")
    ius = req.headers.get("If-Unmodified-Since")
    if ius is not None:
        t = email.utils.parsedate_to_datetime(ius)
        if t is not None and version.timestamp / 1000 > t.timestamp():
            raise PreconditionFailedError("If-Unmodified-Since failed")
    return None


def parse_range(header: str, size: int,
                clamp: bool = True) -> Optional[Tuple[int, int]]:
    """'bytes=a-b' → (begin, end_exclusive).  Returns None for a
    syntactically malformed header (S3 ignores those and serves the full
    object); raises InvalidRangeError (416) for unsatisfiable ranges.

    clamp=True (the GET path): RFC 7233 §2.1 — an end past the object
    clamps to the last byte, and only a start beyond the object (or an
    inverted range) is unsatisfiable; "bytes=50-200" on a 62-byte object
    serves bytes 50-61, not 416 (caught porting ref objects.rs's range
    matrix).  clamp=False (UploadPartCopy's x-amz-copy-source-range):
    AWS REJECTS out-of-bounds copy ranges — silently truncating would
    hand the client a short part and a wrong multipart object."""
    if not header.startswith("bytes="):
        return None
    spec = header[len("bytes="):]
    if "," in spec:
        return None
    a, _, b = spec.partition("-")
    try:
        if a == "":
            # suffix range: last N bytes
            n = int(b)
            if n < 0:
                return None  # "bytes=--5": malformed, serve full object
            if n == 0:
                raise InvalidRangeError("zero suffix range")
            begin, end = max(0, size - n), size
        else:
            begin = int(a)
            end = int(b) + 1 if b != "" else size
            if clamp:
                end = min(end, size)
            elif end > size:
                raise InvalidRangeError(
                    f"range {header} out of bounds for size {size}")
    except ValueError:
        return None
    # common validation — the suffix branch flows through too, so a
    # suffix on an empty object is 416, never a (0, 0) degenerate range
    if begin >= size or begin >= end:
        raise InvalidRangeError(f"range {header} out of bounds for size {size}")
    return begin, end


async def handle_head_object(ctx) -> web.Response:
    _obj, version = await get_object_version(ctx, ctx.key_name)
    meta = version.meta()
    status = check_conditions(ctx, version, meta)
    if status is not None:
        return web.Response(status=status)
    hdrs = object_headers(version, meta)

    from ..common import int_param

    part_number = int_param(ctx.request.query.get("partNumber"), "partNumber")
    if part_number is not None and version.data()[0] == "inline":
        if part_number != 1:
            raise BadRequestError(f"no such part {part_number}")
        hdrs["Content-Length"] = str(meta["size"])
        hdrs["x-amz-mp-parts-count"] = "1"
        return web.Response(status=206, headers=hdrs)
    if part_number is not None and version.data()[0] == "first_block":
        ver_row = await ctx.garage.version_table.get(version.uuid, "")
        if ver_row is None:
            raise NoSuchKeyError("version metadata missing")
        blocks = [(k, v) for k, v in ver_row.sorted_blocks() if k[0] == part_number]
        if not blocks:
            raise BadRequestError(f"no such part {part_number}")
        psize = sum(sz for (_k, (_h, sz)) in blocks)
        nparts = len({k[0] for k, _ in ver_row.sorted_blocks()})
        hdrs["Content-Length"] = str(psize)
        hdrs["x-amz-mp-parts-count"] = str(nparts)
        return web.Response(status=206, headers=hdrs)
    hdrs["Content-Length"] = str(meta["size"])
    return web.Response(status=200, headers=hdrs)


async def handle_get_object(ctx) -> web.StreamResponse:
    garage = ctx.garage
    _obj, version = await get_object_version(ctx, ctx.key_name)
    meta = version.meta()
    status = check_conditions(ctx, version, meta)
    if status is not None:
        return web.Response(status=status)
    hdrs = object_headers(version, meta)
    size = meta["size"]
    data = version.data()

    # range / partNumber selection
    from ..common import int_param

    rng = ctx.request.headers.get("Range")
    part_number = int_param(ctx.request.query.get("partNumber"), "partNumber")
    if rng is not None and part_number is not None:
        raise BadRequestError("cannot combine Range and partNumber")

    if data[0] == "inline":
        body = bytes(data[2])
        if part_number is not None:
            # inline objects behave as a single part
            if part_number != 1:
                raise BadRequestError(f"no such part {part_number}")
            hdrs["Content-Range"] = f"bytes 0-{max(0, len(body)-1)}/{len(body)}"
            hdrs["x-amz-mp-parts-count"] = "1"
            return web.Response(status=206, headers=hdrs, body=body)
        if rng is not None:
            r = parse_range(rng, len(body))
            if r is not None:
                begin, end = r
                hdrs["Content-Range"] = f"bytes {begin}-{end-1}/{len(body)}"
                return web.Response(status=206, headers=hdrs, body=body[begin:end])
        return web.Response(status=200, headers=hdrs, body=body)

    ver_row = await garage.version_table.get(version.uuid, "")
    if ver_row is None:
        raise NoSuchKeyError("version metadata missing")
    blocks = ver_row.sorted_blocks()  # [((part, off), (hash, size))]

    if part_number is not None:
        pblocks = [(k, v) for k, v in blocks if k[0] == part_number]
        if not pblocks:
            raise BadRequestError(f"no such part {part_number}")
        begin = _part_offset(blocks, part_number)
        plen = sum(sz for (_k, (_h, sz)) in pblocks)
        end = begin + plen
        hdrs["Content-Range"] = f"bytes {begin}-{end-1}/{size}"
        hdrs["x-amz-mp-parts-count"] = str(len({k[0] for k, _ in blocks}))
        return await _stream_blocks_range(ctx, hdrs, 206, blocks, begin, end)

    if rng is not None:
        r = parse_range(rng, size)
        if r is not None:
            begin, end = r
            hdrs["Content-Range"] = f"bytes {begin}-{end-1}/{size}"
            return await _stream_blocks_range(ctx, hdrs, 206, blocks, begin, end)

    return await _stream_blocks_range(ctx, hdrs, 200, blocks, 0, size)


def _part_offset(blocks, pn: int) -> int:
    off = 0
    for (p, _o), (_h, sz) in blocks:
        if p < pn:
            off += sz
    return off


class _BlockPump:
    """Prefetch pump for one block: streams its decompressed chunks into a
    bounded queue (constant memory) while earlier blocks are still being
    written to the client — the buffered(PREFETCH) pipeline of
    ref get.rs:458-466, minus the whole-block buffering."""

    QUEUE_CHUNKS = 16  # ≈ 16 × 16 KiB transport chunks per in-flight block

    def __init__(self, garage, h: Hash, order_tag: int):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=self.QUEUE_CHUNKS)
        self.task = asyncio.ensure_future(self._run(garage, h, order_tag))

    async def _run(self, garage, h: Hash, order_tag: int) -> None:
        gen = garage.block_manager.rpc_get_block_streaming(h, order_tag)
        try:
            async for chunk in gen:
                await self.q.put(chunk)
            await self.q.put(None)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # propagated to the writer loop
            await self.q.put(e)
        finally:
            # explicit close (not GC finalizers): the generator's cleanup
            # cancels the block stream so the serving node stops pumping
            await gen.aclose()


async def _stream_blocks_range(
    ctx, hdrs: Dict[str, str], status: int, blocks, begin: int, end: int
) -> web.StreamResponse:
    """Stream the [begin, end) byte range assembled from its intersecting
    blocks (ref get.rs:432-512 body_from_blocks_range): each block is
    streamed chunk-by-chunk from the replica (with mid-transfer node
    failover inside rpc_get_block_streaming), with the next PREFETCH
    blocks' streams already being pumped."""
    garage = ctx.garage
    hdrs["Content-Length"] = str(end - begin)
    hdrs.update(ctx.cors_headers)  # immutable after prepare()
    # a streamed download's duration is the CLIENT's drain pace — keep
    # it out of the CoDel admitted-latency law (api/admission.py) and
    # out of the latency SLO (api_server middleware reads the flag)
    ctx.request["slo_client_paced"] = True
    token = ctx.request.get("admission_token")
    if token is not None:
        token.exclude_sojourn()
    resp = web.StreamResponse(status=status, headers=hdrs)
    await resp.prepare(ctx.request)

    # compute absolute offsets + the intersecting slice per block
    todo: List[Tuple[Hash, int, int]] = []  # (hash, slice_begin, slice_end)
    abs_off = 0
    for (_pk, (h, sz)) in blocks:
        b0, b1 = abs_off, abs_off + sz
        abs_off = b1
        if b1 <= begin or b0 >= end:
            continue
        todo.append((Hash(h), max(0, begin - b0), min(sz, end - b0)))

    n = len(todo)
    pumps: Dict[int, _BlockPump] = {}
    all_pumps: List[_BlockPump] = []

    def spawn(idx: int) -> None:
        pumps[idx] = p = _BlockPump(garage, todo[idx][0], idx)
        all_pumps.append(p)

    try:
        for i in range(min(PREFETCH + 1, n)):
            spawn(i)
        for i in range(n):
            pump = pumps.pop(i)
            nxt = i + PREFETCH + 1
            if nxt < n:
                spawn(nxt)
            s0, s1 = todo[i][1], todo[i][2]
            pos = 0
            while True:
                item = await pump.q.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                c0, c1 = pos, pos + len(item)
                pos = c1
                lo, hi = max(c0, s0), min(c1, s1)
                if hi > lo:
                    await resp.write(item[lo - c0 : hi - c0])
                    # the client drained bytes: it is demonstrably alive,
                    # so the request deadline renews — the budget bounds
                    # time-since-progress, never total transfer time
                    # (a multi-GiB download must not be shed at the 30 s
                    # mark).  Pumps spawned from here on inherit it.
                    refresh_deadline(garage.config.rpc.deadline_default)
        await resp.write_eof()
    except ConnectionError as e:
        # the client hung up mid-download — normal operation (aborted
        # transfer, closed tab); stop the block pumps and return the
        # partially-written response so aiohttp closes out quietly
        logger.debug("client disconnected mid-download: %s", e)
    finally:
        # runs on clean EOF, client ConnectionError, AND the
        # CancelledError aiohttp raises into the handler when the
        # transport dies abruptly (client killed mid-stream, gateway
        # failover): upstream block pumps stop pumping now, and the
        # admission slot frees the moment the stream stops — it must
        # not linger behind a dead connection until outer cleanup
        # (release is idempotent; api_server's finally releases again)
        for p in all_pumps:
            if not p.task.done():
                p.task.cancel()
        if token is not None:
            token.release()
    return resp
