"""Bucket-level endpoints: ListBuckets, Create/Delete/HeadBucket,
location/versioning/acl stubs.

Equivalent of reference src/api/s3/bucket.rs (356 LoC): bucket creation
applies the key's permissions immediately; deletion requires emptiness
(delegated to the model helper).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

from aiohttp import web

from ...model.permission import BucketKeyPerm
from ..common import (
    AccessDeniedError,
    iso_timestamp as _iso,
    s3_xml_root,
    xml_to_bytes,
)


async def handle_list_buckets(ctx) -> web.Response:
    """ListBuckets: all buckets this key may read (ref bucket.rs:40-100)."""
    key = ctx.api_key
    helper = ctx.server.helper
    out = s3_xml_root("ListAllMyBucketsResult")
    owner = ET.SubElement(out, "Owner")
    ET.SubElement(owner, "ID").text = key.key_id
    ET.SubElement(owner, "DisplayName").text = key.params().name.value
    buckets_el = ET.SubElement(out, "Buckets")

    seen = set()
    params = key.params()
    ids = [bid for bid in params.authorized_buckets.items.keys()
           if key.allow_read(bid)]
    for bid in ids:
        try:
            bucket = await helper.get_existing_bucket(bid)
        except Exception:
            continue
        bp = bucket.params()
        names = [n for n, lww in bp.aliases.items.items() if lww.value]
        for alias, lww in params.local_aliases.items.items():
            if lww.value == bytes(bid):
                names.append(alias)
        for name in sorted(set(names)):
            if name in seen:
                continue
            seen.add(name)
            b = ET.SubElement(buckets_el, "Bucket")
            ET.SubElement(b, "Name").text = name
            ET.SubElement(b, "CreationDate").text = _iso(bp.creation_date)
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_create_bucket(ctx) -> web.Response:
    """ref bucket.rs create: needs allow_create_bucket or existing perms."""
    key = ctx.api_key
    helper = ctx.server.helper
    name = ctx.bucket_name
    existing = await helper.resolve_global_bucket_name(name)
    if existing is not None:
        if key.allow_owner(existing) or key.allow_write(existing):
            # idempotent re-create of own bucket (S3 returns 200 outside
            # us-east-1 semantics; garage accepts)
            return web.Response(status=200, headers={"Location": f"/{name}"})
        raise AccessDeniedError("bucket exists and is not yours")
    if not key.params().allow_create_bucket.value:
        raise AccessDeniedError(
            f"key {key.key_id} is not allowed to create buckets"
        )
    bucket = await helper.create_bucket(name)
    await helper.set_bucket_key_permissions(
        bucket.id, key.key_id, BucketKeyPerm(True, True, True)
    )
    return web.Response(status=200, headers={"Location": f"/{name}"})


async def handle_delete_bucket(ctx) -> web.Response:
    await ctx.server.helper.delete_bucket(ctx.bucket_id)
    return web.Response(status=204)


async def handle_head_bucket(ctx) -> web.Response:
    return web.Response(status=200)


async def handle_get_location(ctx) -> web.Response:
    out = s3_xml_root("LocationConstraint")
    out.text = ctx.server.region
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_get_versioning(ctx) -> web.Response:
    # versioning is not supported (ref bucket.rs handle_get_versioning)
    out = s3_xml_root("VersioningConfiguration")
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )


async def handle_get_acl(ctx) -> web.Response:
    key = ctx.api_key
    out = s3_xml_root("AccessControlPolicy")
    owner = ET.SubElement(out, "Owner")
    ET.SubElement(owner, "ID").text = key.key_id
    acl = ET.SubElement(out, "AccessControlList")
    grant = ET.SubElement(acl, "Grant")
    grantee = ET.SubElement(grant, "Grantee")
    ET.SubElement(grantee, "ID").text = key.key_id
    ET.SubElement(grant, "Permission").text = "FULL_CONTROL"
    return web.Response(
        status=200, body=xml_to_bytes(out), content_type="application/xml"
    )
