"""TableData — local storage + CRDT merge engine for one table.

Equivalent of reference src/table/data.rs (SURVEY.md §2.4): trees
`{name}:table`, `:merkle_todo`, `:insert_queue`, `:gc_todo`; the update
transaction decodes → merges → re-encodes and, if changed, writes the
entry + a merkle-todo marker + runs the schema's `updated()` hook, and
enqueues a GC-todo entry when the new value is a tombstone and this node
is the partition leader (data.rs:198-267).
"""

from __future__ import annotations

import logging
import struct
from typing import Any, Callable, List, Optional, Tuple

from ..db import Db, Transaction, Tree
from ..db.counted_tree import CountedTree
from ..rpc.system import System
from ..utils.background import LoopSafeEvent
from ..utils.crdt import now_msec
from ..utils.data import Hash, blake2sum
from .replication import TableReplication
from .schema import Entry, TableSchema, hash_partition_key, sort_key_bytes, tree_key

logger = logging.getLogger("garage_tpu.table.data")


class TableData:
    def __init__(
        self,
        system: System,
        schema: TableSchema,
        replication: TableReplication,
        db: Db,
    ):
        self.system = system
        self.schema = schema
        self.replication = replication
        self.db = db
        name = schema.TABLE_NAME
        self.store: Tree = db.open_tree(f"{name}:table")
        self.merkle_tree: Tree = db.open_tree(f"{name}:merkle_tree")
        # merkle_todo/insert_queue/gc_todo need O(1) len for worker gauges
        # (ref db/counted_tree_hack.rs; sqlite COUNT(*) is O(n))
        self.merkle_todo: CountedTree = CountedTree(db.open_tree(f"{name}:merkle_todo"))
        self.insert_queue: CountedTree = CountedTree(db.open_tree(f"{name}:insert_queue"))
        self.gc_todo: CountedTree = CountedTree(db.open_tree(f"{name}:gc_todo_v2"))
        # notified when merkle_todo / insert_queue gain items —
        # LoopSafeEvent, not asyncio.Event: batched Merkle/queue passes
        # commit from worker threads, and a plain Event set off-loop
        # wakes nobody (the drainer would sleep out a full
        # wait_for_work interval on a refill that landed mid-batch)
        self.merkle_todo_notify = LoopSafeEvent()
        self.insert_queue_notify = LoopSafeEvent()
        # [table] tunables (None outside a full daemon: defaults)
        tcfg = getattr(getattr(system, "config", None), "table", None)
        self.scan_page = int(getattr(tcfg, "scan_page", 1024) or 1024)
        m = getattr(system, "metrics", None)
        if m is not None:
            self._m_scan_pages = m.counter(
                "table_scan_pages_total",
                "range_scan pages served by the local table store, per "
                "table")
            self._m_scan_rows = m.counter(
                "table_scan_rows_total",
                "Rows scanned (before filtering) by local range reads, "
                "per table")
        else:
            self._m_scan_pages = self._m_scan_rows = None

    # --- reads (ref data.rs:92-160) ---

    def tree_key(self, p: Any, s: Any) -> bytes:
        return tree_key(p, s)

    def read_entry(self, p: Any, s: Any) -> Optional[bytes]:
        return self.store.get(self.tree_key(p, s))

    def decode_entry(self, data: bytes) -> Entry:
        return self.schema.decode_entry(data)

    def read_range(
        self,
        partition_hash: Hash,
        start_sort_key: Optional[bytes],
        filter: Any,
        limit: int,
        reverse: bool = False,
        end_sort_key: Optional[bytes] = None,
    ) -> List[bytes]:
        """Encoded entries of one partition from `start_sort_key`,
        filtered (ref data.rs:112-160), bounded above (exclusive) by
        `end_sort_key` — the sub-range contract sharded listings fan out
        over.  Pages through Tree.range_scan: one engine seek + bounded
        read per page instead of a per-row cursor walk."""
        pfx = bytes(partition_hash)
        first = pfx + (start_sort_key or b"")
        # partition keyspace upper bound: hash ‖ 0xff… is not representable,
        # so bound by incrementing the 32-byte prefix
        end = _prefix_upper_bound(pfx)
        if end_sort_key is not None:
            bounded = pfx + end_sort_key
            end = bounded if end is None else min(end, bounded)
        out: List[bytes] = []
        if reverse:
            # descending from the start sort key *inclusive* (ref
            # data.rs range_rev(..=first)); no start key = whole partition
            pos_hi = first + b"\x00" if start_sort_key is not None else end
            lo = pfx
        else:
            pos = first
        while True:
            # floor of 64: a filter-heavy tail must not degenerate into
            # one-row pages (the fetch is cheap; decode stops at limit)
            page_size = max(min(limit - len(out), self.scan_page), 64)
            if reverse:
                page = self.store.range_scan(lo, pos_hi, page_size,
                                             reverse=True)
            else:
                page = self.store.range_scan(pos, end, page_size)
            if self._m_scan_pages is not None and page:
                self._m_scan_pages.inc(table_name=self.schema.TABLE_NAME)
                self._m_scan_rows.inc(
                    len(page), table_name=self.schema.TABLE_NAME)
            for k, v in page:
                if not k.startswith(pfx):
                    return out
                try:
                    ent = self.decode_entry(v)
                except Exception:
                    logger.exception("undecodable entry at %s", k.hex()[:16])
                    continue
                if filter is None or self.schema.matches_filter(ent, filter):
                    out.append(v)
                    if len(out) >= limit:
                        return out
            if len(page) < page_size:
                return out
            if reverse:
                pos_hi = page[-1][0]
            else:
                pos = page[-1][0] + b"\x00"

    # --- mutations (ref data.rs:174-267) ---

    def update_many(self, entries: List[bytes]) -> None:
        for e in entries:
            self.update_entry(e)

    def update_entry(self, update_bytes: bytes) -> Optional[Entry]:
        update = self.decode_entry(update_bytes)

        def merge_fn(tx: Transaction, old: Optional[Entry]) -> Entry:
            if old is not None:
                old.merge(update)
                return old
            return update

        return self.update_entry_with(
            update.partition_key, update.sort_key, merge_fn
        )

    def update_entry_with(
        self,
        p: Any,
        s: Any,
        update_fn: Callable[[Transaction, Optional[Entry]], Entry],
    ) -> Optional[Entry]:
        """The core update transaction (ref data.rs:198-245)."""
        tk = self.tree_key(p, s)

        def txn(tx: Transaction):
            old_bytes = tx.get(self.store, tk)
            old_entry = self.decode_entry(old_bytes) if old_bytes is not None else None
            # old_entry is re-decoded for the hook: update_fn mutates its copy
            hook_old = self.decode_entry(old_bytes) if old_bytes is not None else None
            new_entry = update_fn(tx, old_entry)
            new_bytes = new_entry.encode()
            if new_bytes == old_bytes:
                return None
            new_bytes_hash = blake2sum(new_bytes)
            self.merkle_todo.tx_insert(tx, tk, bytes(new_bytes_hash))
            tx.insert(self.store, tk, new_bytes)
            self.schema.updated(tx, hook_old, new_entry)
            return new_entry, new_bytes_hash

        res = self.db.transaction(txn)
        if res is None:
            return None
        new_entry, new_bytes_hash = res
        self.merkle_todo_notify.set()
        if new_entry.is_tombstone():
            # Only the partition leader (first write node) enqueues GC —
            # avoids GC loops (ref data.rs:246-260).
            pk_hash = Hash(tk[:32])
            nodes = self.replication.write_nodes(pk_hash)
            if nodes and nodes[0] == self.system.id:
                self.gc_todo.insert(
                    gc_todo_key(now_msec(), tk), bytes(new_bytes_hash)
                )
        return new_entry

    def delete_if_equal(self, k: bytes, v: bytes) -> bool:
        """Remove entry only if its current encoding is exactly `v`
        (ref data.rs:269-295)."""

        def txn(tx: Transaction):
            cur = tx.get(self.store, k)
            if cur != v:
                return False
            old_entry = self.decode_entry(v)
            tx.remove(self.store, k)
            self.merkle_todo.tx_insert(tx, k, b"")
            self.schema.updated(tx, old_entry, None)
            return True

        removed = self.db.transaction(txn)
        if removed:
            self.merkle_todo_notify.set()
        return removed

    def delete_if_equal_hash(self, k: bytes, vhash: Hash) -> bool:
        """ref data.rs:297-321."""

        def txn(tx: Transaction):
            cur = tx.get(self.store, k)
            if cur is None or blake2sum(cur) != vhash:
                return None
            old_entry = self.decode_entry(cur)
            tx.remove(self.store, k)
            self.merkle_todo.tx_insert(tx, k, b"")
            self.schema.updated(tx, old_entry, None)
            return cur

        removed = self.db.transaction(txn)
        if removed is not None:
            self.merkle_todo_notify.set()
        return removed is not None

    # --- insert queue (ref data.rs queue_insert) ---

    def queue_insert(self, tx: Transaction, entry: Entry) -> None:
        """Defer an insert from inside another transaction: the entry is
        written to the insert queue and pushed to replicas asynchronously
        by the InsertQueueWorker (ref data.rs:323-341, queue.rs).  Keyed by
        tree_key alone; a second queued update for the same entry is CRDT-
        merged into the pending one, never overwritten."""
        key = entry.tree_key()
        cur = tx.get(self.insert_queue.tree, key)
        if cur is not None:
            pending = self.decode_entry(cur)
            pending.merge(entry)
            entry = pending
        self.insert_queue.tx_insert(tx, key, entry.encode())
        tx.on_commit(self.insert_queue_notify.set)

    # --- counts ---

    def store_len(self) -> int:
        return len(self.store)

    def merkle_todo_len(self) -> int:
        return len(self.merkle_todo)

    def gc_todo_len(self) -> int:
        return len(self.gc_todo)


def gc_todo_key(ts_ms: int, tk: bytes) -> bytes:
    """gc_todo key = 8-byte BE tombstone timestamp ‖ tree key
    (ref gc.rs:340-407)."""
    return struct.pack(">Q", ts_ms) + tk


def parse_gc_todo_key(k: bytes) -> Tuple[int, bytes]:
    return struct.unpack(">Q", k[:8])[0], k[8:]


def _prefix_upper_bound(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every string with this prefix."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return None
