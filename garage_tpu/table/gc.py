"""TableGc — distributed tombstone garbage collection.

Equivalent of reference src/table/gc.rs (SURVEY.md §2.4): tombstones can
only be dropped once *every* replica has them, otherwise anti-entropy
would resurrect the deleted item.  The partition leader queues tombstones
in gc_todo at write time (data.py); after TABLE_GC_DELAY (24 h) the GC
worker runs the 3-phase protocol in batches of ≤1024 (gc.rs:27-32,72-275):

  1. send the tombstone to all replicas (`Update`) so everyone has it,
  2. ask everyone (incl. self) to `DeleteIfEqualHash(key, vhash)`,
  3. drop the gc_todo entry if its value hash is unchanged.

If any replica is unreachable the batch aborts and retries later — GC is
suspended rather than unsafe.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Dict, List, Tuple

from ..net.frame import PRIO_BACKGROUND
from ..rpc.rpc_helper import RequestStrategy
from ..utils.background import Worker, WorkerState
from ..utils.crdt import now_msec
from ..utils.data import Hash, blake2sum
from ..utils.error import GarageError
from .data import TableData, gc_todo_key, parse_gc_todo_key

logger = logging.getLogger("garage_tpu.table.gc")

TABLE_GC_BATCH_SIZE = 1024          # ref gc.rs:27
TABLE_GC_DELAY_MS = 24 * 3600 * 1000  # ref gc.rs:32 (24h)


class TableGc:
    def __init__(self, system, data: TableData):
        self.system = system
        self.data = data
        self.endpoint = system.netapp.endpoint(
            f"garage/table_gc/{data.schema.TABLE_NAME}"
        )
        self.endpoint.set_handler(self._handle)
        # test hook: shrink the delay in integration tests
        self.gc_delay_ms = TABLE_GC_DELAY_MS

    def make_worker(self) -> "GcWorker":
        return GcWorker(self)

    # --- one GC pass (ref gc.rs:72-191) ---

    async def gc_loop_iter(self) -> bool:
        """Process one batch of due entries; returns True if any work done."""
        now = now_msec()
        entries: List[Tuple[bytes, bytes, bytes]] = []  # (todo_key, tk, vhash)
        excluded: List[Tuple[bytes, bytes]] = []
        for k, v in self.data.gc_todo.items():
            ts, tk = parse_gc_todo_key(k)
            if ts + self.gc_delay_ms > now:
                break  # keys are time-ordered: nothing further is due
            cur = self.data.store.get(tk)
            if cur is None or bytes(blake2sum(cur)) != bytes(v):
                # item changed since the tombstone was queued: drop todo
                excluded.append((k, v))
                continue
            entries.append((k, tk, bytes(v)))
            if len(entries) >= TABLE_GC_BATCH_SIZE:
                break
        for k, v in excluded:
            self.data.gc_todo.compare_and_swap(k, v, None)
        if not entries:
            return False

        # group by replica set (ref gc.rs:124-155)
        by_nodes: Dict[tuple, List[Tuple[bytes, bytes, bytes]]] = {}
        for item in entries:
            _k, tk, _vh = item
            nodes = tuple(
                bytes(n) for n in self.data.replication.write_nodes(Hash(tk[:32]))
            )
            by_nodes.setdefault(nodes, []).append(item)

        for nodes, items in by_nodes.items():
            await self._try_send_and_delete(
                [Hash(n) for n in nodes], items
            )
        return True

    async def _try_send_and_delete(self, nodes, items) -> None:
        """ref gc.rs:193-240: phase 1 Update to others, phase 2
        DeleteIfEqualHash everywhere; quorum = all nodes for both."""
        values = []
        deletes = []
        for _k, tk, vh in items:
            v = self.data.store.get(tk)
            if v is None:
                continue
            values.append(v)
            deletes.append([tk, vh])
        if not deletes:
            return
        others = [n for n in nodes if n != self.system.id]
        if others:
            await self.system.rpc.try_call_many(
                self.endpoint,
                others,
                {"t": "update", "vs": values},
                RequestStrategy(
                    rs_quorum=len(others), rs_priority=PRIO_BACKGROUND
                ),
            )
        # everyone (incl. self) deletes-if-unchanged
        await self.system.rpc.try_call_many(
            self.endpoint,
            list(nodes),
            {"t": "delete_if_equal_hash", "items": deletes},
            RequestStrategy(rs_quorum=len(nodes), rs_priority=PRIO_BACKGROUND),
        )
        logger.debug(
            "%s: GC'd %d tombstones", self.data.schema.TABLE_NAME, len(deletes)
        )
        for k, _tk, vh in items:
            self.data.gc_todo.compare_and_swap(k, vh, None)

    # --- server side (ref gc.rs GcRpc) ---

    async def _handle(self, remote, msg, body):
        t = msg.get("t")
        if t == "update":
            self.data.update_many([bytes(v) for v in msg["vs"]])
            return {"ok": True}, None
        if t == "delete_if_equal_hash":
            for tk, vh in msg["items"]:
                self.data.delete_if_equal_hash(bytes(tk), Hash(bytes(vh)))
            return {"ok": True}, None
        raise GarageError(f"unknown gc rpc {t!r}")


class GcWorker(Worker):
    """ref gc.rs:242-275."""

    def __init__(self, gc: TableGc):
        self.gc = gc

    def name(self) -> str:
        return f"{self.gc.data.schema.TABLE_NAME} GC"

    async def work(self) -> WorkerState:
        st = self.status()
        st.queue_length = self.gc.data.gc_todo_len()
        did = await self.gc.gc_loop_iter()
        return WorkerState.BUSY if did else WorkerState.IDLE

    async def wait_for_work(self) -> None:
        await asyncio.sleep(10.0)
