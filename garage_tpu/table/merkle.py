"""MerkleUpdater — incremental per-partition Merkle trees over table items.

Equivalent of reference src/table/merkle.rs (SURVEY.md §2.4): one Merkle
trie per ring partition; the trie descends on the bytes of **blake2(tree
key)** (so no key is a prefix of another); node kinds are Empty,
Intermediate([(next_byte, child_hash)]) and Leaf(item_key, value_hash)
(merkle.rs:45-67).  A todo-queue written transactionally by TableData
drives the updater (merkle.rs:92-253); node hash = blake2 of the node's
canonical serialization; an intermediate left with a single leaf child
collapses back into that leaf (merkle.rs:163-182).

Node db key = 1 byte partition ‖ khash prefix (the framework uses
PARTITION_BITS=8 partitions, ring.py; the reference packs a u16).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, List, Optional, Tuple

from ..db import Transaction
from ..utils.background import Worker, WorkerState
from ..utils.data import Hash, blake2sum
from ..utils.migrate import pack, unpack
from .data import TableData

logger = logging.getLogger("garage_tpu.table.merkle")

EMPTY = None
_UNCHANGED = object()  # "subtree not modified" — distinct from EMPTY (ref
                       # merkle.rs models this as Option<MerkleNode>)
EMPTY_HASH = Hash(b"\x00" * 32)


def _encode_node(node: Any) -> bytes:
    return pack(node)


def _decode_node(data: Optional[bytes]) -> Any:
    if data is None or data == b"":
        return EMPTY
    return unpack(data)


def node_hash(node: Any) -> Hash:
    """Hash of a node; the empty node hashes to all-zeros (ref merkle.rs
    empty_node_hash)."""
    if node is EMPTY:
        return EMPTY_HASH
    return blake2sum(_encode_node(node))


def _is_leaf(node: Any) -> bool:
    return isinstance(node, (list, tuple)) and len(node) == 3 and node[0] == "l"


def _is_int(node: Any) -> bool:
    return isinstance(node, (list, tuple)) and len(node) == 2 and node[0] == "i"


def leaf(key: bytes, vhash: bytes) -> list:
    return ["l", key, bytes(vhash)]


def intermediate(children: List[Tuple[int, bytes]]) -> list:
    return ["i", [[b, bytes(h)] for b, h in sorted(children)]]


def int_children(node: Any) -> List[Tuple[int, bytes]]:
    return [(b, bytes(h)) for b, h in node[1]]


def node_key(partition: int, prefix: bytes) -> bytes:
    return bytes([partition]) + prefix


class MerkleUpdater:
    def __init__(self, data: TableData):
        self.data = data
        # codec feeder (ops/feeder.py), attached by Garage.spawn_workers:
        # node/key hash batches ride it as ragged `mhash` submissions
        # (class bg) so a Merkle backlog drain shares the batching engine
        # the data plane already has.  None (bare-library/tests) =
        # serial blake2sum — bit-identical either way.
        self.feeder = None
        m = getattr(data.system, "metrics", None)
        if m is not None:
            # families shared across tables via registry name-dedup
            self._m_items = m.histogram(
                "merkle_batch_items",
                "Todo items per batched Merkle pass",
                buckets=(1.0, 4.0, 16.0, 64.0, 128.0, 256.0, 512.0,
                         1024.0))
            self._m_nodes = m.counter(
                "merkle_batch_nodes_total",
                "Trie nodes rewritten by the batched Merkle updater "
                "(shared path nodes count once per batch, not once per "
                "item)")
            self._m_hashes = m.counter(
                "merkle_batch_hash_total",
                "Node/key hashes computed through batched Merkle "
                "passes, by route (feeder = ragged codec-feeder batch, "
                "serial = inline blake2sum)")
        else:
            self._m_items = self._m_nodes = self._m_hashes = None

    # --- batched hashing -----------------------------------------------------

    def hash_many(self, bufs: List[bytes]) -> List[Hash]:
        """Hash a batch of byte strings with the table engine's
        blake2sum, riding the codec feeder's ragged mhash path when one
        is attached (one dispatch for the whole batch) and falling back
        to the serial loop otherwise — bit-identical by construction."""
        if not bufs:
            return []
        f = self.feeder
        if f is not None and not f.closed and len(bufs) > 1:
            try:
                # peers=1: the updater blocks on each batch, so the
                # dispatcher must not sleep an SLO window out per batch;
                # concurrent tables' submissions still coalesce because
                # the dispatcher drains everything pending at dispatch
                digs = f.submit_mhash(bufs, peers=1).result()
                if self._m_hashes is not None:
                    self._m_hashes.inc(len(bufs), route="feeder")
                return digs
            except Exception:  # noqa: BLE001 — hashing must never fail
                logger.debug("feeder mhash failed; hashing inline",
                             exc_info=True)
        if self._m_hashes is not None:
            self._m_hashes.inc(len(bufs), route="serial")
        return [blake2sum(b) for b in bufs]

    # --- tree access (ref merkle.rs:255-301) ---

    def read_node(self, tx: Optional[Transaction], nk: bytes) -> Any:
        if tx is not None:
            return _decode_node(tx.get(self.data.merkle_tree, nk))
        return _decode_node(self.data.merkle_tree.get(nk))

    def _put_node(self, tx: Transaction, nk: bytes, node: Any) -> Hash:
        if node is EMPTY:
            tx.remove(self.data.merkle_tree, nk)
        else:
            tx.insert(self.data.merkle_tree, nk, _encode_node(node))
        return node_hash(node)

    def partition_root_hash(self, partition: int) -> Hash:
        """Root hash of one partition's subtree — what sync compares."""
        return node_hash(self.read_node(None, node_key(partition, b"")))

    # --- the update algorithm (ref merkle.rs:92-253) ---

    def update_item(self, k: bytes) -> None:
        """Apply one todo entry for item key `k`.  The todo value is the new
        value hash (b'' = item deleted); it is removed only if unchanged
        after the tree transaction (ref merkle.rs:113-128)."""
        todo_val = self.data.merkle_todo.get(k)
        if todo_val is None:
            return
        new_vhash = None if todo_val == b"" else Hash(todo_val)
        khash = blake2sum(k)
        partition = self.data.replication.partition_of(Hash(k[:32]))

        def txn(tx: Transaction):
            self._update_rec(tx, k, khash, partition, b"", new_vhash)
            cur = tx.get(self.data.merkle_todo.tree, k)
            if cur == todo_val:
                self.data.merkle_todo.tx_remove(tx, k)

        self.data.db.transaction(txn)

    def _update_rec(
        self,
        tx: Transaction,
        k: bytes,
        khash: Hash,
        partition: int,
        prefix: bytes,
        new_vhash: Optional[Hash],
    ) -> Optional[Hash]:
        """Returns the node's new hash, or None if the subtree is unchanged
        (ref merkle.rs:131-253 update_item_rec)."""
        i = len(prefix)
        nk = node_key(partition, prefix)
        node = self.read_node(tx, nk)
        mutate = _UNCHANGED

        if node is EMPTY:
            if new_vhash is not None:
                mutate = leaf(k, bytes(new_vhash))

        elif _is_int(node):
            children = int_children(node)
            next_prefix = prefix + khash[i : i + 1]
            subhash = self._update_rec(tx, k, khash, partition, next_prefix, new_vhash)
            if subhash is not None:
                nb = khash[i]
                children = [(b, h) for b, h in children if b != nb]
                if subhash != EMPTY_HASH:
                    children.append((nb, bytes(subhash)))
                if not children:
                    logger.warning("intermediate collapsed to empty (unexpected)")
                    mutate = EMPTY
                elif len(children) == 1:
                    sub_nk = node_key(partition, prefix + bytes([children[0][0]]))
                    subnode = self.read_node(tx, sub_nk)
                    if _is_leaf(subnode):
                        # hoist the single remaining leaf up one level
                        tx.remove(self.data.merkle_tree, sub_nk)
                        mutate = subnode
                    else:
                        mutate = intermediate(children)
                else:
                    mutate = intermediate(children)

        else:  # leaf
            exlf_k, exlf_vhash = bytes(node[1]), bytes(node[2])
            if exlf_k == k:
                if new_vhash is not None and bytes(new_vhash) != exlf_vhash:
                    mutate = leaf(k, bytes(new_vhash))
                elif new_vhash is None:
                    mutate = EMPTY
            elif new_vhash is not None:
                # split: push the existing leaf down by its own khash byte,
                # then insert our key (ref merkle.rs:196-238)
                exlf_khash = blake2sum(exlf_k)
                assert exlf_khash[:i] == khash[:i]
                children = []
                sub1 = self._update_rec(
                    tx, exlf_k, exlf_khash, partition,
                    prefix + exlf_khash[i : i + 1], Hash(exlf_vhash),
                )
                children.append((exlf_khash[i], bytes(sub1)))
                sub2 = self._update_rec(
                    tx, k, khash, partition, prefix + khash[i : i + 1], new_vhash
                )
                children = [(b, h) for b, h in children if b != khash[i]]
                children.append((khash[i], bytes(sub2)))
                mutate = intermediate(children)

        if mutate is _UNCHANGED:
            return None
        return self._put_node(tx, nk, mutate)

    # --- batched updates (the metadata-at-millions path) --------------------
    #
    # update_item is exact but pays one transaction and a re-hash of the
    # whole root-to-leaf path PER ITEM: a bulk insert of B items sharing
    # trie prefixes rewrites (and blake2s) the shared upper nodes B
    # times.  update_batch applies a whole todo batch structurally first
    # (hashes deferred), then re-hashes each dirty node exactly ONCE,
    # level-batched through hash_many, and commits everything — node
    # writes, removals and todo acknowledgments — in one transaction.
    # The final tree (keys, node encodings, root hash) is bit-identical
    # to applying the same items serially: structure never depends on a
    # hash value (emptiness and the single-leaf collapse are structural
    # tests), and the hash of a node is a pure function of its final
    # structure.  Safe outside a transaction because this worker is the
    # only merkle_tree writer; concurrent item updates only append todo
    # entries, and an entry that changes mid-batch is simply left in the
    # todo queue (same contract as update_item's compare-and-remove).

    def update_batch(self, items: List[Tuple[bytes, Optional[bytes]]]) -> int:
        """Apply todo entries [(key, todo_val)] in one batched pass.
        Returns the number of items applied."""
        items = [(k, tv) for k, tv in items if tv is not None]
        if not items:
            return 0
        khashes = self.hash_many([k for k, _tv in items])
        by_part: dict = {}
        for (k, tv), kh in zip(items, khashes):
            p = self.data.replication.partition_of(Hash(k[:32]))
            by_part.setdefault(p, []).append((k, kh, tv))
        writes: List[Tuple[bytes, Optional[bytes]]] = []  # (nk, enc|None)
        for partition, part_items in by_part.items():
            ctx = _BatchCtx(self, partition)
            for k, kh, tv in part_items:
                new_vhash = None if tv == b"" else Hash(tv)
                self._upd_structural(ctx, k, kh, b"", new_vhash)
            writes.extend(self._finalize(ctx))

        def txn(tx: Transaction):
            for nk, enc in writes:
                if enc is None:
                    tx.remove(self.data.merkle_tree, nk)
                else:
                    tx.insert(self.data.merkle_tree, nk, enc)
            for k, tv in items:
                cur = tx.get(self.data.merkle_todo.tree, k)
                if cur == tv:
                    self.data.merkle_todo.tx_remove(tx, k)

        self.data.db.transaction(txn)
        if self._m_items is not None:
            self._m_items.observe(float(len(items)))
            self._m_nodes.inc(len(writes))
        return len(items)

    def _upd_structural(self, ctx: "_BatchCtx", k: bytes, khash: Hash,
                        prefix: bytes, new_vhash: Optional[Hash]) -> bool:
        """Structural twin of _update_rec: same mutations, hashes
        deferred (dirty intermediates carry None placeholders resolved
        by _finalize).  Returns True iff the subtree changed."""
        i = len(prefix)
        node = ctx.read(prefix)

        if node is EMPTY:
            if new_vhash is None:
                return False
            ctx.write(prefix, leaf(k, bytes(new_vhash)))
            return True

        if _w_is_int(node):
            children = _w_children(node)
            nb = khash[i]
            sub_prefix = prefix + khash[i:i + 1]
            if not self._upd_structural(ctx, k, khash, sub_prefix,
                                        new_vhash):
                return False
            if ctx.read(sub_prefix) is EMPTY:
                children.pop(nb, None)
            else:
                children[nb] = None  # re-hashed by _finalize
            if not children:
                logger.warning("intermediate collapsed to empty (unexpected)")
                ctx.write(prefix, EMPTY)
            elif len(children) == 1:
                (b2,) = children
                sub2 = prefix + bytes([b2])
                subnode = ctx.read(sub2)
                if _is_leaf(subnode):
                    # hoist the single remaining leaf up one level
                    ctx.write(sub2, EMPTY)
                    ctx.write(prefix, subnode)
                else:
                    ctx.write(prefix, _working_int(children))
            else:
                ctx.write(prefix, _working_int(children))
            return True

        # leaf
        exlf_k, exlf_vhash = bytes(node[1]), bytes(node[2])
        if exlf_k == k:
            if new_vhash is not None and bytes(new_vhash) != exlf_vhash:
                ctx.write(prefix, leaf(k, bytes(new_vhash)))
                return True
            if new_vhash is None:
                ctx.write(prefix, EMPTY)
                return True
            return False
        if new_vhash is None:
            return False
        # split: push the existing leaf down by its own khash byte, then
        # insert our key (both recursions may land in the same child)
        exlf_khash = blake2sum(exlf_k)
        assert exlf_khash[:i] == khash[:i]
        children: dict = {}
        self._upd_structural(ctx, exlf_k, exlf_khash,
                             prefix + exlf_khash[i:i + 1], Hash(exlf_vhash))
        children[exlf_khash[i]] = None
        self._upd_structural(ctx, k, khash, prefix + khash[i:i + 1],
                             new_vhash)
        children[khash[i]] = None
        ctx.write(prefix, _working_int(children))
        return True

    def _finalize(self, ctx: "_BatchCtx") -> List[Tuple[bytes, Optional[bytes]]]:
        """Resolve placeholder child hashes bottom-up — every dirty
        level's node encodings hashed in ONE hash_many batch — and
        return the final (node_key, encoding|None) write set."""
        hashes: dict = {}
        writes: List[Tuple[bytes, Optional[bytes]]] = []
        for depth in sorted({len(p) for p in ctx.dirty}, reverse=True):
            prefixes, encodings = [], []
            for p in sorted(ctx.dirty):
                if len(p) != depth:
                    continue
                node = ctx.nodes[p]
                if node is EMPTY:
                    writes.append((node_key(ctx.partition, p), None))
                    continue
                if _is_working_int(node):
                    node = intermediate([
                        (b, bytes(hashes[p + bytes([b])]) if h is None
                         else h)
                        for b, h in node[1].items()
                    ])
                    ctx.nodes[p] = node
                enc = _encode_node(node)
                prefixes.append(p)
                encodings.append(enc)
                writes.append((node_key(ctx.partition, p), enc))
            for p, d in zip(prefixes, self.hash_many(encodings)):
                hashes[p] = d
        return writes

    # --- subtree walks (used by sync) ---

    def collect_leaves(self, partition: int, prefix: bytes) -> List[Tuple[bytes, bytes]]:
        """All (item_key, value_hash) leaves under a node."""
        out: List[Tuple[bytes, bytes]] = []
        self._collect(partition, prefix, out)
        return out

    def _collect(self, partition: int, prefix: bytes, out):
        node = self.read_node(None, node_key(partition, prefix))
        if node is EMPTY:
            return
        if _is_leaf(node):
            out.append((bytes(node[1]), bytes(node[2])))
            return
        for b, _h in int_children(node):
            self._collect(partition, prefix + bytes([b]), out)


class _BatchCtx:
    """One batch's structural overlay over one partition's subtree."""

    __slots__ = ("u", "partition", "nodes", "dirty")

    def __init__(self, updater: MerkleUpdater, partition: int):
        self.u = updater
        self.partition = partition
        self.nodes: dict = {}   # prefix -> node (working forms allowed)
        self.dirty: set = set()

    def read(self, prefix: bytes) -> Any:
        if prefix in self.nodes:
            return self.nodes[prefix]
        node = self.u.read_node(None, node_key(self.partition, prefix))
        self.nodes[prefix] = node
        return node

    def write(self, prefix: bytes, node: Any) -> None:
        self.nodes[prefix] = node
        self.dirty.add(prefix)


def _working_int(children: dict) -> list:
    """Overlay intermediate: {next_byte: hash | None placeholder}."""
    return ["wi", children]


def _is_working_int(node: Any) -> bool:
    return isinstance(node, list) and len(node) == 2 and node[0] == "wi"


def _w_is_int(node: Any) -> bool:
    return _is_int(node) or _is_working_int(node)


def _w_children(node: Any) -> dict:
    if _is_working_int(node):
        return node[1]
    return {b: bytes(h) for b, h in node[1]}


class MerkleWorker(Worker):
    """Drains the merkle_todo queue (ref merkle.rs:303-340): batched
    passes through MerkleUpdater.update_batch ([table] merkle_batch), or
    the legacy one-transaction-per-item path when merkle_batch <= 1."""

    BATCH = 100  # legacy per-item batch bound (merkle_batch <= 1)

    def __init__(self, updater: MerkleUpdater):
        self.updater = updater
        self.data = updater.data
        cfg = getattr(getattr(self.data.system, "config", None), "table",
                      None)
        self.batch = int(getattr(cfg, "merkle_batch", 256) or 256)

    def name(self) -> str:
        return f"{self.data.schema.TABLE_NAME} Merkle"

    async def work(self) -> WorkerState:
        st = self.status()
        # The whole batch runs OFF the event loop (ref merkle.rs:303-340
        # uses spawn_blocking for the same reason): after a bulk insert the
        # todo backlog is thousands of items and the runner re-calls work()
        # continuously while BUSY — hashing them on the loop thread starves
        # every foreground request on a small host for the duration.
        processed = await asyncio.to_thread(self._work_batch)
        remaining = self.data.merkle_todo_len()
        st.queue_length = remaining
        # re-check the todo queue after the batch: items that landed
        # mid-batch behind the cursor (bulk-insert churn) must drain NOW,
        # not after a wait_for_work interval whose notify may already
        # have been consumed
        return (WorkerState.BUSY if processed or remaining
                else WorkerState.IDLE)

    def _collect_todo(self, limit: int) -> List[Tuple[bytes, bytes]]:
        # ONE range_scan page, not a get_gt cursor walk per item: on the
        # native engine each get_gt is a fresh iterator (measured 0.4 ms
        # — it dominated the whole batched drain)
        return self.data.merkle_todo.range_scan(limit=limit)

    def _work_batch(self) -> int:
        if self.batch > 1:
            items = self._collect_todo(self.batch)
            if not items:
                return 0
            try:
                return self.updater.update_batch(items)
            except Exception:
                # belt and braces: a batched-path bug must degrade to
                # the exact serial algorithm, never wedge the table
                logger.exception(
                    "%s: batched Merkle pass failed; falling back to "
                    "per-item updates", self.data.schema.TABLE_NAME)
                for k, _tv in items:
                    self.updater.update_item(k)
                return len(items)
        processed = 0
        cursor = b""
        while processed < self.BATCH:
            nxt = (
                self.data.merkle_todo.first()
                if cursor == b""
                else self.data.merkle_todo.get_gt(cursor)
            )
            if nxt is None:
                break
            key, _val = nxt
            self.updater.update_item(key)
            cursor = key
            processed += 1
        return processed

    async def wait_for_work(self) -> None:
        self.data.merkle_todo_notify.clear()
        if self.data.merkle_todo_len() > 0:
            return
        await self.data.merkle_todo_notify.wait()
