"""MerkleUpdater — incremental per-partition Merkle trees over table items.

Equivalent of reference src/table/merkle.rs (SURVEY.md §2.4): one Merkle
trie per ring partition; the trie descends on the bytes of **blake2(tree
key)** (so no key is a prefix of another); node kinds are Empty,
Intermediate([(next_byte, child_hash)]) and Leaf(item_key, value_hash)
(merkle.rs:45-67).  A todo-queue written transactionally by TableData
drives the updater (merkle.rs:92-253); node hash = blake2 of the node's
canonical serialization; an intermediate left with a single leaf child
collapses back into that leaf (merkle.rs:163-182).

Node db key = 1 byte partition ‖ khash prefix (the framework uses
PARTITION_BITS=8 partitions, ring.py; the reference packs a u16).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, List, Optional, Tuple

from ..db import Transaction
from ..utils.background import Worker, WorkerState
from ..utils.data import Hash, blake2sum
from ..utils.migrate import pack, unpack
from .data import TableData

logger = logging.getLogger("garage_tpu.table.merkle")

EMPTY = None
_UNCHANGED = object()  # "subtree not modified" — distinct from EMPTY (ref
                       # merkle.rs models this as Option<MerkleNode>)
EMPTY_HASH = Hash(b"\x00" * 32)


def _encode_node(node: Any) -> bytes:
    return pack(node)


def _decode_node(data: Optional[bytes]) -> Any:
    if data is None or data == b"":
        return EMPTY
    return unpack(data)


def node_hash(node: Any) -> Hash:
    """Hash of a node; the empty node hashes to all-zeros (ref merkle.rs
    empty_node_hash)."""
    if node is EMPTY:
        return EMPTY_HASH
    return blake2sum(_encode_node(node))


def _is_leaf(node: Any) -> bool:
    return isinstance(node, (list, tuple)) and len(node) == 3 and node[0] == "l"


def _is_int(node: Any) -> bool:
    return isinstance(node, (list, tuple)) and len(node) == 2 and node[0] == "i"


def leaf(key: bytes, vhash: bytes) -> list:
    return ["l", key, bytes(vhash)]


def intermediate(children: List[Tuple[int, bytes]]) -> list:
    return ["i", [[b, bytes(h)] for b, h in sorted(children)]]


def int_children(node: Any) -> List[Tuple[int, bytes]]:
    return [(b, bytes(h)) for b, h in node[1]]


def node_key(partition: int, prefix: bytes) -> bytes:
    return bytes([partition]) + prefix


class MerkleUpdater:
    def __init__(self, data: TableData):
        self.data = data

    # --- tree access (ref merkle.rs:255-301) ---

    def read_node(self, tx: Optional[Transaction], nk: bytes) -> Any:
        if tx is not None:
            return _decode_node(tx.get(self.data.merkle_tree, nk))
        return _decode_node(self.data.merkle_tree.get(nk))

    def _put_node(self, tx: Transaction, nk: bytes, node: Any) -> Hash:
        if node is EMPTY:
            tx.remove(self.data.merkle_tree, nk)
        else:
            tx.insert(self.data.merkle_tree, nk, _encode_node(node))
        return node_hash(node)

    def partition_root_hash(self, partition: int) -> Hash:
        """Root hash of one partition's subtree — what sync compares."""
        return node_hash(self.read_node(None, node_key(partition, b"")))

    # --- the update algorithm (ref merkle.rs:92-253) ---

    def update_item(self, k: bytes) -> None:
        """Apply one todo entry for item key `k`.  The todo value is the new
        value hash (b'' = item deleted); it is removed only if unchanged
        after the tree transaction (ref merkle.rs:113-128)."""
        todo_val = self.data.merkle_todo.get(k)
        if todo_val is None:
            return
        new_vhash = None if todo_val == b"" else Hash(todo_val)
        khash = blake2sum(k)
        partition = self.data.replication.partition_of(Hash(k[:32]))

        def txn(tx: Transaction):
            self._update_rec(tx, k, khash, partition, b"", new_vhash)
            cur = tx.get(self.data.merkle_todo.tree, k)
            if cur == todo_val:
                self.data.merkle_todo.tx_remove(tx, k)

        self.data.db.transaction(txn)

    def _update_rec(
        self,
        tx: Transaction,
        k: bytes,
        khash: Hash,
        partition: int,
        prefix: bytes,
        new_vhash: Optional[Hash],
    ) -> Optional[Hash]:
        """Returns the node's new hash, or None if the subtree is unchanged
        (ref merkle.rs:131-253 update_item_rec)."""
        i = len(prefix)
        nk = node_key(partition, prefix)
        node = self.read_node(tx, nk)
        mutate = _UNCHANGED

        if node is EMPTY:
            if new_vhash is not None:
                mutate = leaf(k, bytes(new_vhash))

        elif _is_int(node):
            children = int_children(node)
            next_prefix = prefix + khash[i : i + 1]
            subhash = self._update_rec(tx, k, khash, partition, next_prefix, new_vhash)
            if subhash is not None:
                nb = khash[i]
                children = [(b, h) for b, h in children if b != nb]
                if subhash != EMPTY_HASH:
                    children.append((nb, bytes(subhash)))
                if not children:
                    logger.warning("intermediate collapsed to empty (unexpected)")
                    mutate = EMPTY
                elif len(children) == 1:
                    sub_nk = node_key(partition, prefix + bytes([children[0][0]]))
                    subnode = self.read_node(tx, sub_nk)
                    if _is_leaf(subnode):
                        # hoist the single remaining leaf up one level
                        tx.remove(self.data.merkle_tree, sub_nk)
                        mutate = subnode
                    else:
                        mutate = intermediate(children)
                else:
                    mutate = intermediate(children)

        else:  # leaf
            exlf_k, exlf_vhash = bytes(node[1]), bytes(node[2])
            if exlf_k == k:
                if new_vhash is not None and bytes(new_vhash) != exlf_vhash:
                    mutate = leaf(k, bytes(new_vhash))
                elif new_vhash is None:
                    mutate = EMPTY
            elif new_vhash is not None:
                # split: push the existing leaf down by its own khash byte,
                # then insert our key (ref merkle.rs:196-238)
                exlf_khash = blake2sum(exlf_k)
                assert exlf_khash[:i] == khash[:i]
                children = []
                sub1 = self._update_rec(
                    tx, exlf_k, exlf_khash, partition,
                    prefix + exlf_khash[i : i + 1], Hash(exlf_vhash),
                )
                children.append((exlf_khash[i], bytes(sub1)))
                sub2 = self._update_rec(
                    tx, k, khash, partition, prefix + khash[i : i + 1], new_vhash
                )
                children = [(b, h) for b, h in children if b != khash[i]]
                children.append((khash[i], bytes(sub2)))
                mutate = intermediate(children)

        if mutate is _UNCHANGED:
            return None
        return self._put_node(tx, nk, mutate)

    # --- subtree walks (used by sync) ---

    def collect_leaves(self, partition: int, prefix: bytes) -> List[Tuple[bytes, bytes]]:
        """All (item_key, value_hash) leaves under a node."""
        out: List[Tuple[bytes, bytes]] = []
        self._collect(partition, prefix, out)
        return out

    def _collect(self, partition: int, prefix: bytes, out):
        node = self.read_node(None, node_key(partition, prefix))
        if node is EMPTY:
            return
        if _is_leaf(node):
            out.append((bytes(node[1]), bytes(node[2])))
            return
        for b, _h in int_children(node):
            self._collect(partition, prefix + bytes([b]), out)


class MerkleWorker(Worker):
    """Drains the merkle_todo queue (ref merkle.rs:303-340, batches of 100)."""

    BATCH = 100

    def __init__(self, updater: MerkleUpdater):
        self.updater = updater
        self.data = updater.data

    def name(self) -> str:
        return f"{self.data.schema.TABLE_NAME} Merkle"

    async def work(self) -> WorkerState:
        st = self.status()
        # The whole batch runs OFF the event loop (ref merkle.rs:303-340
        # uses spawn_blocking for the same reason): after a bulk insert the
        # todo backlog is thousands of items and the runner re-calls work()
        # continuously while BUSY — hashing them on the loop thread starves
        # every foreground request on a small host for the duration.
        processed = await asyncio.to_thread(self._work_batch)
        st.queue_length = self.data.merkle_todo_len()
        return WorkerState.BUSY if processed else WorkerState.IDLE

    def _work_batch(self) -> int:
        processed = 0
        cursor = b""
        while processed < self.BATCH:
            nxt = (
                self.data.merkle_todo.first()
                if cursor == b""
                else self.data.merkle_todo.get_gt(cursor)
            )
            if nxt is None:
                break
            key, _val = nxt
            self.updater.update_item(key)
            cursor = key
            processed += 1
        return processed

    async def wait_for_work(self) -> None:
        self.data.merkle_todo_notify.clear()
        if self.data.merkle_todo_len() > 0:
            return
        await self.data.merkle_todo_notify.wait()
