"""Table replication strategies — which nodes store which partition.

Equivalent of reference src/table/replication/ (SURVEY.md §2.4): the
`TableReplication` interface (parameters.rs:1-33) with the sharded
strategy (ring-based, sharded.rs:16-53) and the full-copy strategy
(all nodes, epidemic writes, local reads, fullcopy.rs:14-50).

These are the storage-domain analogue of an ML stack's parallelism
strategies: they decide data placement and the quorum collective pattern.
"""

from __future__ import annotations

from typing import List, Tuple

from ..rpc.ring import N_PARTITIONS, partition_range
from ..rpc.system import System
from ..utils.data import FixedBytes32, Hash

ALL_ZEROS = Hash(b"\x00" * 32)


class TableReplication:
    """ref table/replication/parameters.rs:1-33."""

    def read_nodes(self, h: Hash) -> List[FixedBytes32]:
        raise NotImplementedError

    def read_quorum(self) -> int:
        raise NotImplementedError

    def write_nodes(self, h: Hash) -> List[FixedBytes32]:
        raise NotImplementedError

    def write_quorum(self) -> int:
        raise NotImplementedError

    def max_write_errors(self) -> int:
        raise NotImplementedError

    def partition_of(self, h: Hash) -> int:
        raise NotImplementedError

    def partitions(self) -> List[Tuple[int, Hash]]:
        """All (partition, first_hash) pairs of the keyspace."""
        raise NotImplementedError


class TableShardedReplication(TableReplication):
    """Partitioned replication over the ring (ref sharded.rs:16-53)."""

    def __init__(
        self,
        system: System,
        replication_factor: int,
        read_quorum: int,
        write_quorum: int,
    ):
        self.system = system
        self.replication_factor = replication_factor
        self._read_quorum = read_quorum
        self._write_quorum = write_quorum

    def read_nodes(self, h: Hash) -> List[FixedBytes32]:
        return self.system.ring.get_nodes(bytes(h), self.replication_factor)

    def read_quorum(self) -> int:
        return self._read_quorum

    def write_nodes(self, h: Hash) -> List[FixedBytes32]:
        return self.system.ring.get_nodes(bytes(h), self.replication_factor)

    def write_quorum(self) -> int:
        return self._write_quorum

    def max_write_errors(self) -> int:
        return self.replication_factor - self._write_quorum

    def partition_of(self, h: Hash) -> int:
        return self.system.ring.partition_of(bytes(h))

    def partitions(self) -> List[Tuple[int, Hash]]:
        return self.system.ring.partitions()


class TableFullReplication(TableReplication):
    """All nodes store everything; reads are local; writes go everywhere
    tolerating `max_faults` failures (ref fullcopy.rs:14-50)."""

    def __init__(self, system: System, max_faults: int = 0):
        self.system = system
        self.max_faults = max_faults

    def _all_nodes(self) -> List[FixedBytes32]:
        nodes = [FixedBytes32(n) for n in self.system.layout.all_nodes()]
        if not nodes:
            nodes = [self.system.id]
        return nodes

    def read_nodes(self, h: Hash) -> List[FixedBytes32]:
        return [self.system.id]

    def read_quorum(self) -> int:
        return 1

    def write_nodes(self, h: Hash) -> List[FixedBytes32]:
        return self._all_nodes()

    def write_quorum(self) -> int:
        n = len(self._all_nodes())
        return n - self.max_faults if n > self.max_faults else 1

    def max_write_errors(self) -> int:
        return self.max_faults

    def partition_of(self, h: Hash) -> int:
        return 0

    def partitions(self) -> List[Tuple[int, Hash]]:
        return [(0, ALL_ZEROS)]


__all__ = [
    "TableReplication",
    "TableShardedReplication",
    "TableFullReplication",
    "N_PARTITIONS",
    "partition_range",
]
