"""Table — the distributed front: quorum insert/get/range with read-repair.

Equivalent of reference src/table/table.rs (SURVEY.md §2.4): writes go to
the partition's replica set via `try_call_many` with the write quorum
(table.rs:104-137); reads use interrupt-after-quorum with latency ordering
and, on divergent replies, merge and asynchronously push the merged value
back to all replicas — read repair (table.rs:228-284); `insert_many`
batches entries per destination node (table.rs:139-206).

RPC messages (ref TableRpc enum, table.rs:46-66) are msgpack dicts:
  {"t":"update", "entries":[bytes]}           → ok
  {"t":"read_entry", "tk": bytes}             → {"v": bytes|None}
  {"t":"read_range", ...}                     → {"vs": [bytes]}
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ..net.frame import PRIO_NORMAL
from ..rpc.rpc_helper import RequestStrategy
from ..rpc.system import System
from ..utils.data import FixedBytes32, Hash
from ..utils.error import GarageError
from ..utils.metrics import maybe_time
from .data import TableData
from .merkle import MerkleUpdater
from .replication import TableReplication
from .schema import Entry, TableSchema, hash_partition_key, sort_key_bytes

logger = logging.getLogger("garage_tpu.table")

TABLE_RPC_TIMEOUT = 30.0


class Table:
    def __init__(
        self,
        system: System,
        schema: TableSchema,
        replication: TableReplication,
        db,
    ):
        self.system = system
        self.schema = schema
        self.replication = replication
        self.data = TableData(system, schema, replication, db)
        self.merkle = MerkleUpdater(self.data)
        self.endpoint = system.netapp.endpoint(
            f"garage/table/{schema.TABLE_NAME}"
        )
        self.endpoint.set_handler(self._handle)
        # attached by Garage.spawn_workers: syncer/gc refs for admin RPC
        self.syncer = None
        self.gc = None
        self._repair_tasks: set = set()  # strong refs: loop holds tasks weakly

        # per-table request metrics (ref table/metrics.rs): metric families
        # are shared across tables (registry dedups by name); each table
        # records with its own table_name label
        m = getattr(system, "metrics", None)
        self._tname = schema.TABLE_NAME
        if m is not None:
            self._m = {
                "gets": m.counter(
                    "table_get_request_counter", "Table get/get_range requests"),
                "puts": m.counter(
                    "table_put_request_counter", "Table insert requests"),
                "get_dur": m.histogram(
                    "table_get_request_duration_seconds", "Table read latency"),
                "put_dur": m.histogram(
                    "table_put_request_duration_seconds", "Table write latency"),
                "size": m.gauge("table_size", "Number of items in table"),
                "merkle_todo": m.gauge(
                    "table_merkle_updater_todo_queue_length",
                    "Merkle updater backlog"),
                "gc_todo": m.gauge(
                    "table_gc_todo_queue_length", "Tombstone GC backlog"),
                # the metadata-at-millions depth trio (short canonical
                # names, `table` label): the queues whose growth is the
                # first sign the table engine is behind its writers —
                # Merkle digestion, batched inserts, tombstone GC.  The
                # two legacy *_queue_length families above stay for
                # dashboard compat.
                "merkle_todo2": m.gauge(
                    "table_merkle_todo",
                    "Rows awaiting Merkle-tree digestion, per table"),
                "insert_queue": m.gauge(
                    "table_insert_queue",
                    "Entries queued in the batched insert queue, per "
                    "table"),
                "gc_todo2": m.gauge(
                    "table_gc_todo",
                    "Tombstones awaiting GC, per table"),
            }
        else:
            self._m = None

    def observe_gauges(self) -> None:
        """Refresh this table's size/backlog gauges (called at scrape)."""
        if self._m is None:
            return
        self._m["size"].set(self.data.store_len(), table_name=self._tname)
        merkle = self.data.merkle_todo_len()
        gc = self.data.gc_todo_len()
        self._m["merkle_todo"].set(merkle, table_name=self._tname)
        self._m["gc_todo"].set(gc, table_name=self._tname)
        self._m["merkle_todo2"].set(merkle, table=self._tname)
        self._m["insert_queue"].set(
            len(self.data.insert_queue), table=self._tname)
        self._m["gc_todo2"].set(gc, table=self._tname)

    # --- client operations ---

    async def insert(self, entry: Entry) -> None:
        """ref table.rs:104-137."""
        if self._m is not None:
            self._m["puts"].inc(table_name=self._tname)
        with self._span("insert"), \
                maybe_time(self._m and self._m["put_dur"],
                           table_name=self._tname):
            await self._insert_inner(entry)

    async def _insert_inner(self, entry: Entry) -> None:
        h = hash_partition_key(entry.partition_key)
        who = self.replication.write_nodes(h)
        e_enc = entry.encode()
        await self.system.rpc.try_call_many(
            self.endpoint,
            who,
            {"t": "update", "entries": [e_enc]},
            RequestStrategy(
                rs_quorum=self.replication.write_quorum(),
                rs_timeout=TABLE_RPC_TIMEOUT,
                # hard integer zone_redundancy: the acked set must span
                # the layout's failure domains (0 = availability-first)
                rs_required_zones=self.system.write_zone_requirement(who),
            ),
        )

    async def insert_many(self, entries: List[Entry]) -> None:
        """Batch insert grouped per destination node (ref table.rs:139-206);
        fails if any entry missed its write quorum."""
        per_node: Dict[FixedBytes32, List[bytes]] = {}
        per_node_keys: Dict[FixedBytes32, List[int]] = {}
        candidates: List[List[FixedBytes32]] = []
        for i, entry in enumerate(entries):
            h = hash_partition_key(entry.partition_key)
            e_enc = entry.encode()
            who = self.replication.write_nodes(h)
            candidates.append(who)
            for n in who:
                per_node.setdefault(n, []).append(e_enc)
                per_node_keys.setdefault(n, []).append(i)

        async def send(node, batch):
            await self.endpoint.call(
                node,
                {"t": "update", "entries": batch},
                timeout=TABLE_RPC_TIMEOUT,
            )

        results = await asyncio.gather(
            *[send(n, b) for n, b in per_node.items()], return_exceptions=True
        )
        ok_count = [0] * len(entries)
        ok_zones = [set() for _ in entries]
        for (node, _), res in zip(per_node.items(), results):
            if not isinstance(res, Exception):
                z = self.system.zone_of(node)
                for i in per_node_keys[node]:
                    ok_count[i] += 1
                    if z is not None:
                        ok_zones[i].add(z)
        quorum = self.replication.write_quorum()
        failed = sum(1 for c in ok_count if c < quorum)
        if failed:
            raise GarageError(
                f"insert_many: {failed}/{len(entries)} entries below write quorum"
            )
        # all sends are already in (gather, no early return), so the
        # per-entry zone check costs nothing extra: an entry that met
        # its numeric quorum inside ONE dark-zone-complement still fails
        # typed when the layout demands spread
        zone_failed = sum(
            1 for i in range(len(entries))
            if (req := self.system.write_zone_requirement(candidates[i])) > 1
            and len(ok_zones[i]) < req
        )
        if zone_failed:
            from ..utils.error import ZoneQuorumError

            # same observable as the rpc_helper write path: the Grafana
            # panel / playbook signal must see batched failures too
            if self.system.rpc.m_zone_errors is not None:
                self.system.rpc.m_zone_errors.inc(
                    endpoint=self.endpoint.path)
            raise ZoneQuorumError(
                f"insert_many: {zone_failed}/{len(entries)} entries acked "
                f"in fewer zones than the layout requires"
            )

    def _span(self, op: str):
        """Per-table-op tracing span (ref table/table.rs:105-110);
        Tracer.span is a shared no-op when tracing is off."""
        return self.system.tracer.span(
            f"Table {self._tname} {op}", table=self._tname, op=op
        )

    def _read_timer(self):
        if self._m is not None:
            self._m["gets"].inc(table_name=self._tname)
        return maybe_time(self._m and self._m["get_dur"],
                          table_name=self._tname)

    async def get(self, p: Any, s: Any) -> Optional[Entry]:
        """Quorum read with read-repair (ref table.rs:228-284)."""
        with self._span("get"), self._read_timer():
            return await self._get_inner(p, s)

    async def _get_inner(self, p: Any, s: Any) -> Optional[Entry]:
        h = hash_partition_key(p)
        who = self.replication.read_nodes(h)
        tk = self.data.tree_key(p, s)
        resps = await self.system.rpc.try_call_many(
            self.endpoint,
            who,
            {"t": "read_entry", "tk": tk},
            RequestStrategy(
                rs_quorum=self.replication.read_quorum(),
                rs_interrupt_after_quorum=True,
                rs_timeout=TABLE_RPC_TIMEOUT,
                rs_idempotent=True,  # pure read: retry/hedge freely
            ),
        )
        ret: Optional[Entry] = None
        ret_enc: Optional[bytes] = None
        not_all_same = False
        for r in resps:
            v = r.get("v")
            if v is None:
                if ret is not None:
                    not_all_same = True
                continue
            ent = self.data.decode_entry(bytes(v))
            if ret is None:
                ret, ret_enc = ent, bytes(v)
            else:
                # any reply that differs from the accumulated value means a
                # replica is stale — even if the merge absorbs it (ref
                # table.rs:252-265 flags whenever x != ret)
                if bytes(v) != ret_enc:
                    not_all_same = True
                ret.merge(ent)
                ret_enc = ret.encode()
        if ret is not None and not_all_same:
            self._spawn_repair(ret, who)
        return ret

    async def get_range(
        self,
        p: Any,
        start_sort_key: Optional[Any] = None,
        filter: Any = None,
        limit: int = 100,
        reverse: bool = False,
        end_sort_key: Optional[Any] = None,
    ) -> List[Entry]:
        """Quorum range read, merged per key, with read-repair of divergent
        items (ref table.rs:314-407).  `end_sort_key` (exclusive) bounds
        the scan — the sub-range contract sharded listings fan out over."""
        with self._span("get_range"), self._read_timer():
            return await self._get_range_inner(
                p, start_sort_key, filter, limit, reverse, end_sort_key
            )

    async def _get_range_inner(
        self, p, start_sort_key=None, filter=None, limit=100, reverse=False,
        end_sort_key=None,
    ) -> List[Entry]:
        h = hash_partition_key(p)
        who = self.replication.read_nodes(h)
        msg = {
            "t": "read_range",
            "ph": bytes(h),
            "sk": sort_key_bytes(start_sort_key) if start_sort_key is not None else None,
            "filter": filter,
            "limit": limit,
            "rev": reverse,
        }
        if end_sort_key is not None:
            msg["ek"] = sort_key_bytes(end_sort_key)
        resps = await self.system.rpc.try_call_many(
            self.endpoint,
            who,
            msg,
            RequestStrategy(
                rs_quorum=self.replication.read_quorum(),
                rs_interrupt_after_quorum=True,
                rs_timeout=TABLE_RPC_TIMEOUT,
                rs_idempotent=True,  # pure read: retry/hedge freely
            ),
        )
        # merge per tree-key (ref table.rs:353-407)
        merged: Dict[bytes, Entry] = {}
        seen_count: Dict[bytes, int] = {}
        diverged: set = set()
        # a key missing from one response only proves divergence if it lies
        # inside that response's returned window — otherwise it may simply
        # have been truncated by `limit` (window = everything if untruncated)
        windows: List[Optional[bytes]] = []  # per-response window edge, None=∞
        for r in resps:
            vs = r.get("vs", [])
            edge = None
            for v in vs:
                ent = self.data.decode_entry(bytes(v))
                tk = ent.tree_key()
                # the truncation edge is the *last* key in iteration order:
                # max for forward reads, min for reverse reads
                if edge is None or (tk < edge if reverse else tk > edge):
                    edge = tk
                seen_count[tk] = seen_count.get(tk, 0) + 1
                if tk in merged:
                    before = merged[tk].encode()
                    merged[tk].merge(ent)
                    if merged[tk].encode() != before or before != bytes(v):
                        diverged.add(tk)
                else:
                    merged[tk] = ent
            windows.append(edge if len(vs) >= limit else None)
        if len(resps) > 1:
            for tk, c in seen_count.items():
                covered = all(
                    w is None or (tk >= w if reverse else tk <= w)
                    for w in windows
                )
                if c < len(resps) and covered:
                    diverged.add(tk)
        for tk in diverged:
            self._spawn_repair(merged[tk], who)
        out = sorted(merged.items(), key=lambda kv: kv[0], reverse=reverse)
        ents = [
            e for _tk, e in out
            if filter is None or self.schema.matches_filter(e, filter)
        ]
        return ents[:limit]

    def _spawn_repair(self, entry: Entry, who: List[FixedBytes32]) -> None:
        """Asynchronously push the merged value back to all replicas
        (ref table.rs:271-283 repair_on_read)."""

        async def repair():
            try:
                await self.system.rpc.try_call_many(
                    self.endpoint,
                    who,
                    {"t": "update", "entries": [entry.encode()]},
                    RequestStrategy(rs_quorum=len(who), rs_timeout=TABLE_RPC_TIMEOUT),
                )
            except Exception as e:
                logger.debug(
                    "%s: read repair failed: %s", self.schema.TABLE_NAME, e
                )

        task = asyncio.get_running_loop().create_task(repair())
        self._repair_tasks.add(task)
        task.add_done_callback(self._repair_tasks.discard)

    # --- server side (ref table.rs:426-461) ---

    async def _handle(self, remote, msg, body):
        t = msg.get("t")
        if t == "update":
            self.data.update_many([bytes(e) for e in msg["entries"]])
            return {"ok": True}, None
        if t == "read_entry":
            v = self.data.store.get(bytes(msg["tk"]))
            return {"v": v}, None
        if t == "read_range":
            vs = self.data.read_range(
                Hash(bytes(msg["ph"])),
                bytes(msg["sk"]) if msg.get("sk") is not None else None,
                msg.get("filter"),
                int(msg.get("limit", 100)),
                bool(msg.get("rev", False)),
                bytes(msg["ek"]) if msg.get("ek") is not None else None,
            )
            return {"vs": vs}, None
        raise GarageError(f"unknown table rpc {t!r}")
