"""Replicated CRDT table engine.

Equivalent of reference src/table/ (SURVEY.md §2.4): a generic table of
CRDT entries addressed by (partition key, sort key), replicated over the
cluster with quorum reads/writes, Merkle-tree anti-entropy, distributed
tombstone GC and an async insert queue.
"""

from .schema import Entry, TableSchema, hash_partition_key, tree_key
from .replication import (
    TableFullReplication,
    TableReplication,
    TableShardedReplication,
)
from .data import TableData
from .table import Table
from .merkle import MerkleUpdater, MerkleWorker
from .sync import TableSyncer
from .gc import TableGc
from .queue import InsertQueueWorker

__all__ = [
    "Entry",
    "TableSchema",
    "hash_partition_key",
    "tree_key",
    "TableReplication",
    "TableShardedReplication",
    "TableFullReplication",
    "TableData",
    "Table",
    "MerkleUpdater",
    "MerkleWorker",
    "TableSyncer",
    "TableGc",
    "InsertQueueWorker",
]
