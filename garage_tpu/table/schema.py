"""Table schema: partition/sort keys, entries, filters.

Equivalent of reference src/table/schema.rs:12-103: `PartitionKey::hash()`
is blake2 for strings and identity for 32-byte values (schema.rs:19-32);
entries are CRDTs with versioned serialization; the schema's `updated()`
hook runs inside the update transaction (schema.rs:88-100).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Type

from ..db import Transaction
from ..utils.crdt import Crdt
from ..utils.data import FixedBytes32, Hash, blake2sum
from ..utils.migrate import Migrated


def hash_partition_key(p: Any) -> Hash:
    """ref schema.rs:19-32: blake2 of strings, identity for FixedBytes32.
    Tuples (e.g. K2V's (bucket_id, partition_key)) hash their blake2-joined
    parts, matching the reference's K2VItemPartition composite key."""
    if isinstance(p, FixedBytes32):
        return p
    if isinstance(p, str):
        return blake2sum(p.encode())
    if isinstance(p, bytes):
        if len(p) == 32:
            return Hash(p)
        return blake2sum(p)
    if isinstance(p, tuple):
        # length-prefix each part so ("a","bc") and ("ab","c") can't collide
        buf = b""
        for x in p:
            part = bytes(x) if isinstance(x, (bytes, FixedBytes32)) else str(x).encode()
            buf += len(part).to_bytes(4, "big") + part
        return blake2sum(buf)
    raise TypeError(f"unsupported partition key type {type(p)!r}")


def sort_key_bytes(s: Any) -> bytes:
    """ref schema.rs:37-52 SortKey::sort_key."""
    if isinstance(s, (bytes, FixedBytes32)):
        return bytes(s)
    if isinstance(s, str):
        return s.encode()
    raise TypeError(f"unsupported sort key type {type(s)!r}")


def tree_key(p: Any, s: Any) -> bytes:
    """DB key of an entry: hash(P) ‖ sort_key (ref table/data.rs:323-329)."""
    return bytes(hash_partition_key(p)) + sort_key_bytes(s)


class Entry(Crdt, Migrated):
    """A table entry: CRDT + versioned serialization + keys
    (ref schema.rs:57-69).  Subclasses define `partition_key`/`sort_key`
    properties and CRDT merge."""

    @property
    def partition_key(self) -> Any:
        raise NotImplementedError

    @property
    def sort_key(self) -> Any:
        raise NotImplementedError

    def is_tombstone(self) -> bool:
        return False

    def tree_key(self) -> bytes:
        return tree_key(self.partition_key, self.sort_key)


class TableSchema:
    """ref schema.rs:72-103.  Subclasses set TABLE_NAME and ENTRY (the
    entry class, used to decode stored bytes) and may override `updated`
    (transactional cross-table hook) and `matches_filter`."""

    TABLE_NAME: str = "?"
    ENTRY: Type[Entry] = Entry

    def decode_entry(self, data: bytes) -> Entry:
        return self.ENTRY.decode(data)  # type: ignore[return-value]

    def updated(
        self,
        tx: Transaction,
        old: Optional[Entry],
        new: Optional[Entry],
    ) -> None:
        """Called inside the update transaction whenever an entry changes
        (ref schema.rs:88-100) — the cross-table coupling point (e.g.
        block_ref → rc incref/decref)."""

    def matches_filter(self, entry: Entry, filter: Any) -> bool:
        """ref schema.rs:102 — default: tombstones don't match."""
        return not entry.is_tombstone()


class DeletedFilter:
    """ref table/util.rs DeletedFilter — Any/Deleted/NotDeleted."""

    ANY = "any"
    DELETED = "deleted"
    NOT_DELETED = "not_deleted"

    @staticmethod
    def matches(filter: str, is_deleted: bool) -> bool:
        if filter == DeletedFilter.ANY:
            return True
        if filter == DeletedFilter.DELETED:
            return is_deleted
        return not is_deleted


EMPTY_SORT_KEY = ""
