"""TableSyncer — Merkle anti-entropy between replicas.

Equivalent of reference src/table/sync.rs (SURVEY.md §2.4): every
ANTI_ENTROPY_INTERVAL (10 min), on ring change and on demand, each stored
partition's Merkle root hash is compared with the other replicas'; on
mismatch the tries are descended in parallel and differing items are
pushed in ≤256-item batches (sync.rs:286-415).  Partitions this node no
longer stores are offloaded: sent whole to the current replicas, then
deleted locally (sync.rs:170-269).

Sync is push-only and symmetric: each replica pushes what the other lacks
in its own sync round, so convergence needs no pull protocol.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import List, Optional

from ..net.frame import PRIO_BACKGROUND
from ..rpc.rpc_helper import RequestStrategy
from ..utils.background import Worker, WorkerState
from ..utils.data import FixedBytes32, Hash
from ..utils.error import GarageError
from .merkle import (
    EMPTY,
    EMPTY_HASH,
    MerkleUpdater,
    _encode_node,
    _is_int,
    _is_leaf,
    int_children,
    node_hash,
    node_key,
)

logger = logging.getLogger("garage_tpu.table.sync")

ANTI_ENTROPY_INTERVAL = 600.0  # ref sync.rs:30 (10 min)
BATCH_SIZE = 256               # ref sync.rs push batches
OFFLOAD_BATCH = 1024
SYNC_NODE_RPC_MAX = 65536      # server-side sanity cap on one get_nodes


class TableSyncer:
    def __init__(self, system, data, merkle: MerkleUpdater):
        self.system = system
        self.data = data
        self.merkle = merkle
        self.endpoint = system.netapp.endpoint(
            f"garage/table_sync/{data.schema.TABLE_NAME}"
        )
        self.endpoint.set_handler(self._handle)
        self.worker: Optional[SyncWorker] = None
        # [table] sync_batch_nodes: Merkle nodes shipped per descent RPC
        # round (<= 1 restores the legacy one-node-per-round walk)
        tcfg = getattr(getattr(system, "config", None), "table", None)
        self.sync_batch_nodes = int(
            getattr(tcfg, "sync_batch_nodes", 512) or 512)
        # peers that answered `get_nodes` with unknown-rpc: mixed-version
        # fallback to the per-node descent until the process restarts
        self._peer_pernode: dict = {}
        # cumulative descent RPC rounds (bench A/B evidence)
        self.node_rpcs = 0
        # sync item counters (ref table/metrics.rs sync_items_sent/received)
        # — families shared across tables via registry name-dedup
        m = getattr(system, "metrics", None)
        if m is not None:
            self._m = {
                "sent": m.counter(
                    "table_sync_items_sent",
                    "Items sent to other nodes during anti-entropy"),
                "recv": m.counter(
                    "table_sync_items_received",
                    "Items received from other nodes during anti-entropy"),
                # Merkle sync convergence signal for the metadata arc: a
                # cold-joining node's catch-up is `synced` rounds turning
                # into `in_sync`; persistent `error` rounds mean a
                # partition that cannot converge
                "rounds": m.counter(
                    "table_merkle_sync_rounds_total",
                    "Per-peer-partition anti-entropy rounds by outcome "
                    "(in_sync = roots matched, synced = diffs pushed, "
                    "offload = partition handed to its new replicas, "
                    "error = round failed)"),
            }
            self._m["node_rpcs"] = m.counter(
                "table_sync_node_rpc_total",
                "Merkle descent RPC rounds by mode (batched = whole "
                "frontier per round, pernode = legacy one node per "
                "round); the batched/pernode ratio is the convergence "
                "win at cold-node join")
        else:
            self._m = None

    def _node_rpc(self, mode: str) -> None:
        self.node_rpcs += 1
        if self._m is not None:
            self._m["node_rpcs"].inc(
                mode=mode, table_name=self.data.schema.TABLE_NAME)

    def _round(self, result: str) -> None:
        if self._m is not None:
            self._m["rounds"].inc(
                result=result, table_name=self.data.schema.TABLE_NAME)

    def _count(self, which: str, n: int) -> None:
        if self._m is not None and n:
            self._m[which].inc(n, table_name=self.data.schema.TABLE_NAME)

    def make_worker(self) -> "SyncWorker":
        self.worker = SyncWorker(self)
        self.system.on_ring_change(lambda _ring: self.worker.add_full_sync())
        return self.worker

    def add_full_sync(self):
        if self.worker is not None:
            self.worker.add_full_sync()

    # --- one partition (ref sync.rs:110-168) ---

    async def sync_partition(self, partition: int, first_hash: Hash) -> None:
        nodes = self.data.replication.write_nodes(first_hash)
        if self.system.id in nodes:
            others = [n for n in nodes if n != self.system.id]
            await asyncio.gather(
                *[self._do_sync_with(partition, n) for n in others],
                return_exceptions=False,
            )
        elif nodes:
            await self._offload_partition(partition, nodes)

    # --- push sync (ref sync.rs:286-415) ---

    async def _do_sync_with(self, partition: int, who: FixedBytes32) -> None:
        try:
            await self._do_sync_with_inner(partition, who)
        except Exception:
            # Exception, NOT BaseException: a CancelledError from
            # worker shutdown is routine, and counting it as an `error`
            # round would grow the "partition cannot converge" signal on
            # every restart across the fleet
            self._round("error")
            raise

    async def _do_sync_with_inner(self, partition: int,
                                  who: FixedBytes32) -> None:
        root_nk = node_key(partition, b"")
        local_root = self.merkle.read_node(None, root_nk)
        local_hash = node_hash(local_root)
        resp = await self.endpoint.call(
            who,
            {"t": "root_ck", "p": partition},
            prio=PRIO_BACKGROUND,
        )
        remote_hash = bytes(resp["ck"])
        if bytes(local_hash) == remote_hash:
            self._round("in_sync")
            return
        bn = self.sync_batch_nodes
        if bn <= 1 or self._peer_pernode.get(bytes(who)):
            await self._descend_pernode(partition, who, root_nk)
        else:
            try:
                await self._descend_batched(partition, who, root_nk, bn)
            except GarageError as e:
                if "unknown sync rpc" not in str(e):
                    raise
                # a pre-batching peer: remember it and walk per-node
                self._peer_pernode[bytes(who)] = True
                logger.info(
                    "%s: peer lacks get_nodes; falling back to per-node "
                    "descent", self.data.schema.TABLE_NAME)
                await self._descend_pernode(partition, who, root_nk)
        self._round("synced")

    async def _descend_batched(self, partition: int, who: FixedBytes32,
                               root_nk: bytes, batch_nodes: int) -> None:
        """Breadth-wise batched descent: the whole differing frontier
        ships in ≤ `batch_nodes` node sets per RPC round, so a cold
        node's catch-up costs O(depth) round-trips instead of O(nodes).
        Pushes the same item set as the per-node walk: the per-level
        child-hash comparison is identical, only the fetch granularity
        changes.  Leaf verification hashes ride the Merkle updater's
        batched hash path (codec feeder, bg class)."""
        frontier: List[bytes] = [root_nk]
        to_send: List[bytes] = []
        while frontier:
            chunk, frontier = frontier[:batch_nodes], frontier[batch_nodes:]
            lmap = {nk: self.merkle.read_node(None, nk) for nk in chunk}
            # local EMPTY: remote has extra data; its own round pushes
            ask = [nk for nk in chunk if lmap[nk] is not EMPTY]
            if not ask:
                continue
            r = await self.endpoint.call(
                who, {"t": "get_nodes", "nks": ask}, prio=PRIO_BACKGROUND
            )
            self._node_rpc("batched")
            rnodes = r.get("nodes")
            if not isinstance(rnodes, list) or len(rnodes) != len(ask):
                raise GarageError(
                    f"get_nodes answered {len(rnodes or [])} nodes "
                    f"for {len(ask)}")
            # batched sync-time node verification: every leaf pair's
            # hashes in ONE ragged feeder batch (the serial walk hashes
            # one node per round-trip)
            pairs = [(nk, rn) for nk, rn in zip(ask, rnodes)
                     if _is_leaf(lmap[nk])]
            enc: List[bytes] = []
            for nk, rn in pairs:
                enc.append(_encode_node(lmap[nk]))
                if rn is not None:
                    enc.append(_encode_node(rn))
            # off-loop: hash_many blocks on the feeder future — parking
            # the event loop would stall every foreground request for
            # the duration of each descent round
            digs = iter(await asyncio.to_thread(self.merkle.hash_many,
                                                enc) if enc else ())
            leaf_diff: dict = {}
            for nk, rn in pairs:
                lh = bytes(next(digs))
                rh = bytes(next(digs)) if rn is not None else bytes(EMPTY_HASH)
                leaf_diff[nk] = lh != rh
            for nk, rnode in zip(ask, rnodes):
                node = lmap[nk]
                if _is_leaf(node):
                    if leaf_diff[nk]:
                        to_send.append(bytes(node[1]))
                else:
                    rchildren = (
                        dict(int_children(rnode))
                        if rnode is not None and _is_int(rnode)
                        else {}
                    )
                    for b, h in int_children(node):
                        if rchildren.get(b) != h:
                            frontier.append(nk + bytes([b]))
                while len(to_send) >= BATCH_SIZE:
                    await self._send_items(who, to_send[:BATCH_SIZE])
                    to_send = to_send[BATCH_SIZE:]
        if to_send:
            await self._send_items(who, to_send)

    async def _descend_pernode(self, partition: int, who: FixedBytes32,
                               root_nk: bytes) -> None:
        """Legacy descent (ref sync.rs:286-415): one node per RPC round
        — kept as the mixed-version fallback and the bench's paired-A/B
        baseline."""
        todo: List[bytes] = [root_nk]
        to_send: List[bytes] = []
        while todo:
            nk = todo.pop()
            node = self.merkle.read_node(None, nk)
            if node is EMPTY:
                continue  # remote has extra data; its own round pushes to us
            r = await self.endpoint.call(
                who, {"t": "get_node", "nk": nk}, prio=PRIO_BACKGROUND
            )
            self._node_rpc("pernode")
            rnode = r.get("node")
            if _is_leaf(node):
                rh = node_hash(rnode) if rnode is not None else EMPTY_HASH
                if bytes(node_hash(node)) != bytes(rh):
                    to_send.append(bytes(node[1]))
            else:
                # local intermediate: diff children against remote's child map
                rchildren = (
                    dict(int_children(rnode))
                    if rnode is not None and _is_int(rnode)
                    else {}
                )
                for b, h in int_children(node):
                    if rchildren.get(b) != h:
                        todo.append(nk + bytes([b]))
            if len(to_send) >= BATCH_SIZE:
                await self._send_items(who, to_send)
                to_send = []
        if to_send:
            await self._send_items(who, to_send)

    async def _send_items(self, who: FixedBytes32, keys: List[bytes]) -> None:
        values = []
        for k in keys:
            v = self.data.store.get(k)
            if v is not None:
                values.append(v)
        if not values:
            return
        await self.endpoint.call(
            who, {"t": "items", "vs": values}, prio=PRIO_BACKGROUND
        )
        self._count("sent", len(values))

    # --- offload (ref sync.rs:170-269) ---

    async def _offload_partition(
        self, partition: int, nodes: List[FixedBytes32]
    ) -> None:
        """We hold data for a partition that is no longer ours: send all of
        it to the real replicas (quorum = all), then delete locally."""
        if len(self.data.replication.partitions()) == 1:
            # single-partition replication (full-copy): the whole keyspace
            begin, end = None, None
        else:
            begin = bytes([partition])
            end = bytes([partition + 1]) if partition < 255 else None
        while True:
            batch = []
            for k, v in self.data.store.items(begin, end):
                batch.append((k, v))
                if len(batch) >= OFFLOAD_BATCH:
                    break
            if not batch:
                break
            values = [v for _k, v in batch]
            await self.system.rpc.try_call_many(
                self.endpoint,
                nodes,
                {"t": "items", "vs": values},
                RequestStrategy(rs_quorum=len(nodes), rs_priority=PRIO_BACKGROUND),
            )
            self._count("sent", len(values))
            for k, v in batch:
                self.data.delete_if_equal(k, v)
            logger.info(
                "%s: offloaded %d items of partition %d",
                self.data.schema.TABLE_NAME, len(batch), partition,
            )
        self._round("offload")

    # --- server side (ref sync.rs SyncRpc) ---

    async def _handle(self, remote, msg, body):
        t = msg.get("t")
        if t == "root_ck":
            ck = self.merkle.partition_root_hash(int(msg["p"]))
            return {"ck": bytes(ck)}, None
        if t == "get_node":
            node = self.merkle.read_node(None, bytes(msg["nk"]))
            return {"node": node}, None
        if t == "get_nodes":
            nks = [bytes(nk) for nk in msg["nks"]]
            if len(nks) > SYNC_NODE_RPC_MAX:
                raise GarageError(
                    f"get_nodes batch of {len(nks)} exceeds "
                    f"{SYNC_NODE_RPC_MAX}")
            return {"nodes": [self.merkle.read_node(None, nk)
                              for nk in nks]}, None
        if t == "items":
            self.data.update_many([bytes(v) for v in msg["vs"]])
            self._count("recv", len(msg["vs"]))
            return {"ok": True}, None
        raise GarageError(f"unknown sync rpc {t!r}")


class SyncWorker(Worker):
    """ref sync.rs:493-614: queue of partitions to sync, refilled by the
    anti-entropy timer, ring changes and manual full-sync requests."""

    def __init__(self, syncer: TableSyncer):
        self.syncer = syncer
        self.todo: List = []
        self.next_full_sync = time.monotonic() + random.uniform(0.0, 30.0)
        self._notify = asyncio.Event()
        self._fail_streak = 0

    def name(self) -> str:
        return f"{self.syncer.data.schema.TABLE_NAME} sync"

    def add_full_sync(self):
        self.todo = list(self.syncer.data.replication.partitions())
        self.next_full_sync = time.monotonic() + anti_entropy_interval()
        self._notify.set()

    async def work(self) -> WorkerState:
        st = self.status()
        if time.monotonic() >= self.next_full_sync:
            self.add_full_sync()
        st.queue_length = len(self.todo)
        if not self.todo:
            return WorkerState.IDLE
        partition, first_hash = self.todo.pop(0)
        st.queue_length = len(self.todo)
        st.progress = f"partition {partition}"
        try:
            await self.syncer.sync_partition(partition, first_hash)
            self._fail_streak = 0
        except Exception as e:
            # A failed partition goes to the BACK of the queue and the
            # worker keeps going — raising here fed the runner's global
            # exponential backoff, so a ~30 s peer outage during a
            # 256-partition pass racked up enough consecutive errors to
            # freeze sync for the better part of an hour AFTER the peer
            # came back (observed during node-loss recovery).  Only when
            # a whole sweep makes no progress do we pause briefly.
            logger.debug(
                "%s: sync of partition %d failed (requeued): %s",
                self.syncer.data.schema.TABLE_NAME, partition, e,
            )
            st.errors += 1
            st.last_error = f"{type(e).__name__}: {e}"
            st.last_error_time = time.time()
            self.todo.append((partition, first_hash))
            self._fail_streak += 1
            if self._fail_streak >= max(8, len(self.todo)):
                self._fail_streak = 0
                await asyncio.sleep(10.0)
        return WorkerState.BUSY

    async def wait_for_work(self) -> None:
        self._notify.clear()
        delay = max(0.1, self.next_full_sync - time.monotonic())
        try:
            await asyncio.wait_for(self._notify.wait(), timeout=delay)
        except asyncio.TimeoutError:
            pass


def anti_entropy_interval() -> float:
    """Test hook: module-level override point."""
    return ANTI_ENTROPY_INTERVAL
