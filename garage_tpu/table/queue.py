"""InsertQueueWorker — drains the transactional insert queue.

Equivalent of reference src/table/queue.rs:15-77: entries written to the
insert queue from inside other tables' update transactions (via
`TableData.queue_insert`) are re-inserted through the normal distributed
path in batches of ≤1024, then removed if unchanged.
"""

from __future__ import annotations

import logging
from typing import List, Tuple

from ..utils.background import Worker, WorkerState

logger = logging.getLogger("garage_tpu.table.queue")

BATCH_SIZE = 1024  # ref queue.rs:12


class InsertQueueWorker(Worker):
    def __init__(self, table):
        self.table = table

    def name(self) -> str:
        return f"{self.table.schema.TABLE_NAME} queue"

    async def work(self) -> WorkerState:
        data = self.table.data
        batch: List[Tuple[bytes, bytes]] = []
        for k, v in data.insert_queue.items():
            batch.append((k, v))
            if len(batch) >= BATCH_SIZE:
                break
        self.status().queue_length = len(data.insert_queue)
        if not batch:
            return WorkerState.IDLE
        entries = []
        for _k, v in batch:
            try:
                entries.append(data.decode_entry(v))
            except Exception:
                logger.exception("undecodable queued insert, dropping")
        if entries:
            await self.table.insert_many(entries)
        # remove only what we processed, and only if unchanged
        def txn(tx):
            for k, v in batch:
                if tx.get(data.insert_queue.tree, k) == v:
                    data.insert_queue.tx_remove(tx, k)

        data.db.transaction(txn)
        self.status().queue_length = len(data.insert_queue)
        return WorkerState.BUSY

    # wait_for_work's len re-check plus the LoopSafeEvent notify
    # (table/data.py) close the mid-batch-refill idle gap: an insert
    # queued from a worker thread while a batch was in flight wakes the
    # drainer instead of waiting out a full notify interval

    async def wait_for_work(self) -> None:
        data = self.table.data
        data.insert_queue_notify.clear()
        if len(data.insert_queue) > 0:
            return
        await data.insert_queue_notify.wait()
