"""S3 data model tables (ref src/model/s3/)."""

from .object_table import (
    Object,
    ObjectVersion,
    ObjectVersionData,
    ObjectVersionHeaders,
    ObjectVersionMeta,
)
from .version_table import Version, VersionBlock, VersionBlockKey
from .block_ref_table import BlockRef
from .mpu_table import MultipartUpload, MpuPart

__all__ = [
    "Object",
    "ObjectVersion",
    "ObjectVersionData",
    "ObjectVersionHeaders",
    "ObjectVersionMeta",
    "Version",
    "VersionBlock",
    "VersionBlockKey",
    "BlockRef",
    "MultipartUpload",
    "MpuPart",
]
