"""Object table — the S3 object metadata rows.

Equivalent of reference src/model/s3/object_table.rs (SURVEY.md §2.6):
an object row (P = bucket uuid, S = object key) holds a list of versions
sorted by (timestamp, uuid); each version's state machine is
Uploading{multipart, headers} → Complete(Inline | FirstBlock) | Aborted
(object_table.rs:20-213).  The CRDT merge unions version lists, merges
states pointwise (Aborted wins; Complete wins over Uploading), then prunes
every version strictly older than the most recent Complete one
(object_table.rs:324-355).  The transactional `updated()` hook propagates
disappearing/aborted versions as Version-table tombstones and feeds the
bucket object counters (object_table.rs:357-518).
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, List, Optional, Tuple

from ...table.schema import Entry, TableSchema
from ...utils.data import Uuid

# counter names (ref object_table.rs:480-518)
OBJECTS = "objects"
UNFINISHED_UPLOADS = "unfinished_uploads"
BYTES = "bytes"


class ObjectVersionHeaders:
    """Headers stored with a version: content-type + other meta headers
    (ref object_table.rs ObjectVersionHeaders). Plain dict carrier."""

    @staticmethod
    def new(content_type: str = "application/octet-stream", other: Optional[Dict[str, str]] = None) -> Dict:
        return {"content_type": content_type, "other": other or {}}


class ObjectVersionMeta:
    """{headers, size, etag} (ref object_table.rs:106-115). Dict carrier."""

    @staticmethod
    def new(headers: Dict, size: int, etag: str) -> Dict:
        return {"headers": headers, "size": size, "etag": etag}


class ObjectVersionData:
    """DeleteMarker | Inline(meta, bytes) | FirstBlock(meta, hash)
    (object_table.rs:117-131)."""

    @staticmethod
    def inline(meta: Dict, data: bytes) -> List:
        return ["inline", meta, data]

    @staticmethod
    def first_block(meta: Dict, hash32: bytes) -> List:
        return ["first_block", meta, bytes(hash32)]

    @staticmethod
    def delete_marker() -> List:
        return ["delete_marker"]


class ObjectVersion:
    """One version of an object (ref object_table.rs:85-213)."""

    __slots__ = ("uuid", "timestamp", "state")

    def __init__(self, uuid: Uuid, timestamp: int, state: List):
        self.uuid = uuid
        self.timestamp = timestamp
        # state: ["uploading", multipart(bool), headers(dict)]
        #      | ["complete", data]   | ["aborted"]
        self.state = state

    @staticmethod
    def uploading(uuid: Uuid, timestamp: int, multipart: bool, headers: Dict) -> "ObjectVersion":
        return ObjectVersion(uuid, timestamp, ["uploading", multipart, headers])

    def sort_key_tuple(self) -> Tuple[int, bytes]:
        # versions are ordered by (timestamp, uuid) (ref object_table.rs:189-198)
        return (self.timestamp, bytes(self.uuid))

    def is_uploading(self, check_multipart: Optional[bool] = None) -> bool:
        return self.state[0] == "uploading" and (
            check_multipart is None or bool(self.state[1]) == check_multipart
        )

    def is_aborted(self) -> bool:
        return self.state[0] == "aborted"

    def is_complete(self) -> bool:
        return self.state[0] == "complete"

    def is_data(self) -> bool:
        """Has actual stored data (complete and not a delete marker)."""
        return self.is_complete() and self.state[1][0] != "delete_marker"

    def data(self) -> Optional[List]:
        return self.state[1] if self.is_complete() else None

    def meta(self) -> Optional[Dict]:
        d = self.data()
        return d[1] if d is not None and d[0] != "delete_marker" else None

    def size(self) -> int:
        m = self.meta()
        return int(m["size"]) if m else 0

    def etag(self) -> str:
        m = self.meta()
        return str(m["etag"]) if m else ""

    def merge_state(self, other: "ObjectVersion") -> None:
        """ref object_table.rs:133-160 ObjectVersionState::merge."""
        a, b = self.state, other.state
        if a[0] == "aborted":
            return
        if b[0] == "aborted":
            self.state = ["aborted"]
        elif b[0] == "complete" and a[0] == "uploading":
            self.state = b
        # complete+complete / uploading+uploading: deterministic content, keep

    def pack(self) -> List:
        return [bytes(self.uuid), self.timestamp, self.state]

    @classmethod
    def unpack(cls, v: List) -> "ObjectVersion":
        st = list(v[2])
        if st[0] == "complete":
            d = list(st[1])
            if d[0] == "delete_marker":
                st[1] = ["delete_marker"]
            elif d[0] == "inline":
                st[1] = ["inline", dict(d[1]), bytes(d[2])]
            else:
                st[1] = ["first_block", dict(d[1]), bytes(d[2])]
        return cls(Uuid(bytes(v[0])), int(v[1]), st)


class Object(Entry):
    """ref object_table.rs:20-83: P = bucket uuid, S = key."""

    VERSION_MARKER = b"GT01object"

    def __init__(self, bucket_id: Uuid, key: str, versions: Optional[List[ObjectVersion]] = None):
        self.bucket_id = bucket_id
        self.key = key
        self._versions: List[ObjectVersion] = versions or []
        self._versions.sort(key=lambda v: v.sort_key_tuple())

    @property
    def partition_key(self) -> Uuid:
        return self.bucket_id

    @property
    def sort_key(self) -> str:
        return self.key

    def versions(self) -> List[ObjectVersion]:
        return self._versions

    def add_version(self, v: ObjectVersion) -> None:
        """Insert preserving (timestamp, uuid) order; merge state if the
        same uuid already exists (ref object_table.rs:60-77)."""
        for mine in self._versions:
            if mine.uuid == v.uuid:
                mine.merge_state(v)
                return
        keys = [x.sort_key_tuple() for x in self._versions]
        self._versions.insert(bisect.bisect_left(keys, v.sort_key_tuple()), v)

    def last_complete_version(self) -> Optional[ObjectVersion]:
        for v in reversed(self._versions):
            if v.is_complete():
                return v
        return None

    def is_tombstone(self) -> bool:
        # a row whose only remaining version is a delete marker carries no
        # data and is GC-able (ref object_table.rs is_tombstone)
        return len(self._versions) == 0 or (
            len(self._versions) == 1
            and self._versions[0].is_complete()
            and not self._versions[0].is_data()
        )

    def merge(self, other: "Object") -> None:
        """ref object_table.rs:324-355."""
        for v in other._versions:
            self.add_version(v)
        # prune: drop everything strictly older than the last complete
        last_complete_i = None
        for i in range(len(self._versions) - 1, -1, -1):
            if self._versions[i].is_complete():
                last_complete_i = i
                break
        if last_complete_i is not None:
            self._versions = self._versions[last_complete_i:]
        # aborted versions are kept only while nothing newer is complete
        # (they still need to propagate); merge of two aborted-only lists
        # keeps them all, which is fine — they carry no data

    def last_data_version(self) -> Optional[ObjectVersion]:
        """Newest complete version that is real data (not a delete marker)."""
        last = self.last_complete_version()
        return last if last is not None and last.is_data() else None

    def counts(self) -> List[Tuple[str, int]]:
        """Counter contributions of this row (ref object_table.rs:480-518)."""
        last = self.last_data_version()
        objects = 1 if last is not None else 0
        nbytes = last.size() if last is not None else 0
        unfinished = sum(1 for v in self._versions if v.is_uploading())
        return [(OBJECTS, objects), (BYTES, nbytes), (UNFINISHED_UPLOADS, unfinished)]

    def fields(self) -> Any:
        return [bytes(self.bucket_id), self.key, [v.pack() for v in self._versions]]

    @classmethod
    def from_fields(cls, b: Any) -> "Object":
        return cls(
            Uuid(bytes(b[0])), b[1], [ObjectVersion.unpack(v) for v in b[2]]
        )


class ObjectTableSchema(TableSchema):
    """ref object_table.rs:357-478 — the updated() hook chain start."""

    TABLE_NAME = "object"
    ENTRY = Object

    def __init__(self, version_table=None, mpu_table=None, counter=None):
        # set post-construction by Garage (circular wiring)
        self.version_table = version_table
        self.mpu_table = mpu_table
        self.counter = counter

    def updated(self, tx, old: Optional[Object], new: Optional[Object]) -> None:
        from .version_table import Version

        if self.counter is not None:
            # counters aggregate per bucket (CP = bucket id, CS = empty —
            # ref object_table.rs CountedItem impl)
            self.counter.count(
                tx,
                bytes((old or new).bucket_id),
                "",
                old.counts() if old is not None else [],
                new.counts() if new is not None else [],
            )
        # Deletion propagation requires BOTH old and new rows (ref
        # object_table.rs:398 `if let (Some(old_v), Some(new_v))`):
        # new=None means a raw LOCAL deletion — partition offload after a
        # layout change (sync.rs offload_partition → delete_if_equal) or
        # GC — where the data still exists on the real replicas.  Treating
        # it as "all versions deleted" would enqueue version tombstones
        # that REPLICATE to the version table's replica set and cascade
        # (version → block_ref → rc → block GC) into cluster-wide data
        # loss on every layout change.
        if old is None or new is None:
            return
        new_by_uuid = {bytes(v.uuid): v for v in new.versions()}
        for ov in old.versions():
            nv = new_by_uuid.get(bytes(ov.uuid))
            # a version that was active and is now gone or aborted must be
            # deleted from the version table (object_table.rs:398-429);
            # for multipart uploads ov.uuid doubles as the upload id and
            # the *final* version uuid, so this also reaps the final
            # version when a completed MPU object is later deleted
            became_deleted = (nv is None and not ov.is_aborted()) or (
                nv is not None and nv.is_aborted() and not ov.is_aborted()
            )
            if became_deleted and self.version_table is not None:
                vdel = Version.new(ov.uuid, bytes(old.bucket_id), old.key, deleted=True)
                self.version_table.data.queue_insert(tx, vdel)
            # independently: once a multipart upload stops Uploading
            # (aborted, completed, or pruned), its MPU row is tombstoned,
            # cascading to all part versions (object_table.rs:431-460);
            # after completion the final version carries its own refs
            if ov.is_uploading(check_multipart=True) and self.mpu_table is not None:
                mpu_done = nv is None or not nv.is_uploading()
                if mpu_done:
                    from .mpu_table import MultipartUpload

                    mdel = MultipartUpload(
                        ov.uuid, ov.timestamp, bytes(old.bucket_id),
                        old.key, deleted=True,
                    )
                    self.mpu_table.data.queue_insert(tx, mdel)

    def matches_filter(self, entry: Object, filter: Any) -> bool:
        if filter is None:
            return entry.last_data_version() is not None
        if filter == "uploading":
            # node-side (ref ObjectFilter::IsUploading): rows without an
            # in-progress upload never leave the replica — a cleanup scan
            # must not ship a bucket's inline object bytes over RPC
            return any(v.is_uploading() for v in entry.versions())
        return True


async def abort_uploads(object_table, obj: Object, predicate) -> int:
    """Abort every in-progress upload version of `obj` that matches
    `predicate(version)`.  Inserting the aborted versions rides the
    updated() hook cascade: MPU rows tombstone, part versions and their
    block refs drop.  Shared by the lifecycle worker's
    abort-incomplete-multipart-upload rule and the admin
    `bucket cleanup-incomplete-uploads` command — the CRDT state literal
    and the cascade contract live in exactly one place."""
    aborted = [
        ObjectVersion(v.uuid, v.timestamp, ["aborted"])
        for v in obj.versions()
        if v.is_uploading() and predicate(v)
    ]
    if aborted:
        await object_table.insert(Object(obj.bucket_id, obj.key, aborted))
    return len(aborted)
