"""Block reference table — the metadata→block-layer coupling point.

Equivalent of reference src/model/s3/block_ref_table.rs:12-86: P = block
hash, S = version uuid, with an or-merged deleted flag; the `updated()`
hook calls `block_incref`/`block_decref` on the block manager inside the
same transaction, so block refcounts exactly track live references.
"""

from __future__ import annotations

from typing import Any, Optional

from ...table.schema import Entry, TableSchema
from ...utils.crdt import CrdtBool
from ...utils.data import Hash, Uuid


class BlockRef(Entry):
    VERSION_MARKER = b"GT01blockref"

    def __init__(self, block: Hash, version: Uuid, deleted: bool = False):
        self.block = block
        self.version = version
        self.deleted = CrdtBool(deleted)

    @property
    def partition_key(self) -> Hash:
        return self.block

    @property
    def sort_key(self) -> bytes:
        return bytes(self.version)

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def merge(self, other: "BlockRef") -> None:
        self.deleted.merge(other.deleted)

    def fields(self) -> Any:
        return [bytes(self.block), bytes(self.version), self.deleted.value]

    @classmethod
    def from_fields(cls, b: Any) -> "BlockRef":
        return cls(Hash(bytes(b[0])), Uuid(bytes(b[1])), bool(b[2]))


class BlockRefTableSchema(TableSchema):
    TABLE_NAME = "block_ref"
    ENTRY = BlockRef

    def __init__(self, block_manager=None):
        self.block_manager = block_manager
        # set by Garage when distributed parity is on: fired (post-commit)
        # with the block hash when a LIVE version-ref transitions to dead —
        # the receiver checks whether any live version-ref remains and
        # tombstones the block's parity-index rows if not.  This is the
        # GLOBAL deletion signal; a node deleting its local copy during
        # migration/offload must never GC cluster-wide parity state.
        self.on_ref_dropped = None

    def updated(self, tx, old: Optional[BlockRef], new: Optional[BlockRef]) -> None:
        """ref block_ref_table.rs:65-81."""
        if self.block_manager is None:
            return
        ent = old or new
        block = ent.block
        was = old is not None and not old.deleted.value
        now = new is not None and not new.deleted.value
        if now and not was:
            self.block_manager.block_incref(tx, block)
        if was and not now:
            self.block_manager.block_decref(tx, block)
            # Global-deletion signal: only a LOGICAL tombstone (new row
            # with deleted=True) means the reference is gone cluster-wide.
            # new=None is PHYSICAL removal — partition offload after a
            # layout change, or tombstone GC — and says nothing about
            # liveness; firing there tombstoned (stickily) the parity
            # index of blocks that were merely migrating.
            if (self.on_ref_dropped is not None and new is not None
                    and new.deleted.value):
                from ..parity_index_table import is_parity_ref

                if not is_parity_ref(ent.version):
                    cb, h = self.on_ref_dropped, block
                    tx.on_commit(lambda: cb(h))

    def matches_filter(self, entry: BlockRef, filter: Any) -> bool:
        from ...table.schema import DeletedFilter

        if filter is None:
            return not entry.deleted.value
        return DeletedFilter.matches(filter, entry.deleted.value)
